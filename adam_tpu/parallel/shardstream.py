"""Pod-scale elastic sharded streaming: lose a worker mid-stream, keep
the run.

The reference's Spark layer got fault tolerance for free (lineage +
task retry, SURVEY §5); our streaming hot path was single-host — one
preempted worker killed the whole run.  This module expresses the
streaming workloads as sharded MapReduce over a multi-host fleet, the
DrJAX broadcast/map/reduce decomposition (arXiv:2403.07128) at process
granularity:

* **broadcast** — a pure, replayable shard plan
  (:func:`decide_shard_plan`, event ``shard_plan_selected``) assigns
  contiguous *unit* ranges (fixed ``unit_rows``-row slices of the
  input) to hosts.  Contiguous ranges are the locality axis: for a
  position-sorted input they are contiguous genome ranges, and the
  genome partitioner (``GenomicRegionPartitioner``) optionally snaps
  shard boundaries onto genome-bin edges (``unit_bins``).
* **map** — each host runs the EXISTING single-host machinery on its
  shard: the shape-bucketed executor, the PR 5 retry→split→CPU-degrade
  ladder per chunk, the obs/metrics plane — all compose per-host
  unchanged.  Workers never share a jax mesh, so a lost peer cannot
  wedge a collective (the design parallel/elastic.py already argues:
  XLA SPMD cannot drop a peer mid-program; CPU jaxlibs do not even
  implement multiprocess computations).  The control plane is the
  fleet directory: atomic JSON (checkpoint.atomic_write discipline)
  for plan / assignment / lease / progress, immutable ``.npz`` commit
  files for results.
* **reduce** — per-shard results merge through the existing monoid
  paths: flagstat 18×2 counter blocks sum, RecalTable count tensors
  sum (``tables_to_recal``), per-worker obs sidecars fold into the
  supervisor's registry exactly like the elastic supervisor's merge.

The robustness core: every unit's result is committed durably and
*per unit* (result file first, progress marker second), so a worker
preempted mid-stream loses only its uncommitted units.  The elastic
supervisor detects loss via process exit **or heartbeat lease expiry**
(a hung worker shows no exit code; the stale lease converts "silent"
into "dead", and the supervisor fences it with SIGKILL before
reassigning).  Recovery is the pure
:func:`decide_shard_reassignment` (event ``shard_reassigned``):
respawn a new incarnation of the same shard (resuming from committed
units), or — past the restart budget — redistribute the remaining
range across survivors (shrink-to-fit).  Deadline-based speculative
execution (:func:`decide_shard_speculation`, off by default) re-runs
the slowest shard's tail range on an idle survivor; the merge
deduplicates units (first committer wins, by (incarnation, shard,
seq) order), so duplicated work can never double-count — unit results
are exact integer monoids, so WHO computed a unit is value-irrelevant.

Re-decode is honest: a respawned worker re-reads whatever input bytes
it must traverse to reach its remaining range, and those bytes land in
the I/O ledger (per-worker sidecars; the supervisor's fold sums them)
— never silently absorbed.

The DATA PLANE (ROADMAP item 3's zero-copy slice) rides three pure,
replayable decisions on top of that spine:

* ``ringplane.decide_transport`` (event ``transport_selected``) —
  same-box fleets carry unit results over a shared-memory mmap ring
  (Arrow-IPC segments, seqlock commit cursor, torn-segment detection;
  parallel/ringplane.py) while the filesystem spool REMAINS the
  durable spine: the npz commit renames before the ring publish, so
  ring contents are always a subset of the spool and the crash
  contract is untouched.  ``spool_sync=batched`` drops the spool to
  ONE directory fsync per commit window (ordered-journal rename
  ordering keeps commit-before-marker durable).
* ``ringplane.decide_shard_entry`` (event ``shard_entry_selected``) —
  SAM byte offsets / BAM BGZF virtual offsets (``io/sam.scan_sam_units``
  / ``io/bam.scan_bam_units``) let a shard SEEK to its unit range
  instead of forward-decoding from row 0; the honest re-decode bytes
  collapse to ~0 and the ledger charges only what was read.
* unit-granular stealing (``FleetPolicy.steal``, event ``unit_stolen``)
  — an idle survivor claims single pending units off the claim table
  (``O_EXCL`` create, one winner) so a straggler's tail drains without
  a lease expiry; the merge dedup stays the correctness backstop.

tools/check_metrics.py validates the event schemas;
tools/check_executor.py replays every plan/reassignment decision;
tests/test_shardstream.py pins the chaos matrix (SIGKILL / latency /
torn-checkpoint × shard → byte-identical or cleanly typed).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..checkpoint import atomic_np_write, atomic_write
from ..checkpoint import fsync_dir as _fsync_dir
from ..resilience import faults
from ..resilience.retry import (RETRY_SEED_ENV, FleetPolicy,
                                resolve_fleet_policy)
from . import netplane, ringplane

#: fleet-dir layout (every path is relative to the fleet dir)
PLAN_FILE = "plan.json"
DONE_FILE = "done"
ASSIGN_DIR = "assign"
EXTRA_DIR = "extra"
LEASE_DIR = "leases"
PROGRESS_DIR = "progress"
COMMIT_DIR = "commits"
LOG_DIR = "logs"
#: net-transport worker-local spools (one per shard, under the
#: supervisor's fleet dir only because the emulated pod shares a box —
#: a real cross-box worker roots its local spool anywhere)
LOCAL_DIR = "local"

#: per-worker CPU budget (Arrow decode/IO pools), stamped by the
#: supervisor when ``worker_cpus`` is set — hosts emulated on one box
#: must not oversubscribe each other
FLEET_WORKER_CPUS_ENV = "ADAM_TPU_FLEET_WORKER_CPUS"


# ---------------------------------------------------------------------------
# small helpers: runs encoding + atomic fleet-dir JSON
# ---------------------------------------------------------------------------

def _to_runs(units: Sequence[int]) -> List[List[int]]:
    """Sorted unit ids -> compact [lo, hi) runs (events record runs, so
    a reassignment of a million units is a few ints, not a list)."""
    runs: List[List[int]] = []
    for u in sorted(set(int(u) for u in units)):
        if runs and runs[-1][1] == u:
            runs[-1][1] = u + 1
        else:
            runs.append([u, u + 1])
    return runs


def _from_runs(runs: Sequence[Sequence[int]]) -> List[int]:
    out: List[int] = []
    for lo, hi in runs:
        out.extend(range(int(lo), int(hi)))
    return out


def _write_json(path: str, doc: dict, fault_site: Optional[str] = None,
                fsync: bool = True) -> None:
    atomic_write(path, json.dumps(doc, sort_keys=True),
                 fault_site=fault_site, fsync=fsync)


def _read_json(path: str) -> Optional[dict]:
    """Tolerant read: missing or torn file -> None (the atomic-write
    discipline means a torn TARGET never exists; a torn TMP left by a
    crashed writer is simply not the target)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def _digest(inputs: dict) -> str:
    return hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# the pure decisions
# ---------------------------------------------------------------------------

def decide_shard_plan(*, n_units: int, n_hosts: int, unit_rows: int,
                      total_rows: int,
                      unit_bins: Optional[Sequence[int]] = None) -> dict:
    """The fleet's broadcast step — PURE.

    Contiguous balanced unit ranges per host (locality: contiguous file
    order is contiguous genome order for a sorted input).  When
    ``unit_bins`` (the genome-partitioner bin of each unit's first row)
    is given, interior shard boundaries snap to the nearest bin
    transition within a small window, so a shard boundary prefers a
    genome-bin edge over splitting a bin across hosts.  Recorded in
    full (``inputs`` + ``input_digest``) by ``shard_plan_selected`` so
    tools/check_executor.py replays the decision offline (the
    ``decide_plan`` convention).
    """
    inputs = dict(n_units=int(n_units), n_hosts=int(n_hosts),
                  unit_rows=int(unit_rows), total_rows=int(total_rows),
                  unit_bins=None if unit_bins is None
                  else [int(b) for b in unit_bins])
    reasons = ["contiguous"]
    hosts = max(min(inputs["n_hosts"], inputs["n_units"]), 1)
    if hosts < inputs["n_hosts"]:
        reasons.append("clamped-to-units")
    bounds = [i * inputs["n_units"] // hosts for i in range(hosts + 1)]
    bins = inputs["unit_bins"]
    if bins is not None and len(bins) == inputs["n_units"] and hosts > 1:
        window = max(inputs["n_units"] // (4 * hosts), 1)
        snapped = False
        for i in range(1, hosts):
            b = bounds[i]
            lo = max(bounds[i - 1] + 1, b - window)
            hi = min(bounds[i + 1] - 1, b + window)
            best = None
            for j in range(lo, hi + 1):
                if 0 < j < len(bins) and bins[j] != bins[j - 1]:
                    if best is None or abs(j - b) < abs(best - b):
                        best = j
            if best is not None and best != b:
                bounds[i] = best
                snapped = True
        if snapped:
            reasons.append("bin-snap")
    assignments = [[bounds[i], bounds[i + 1]] for i in range(hosts)]
    return dict(n_hosts=hosts, n_units=inputs["n_units"],
                unit_rows=inputs["unit_rows"],
                assignments=assignments, reason="+".join(reasons),
                inputs=inputs, input_digest=_digest(inputs))


def decide_shard_reassignment(*, shard: int, incarnation: int,
                              restarts_used: int, max_restarts: int,
                              remaining_runs: Sequence[Sequence[int]],
                              survivors: Sequence[int],
                              redistribute: bool,
                              error_code: str) -> dict:
    """One dead/lost shard's next action — PURE.

    ``action`` ∈ ``none`` (nothing uncommitted remains) / ``respawn``
    (a new incarnation of the same shard resumes the remaining range) /
    ``redistribute`` (shrink-to-fit: the remaining range splits into
    contiguous slices across the sorted survivors) / ``fail`` (restart
    budget exhausted and nowhere to shrink to).  Recorded in full by
    ``shard_reassigned`` (cause ``death``); tools/check_executor.py
    replays it.
    """
    inputs = dict(shard=int(shard), incarnation=int(incarnation),
                  restarts_used=int(restarts_used),
                  max_restarts=int(max_restarts),
                  remaining_runs=[[int(a), int(b)]
                                  for a, b in remaining_runs],
                  survivors=sorted(int(s) for s in survivors),
                  redistribute=bool(redistribute),
                  error_code=str(error_code))
    remaining = _from_runs(inputs["remaining_runs"])
    action, new_inc, splits, reason = "fail", None, [], ""
    if not remaining:
        action, reason = "none", "nothing-uncommitted"
    elif inputs["restarts_used"] < inputs["max_restarts"]:
        action = "respawn"
        new_inc = inputs["incarnation"] + 1
        reason = (f"{inputs['error_code']}:restart "
                  f"{inputs['restarts_used'] + 1}/{inputs['max_restarts']}")
    elif inputs["redistribute"] and inputs["survivors"]:
        action = "redistribute"
        surv = inputs["survivors"]
        n = len(remaining)
        for i, s in enumerate(surv):
            lo = i * n // len(surv)
            hi = (i + 1) * n // len(surv)
            if hi > lo:
                splits.append([s, _to_runs(remaining[lo:hi])])
        reason = f"{inputs['error_code']}:shrink-to-fit:{len(surv)}"
    else:
        reason = (f"{inputs['error_code']}:restarts-exhausted:"
                  "no-survivors" if not inputs["survivors"]
                  else f"{inputs['error_code']}:restarts-exhausted:"
                  "redistribute-off")
    return dict(action=action, new_incarnation=new_inc, splits=splits,
                reason=reason, inputs=inputs,
                input_digest=_digest(inputs))


def decide_shard_speculation(*, candidates: Sequence[Sequence],
                             idle: Sequence[int],
                             factor: float) -> dict:
    """Whether to speculatively re-run the slowest shard's tail — PURE.

    ``candidates`` is ``[[shard, remaining_runs, rate], ...]`` for
    every shard with uncommitted units (``rate`` = committed units per
    second, rounded); ``idle`` the draining shards with spare capacity.
    The slowest shard (largest ETA; ties -> lowest id) is speculated
    when the best candidate rate is at least ``factor`` times its rate
    (or it has made no progress at all), handing the LATTER half of its
    remaining range to the first idle survivor.  The merge dedups per
    unit, so the original keeps running — first commit wins and no unit
    ever counts twice.  Recorded by ``shard_reassigned`` (cause
    ``speculation``).
    """
    inputs = dict(
        candidates=[[int(s), [[int(a), int(b)] for a, b in runs],
                     round(float(r), 6)] for s, runs, r in candidates],
        idle=sorted(int(i) for i in idle),
        factor=round(float(factor), 6))
    out = dict(action="none", victim=None, target=None, tail_runs=[],
               reason="", inputs=inputs, input_digest=_digest(inputs))
    if not inputs["candidates"] or not inputs["idle"]:
        out["reason"] = "no-candidates" if not inputs["candidates"] \
            else "no-idle-survivor"
        return out
    best_rate = max(r for _, _, r in inputs["candidates"])

    def eta(entry):
        s, runs, r = entry
        n = sum(hi - lo for lo, hi in runs)
        return (n / r) if r > 0 else float("inf")

    victim = sorted(inputs["candidates"],
                    key=lambda e: (-eta(e), e[0]))[0]
    v_shard, v_runs, v_rate = victim
    if v_rate > 0 and best_rate < inputs["factor"] * v_rate:
        out["reason"] = "within-deadline"
        return out
    remaining = _from_runs(v_runs)
    if not remaining:
        out["reason"] = "victim-empty"
        return out
    tail = remaining[len(remaining) // 2:] or remaining[-1:]
    out.update(action="speculate", victim=v_shard,
               target=inputs["idle"][0], tail_runs=_to_runs(tail),
               reason=f"eta-straggler:rate={v_rate}:best={best_rate}")
    return out


def _emit_reassigned(cause: str, d: dict, **extra) -> None:
    obs.registry().counter("shard_reassignments", cause=cause).inc()
    fields = dict(cause=cause, action=d["action"], reason=d["reason"],
                  inputs=d["inputs"], input_digest=d["input_digest"])
    if cause == "death":
        fields.update(shard=d["inputs"]["shard"],
                      new_incarnation=d["new_incarnation"],
                      splits=d["splits"])
    else:
        fields.update(shard=d["victim"], victim=d["victim"],
                      target=d["target"], tail_runs=d["tail_runs"])
    fields.update(extra)
    obs.emit("shard_reassigned", **fields)


# ---------------------------------------------------------------------------
# input sizing + range readers (the locality-aware map side)
# ---------------------------------------------------------------------------

def _input_kind(path: str) -> str:
    """'sam' / 'bam' / 'parquet' — the shard-entry taxonomy."""
    p = str(path)
    if p.endswith(".sam"):
        return "sam"
    if p.endswith(".bam"):
        return "bam"
    return "parquet"


def count_input_rows(path: str) -> int:
    """Total reads in the input — exact.  Parquet: footer sums (free).
    SAM: a byte scan counting record lines (no field parse).  BAM:
    a BGZF length-walk (``io/bam.scan_bam_units`` — inflate + hop
    ``block_size`` fields, no Arrow rows); non-BGZF BAM falls back to
    the full decode walk (documented cost; the fleet plan needs the
    row count once, and the supervisor pays it, not every worker)."""
    p = str(path)
    if p.endswith(".sam"):
        n = 0
        with open(p, "rb") as f:
            for line in f:
                if line and not line.startswith(b"@") and line.strip():
                    n += 1
        return n
    if p.endswith(".bam"):
        from ..io.bam import scan_bam_units
        scanned = scan_bam_units(p)
        if scanned is not None:
            return int(scanned["total_rows"])
        from ..io.stream import open_read_stream
        return sum(t.num_rows for t in
                   open_read_stream(p, columns=["flags"],
                                    chunk_rows=1 << 20))
    import pyarrow.parquet as pq
    if os.path.isdir(p):
        return sum(pq.ParquetFile(os.path.join(p, f)).metadata.num_rows
                   for f in sorted(os.listdir(p))
                   if f.endswith(".parquet"))
    return pq.ParquetFile(p).metadata.num_rows


def unit_bins_for(path: str, unit_rows: int, n_units: int,
                  n_hosts: int) -> Optional[List[int]]:
    """Genome-partitioner bin of each unit's FIRST row (the plan's
    locality hint), from one projected 2-int-column scan of a Parquet
    input.  Best-effort: None on any trouble (SAM/BAM input, missing
    columns, unknown contigs) — the plan then stays plain contiguous."""
    p = str(path)
    if p.endswith(".sam") or p.endswith(".bam"):
        return None
    try:
        from ..io.parquet import iter_tables
        from ..packing import column_int64
        from .partitioner import GenomicRegionPartitioner
        from .pipeline import _prescan_seq_dict

        seq_dict = _prescan_seq_dict(p, unit_rows)
        if not len(list(seq_dict)):
            return None
        part = GenomicRegionPartitioner.from_dictionary(
            max(n_hosts, 1), seq_dict)
        refids = np.zeros(n_units, np.int64)
        starts = np.zeros(n_units, np.int64)
        off = 0
        for t in iter_tables(p, columns=["referenceId", "start"],
                             chunk_rows=max(unit_rows, 1 << 16)):
            n = t.num_rows
            first = -(-off // unit_rows)        # ceil: next boundary
            while first * unit_rows < off + n and first < n_units:
                row = first * unit_rows - off
                refids[first] = column_int64(t, "referenceId", -1)[row]
                starts[first] = column_int64(t, "start", 0)[row]
                first += 1
            off += n
        return [int(b) for b in part.partition(refids,
                                               np.maximum(starts, 0))]
    except Exception:  # noqa: BLE001 — locality is a hint, never fatal
        return None


def build_unit_index(input_path: str, unit_rows: int) -> Optional[dict]:
    """The shard-entry index for a SAM/BAM input: per-unit seek targets
    (SAM byte offsets; BAM BGZF virtual offsets), built by one cheap
    byte/length walk at plan time.  None when no index is possible —
    non-BGZF BAM, a SAM whose body lazily registers record groups
    (entry order would change ``recordGroupId`` assignment), or a
    Parquet input (row-group skip needs no index)."""
    p = str(input_path)
    try:
        if p.endswith(".sam"):
            from ..io.sam import scan_sam_units
            scanned = scan_sam_units(p, unit_rows)
            if not scanned["safe"]:
                return None
            return dict(kind="sam", unit_rows=int(unit_rows),
                        total_rows=int(scanned["total_rows"]),
                        offsets=scanned["offsets"])
        if p.endswith(".bam"):
            from ..io.bam import scan_bam_units
            scanned = scan_bam_units(p, unit_rows)
            if scanned is None:
                return None
            return dict(kind="bam", unit_rows=int(unit_rows),
                        total_rows=int(scanned["total_rows"]),
                        voffs=scanned["voffs"])
    except OSError:
        return None
    return None


def _rebatch_units(tables, first_unit: int, unit_rows: int):
    """Yield (unit_id, table) with exact unit boundaries from a stream
    of arbitrarily-chunked tables starting at global row
    first_unit*unit_rows."""
    import pyarrow as pa

    unit = first_unit
    parts: list = []
    have = 0
    for t in tables:
        parts.append(t)
        have += t.num_rows
        while have >= unit_rows:
            whole = pa.concat_tables(parts)
            yield unit, whole.slice(0, unit_rows)
            rest = whole.slice(unit_rows)
            parts = [rest] if rest.num_rows else []
            have -= unit_rows
            unit += 1
    if have:
        yield unit, pa.concat_tables(parts)


def _rg_compressed_bytes(rg_meta, roots: Optional[set]) -> int:
    total = 0
    for c in range(rg_meta.num_columns):
        col = rg_meta.column(c)
        root = col.path_in_schema.split(".", 1)[0]
        if roots is None or root in roots:
            total += col.total_compressed_size
    return total


def _parquet_range_tables(path: str, row_lo: int, row_hi: int,
                          columns: Optional[Sequence[str]],
                          io_kind: str, io_pass: str):
    """Tables covering global rows [row_lo, row_hi) of a Parquet
    file/dataset, reading ONLY the overlapping row groups (the locality
    payoff: a shard's I/O is its range, not the file).  Bytes actually
    read land in the I/O ledger under ``io_pass`` (projected,
    compressed — the honest-accounting currency)."""
    import pyarrow.parquet as pq

    files = sorted(os.path.join(path, f) for f in os.listdir(path)
                   if f.endswith(".parquet")) \
        if os.path.isdir(path) else [path]
    roots = None if columns is None \
        else {c.split(".", 1)[0] for c in columns}
    base = 0
    for fpath in files:
        pf = pq.ParquetFile(fpath)
        md = pf.metadata
        nr = md.num_rows
        if base + nr <= row_lo:
            base += nr
            continue
        if base >= row_hi:
            break
        gb = base
        for g in range(md.num_row_groups):
            rg = md.row_group(g)
            gn = rg.num_rows
            if gb + gn > row_lo and gb < row_hi and gn:
                obs.ioledger.record(io_kind,
                                    _rg_compressed_bytes(rg, roots),
                                    io_pass)
                tbl = pf.read_row_group(
                    g, columns=list(columns) if columns else None)
                s = max(row_lo - gb, 0)
                e = min(row_hi - gb, gn)
                yield tbl.slice(s, e - s)
            gb += gn
        base += nr


def _unit_tables(path: str, units: Sequence[int], unit_rows: int,
                 columns: Optional[Sequence[str]], io_kind: str,
                 io_pass: str, io_procs: int = 1,
                 entry: str = "forward", index: Optional[dict] = None):
    """(unit_id, table) pairs for the requested units, contiguous run
    by contiguous run.

    Parquet: row-group skip — only overlapping groups decode.  SAM/BAM
    with ``entry="index"`` and a unit index (:func:`build_unit_index`):
    the reader SEEKS to each run's first unit (SAM byte offset / BAM
    BGZF virtual offset) and decodes only the run — the ledger charges
    the bytes actually inflated, which is the ~0-re-decode payoff.
    Otherwise one forward stream per worker: rows before the shard's
    first unit are decoded-and-skipped, and that traversal is counted
    by the stream opener's ledger hook — the honest re-decode cost of
    recovery on unindexed text/BGZF inputs."""
    units = sorted(set(int(u) for u in units))
    if not units:
        return
    runs = _to_runs(units)
    p = str(path)
    if not (p.endswith(".sam") or p.endswith(".bam")):
        for lo, hi in runs:
            yield from _rebatch_units(
                _parquet_range_tables(p, lo * unit_rows, hi * unit_rows,
                                      columns, io_kind, io_pass),
                lo, unit_rows)
        return
    if entry == "index" and index is not None:
        def on_bytes(n: int) -> None:
            obs.ioledger.record(io_kind, int(n), io_pass)

        cols = list(columns) if columns else None
        for lo, hi in runs:
            if p.endswith(".sam"):
                from ..io.sam import open_sam_stream_at
                _sd, _rg, stream = open_sam_stream_at(
                    p, int(index["offsets"][lo]), chunk_rows=unit_rows,
                    on_bytes=on_bytes)
            else:
                from ..io.bam import open_bam_stream_at
                moff, intra = index["voffs"][lo]
                _sd, _rg, stream = open_bam_stream_at(
                    p, int(moff), int(intra), chunk_rows=unit_rows,
                    io_procs=io_procs, on_bytes=on_bytes)
            projected = (t.select(cols) if cols else t for t in stream)
            for unit, table in _rebatch_units(projected, lo, unit_rows):
                yield unit, table
                if unit >= hi - 1:
                    break
        return
    from ..io.stream import open_read_stream

    with obs.ioledger.pass_scope(io_pass):
        stream = open_read_stream(p, columns=columns,
                                  chunk_rows=unit_rows,
                                  io_procs=io_procs)
    want = set(units)
    last = units[-1]
    for unit, table in _rebatch_units(iter(stream), 0, unit_rows):
        if unit in want:
            yield unit, table
        if unit >= last:
            break


#: public name for the range reader — the fleet-serve scheduler's
#: ``flagstat_range`` sub-jobs (serve/scheduler.py) walk shard unit
#: ranges through the exact same row-group-skipping path the shard
#: fleet's workers use
unit_tables = _unit_tables


# ---------------------------------------------------------------------------
# worker-side task runtimes (the map functions)
# ---------------------------------------------------------------------------

def _flagstat_runtime(spec: dict):
    """Per-unit 18x2 flagstat counter blocks through the product
    dispatch ladder (pad to the canonical rung, retry/split/CPU-degrade
    — parallel/pipeline.streaming_flagstat's padded path, per unit)."""
    import jax
    import jax.numpy as jnp

    from ..ops.flagstat import (flagstat_kernel_wire32,
                                flagstat_wire32_sharded)
    from ..platform import is_tpu_backend
    from .executor import StreamExecutor
    from .mesh import make_mesh, reads_sharding
    from .pipeline import _wire32_from_table

    mesh = make_mesh()
    on_tpu = is_tpu_backend()
    ex = StreamExecutor(mesh, int(spec["unit_rows"]), on_tpu=on_tpu)
    pex = ex.begin_pass("flagstat", bytes_per_row=4.0)
    impl = os.environ.get("ADAM_TPU_FLAGSTAT_IMPL", "auto")
    if impl == "pallas" or (impl == "auto" and on_tpu):
        from ..ops.flagstat_pallas import flagstat_wire32_sharded_pallas
        kernel = flagstat_wire32_sharded_pallas(mesh,
                                                interpret=not on_tpu,
                                                donate=pex.donate)
    else:
        kernel = flagstat_wire32_sharded(mesh, donate=pex.donate)
    sharding = reads_sharding(mesh)
    mesh_mult = max(getattr(mesh, "size", 1) or 1, 1)

    def pad(w):
        n_pad = pex.pad_rows(len(w))
        if n_pad != len(w):
            return np.concatenate(
                [w, np.zeros(n_pad - len(w), np.uint32)])
        return w

    def host_cpu(wire_padded):
        with jax.default_device(jax.devices("cpu")[0]):
            return np.asarray(flagstat_kernel_wire32(
                jnp.asarray(wire_padded))).astype(np.int64)

    def halves(w, err):
        rows = len(w)
        mid = max((rows // 2) // mesh_mult, 1) * mesh_mult
        if rows <= mesh_mult or mid >= rows:
            raise err
        return sub(w[:mid]) + sub(w[mid:])

    def sub(w):
        padded = pad(w)
        c = pex.dispatch(
            "count-split",
            lambda attempt: kernel(jax.device_put(padded, sharding)),
            split=lambda e: halves(w, e),
            fallback=lambda e: host_cpu(padded))
        return np.asarray(c).astype(np.int64)

    def unit_result(unit_id: int, table) -> Dict[str, np.ndarray]:
        wire = _wire32_from_table(table)
        padded = pad(wire)
        counts = pex.dispatch(
            "count",
            lambda attempt: kernel(jax.device_put(padded, sharding)),
            split=lambda e: halves(wire, e),
            fallback=lambda e: host_cpu(padded))
        obs.chunk_processed("flagstat", table.num_rows,
                            bytes_in=4 * table.num_rows)
        return {"counts": np.asarray(counts).astype(np.int64)}

    return unit_result, ex


#: the 7 RecalTable count-tensor keys a bqsr commit stores
_BQSR_KEYS = tuple(f"t{i}" for i in range(7))


def _bqsr_runtime(spec: dict):
    """Per-unit RecalTable count tensors through the product count path
    (``count_tables_device``), joining the coordinator's dup bits and
    hoisted MD events back by global row — the fused stream 2, one
    shard's slice at a time."""
    import jax

    from ..bqsr.recalibrate import (_COUNT_IMPL_ENV, count_tables_device)
    from ..packing import pack_reads
    from ..platform import is_tpu_backend
    from .executor import StreamExecutor
    from .mesh import make_mesh
    from .pipeline import _MdEventStore, _apply_dup_bits

    params = spec["params"]
    n_rg_run = int(params["n_rg_run"])
    bucket_len = int(params["bucket_len"])
    unit_rows = int(spec["unit_rows"])
    fleet_dir = spec["fleet_dir"]

    # broadcast blobs map ONCE per worker process (ringplane's memo):
    # N shard incarnations in one process share the read-only mapping
    # instead of re-reading the blob per shard
    dup = None
    if params.get("has_dup"):
        dup = ringplane.load_broadcast_array(
            os.path.join(fleet_dir, "dup.npy"))
    mdstore = None
    if params.get("has_md"):
        z = ringplane.load_broadcast_npz(
            os.path.join(fleet_dir, "md.npz"))
        mdstore = _MdEventStore()
        mdstore.has_md = z["has_md"]
        mdstore.ev_rows = z["ev_rows"]
        mdstore.ev_pos = z["ev_pos"]
    snp_table = None
    if params.get("snp_path"):
        from ..models.snptable import SnpTable
        snp_table = SnpTable.from_vcf(params["snp_path"])

    mesh = make_mesh()
    ex = StreamExecutor(mesh, unit_rows, on_tpu=is_tpu_backend())
    pex = ex.begin_pass(
        "s2", bytes_per_row=2.0 * max(bucket_len, 1) + 64.0)

    def cpu_fallback(table, batch, md_info):
        old = os.environ.get(_COUNT_IMPL_ENV)
        os.environ[_COUNT_IMPL_ENV] = "host"
        try:
            with jax.default_device(jax.devices("cpu")[0]):
                out = count_tables_device(
                    table, batch, snp_table, n_read_groups=n_rg_run,
                    mesh=None, md_info=md_info)
        finally:
            if old is None:
                os.environ.pop(_COUNT_IMPL_ENV, None)
            else:
                os.environ[_COUNT_IMPL_ENV] = old
        return tuple(np.asarray(a) for a in out)

    def unit_result(unit_id: int, table) -> Dict[str, np.ndarray]:
        n = table.num_rows
        lo = unit_id * unit_rows
        if dup is not None:
            table = _apply_dup_bits(table, np.asarray(dup[lo:lo + n]))
        md_info = None if mdstore is None else \
            mdstore.md_info_for(np.arange(lo, lo + n, dtype=np.int64))
        batch = pack_reads(table,
                           pad_rows_to=pex.pad_rows(n, bucket_len),
                           bucket_len=bucket_len)
        out = pex.dispatch(
            "count",
            lambda attempt, t=table, b=batch, mi=md_info:
                count_tables_device(
                    t, b, snp_table, n_read_groups=n_rg_run,
                    mesh=mesh, donate=pex.donate and attempt == 1,
                    md_info=mi, layout="padded"),
            fallback=lambda e, t=table, b=batch, mi=md_info:
                cpu_fallback(t, b, mi))
        obs.chunk_processed("s2", n, bytes_in=table.nbytes)
        return {k: np.asarray(a).astype(np.int64)
                for k, a in zip(_BQSR_KEYS, out)}

    return unit_result, ex


_RUNTIMES: Dict[str, Callable] = {"flagstat": _flagstat_runtime,
                                  "bqsr_count": _bqsr_runtime}

def _task_io(spec: dict) -> Tuple[Optional[List[str]], str, str]:
    """Per-task range-reader configuration: (projected columns, ledger
    kind, ledger pass) — the same projections the single-host passes
    read, so fleet and single-host runs charge identical I/O."""
    if spec["task"] == "flagstat":
        from ..io.dispatch import FLAGSTAT_COLUMNS
        return list(FLAGSTAT_COLUMNS), "decoded", "flagstat"
    return list(spec["params"]["columns"]), "reread", "s2"


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def _write_lease(path: str, doc: dict) -> None:
    """Lease rewrite: atomic_write's tmp+rename WITHOUT its per-file
    syncs — the renewal round ends with ONE directory fsync (see
    Heartbeat._beat).  Leases are the one durable artifact where
    content durability is NOT load-bearing: the supervisor reads only
    the file's mtime, rename visibility is immediate on the same mount,
    and a lease lost to power failure just reads as stale — which
    fences and respawns the worker, the safe direction.  Everything
    else keeps the full atomic_write discipline."""
    atomic_write(path, json.dumps(doc, sort_keys=True), fsync=False)


class Heartbeat:
    """The worker's lease renewal loop: every ``heartbeat_s`` fire the
    ``shard_lease`` fault site, then atomically rewrite the lease file.
    The supervisor reads the file's mtime; a stale lease past the TTL
    is a lost worker.  An injected lease error is treated as fatal FOR
    THIS WORKER (typed stderr line, hard exit) — the fleet layer, not
    the worker, owns recovery.  Shared by the shard fleet's workers and
    the fleet-serve workers (serve/scheduler.py) — one lease protocol,
    one fault site, one chaos matrix.

    Renewal is BATCHED (ROADMAP item 3's data-plane slice): a round
    writes the lease tmp+rename without a per-file fsync, then fsyncs
    the lease DIRECTORY once — one fsync per renewal round instead of
    two per lease.  Expiry-detection latency is unchanged (the
    supervisor polls mtimes, and renames are visible immediately);
    tests/test_shardstream.py pins it and the chaos matrix's
    lease-expiry legs re-prove the end-to-end behavior."""

    def __init__(self, path: str, heartbeat_s: float, incarnation: int):
        self.path = path
        self.heartbeat_s = heartbeat_s
        self.incarnation = incarnation
        self._stop = threading.Event()
        self._seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shard-lease")

    def start(self) -> "Heartbeat":
        self._beat()                    # lease exists before any work
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _beat(self) -> None:
        faults.fire("shard_lease", path=self.path)
        self._seq += 1
        _write_lease(self.path, dict(seq=self._seq, pid=os.getpid(),
                                     incarnation=self.incarnation))
        # ONE fsync per renewal round (the directory), not two per
        # lease (file + dir) — batched renewal
        _fsync_dir(os.path.dirname(os.path.abspath(self.path)) or ".")

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self._beat()
            except faults.InjectedFault as e:
                sys.stderr.write(
                    f"shard-worker: lease renewal failed (typed): "
                    f"{type(e).__name__}: {e}\n")
                sys.stderr.flush()
                os._exit(13)
            except OSError as e:        # fleet dir gone: supervisor died
                sys.stderr.write(
                    f"shard-worker: lease write failed: {e}\n")
                os._exit(14)


def _commit_unit_results(fleet_dir: str, shard: int, incarnation: int,
                         seq: int, results: List[Tuple[int, dict]],
                         fsync: bool = True) -> str:
    """One immutable commit file: unit ids + their result arrays,
    written tmp+rename (never torn).  ``fsync=False`` is the batched
    spool: the caller fsyncs the commit DIRECTORY once per window
    instead (see ``run_shard_worker.flush``).  Returns the committed
    path."""
    arrays: Dict[str, np.ndarray] = {
        "units": np.array([u for u, _ in results], np.int64)}
    for key in results[0][1]:
        arrays[key] = np.stack([r[key] for _, r in results])
    path = os.path.join(fleet_dir, COMMIT_DIR,
                        f"shard{shard}-inc{incarnation}-{seq:06d}.npz")
    return atomic_np_write(path, lambda f: np.savez(f, **arrays),
                           fsync=fsync)


class _FileWorkerPlane:
    """The shared-filesystem worker plane: plan/assign/extra/done ride
    files in the fleet dir, leases are mtime heartbeats, and delivery
    is the spool itself (plus the mmap ring when the transport says
    so).  ``netplane.NetWorkerPlane`` presents the same surface over
    TCP; ``_run_worker_body`` is written against the surface, so the
    worker loop cannot drift between transports."""

    supports_steal = True

    def __init__(self, fleet_dir: str, shard: int):
        self.dir = fleet_dir
        self.shard = shard
        self._ring: Optional["ringplane.RingWriter"] = None
        self._assign_path = os.path.join(fleet_dir, ASSIGN_DIR,
                                         f"shard{shard}.json")
        self._sup_pid = 0

    def load(self) -> Optional[dict]:
        spec = _read_json(os.path.join(self.dir, PLAN_FILE))
        if spec is None:
            return None
        assign = _read_json(self._assign_path) or {}
        self._sup_pid = int(spec.get("supervisor_pid") or 0)
        return dict(spec=dict(spec, fleet_dir=self.dir),
                    incarnation=int(assign.get("incarnation", 0)),
                    runs=list(assign.get("runs", [])))

    def prepare(self, spec: dict, incarnation: int) -> None:
        if spec.get("transport") == "ring":
            self._ring = ringplane.RingWriter(
                os.path.join(self.dir, ringplane.RING_DIR,
                             f"shard{self.shard}-inc{incarnation}.ring"),
                int(spec.get("ring_bytes")
                    or ringplane.DEFAULT_RING_BYTES),
                self.shard, incarnation)

    def heartbeat(self, heartbeat_s: float,
                  incarnation: int) -> Heartbeat:
        return Heartbeat(
            os.path.join(self.dir, LEASE_DIR, f"shard{self.shard}.json"),
            heartbeat_s, incarnation).start()

    def publish(self, seq: int, results: List[Tuple[int, dict]]) -> None:
        if self._ring is not None:
            self._ring.publish(seq, results)

    def poll(self, incarnation: int, seen_version: int,
             ticks: int) -> dict:
        """One drain tick: done file, incarnation fencing, orphan
        detection (a hard-killed supervisor never writes the done
        file), and the redistributed-extra relay."""
        if os.path.exists(os.path.join(self.dir, DONE_FILE)):
            return dict(stop=True, extra=None)
        cur = _read_json(self._assign_path) or {}
        if int(cur.get("incarnation", incarnation)) != incarnation:
            return dict(stop=True, extra=None)  # fenced: newer owner
        if self._sup_pid and ticks % 40 == 0:   # ~every 2 s
            try:
                os.kill(self._sup_pid, 0)
            except OSError:
                sys.stderr.write(
                    "shard-worker: supervisor gone — exiting "
                    "orphaned drain\n")
                return dict(stop=True, extra=None)
        extra = _read_json(os.path.join(
            self.dir, EXTRA_DIR, f"shard{self.shard}.json")) or {}
        out = dict(stop=False, extra=None)
        if int(extra.get("version", 0)) > seen_version:
            out["extra"] = (int(extra["version"]),
                            list(extra.get("runs", [])))
        return out

    def close(self) -> None:
        if self._ring is not None:
            self._ring.close()


def run_shard_worker(fleet_dir: str, shard: int) -> int:
    """One fleet worker: load the plan + this shard's assignment
    (files, or the net boot handshake), stream the assigned unit
    ranges through the product executor, commit each unit's result
    durably (commit file, then progress marker), then drain — pick up
    redistributed / speculative extra units until the supervisor says
    done.

    ``ADAM_TPU_FLEET_NET`` in the env selects the TCP plane:
    ``fleet_dir`` is then this worker's LOCAL spool, and everything
    shared rides netplane.  A net worker whose peer stays unreachable
    past the retry budget degrades typed: onto the shared spool when
    one is usable (NetDegraded — re-enter the file plane there), else
    a clean typed exit that the supervisor redistributes."""
    addr = os.environ.get(netplane.NET_ENV)
    try:
        if addr:
            try:
                return _run_worker_body(
                    netplane.NetWorkerPlane(addr, fleet_dir, shard),
                    shard)
            except netplane.NetDegraded as e:
                sys.stderr.write(
                    f"shard-worker: {e}\n")
                return _run_worker_body(
                    _FileWorkerPlane(e.shared_dir, shard), shard)
            except netplane.NetUnreachable as e:
                sys.stderr.write(
                    f"shard-worker: net plane unreachable (typed): "
                    f"{type(e).__name__}: {e}\n")
                return 15
        return _run_worker_body(_FileWorkerPlane(fleet_dir, shard),
                                shard)
    finally:
        obs.ioledger.emit_events()


def _run_worker_body(plane, shard: int) -> int:
    """The transport-agnostic worker loop (see run_shard_worker).

    Recovery contract: everything before the last progress marker is
    lost-proof; a respawned incarnation recomputes only uncommitted
    units (units any OTHER worker already committed are skipped too —
    the supervisor prunes them from the respawn assignment, and the
    merge dedups regardless).  The marker lands only after
    ``plane.publish`` returns — on the net plane that means after the
    supervisor ACKED the segment, so a kill mid-send recomputes and
    resends instead of losing the window."""
    faults.fire("worker_proc")
    boot = plane.load()
    if boot is None:
        print(f"shard-worker: no readable plan via {plane.dir}",
              file=sys.stderr)
        return 2
    spec = boot["spec"]
    my_inc = int(boot["incarnation"])
    units = _from_runs(boot["runs"])
    fleet_dir = plane.dir
    progress_path = os.path.join(fleet_dir, PROGRESS_DIR,
                                 f"shard{shard}.json")
    prog = _read_json(progress_path) or {}
    done_units = set(_from_runs(prog.get("done_runs", [])))

    obs.registry().gauge("shard_id").set(shard)
    obs.registry().gauge("shard_incarnation").set(my_inc)

    plane.prepare(spec, my_inc)
    hb = plane.heartbeat(float(spec["policy"]["heartbeat_s"]), my_inc)
    unit_result, ex = _RUNTIMES[spec["task"]](spec)
    columns, io_kind, io_pass = _task_io(spec)
    unit_rows = int(spec["unit_rows"])
    commit_every = max(int(spec.get("commit_every", 1)), 1)
    entry = str(spec.get("entry", "forward"))
    unit_index = spec.get("unit_index")
    batched = spec.get("spool_sync") == "batched"
    steal_on = bool(spec.get("policy", {}).get("steal")) \
        and plane.supports_steal
    seq = 0
    pending: List[Tuple[int, dict]] = []
    mine = set(units)

    def flush() -> None:
        nonlocal seq
        if not pending:
            return
        seq += 1
        # the durable spine FIRST: the npz rename precedes the ring
        # publish, so ring contents are always a subset of the spool.
        # Batched spool: no per-file fsyncs; ONE commit-dir fsync per
        # window (ordered-journal renames become durable in order, so
        # commit-before-marker still holds), then the marker rename
        # rides un-fsynced.  Per-flush fsyncs: 1 batched vs 4 every
        # (commit file+dir, marker file+dir) — spool_fsyncs records it.
        path = _commit_unit_results(fleet_dir, shard, my_inc, seq,
                                    pending, fsync=not batched)
        if batched:
            _fsync_dir(os.path.join(fleet_dir, COMMIT_DIR))
        obs.registry().counter("spool_fsyncs").inc(1 if batched else 4)
        try:
            obs.registry().counter("spool_bytes").inc(
                os.path.getsize(path))
        except OSError:
            pass
        # delivery AFTER the local spool rename, BEFORE the marker:
        # the ring's publish is advisory (the spool is shared), the
        # net plane's blocks until the supervisor ACKS — either way a
        # marker can only cover work the supervisor can reach
        plane.publish(seq, pending)
        done_units.update(u for u, _ in pending)
        pending.clear()
        # marker AFTER the commit file: a crash between them only
        # recomputes (merge dedups); the reverse order could mark work
        # that never landed.  The checkpoint_write fault site tears the
        # in-flight tmp here — the chaos matrix's torn-marker cell.
        _write_json(progress_path,
                    dict(done_runs=_to_runs(sorted(done_units)),
                         incarnation=my_inc),
                    fault_site="checkpoint_write", fsync=not batched)

    def _claimed_elsewhere(unit: int) -> bool:
        doc = ringplane.claim_owner(fleet_dir, unit)
        return doc is not None and int(doc.get("shard", -1)) != shard

    def process(unit_ids: Sequence[int]) -> None:
        todo = [u for u in unit_ids if u not in done_units]
        if steal_on:
            # a thief already claimed these tail units; skipping them
            # is advisory (merge dedup is the backstop) — the drain
            # loop re-checks in case the thief dies and its claims are
            # released by the supervisor
            todo = [u for u in todo if not _claimed_elsewhere(u)]
        for unit, table in _unit_tables(
                spec["input"], todo, unit_rows, columns, io_kind,
                io_pass, io_procs=int(spec.get("io_procs", 1)),
                entry=entry, index=unit_index):
            pending.append((unit, unit_result(unit, table)))
            if len(pending) >= commit_every:
                flush()
        flush()

    def steal_once() -> Optional[int]:
        """Claim ONE pending unit from another shard's tail (O_EXCL
        create = one winner).  None when nothing is stealable."""
        for apath in sorted(_glob.glob(os.path.join(
                fleet_dir, ASSIGN_DIR, "shard*.json"))):
            victim = int(os.path.basename(apath)[5:-5])
            if victim == shard:
                continue
            a = _read_json(apath) or {}
            theirs = set(_from_runs(a.get("runs", [])))
            e = _read_json(os.path.join(fleet_dir, EXTRA_DIR,
                                        f"shard{victim}.json")) or {}
            theirs |= set(_from_runs(e.get("runs", [])))
            vprog = _read_json(os.path.join(
                fleet_dir, PROGRESS_DIR, f"shard{victim}.json")) or {}
            theirs -= set(_from_runs(vprog.get("done_runs", [])))
            theirs -= done_units
            # tail first: the victim works head-first, so the tail is
            # the least likely to be in flight on its side
            for u in sorted(theirs, reverse=True):
                if ringplane.claim_owner(fleet_dir, u) is not None:
                    continue
                if ringplane.claim_unit(fleet_dir, u, shard, my_inc):
                    obs.registry().counter("unit_steals").inc()
                    obs.emit("unit_stolen", unit=int(u),
                             victim=victim, thief=shard,
                             incarnation=my_inc)
                    return u
        return None

    try:
        process(units)
        # drain: redistributed/speculative extras arrive via the
        # plane's relay (extra file, or the net status poll); exit when
        # the supervisor declares the fleet done — or when the plane
        # says stop (fenced by a newer incarnation, or the supervisor
        # itself is GONE and an orphaned worker spinning forever would
        # leak a whole jax process)
        seen_version = 0
        ticks = 0
        while True:
            ticks += 1
            p = plane.poll(my_inc, seen_version, ticks)
            if p["stop"]:
                break
            if p["extra"] is not None:
                seen_version, extra_runs = p["extra"]
                new_units = _from_runs(extra_runs)
                mine.update(new_units)
                process(new_units)
            if steal_on:
                stolen = steal_once()
                if stolen is not None:
                    process([stolen])
                    continue        # keep pulling while there is work
                if ticks % 20 == 0:
                    # a thief that claimed OUR tail may have died; the
                    # supervisor releases its claims, and this sweep
                    # recomputes whatever came back (no-op otherwise)
                    process(sorted(mine - done_units))
            time.sleep(0.05)
    finally:
        hb.stop()
        plane.close()
        ex.finish()
    return 0


def worker_main(argv: Optional[List[str]] = None) -> int:
    """``python -m adam_tpu.parallel.shardstream FLEET_DIR SHARD_ID`` —
    the supervisor-spawned worker entry (env carries the metrics
    sidecar path, incarnation, shard id, and fault plan, exactly like
    elastic workers)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 2:
        print("usage: python -m adam_tpu.parallel.shardstream "
              "FLEET_DIR SHARD_ID", file=sys.stderr)
        return 2
    fleet_dir, shard = argv[0], int(argv[1])
    # per-host CPU budget: hosts emulated on one box must not
    # oversubscribe each other's cores (a real pod gives each host its
    # own) — bound Arrow's decode pool before anything imports jax
    cpus = os.environ.get(FLEET_WORKER_CPUS_ENV)
    if cpus:
        try:
            import pyarrow as _pa
            _pa.set_cpu_count(max(int(cpus), 1))
            _pa.set_io_thread_count(max(int(cpus), 1))
        except (ValueError, ImportError):
            pass
    from ..platform import honor_platform_env
    honor_platform_env()
    try:
        faults.install_from_env()
    except (OSError, ValueError) as e:
        print(f"shard-worker: bad fault plan: {e}", file=sys.stderr)
        return 2
    try:
        with obs.metrics_run_from_env(
                argv=["shard-worker", fleet_dir, str(shard)],
                config=dict(fleet_dir=fleet_dir, shard=shard),
                command="shard-worker"):
            obs.series.maybe_start_from_env()
            try:
                return run_shard_worker(fleet_dir, shard)
            finally:
                obs.series.stop_series()
    except faults.InjectedFault as e:
        print(f"shard-worker: {type(e).__name__}: {e}", file=sys.stderr)
        return 3


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

class _ShardState:
    def __init__(self, shard: int, runs: List[List[int]]):
        self.shard = shard
        self.runs = runs
        self.incarnation = 0
        self.restarts = 0
        self.proc: Optional[subprocess.Popen] = None
        self.spawned_at = 0.0
        self.closed = False             # no proc should run for it
        self.extra_version = 0
        self.extra_units: List[int] = []
        self.speculated = False


def _repo_root() -> str:
    import adam_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(adam_tpu.__file__)))


class ShardSupervisor:
    """The fleet control plane: spawn, watch (exit codes + leases),
    reassign, and merge.  One instance per fleet run."""

    def __init__(self, spec: dict, plan: dict, fleet_dir: str,
                 policy: FleetPolicy, env: Optional[dict] = None,
                 boot_grace_s: float = 90.0, timeout_s: float = 900.0,
                 worker_cpus: Optional[int] = None):
        self.spec = spec
        self.plan = plan
        self.fleet_dir = fleet_dir
        self.policy = policy
        self.env = dict(env if env is not None else os.environ)
        if worker_cpus:
            self.env[FLEET_WORKER_CPUS_ENV] = str(int(worker_cpus))
            # OpenMP-backed kernels (numpy BLAS) respect this at import
            self.env.setdefault("OMP_NUM_THREADS", str(int(worker_cpus)))
        self.boot_grace_s = max(boot_grace_s, policy.lease_ttl_s)
        self.timeout_s = timeout_s
        self.states: Dict[int, _ShardState] = {}
        self.all_units = list(range(plan["n_units"]))
        self._commit_units: Dict[str, List[int]] = {}
        self._dups = 0
        #: ring transport state: one reader per ring file, decoded
        #: segments keyed (incarnation, shard, seq) — the SAME key as
        #: the npz commit files, because a segment and its npz twin are
        #: one commit, not a duplicate
        self._ring_readers: Dict[str, "ringplane.RingReader"] = {}
        self._ring_results: Dict[Tuple[int, int, int],
                                 List[Tuple[int, dict]]] = {}
        #: net transport state: the TCP server (started in run()) —
        #: its drained segments land in _ring_results under the SAME
        #: (incarnation, shard, seq) keys, so scan/merge/dedup are one
        #: code path across all three transports
        self.net: Optional["netplane.NetServer"] = None

    # -- spawn -------------------------------------------------------------

    def _worker_env(self, shard: int, incarnation: int) -> dict:
        wenv = dict(self.env)
        wenv[obs.METRICS_ENV] = os.path.join(
            self.fleet_dir, LOG_DIR,
            f"shard{shard}-inc{incarnation}.metrics.jsonl")
        if obs.series.active() is not None:
            # the live plane follows the supervisor's choice: when it
            # samples, each incarnation writes its own series next to
            # its metrics sidecar (fold_series_files merges them)
            wenv[obs.SERIES_ENV] = os.path.join(
                self.fleet_dir, LOG_DIR,
                f"shard{shard}-inc{incarnation}.series.jsonl")
        wenv[faults.INCARNATION_ENV] = str(incarnation)
        wenv[faults.SHARD_ENV] = str(shard)
        # fleet-scoped retry policy: each host draws a DISTINCT
        # deterministic jitter stream, so a shared transient (one flaky
        # interconnect) cannot re-synchronize every host's retries
        base = 0
        try:
            base = int(self.env.get(RETRY_SEED_ENV) or 0)
        except ValueError:
            pass
        wenv[RETRY_SEED_ENV] = str(base + 1000 * (shard + 1))
        if self.net is not None:
            wenv[netplane.NET_ENV] = self.net.address()
            # the degradation target: this fleet dir IS a usable shared
            # spool on the emulated pod; a caller-provided env may
            # override it (empty = no shared filesystem exists)
            wenv.setdefault(netplane.SHARED_DIR_ENV, self.fleet_dir)
        root = _repo_root()
        wenv["PYTHONPATH"] = root + os.pathsep + \
            wenv.get("PYTHONPATH", "")
        return wenv

    def _spawn(self, st: _ShardState) -> None:
        # drop the previous incarnation's lease BEFORE the new worker
        # starts: judging a respawn against its predecessor's stale
        # mtime would declare it lost mid-import and burn the whole
        # restart budget in one poll cycle — a fresh incarnation must
        # get the boot grace, then live on its OWN heartbeats
        try:
            os.unlink(os.path.join(self.fleet_dir, LEASE_DIR,
                                   f"shard{st.shard}.json"))
        except OSError:
            pass
        if self.net is not None:
            self.net.clear_lease(st.shard)
            # the boot handshake must see THIS incarnation's
            # assignment, not a stale snapshot
            self.net.update_state(
                st.shard, incarnation=st.incarnation, runs=st.runs,
                extra_version=st.extra_version,
                extra_runs=_to_runs(st.extra_units))
        log_path = os.path.join(
            self.fleet_dir, LOG_DIR,
            f"shard{st.shard}-inc{st.incarnation}.log")
        worker_dir = self.fleet_dir
        if self.net is not None:
            # net workers get NOTHING shared: their argv dir is a
            # worker-local spool, everything else arrives over TCP
            worker_dir = os.path.join(self.fleet_dir, LOCAL_DIR,
                                      f"shard{st.shard}")
            os.makedirs(worker_dir, exist_ok=True)
        argv = [sys.executable, "-m", "adam_tpu.parallel.shardstream",
                worker_dir, str(st.shard)]
        with open(log_path, "w") as log:
            st.proc = subprocess.Popen(
                argv, stdout=log, stderr=subprocess.STDOUT,
                env=self._worker_env(st.shard, st.incarnation))
        st.spawned_at = time.monotonic()
        obs.registry().counter("shard_spawns").inc()

    # -- commit scanning ---------------------------------------------------

    def _poll_rings(self) -> None:
        """Drain newly committed ring segments into ``_ring_results``.
        A ring file that does not parse yet (the writer is mid-create)
        is retried next poll; a payload that fails to decode counts as
        torn and is skipped — the npz twin on the spool covers it."""
        if self.spec.get("transport") != "ring":
            return
        for path in sorted(_glob.glob(os.path.join(
                self.fleet_dir, ringplane.RING_DIR, "*.ring"))):
            rd = self._ring_readers.get(path)
            if rd is None:
                try:
                    rd = ringplane.RingReader(path)
                except (OSError, ValueError):
                    continue
                self._ring_readers[path] = rd
            for seq, _n, payload in rd.poll():
                try:
                    results = ringplane.decode_unit_results(payload)
                except Exception:  # noqa: BLE001 — torn, spool covers
                    obs.registry().counter("ring_torn_segments").inc()
                    continue
                self._ring_results[(rd.incarnation, rd.shard,
                                    int(seq))] = results

    def _poll_net(self) -> None:
        """Drain TCP-delivered segments into ``_ring_results``.  Every
        payload already passed the frame CRC; one that still fails to
        decode counts as torn and is skipped — the worker's LOCAL spool
        has it, and the worker resends on reconnect."""
        if self.net is None:
            return
        for key, payload in self.net.drain_results():
            try:
                results = ringplane.decode_unit_results(payload)
            except Exception:  # noqa: BLE001 — torn, sender resends
                obs.registry().counter("net_torn_segments").inc()
                continue
            self._ring_results[key] = results

    def _scan_commits(self) -> Dict[int, Tuple]:
        """unit -> (sort_key, path, row) for the winning commit of each
        unit (first by (incarnation, shard, seq) — deterministic, and
        value-irrelevant: unit results are exact monoids).  ``path`` is
        None for a ring-delivered commit (its arrays sit decoded in
        ``_ring_results``); a ring segment's npz twin shares its key
        and is skipped WITHOUT an np.load — the zero-copy payoff on the
        supervisor side.  Commit files are immutable once renamed, so
        parses cache."""
        self._poll_rings()
        self._poll_net()
        best: Dict[int, Tuple] = {}
        self._dups = 0
        entries: List[Tuple[Tuple[int, int, int], Optional[str],
                            List[int]]] = []
        for key, results in self._ring_results.items():
            entries.append((key, None, [u for u, _ in results]))
        ring_keys = set(self._ring_results)
        for path in sorted(_glob.glob(os.path.join(
                self.fleet_dir, COMMIT_DIR, "*.npz"))):
            name = os.path.basename(path)[:-4]
            s, i, q = name.split("-")
            key = (int(i[3:]), int(s[5:]), int(q))
            if key in ring_keys:
                continue        # the ring already delivered this commit
            if path not in self._commit_units:
                try:
                    with np.load(path) as z:
                        self._commit_units[path] = \
                            [int(u) for u in z["units"]]
                except (OSError, ValueError, KeyError, EOFError):
                    continue        # in-flight or torn: ignore
            entries.append((key, path, self._commit_units[path]))
        for key, path, units in sorted(entries,
                                       key=lambda e: e[0]):
            for row, unit in enumerate(units):
                if unit in best:
                    self._dups += 1
                    if key >= best[unit][0]:
                        continue
                best[unit] = (key, path, row)
        return best

    def _committed_by_shard(self, best: Dict[int, Tuple]
                            ) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for key, _, _ in best.values():
            out[key[1]] = out.get(key[1], 0) + 1
        return out

    # -- death / lease handling --------------------------------------------

    def _handle_loss(self, st: _ShardState, error_code: str,
                     committed: Dict[int, Tuple]) -> None:
        # fence first: a half-dead worker must not keep committing
        # after its range is handed elsewhere (the merge would dedup,
        # but fencing keeps the failure windows crisp)
        if st.proc is not None and st.proc.poll() is None:
            st.proc.kill()
            try:
                st.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        obs.registry().counter("shard_deaths",
                               code=error_code).inc()
        if self.spec.get("transport") == "ring":
            # the writer is dead (fenced above), so the tail is stable:
            # drain what it committed, then count a torn in-flight
            # segment if the kill landed mid-publish.  Torn segments
            # are DETECTED AND IGNORED — the npz spool is the spine.
            path = os.path.join(
                self.fleet_dir, ringplane.RING_DIR,
                f"shard{st.shard}-inc{st.incarnation}.ring")
            rd = self._ring_readers.get(path)
            if rd is None and os.path.exists(path):
                try:
                    rd = ringplane.RingReader(path)
                    self._ring_readers[path] = rd
                except (OSError, ValueError):
                    rd = None
            if rd is not None:
                for seq, _n, payload in rd.poll():
                    try:
                        self._ring_results[
                            (rd.incarnation, rd.shard, int(seq))] = \
                            ringplane.decode_unit_results(payload)
                    except Exception:  # noqa: BLE001
                        obs.registry().counter(
                            "ring_torn_segments").inc()
                torn = rd.scan_tail()
                if torn:
                    obs.registry().counter(
                        "ring_torn_segments").inc(torn)
        if self.net is not None:
            # drain anything the server acked before the death; a torn
            # in-flight frame was already dropped at the connection
            # (CRC/length validation), so there is no tail to scan —
            # the respawned incarnation recomputes and resends it
            self._poll_net()
            self.net.clear_lease(st.shard)
        if self.policy.steal:
            # claims the dead shard took as a THIEF would otherwise pin
            # their units forever (nobody else will touch a claimed
            # unit while its owner's claim file exists)
            ringplane.release_shard_claims(
                self.fleet_dir, st.shard, set(committed))
        remaining = sorted(
            (set(_from_runs(st.runs)) | set(st.extra_units))
            - set(committed))
        survivors = sorted(
            s for s, o in self.states.items()
            if s != st.shard and not o.closed
            and o.proc is not None and o.proc.poll() is None)
        d = decide_shard_reassignment(
            shard=st.shard, incarnation=st.incarnation,
            restarts_used=st.restarts,
            max_restarts=self.policy.max_restarts,
            remaining_runs=_to_runs(remaining), survivors=survivors,
            redistribute=self.policy.redistribute,
            error_code=error_code)
        _emit_reassigned("death", d)
        if d["action"] == "none":
            st.closed = True
            return
        if d["action"] == "respawn":
            st.incarnation = d["new_incarnation"]
            st.restarts += 1
            st.runs = _to_runs(remaining)
            st.extra_units = []
            # a fresh incarnation is a fresh straggler candidate: the
            # old one's speculation mark must not exclude it forever
            st.speculated = False
            _write_json(
                os.path.join(self.fleet_dir, ASSIGN_DIR,
                             f"shard{st.shard}.json"),
                dict(runs=st.runs, incarnation=st.incarnation))
            self._spawn(st)
            return
        if d["action"] == "redistribute":
            st.closed = True
            for target, runs in d["splits"]:
                self._give_extra(self.states[target], _from_runs(runs))
            return
        raise RuntimeError(
            f"shard fleet failed: shard {st.shard} lost "
            f"({error_code}) with {len(remaining)} units uncommitted, "
            f"restart budget exhausted and no survivors to shrink onto")

    def _give_extra(self, st: _ShardState, units: List[int]) -> None:
        st.extra_units = sorted(set(st.extra_units) | set(units))
        st.extra_version += 1
        _write_json(
            os.path.join(self.fleet_dir, EXTRA_DIR,
                         f"shard{st.shard}.json"),
            dict(runs=_to_runs(st.extra_units),
                 version=st.extra_version))

    def _check_lease(self, st: _ShardState, now: float) -> bool:
        """True when the shard's lease has expired (stale heartbeat).

        On the net transport the lease is socket-level: the age of the
        last lease message RECEIVED from the shard's current
        incarnation (supervisor-local monotonic clock — nothing is
        compared across hosts).  The filesystem lease still counts as
        a fallback: a worker that degraded onto the shared spool
        renews there, and fencing it for using the sanctioned
        degradation path would defeat the point."""
        age: Optional[float] = None
        if self.net is not None:
            age = self.net.lease_age(st.shard, st.incarnation)
        file_age: Optional[float] = None
        lease = os.path.join(self.fleet_dir, LEASE_DIR,
                             f"shard{st.shard}.json")
        try:
            file_age = time.time() - os.path.getmtime(lease)
        except OSError:
            pass
        if file_age is not None and (age is None or file_age < age):
            age = file_age
        if age is None:
            # no lease yet: only the boot grace applies (jax import on
            # a cold worker takes seconds; a TTL-sized wait would
            # declare every healthy worker dead at startup)
            return (now - st.spawned_at) > self.boot_grace_s
        if age <= self.policy.lease_ttl_s:
            return False
        obs.registry().counter("shard_lease_expiries").inc()
        obs.emit("shard_lease_expired", shard=st.shard,
                 age_s=round(age, 3),
                 ttl_s=round(self.policy.lease_ttl_s, 3))
        return True

    # -- speculation -------------------------------------------------------

    def _maybe_speculate(self, committed: Dict[int, Tuple],
                         now: float) -> None:
        by_shard = self._committed_by_shard(committed)
        candidates = []
        idle = []
        for s, st in sorted(self.states.items()):
            if st.closed or st.proc is None or \
                    st.proc.poll() is not None:
                continue
            mine = set(_from_runs(st.runs)) | set(st.extra_units)
            remaining = sorted(mine - set(committed))
            elapsed = max(now - st.spawned_at, 1e-3)
            rate = round(by_shard.get(s, 0) / elapsed, 6)
            obs.registry().gauge("shard_progress_rate",
                                 shard=str(s)).set(rate)
            if remaining:
                # a shard still inside its boot grace with no commits
                # is importing jax, not straggling — _check_lease
                # grants the same window before declaring death
                booting = rate == 0 and \
                    (now - st.spawned_at) < self.boot_grace_s
                if not st.speculated and not booting:
                    candidates.append([s, _to_runs(remaining), rate])
            else:
                idle.append(s)
        if not candidates or not idle:
            return
        d = decide_shard_speculation(candidates=candidates, idle=idle,
                                     factor=self.policy.speculate_factor)
        if d["action"] != "speculate":
            return
        _emit_reassigned("speculation", d)
        self.states[d["victim"]].speculated = True
        self._give_extra(self.states[d["target"]],
                         _from_runs(d["tail_runs"]))

    # -- the run loop ------------------------------------------------------

    def run(self) -> Dict[int, Tuple]:
        # a reused fleet dir must belong to THIS run: stale commit
        # files from a different input/plan would count as committed
        # units and merge wrong-input results without any error.  Same
        # digest = same input + unit boundaries, so its commits are
        # valid resume state (the CheckpointDir reject-on-mismatch
        # discipline, fleet edition).
        prev = _read_json(os.path.join(self.fleet_dir, PLAN_FILE))
        if prev is not None and prev.get("plan_digest") != \
                self.plan["input_digest"]:
            raise ValueError(
                f"fleet dir {self.fleet_dir!r} belongs to a different "
                "run (input/unit plan changed); delete it or use "
                "another -fleet_dir")
        dirs = [ASSIGN_DIR, EXTRA_DIR, LEASE_DIR, PROGRESS_DIR,
                COMMIT_DIR, LOG_DIR]
        if self.spec.get("transport") == "ring":
            dirs.append(ringplane.RING_DIR)
        if self.policy.steal:
            dirs.append(ringplane.CLAIM_DIR)
        for d in dirs:
            os.makedirs(os.path.join(self.fleet_dir, d), exist_ok=True)
        plan_doc = dict(self.spec,
                        plan_digest=self.plan["input_digest"],
                        supervisor_pid=os.getpid())
        _write_json(os.path.join(self.fleet_dir, PLAN_FILE), plan_doc)
        if self.spec.get("transport") == "net":
            # broadcast blobs (task seed files at the fleet-dir root,
            # e.g. dup.npy / md.npz) ship over TCP: workers never read
            # the shared dir on this transport
            blobs = {
                name: os.path.join(self.fleet_dir, name)
                for name in sorted(os.listdir(self.fleet_dir))
                if not name.startswith(".")
                and name not in (PLAN_FILE, DONE_FILE)
                and os.path.isfile(os.path.join(self.fleet_dir, name))}
            self.net = netplane.NetServer(plan_doc, blobs).start()
        for shard, (lo, hi) in enumerate(self.plan["assignments"]):
            st = _ShardState(shard, [[lo, hi]] if hi > lo else [])
            self.states[shard] = st
            _write_json(
                os.path.join(self.fleet_dir, ASSIGN_DIR,
                             f"shard{shard}.json"),
                dict(runs=st.runs, incarnation=0))
            self._spawn(st)
        deadline = time.monotonic() + self.timeout_s
        try:
            while True:
                self._sync_net_state()
                committed = self._scan_commits()
                obs.registry().gauge("shard_units_committed").set(
                    len(committed))
                if len(committed) >= len(self.all_units):
                    break
                now = time.monotonic()
                if now > deadline:
                    raise RuntimeError(
                        f"shard fleet timed out after {self.timeout_s}s "
                        f"({len(committed)}/{len(self.all_units)} units "
                        "committed)")
                for st in list(self.states.values()):
                    if st.closed or st.proc is None:
                        continue
                    rc = st.proc.poll()
                    if rc is not None:
                        # signals (SIGKILL preemption) vs error exits;
                        # a clean exit with work remaining is INTERNAL
                        # too (the worker broke its drain contract)
                        code = "PREEMPTED" if rc < 0 else "INTERNAL"
                        self._handle_loss(st, code, committed)
                        continue
                    if self._check_lease(st, now):
                        self._handle_loss(st, "DEADLINE_EXCEEDED",
                                          committed)
                if self.policy.speculate:
                    self._maybe_speculate(committed, time.monotonic())
                time.sleep(0.1)
            # release the drain loops, then collect workers (net
            # workers poll the done flag over TCP; a degraded worker
            # watches the file)
            if self.net is not None:
                self._sync_net_state()
                self.net.set_done()
            with open(os.path.join(self.fleet_dir, DONE_FILE), "w") as f:
                f.write("done\n")
            for st in self.states.values():
                if st.proc is not None and st.proc.poll() is None:
                    try:
                        st.proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        st.proc.terminate()
                        try:
                            st.proc.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            st.proc.kill()
            return committed
        finally:
            for st in self.states.values():
                if st.proc is not None and st.proc.poll() is None:
                    st.proc.kill()
            for rd in self._ring_readers.values():
                rd.close()
            if self.net is not None:
                self.net.close()

    def _sync_net_state(self) -> None:
        """Push each shard's assignment snapshot into the net server —
        the status relay workers poll (extra runs, fencing incarnation,
        done flag all ride it)."""
        if self.net is None:
            return
        for s, st in self.states.items():
            self.net.update_state(
                s, incarnation=st.incarnation, runs=st.runs,
                extra_version=st.extra_version,
                extra_runs=_to_runs(st.extra_units))

    # -- sidecar fold ------------------------------------------------------

    def fold_worker_metrics(self) -> int:
        """Fold every worker sidecar's registry snapshot into THIS
        process's registry (counter sum / gauge max / histogram merge —
        the elastic supervisor's discipline).  Returns sidecars folded.
        Workers never hold fleet views, so every sidecar folds."""
        from ..obs import read_snapshot_file, registry
        n = 0
        for path in sorted(_glob.glob(os.path.join(
                self.fleet_dir, LOG_DIR, "*.metrics.jsonl"))):
            snap = read_snapshot_file(path)
            if snap is None:
                continue
            registry().merge(snap)
            n += 1
        if n:
            registry().gauge("fleet_merged").set(1)
        return n


# ---------------------------------------------------------------------------
# fleet entry points (broadcast + map + reduce, one call)
# ---------------------------------------------------------------------------

def _build_plan(input_path: str, hosts: int, unit_rows: Optional[int],
                locality: bool = True) -> Tuple[dict, int, int]:
    total_rows = count_input_rows(input_path)
    if unit_rows is None:
        # granular enough to balance and to lose little on a death
        # (~8 units per host), bounded below so tiny inputs still shard
        unit_rows = max(-(-total_rows // max(8 * hosts, 1)), 256)
    n_units = max(-(-total_rows // unit_rows), 1)
    bins = unit_bins_for(input_path, unit_rows, n_units, hosts) \
        if locality else None
    plan = decide_shard_plan(n_units=n_units, n_hosts=hosts,
                             unit_rows=unit_rows, total_rows=total_rows,
                             unit_bins=bins)
    obs.registry().counter("shard_plans").inc()
    obs.emit("shard_plan_selected", n_hosts=plan["n_hosts"],
             n_units=plan["n_units"], unit_rows=plan["unit_rows"],
             assignments=plan["assignments"], reason=plan["reason"],
             inputs=plan["inputs"], input_digest=plan["input_digest"])
    return plan, total_rows, unit_rows


def run_fleet(task: str, input_path: str, *, hosts: int,
              unit_rows: Optional[int] = None,
              params: Optional[dict] = None,
              fleet_dir: Optional[str] = None,
              policy: Optional[FleetPolicy] = None,
              env: Optional[dict] = None,
              commit_every: int = 1,
              io_procs: int = 1,
              timeout_s: float = 900.0,
              locality: bool = True,
              worker_cpus: Optional[int] = None,
              seed: Optional[Callable[[str], None]] = None,
              transport: Optional[str] = None,
              spool_sync: Optional[str] = None,
              entry: Optional[str] = None
              ) -> Dict[str, np.ndarray]:
    """Run one sharded MapReduce workload to completion and return the
    merged (monoid-reduced) result arrays.

    The supervisor lives in THIS process (its events/metrics land in
    the caller's telemetry run); workers are separate processes.  The
    fleet dir defaults to a temp dir removed on success; pass one to
    keep the plan/commit/lease audit trail.  ``commit_every`` batches
    units per durable commit; a coarser cadence only widens what a
    preempted worker recomputes, never what the run returns.

    ``transport`` ("auto"/"ring"/"fleet_dir", env
    ``ADAM_TPU_FLEET_TRANSPORT``) picks how unit results travel:
    same-box fleets default to the shared-memory ring
    (``ringplane``), with the npz spool kept as the durable spine.
    ``spool_sync`` ("auto"/"batched"/"every", env
    ``ADAM_TPU_FLEET_SPOOL_SYNC``) batches the spool's fsyncs to one
    per commit window when the ring carries delivery.  ``entry``
    ("auto"/"index"/"forward", env ``ADAM_TPU_FLEET_ENTRY``) lets
    SAM/BAM shards seek straight to their unit range via a prescan
    index instead of forward-decoding from byte zero.  All three are
    pure replayable decisions (``decide_transport`` /
    ``decide_shard_entry``)."""
    import shutil

    policy = policy or resolve_fleet_policy()
    own_dir = fleet_dir is None
    if own_dir:
        fleet_dir = tempfile.mkdtemp(prefix="adam_tpu_fleet_")
    os.makedirs(fleet_dir, exist_ok=True)
    if seed is not None:
        # task sidecar files (dup bits, MD events) land in the fleet
        # dir before any worker spawns — ONE dir lifecycle (creation,
        # keep-on-failure, success cleanup) for every task
        seed(fleet_dir)
    plan, total_rows, unit_rows = _build_plan(
        input_path, hosts, unit_rows, locality=locality)
    if total_rows == 0:
        # nothing to shard: the phantom single unit would never commit
        # (no rows to read) and the supervisor would spin to timeout —
        # return the empty monoid, like the single-host stream does
        if own_dir:
            shutil.rmtree(fleet_dir, ignore_errors=True)
        return {}
    # a real same-box signal: the supervisor's host identity vs the
    # identity the workers will boot with (their env's
    # ADAM_TPU_FLEET_HOST_ID, reported back in the net handshake).
    # net_available joins the decision inputs ONLY when the net leg is
    # in play (cross-box, or explicitly requested) — pre-net sidecars
    # replay digest-identical
    requested = str(transport or os.environ.get(
        ringplane.TRANSPORT_ENV, "auto"))
    same_box = netplane.host_identity(env) == netplane.host_identity()
    tkw = {}
    if requested == "net" or not same_box:
        tkw["net_available"] = netplane.probe_net()
    td = ringplane.decide_transport(
        requested=requested,
        same_box=same_box,
        mmap_capable=ringplane.probe_mmap(fleet_dir),
        spool_requested=str(spool_sync or os.environ.get(
            ringplane.SPOOL_SYNC_ENV, "auto")),
        **tkw)
    obs.registry().counter("transport_decisions").inc()
    obs.emit("transport_selected", transport=td["transport"],
             spool_sync=td["spool_sync"], reason=td["reason"],
             inputs=td["inputs"], input_digest=td["input_digest"])
    if td["transport"] == "net" and policy.steal:
        # unit stealing rides a shared claim table (O_EXCL files) —
        # exactly what net workers do not have
        policy = dataclasses.replace(policy, steal=False)
    kind = _input_kind(input_path)
    entry_requested = str(entry or os.environ.get(
        ringplane.ENTRY_ENV, "auto"))
    unit_index = None
    if kind in ("sam", "bam"):
        # only-when-engaged: parquet inputs read native row groups and
        # never emit a shard_entry decision, so existing sidecars and
        # replay baselines are untouched
        if entry_requested != "forward":
            unit_index = build_unit_index(input_path, unit_rows)
        ed = ringplane.decide_shard_entry(
            kind=kind, requested=entry_requested,
            index_available=unit_index is not None)
        obs.emit("shard_entry_selected", entry=ed["entry"],
                 reason=ed["reason"], inputs=ed["inputs"],
                 input_digest=ed["input_digest"])
    else:
        ed = dict(entry="forward")
    spec = dict(task=task, input=os.path.abspath(input_path),
                unit_rows=unit_rows, n_units=plan["n_units"],
                total_rows=total_rows, params=params or {},
                commit_every=int(commit_every),
                io_procs=int(io_procs),
                transport=td["transport"],
                spool_sync=td["spool_sync"],
                entry=ed["entry"],
                policy=dict(heartbeat_s=policy.heartbeat_s,
                            lease_ttl_s=policy.lease_ttl_s,
                            steal=policy.steal))
    if td["transport"] == "ring":
        spec["ring_bytes"] = int(os.environ.get(
            ringplane.RING_BYTES_ENV, ringplane.DEFAULT_RING_BYTES))
    if ed["entry"] == "index":
        spec["unit_index"] = unit_index
    sup = ShardSupervisor(spec, plan, fleet_dir, policy, env=env,
                          timeout_s=timeout_s, worker_cpus=worker_cpus)
    t0 = time.perf_counter()
    try:
        winners = sup.run()
        merged = _merge_commits(winners, sup)
        obs.emit("shard_merge", units=len(winners),
                 duplicates=int(sup._dups),
                 shards=plan["n_hosts"],
                 wall_s=round(time.perf_counter() - t0, 6))
        obs.registry().counter("shard_units_deduped").inc(sup._dups)
        sup.fold_worker_metrics()
    except BaseException:
        # a FAILED fleet keeps its dir: the worker logs and metrics
        # sidecars under logs/ are the only record of WHY workers died
        # — deleting them would be exactly the silent absorption this
        # module exists to prevent
        if own_dir:
            sys.stderr.write(
                f"shard fleet failed; audit trail kept at "
                f"{fleet_dir} (worker logs + sidecars under "
                f"{LOG_DIR}/)\n")
        raise
    if own_dir:
        shutil.rmtree(fleet_dir, ignore_errors=True)
    return merged


def _merge_commits(winners: Dict[int, Tuple], sup: ShardSupervisor
                   ) -> Dict[str, np.ndarray]:
    """Reduce: sum each unit's winning result arrays (exact integer
    monoid — the same fold order-independence the single-host chunk
    accumulators rely on).  A winner with ``path is None`` arrived via
    the shared-memory ring and merges from the decoded segment — no
    disk read at all."""
    acc: Dict[str, np.ndarray] = {}
    loaded: Dict[str, "np.lib.npyio.NpzFile"] = {}
    for unit in sorted(winners):
        ckey, path, row = winners[unit]
        if path is None:
            for key, arr in sup._ring_results[ckey][row][1].items():
                arr = arr.astype(np.int64)
                acc[key] = arr if key not in acc else acc[key] + arr
            continue
        if path not in loaded:
            loaded[path] = np.load(path)
        z = loaded[path]
        for key in z.files:
            if key == "units":
                continue
            arr = z[key][row].astype(np.int64)
            acc[key] = arr if key not in acc else acc[key] + arr
    for z in loaded.values():
        z.close()
    return acc


def fleet_flagstat(path: str, *, hosts: int,
                   unit_rows: Optional[int] = None,
                   fleet_dir: Optional[str] = None,
                   policy: Optional[FleetPolicy] = None,
                   env: Optional[dict] = None,
                   commit_every: int = 1,
                   io_procs: int = 1,
                   timeout_s: float = 900.0,
                   worker_cpus: Optional[int] = None,
                   transport: Optional[str] = None,
                   spool_sync: Optional[str] = None,
                   entry: Optional[str] = None):
    """Sharded streaming flagstat: per-unit 18x2 counter blocks from N
    worker processes, summed — byte-identical to the single-host
    :func:`parallel.pipeline.streaming_flagstat` (counters are an exact
    monoid over reads; unit boundaries cannot change a bit).  Returns
    ``(failed, passed)`` like the single-host call."""
    from ..ops.flagstat import FlagStatMetrics

    merged = run_fleet("flagstat", path, hosts=hosts,
                       unit_rows=unit_rows, fleet_dir=fleet_dir,
                       policy=policy, env=env,
                       commit_every=commit_every, io_procs=io_procs,
                       timeout_s=timeout_s, worker_cpus=worker_cpus,
                       transport=transport, spool_sync=spool_sync,
                       entry=entry)
    totals = merged.get("counts")
    if totals is None:
        totals = np.zeros((18, 2), np.int64)
    passed = FlagStatMetrics.from_counters(totals[:, 0])
    failed = FlagStatMetrics.from_counters(totals[:, 1])
    return failed, passed


def fleet_bqsr_count(path: str, *, hosts: int, n_rg_run: int,
                     bucket_len: int,
                     columns: Sequence[str],
                     dup: Optional[np.ndarray] = None,
                     mdstore=None,
                     snp_path: Optional[str] = None,
                     unit_rows: Optional[int] = None,
                     fleet_dir: Optional[str] = None,
                     policy: Optional[FleetPolicy] = None,
                     env: Optional[dict] = None,
                     commit_every: int = 1,
                     timeout_s: float = 900.0,
                     transport: Optional[str] = None,
                     spool_sync: Optional[str] = None,
                     entry: Optional[str] = None):
    """Sharded fused stream 2: the RecalTable count over a Parquet
    reads dataset, distributed across hosts and merged through the
    RecalTable monoid — byte-identical to the single-host count (exact
    integer tensors; unit order is irrelevant under addition).  The
    coordinator's markdup dup bits and hoisted MD events ship once via
    the fleet dir (``run_fleet``'s ``seed`` hook, so the dir lifecycle
    — keep-on-failure, success cleanup — has one owner) and re-join
    per shard by global row index."""
    from ..bqsr.recalibrate import tables_to_recal

    def seed(d: str) -> None:
        # atomic_np_write like the unit commits: a supervisor crash
        # mid-seed must not leave a torn dup/md blob that a rerun's
        # workers would load as broadcast state
        if dup is not None:
            atomic_np_write(os.path.join(d, "dup.npy"),
                             lambda f: np.save(f, np.asarray(dup)))
        if mdstore is not None:
            atomic_np_write(
                os.path.join(d, "md.npz"),
                lambda f: np.savez(f, has_md=mdstore.has_md,
                                   ev_rows=mdstore.ev_rows,
                                   ev_pos=mdstore.ev_pos))

    params = dict(n_rg_run=int(n_rg_run),
                  bucket_len=int(bucket_len),
                  columns=list(columns),
                  has_dup=dup is not None,
                  has_md=mdstore is not None,
                  snp_path=snp_path)
    merged = run_fleet("bqsr_count", path, hosts=hosts,
                       unit_rows=unit_rows, params=params,
                       fleet_dir=fleet_dir, policy=policy, env=env,
                       commit_every=commit_every,
                       timeout_s=timeout_s, seed=seed,
                       transport=transport, spool_sync=spool_sync,
                       entry=entry)
    if not merged:
        from ..bqsr.table import RecalTable
        return RecalTable(n_read_groups=max(n_rg_run, 1),
                          max_read_len=max(bucket_len, 1))
    tensors = tuple(merged[k] for k in _BQSR_KEYS)
    return tables_to_recal(tensors, n_rg_run, max(bucket_len, 1))


if __name__ == "__main__":
    sys.exit(worker_main())
