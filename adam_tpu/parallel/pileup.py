"""Distributed genome-binned pileup counting — the sequence-parallel path.

The reference aggregates pileups with a position-keyed Spark shuffle
(PileupAggregator.scala:200-218) and scales along the genome axis by binning
+ boundary-read duplication (AdamRDDFunctions.scala:144-191, SURVEY.md §5).
Here the genome axis maps onto the device mesh: the partitioner assigns each
read (duplicated across bin boundaries) to a genome bin, each device owns one
contiguous stripe of bins, and per-position evidence is a scatter-add into a
dense [bin_span, channels] count tensor — ``segment_sum`` instead of a
shuffle.  Under ``shard_map`` every device counts its own stripe; no
collective is needed for the counts themselves (positions are disjoint by
construction), which is exactly why the binning layout is the right one for
ICI-poor topologies.

Channels: A, C, G, T, other-base, insertion, deletion, soft-clip,
reverse-strand, coverage, base-quality sum, mapq sum.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import schema as S
from ..ops.pileup import pileup_walk
from ..ops import cigar as C
from ..platform import shard_map

CHANNELS = ("A", "C", "G", "T", "N_OTHER", "INS", "DEL", "CLIP",
            "REVERSE", "COVERAGE", "QUAL_SUM", "MAPQ_SUM")
N_CHANNELS = len(CHANNELS)
(CH_A, CH_C, CH_G, CH_T, CH_OTHER, CH_INS, CH_DEL, CH_CLIP,
 CH_REVERSE, CH_COVERAGE, CH_QUAL, CH_MAPQ) = range(N_CHANNELS)


@partial(jax.jit, static_argnames=("bin_span", "max_len"))
def pileup_count_kernel(bases, quals, start, flags, mapq, valid,
                        cigar_ops, cigar_lens, bin_start,
                        bin_span: int, max_len: int) -> jnp.ndarray:
    """[bin_span, N_CHANNELS] int32 counts for positions
    [bin_start, bin_start + bin_span).

    Per-base events follow the pileup walk (Reads2PileupProcessor semantics):
    M bases count their base channel + coverage + qual/mapq sums; I bases
    count INS at the pinned position; S bases count CLIP; D positions
    (reference-consuming, no read base) count DEL via the cigar geometry.
    """
    N, L = bases.shape
    pos, op, off_in_op, op_len, in_read = pileup_walk(
        start, cigar_ops, cigar_lens, max_len)
    rel = pos - bin_start
    ok = in_read & valid[:, None] & (rel >= 0) & (rel < bin_span)
    rel = jnp.clip(rel, 0, bin_span - 1)

    is_m = (op == S.CIGAR_M) | (op == S.CIGAR_EQ) | (op == S.CIGAR_X)
    is_i = op == S.CIGAR_I
    is_s = op == S.CIGAR_S
    reverse = ((flags & S.FLAG_REVERSE) != 0)[:, None]

    out = jnp.zeros((bin_span, N_CHANNELS), jnp.int32)

    def add(out, mask, channel, val=1):
        w = jnp.where(ok & mask, val, 0).astype(jnp.int32)
        return out.at[rel.reshape(-1), channel].add(w.reshape(-1))

    base_ch = jnp.where(bases < 4, bases, CH_OTHER)
    w_base = jnp.where(ok & is_m, 1, 0).astype(jnp.int32)
    out = out.at[rel.reshape(-1), base_ch.reshape(-1)].add(w_base.reshape(-1))
    out = add(out, is_m, CH_COVERAGE)
    out = add(out, is_m, CH_QUAL, jnp.maximum(quals, 0).astype(jnp.int32))
    out = add(out, is_m, CH_MAPQ,
              jnp.broadcast_to(jnp.maximum(mapq, 0)[:, None], (N, L)))
    out = add(out, is_m & reverse, CH_REVERSE)
    out = add(out, is_i, CH_INS)
    out = add(out, is_s, CH_CLIP)

    # deletion events: reference positions consumed by D ops.  Each D op
    # covers [d_start, d_start + len); instead of expanding per position
    # (which would bound the deletion length) we scatter a +1/-1 difference
    # pair clipped to the bin and prefix-sum — any deletion length in O(span).
    ref_adv = C._table(np.array(S.CIGAR_CONSUMES_REF, np.int32),
                       cigar_ops) * cigar_lens
    ref_before = jnp.cumsum(ref_adv, axis=1) - ref_adv
    d_start = start[:, None] + ref_before - bin_start          # [N, Cc]
    d_end = d_start + cigar_lens
    is_d = (cigar_ops == S.CIGAR_D) & valid[:, None]
    lo = jnp.clip(d_start, 0, bin_span)
    hi = jnp.clip(d_end, 0, bin_span)
    w_d = jnp.where(is_d & (hi > lo), 1, 0).astype(jnp.int32)
    diff = jnp.zeros((bin_span + 1,), jnp.int32)
    diff = diff.at[lo.reshape(-1)].add(w_d.reshape(-1))
    diff = diff.at[hi.reshape(-1)].add(-w_d.reshape(-1))
    out = out.at[:, CH_DEL].add(jnp.cumsum(diff)[:bin_span])
    return out


@lru_cache(maxsize=None)
def sharded_pileup_counts(mesh, bin_span: int, max_len: int):
    """shard_map-compiled binned pileup: each device counts its own genome
    stripe.  Inputs are sharded on the read axis (reads pre-routed to their
    bin's device by the partitioner) plus a per-device bin_start scalar.
    Memoized per (mesh, bin_span, max_len): a fresh shard_map+jit per
    call would retrace every invocation (the warm-path recompile leak
    flagstat_wire32_sharded documents)."""
    from jax.sharding import PartitionSpec as P
    from .mesh import READS_AXIS
    spec = P(READS_AXIS)

    def step(bases, quals, start, flags, mapq, valid, cigar_ops, cigar_lens,
             bin_start):
        return pileup_count_kernel(bases, quals, start, flags, mapq, valid,
                                   cigar_ops, cigar_lens, bin_start[0],
                                   bin_span=bin_span, max_len=max_len)

    fn = shard_map(step, mesh=mesh,
                       in_specs=(spec,) * 8 + (spec,),
                       out_specs=spec)
    return jax.jit(fn)


def route_reads_to_stripes(refid, start, end, mapped, valid,
                           stripe_starts: np.ndarray,
                           stripe_span: int):
    """Host-side reshard for one contig: assign reads (duplicated across
    stripe boundaries) to per-device genome stripes.

    ``stripe_starts`` are the genome positions where each device's stripe
    begins (stripe d covers [stripe_starts[d], stripe_starts[d]+stripe_span)).
    Returns (gather_rows, device_of_row): a read appears once per stripe its
    [start, end) span touches — the boundary-duplication trick
    (AdamRDDFunctions.scala:175-183).
    """
    rows_ok = np.flatnonzero(np.asarray(mapped) & np.asarray(valid))
    s = np.asarray(start)[rows_ok]
    e = np.maximum(np.asarray(end)[rows_ok], s + 1)
    lo = np.searchsorted(stripe_starts, s, side="right") - 1
    hi = np.searchsorted(stripe_starts, e - 1, side="right") - 1
    lo = np.clip(lo, 0, len(stripe_starts) - 1)
    hi = np.clip(hi, lo, len(stripe_starts) - 1)
    n_stripes = (hi - lo + 1).astype(np.int64)
    gather = rows_ok[np.repeat(np.arange(len(rows_ok)), n_stripes)]
    offsets = np.arange(int(n_stripes.sum())) - \
        np.repeat(np.cumsum(n_stripes) - n_stripes, n_stripes)
    device = (lo[np.repeat(np.arange(len(rows_ok)), n_stripes)] + offsets)
    return gather.astype(np.int64), device.astype(np.int32)
