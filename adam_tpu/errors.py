"""User-facing error types.

``FormatError`` marks malformed *input data* (bad BAM magic, unparseable
SAM/VCF, cigar overflow...).  The CLI catches it and prints a one-line
message; genuine programming errors (arbitrary ValueError etc.) keep their
tracebacks.
"""

import os
import threading


class FormatError(ValueError):
    pass


class ValidationStringency:
    """SAM-tools style record validation levels
    (Bam2Adam.scala:46-47 exposes samtools' STRICT/LENIENT/SILENT;
    the reference CLI defaults to LENIENT)."""
    STRICT = "strict"
    LENIENT = "lenient"
    SILENT = "silent"


#: LENIENT stderr warning cap: first K records warn individually, then
#: one suppression notice, then silence — a badly corrupt BAM must not
#: emit one stderr line per record (millions of lines on WGS inputs).
#: Every drop still counts in the ``malformed_records`` obs counter and
#: the end-of-run summary (:func:`malformed_summary`).
MAX_MALFORMED_WARNINGS_ENV = "ADAM_TPU_MAX_MALFORMED_WARNINGS"
DEFAULT_MAX_MALFORMED_WARNINGS = 10

_MALFORMED_LOCK = threading.Lock()
_MALFORMED = {"dropped": 0, "warned": 0}


def _warning_cap() -> int:
    try:
        v = os.environ.get(MAX_MALFORMED_WARNINGS_ENV)
        return int(v) if v else DEFAULT_MAX_MALFORMED_WARNINGS
    except ValueError:
        return DEFAULT_MAX_MALFORMED_WARNINGS


def handle_malformed(stringency: str, message: str, cause=None) -> None:
    """Apply a stringency decision to one malformed input record: STRICT
    raises :class:`FormatError`, LENIENT warns on stderr (capped — see
    :data:`MAX_MALFORMED_WARNINGS_ENV`) and drops the record, SILENT
    drops it quietly.  Every dropped record counts in the
    ``malformed_records`` obs counter either way.  An unrecognized level
    is a caller bug and raises — falling through to silent would invert
    the strictness the caller asked for."""
    if stringency == ValidationStringency.STRICT:
        raise FormatError(message) from cause
    if stringency == ValidationStringency.LENIENT:
        from . import obs

        obs.registry().counter("malformed_records").inc()
        cap = _warning_cap()
        with _MALFORMED_LOCK:
            _MALFORMED["dropped"] += 1
            warned = _MALFORMED["warned"]
            if warned <= cap:
                _MALFORMED["warned"] = warned + 1
        import sys
        if warned < cap:
            print(f"warning: {message} (dropped)", file=sys.stderr)
        elif warned == cap:
            print(f"warning: {cap} malformed-record warnings shown; "
                  "suppressing the rest (drops still counted — see the "
                  "end-of-run summary / malformed_records metric)",
                  file=sys.stderr)
    elif stringency == ValidationStringency.SILENT:
        from . import obs

        obs.registry().counter("malformed_records").inc()
        with _MALFORMED_LOCK:
            _MALFORMED["dropped"] += 1
    else:
        raise ValueError(
            f"unknown validation stringency {stringency!r} "
            f"(want strict/lenient/silent)")


def malformed_summary():
    """One end-of-run line summarizing dropped records, or ``None`` when
    nothing was dropped (the CLI prints it after every command)."""
    with _MALFORMED_LOCK:
        dropped = _MALFORMED["dropped"]
        warned = min(_MALFORMED["warned"], _warning_cap())
    if not dropped:
        return None
    suppressed = dropped - warned
    line = f"dropped {dropped} malformed record(s) this run"
    if suppressed > 0:
        line += f" ({suppressed} warning(s) suppressed)"
    return line


def malformed_count() -> int:
    """Records dropped since the last reset — the serve front-end scopes
    this per job, so one tenant's dirty input is accounted to that
    tenant's result document, never a neighbor's."""
    with _MALFORMED_LOCK:
        return _MALFORMED["dropped"]


def reset_malformed() -> None:
    """Zero the per-run malformed-record accounting (test isolation and
    the CLI's / serve loop's per-invocation scope)."""
    with _MALFORMED_LOCK:
        _MALFORMED["dropped"] = 0
        _MALFORMED["warned"] = 0
