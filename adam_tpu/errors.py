"""User-facing error types.

``FormatError`` marks malformed *input data* (bad BAM magic, unparseable
SAM/VCF, cigar overflow...).  The CLI catches it and prints a one-line
message; genuine programming errors (arbitrary ValueError etc.) keep their
tracebacks.
"""


class FormatError(ValueError):
    pass
