"""User-facing error types.

``FormatError`` marks malformed *input data* (bad BAM magic, unparseable
SAM/VCF, cigar overflow...).  The CLI catches it and prints a one-line
message; genuine programming errors (arbitrary ValueError etc.) keep their
tracebacks.
"""


class FormatError(ValueError):
    pass


class ValidationStringency:
    """SAM-tools style record validation levels
    (Bam2Adam.scala:46-47 exposes samtools' STRICT/LENIENT/SILENT;
    the reference CLI defaults to LENIENT)."""
    STRICT = "strict"
    LENIENT = "lenient"
    SILENT = "silent"


def handle_malformed(stringency: str, message: str, cause=None) -> None:
    """Apply a stringency decision to one malformed input record: STRICT
    raises :class:`FormatError`, LENIENT warns on stderr and drops the
    record, SILENT drops it quietly.  An unrecognized level is a caller
    bug and raises — falling through to silent would invert the strictness
    the caller asked for."""
    if stringency == ValidationStringency.STRICT:
        raise FormatError(message) from cause
    if stringency == ValidationStringency.LENIENT:
        import sys
        print(f"warning: {message} (dropped)", file=sys.stderr)
    elif stringency != ValidationStringency.SILENT:
        raise ValueError(
            f"unknown validation stringency {stringency!r} "
            f"(want strict/lenient/silent)")
