"""The long-lived serve loop: warm once, serve many.

One :class:`ServeServer` owns one device-warm process.  Boot pays the
cold-start tolls exactly once (platform.warm — backend init, the
deferred compile-cache decision, a priming dispatch) and every job after
that rides the warm jit caches; because the server, not the client,
owns the chunk-size/ladder knobs, every tenant's jobs land on the one
canonical shape ladder and job 2+ of a command shape recompiles nothing
(the zero-recompile pin, tests/test_serve.py).

Per-tenant isolation, all riding existing machinery:

* the fault plane scopes to the running job's tenant
  (``faults.set_tenant``) — a plan rule carrying ``tenant`` fires only
  inside that tenant's execution;
* the malformed-record budget resets per job and the job's drop count
  lands in its result document, not on a neighbor;
* a job's typed failure (bad input, injected fault past the recovery
  ladder, anything else) writes ``failed/<job>.json`` and the loop
  serves on — one tenant's failure never touches another's bytes;
* obs: every job completion emits a ``tenant_job`` event and runs under
  a ``tenant:<tenant>:<job>`` trace span, so one sidecar/timeline
  splits cleanly by tenant.

Shared dispatches (serve/packed.py) degrade, never fail collectively: a
shared dispatch error re-runs each member solo (exact monoid — bytes
cannot change), recorded as ``serve_pack_degraded``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from .. import obs
from ..checkpoint import atomic_write
from ..errors import FormatError, malformed_count, reset_malformed
from ..resilience import faults
from ..resilience.faults import InjectedFault
from ..resilience.retry import backoff_delay
from . import jobspec, status as status_mod
from .admission import DEFAULT_PACK_SEGMENTS, decide_admission
from .overload import (AdmissionLimits, OverloadPolicy, OverloadTracker,
                       resolve_admission_limits, resolve_overload_policy,
                       rss_mb)
from .packed import SharedDispatchError, packed_flagstat

#: the per-tenant SLO shutdown report file name (single-host serve
#: writes it next to the spool dirs; the fleet scheduler reuses the
#: same helpers for its own)
SLO_REPORT_FILE = "serve_report.json"


def _pctl(values, q: float) -> float:
    """Nearest-rank percentile over a non-empty list (pure python — the
    report must not need a device library)."""
    vs = sorted(values)
    idx = max(int(-(-q * len(vs) // 100)) - 1, 0)
    return vs[min(idx, len(vs) - 1)]


def slo_observe(slo: dict, tenant: str, queue_s, service_s) -> None:
    """Fold one served job's latency split into the per-tenant SLO
    accumulator (plus the obs histograms, so worker sidecars carry the
    distribution even when the report is written elsewhere)."""
    rec = slo.setdefault(tenant, {"queue_s": [], "service_s": []})
    for key, v in (("queue_s", queue_s), ("service_s", service_s)):
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and v >= 0:
            rec.setdefault(key, []).append(float(v))
            obs.registry().histogram(
                f"serve_{key.replace('_s', '')}_seconds",
                tenant=tenant).observe(float(v))


#: the overload-outcome counters that join the per-tenant SLO report
#: (docs/ARCHITECTURE.md §6m): deadline_hit = a deadlined job served in
#: time, deadline_missed = cancelled queued past its deadline,
#: rejected = shed by quota or brownout with a typed ``rejected/`` doc
SLO_COUNT_KEYS = ("deadline_hit", "deadline_missed", "rejected")


def slo_count(slo: dict, tenant: str, key: str, n: int = 1) -> None:
    """Bump one per-tenant overload-outcome counter in the SLO
    accumulator (``key`` ∈ :data:`SLO_COUNT_KEYS`)."""
    rec = slo.setdefault(tenant, {"queue_s": [], "service_s": []})
    rec[key] = rec.get(key, 0) + n


def slo_summary(slo: dict) -> dict:
    """Per-tenant p50/p99 of queue-wait and service time — the gated
    tail numbers, not a claim — plus the overload-outcome counts
    (deadline hits/misses, typed rejections) when any occurred."""
    out = {}
    for tenant in sorted(slo):
        rec = slo[tenant]
        ten = {"jobs": max(len(rec.get("queue_s", ())),
                           len(rec.get("service_s", ())))}
        for key in ("queue_s", "service_s"):
            vs = rec.get(key) or []
            if vs:
                ten[key] = {"p50": round(_pctl(vs, 50), 6),
                            "p99": round(_pctl(vs, 99), 6)}
        for key in SLO_COUNT_KEYS:
            if rec.get(key):
                ten[key] = int(rec[key])
        out[tenant] = ten
    return out


def retire_deadline(spool: str, slo: dict, path: str, canon: dict,
                    wait_s: float, deadline_s: float) -> bool:
    """Retire one queued-past-deadline job with a typed
    ``DeadlineExceeded`` failure doc (never dispatched — a result
    nobody is waiting for must not occupy a warm worker).  One
    implementation for the single-host loop AND the fleet front door:
    the doc shape, event, counters and SLO accounting must never skew
    between them."""
    claimed = jobspec.claim_job(spool, path)
    if claimed is None:
        return False
    obs.registry().counter("deadline_missed",
                           tenant=canon["tenant"]).inc()
    obs.emit("deadline_missed", job_id=canon["job_id"],
             tenant=canon["tenant"], wait_s=round(wait_s, 3),
             deadline_s=round(deadline_s, 3))
    slo_count(slo, canon["tenant"], "deadline_missed")
    jobspec.write_result(
        spool, canon, ok=False,
        error=(f"cancelled: queued {wait_s:.3f}s past its "
               f"{deadline_s:.3f}s deadline"),
        error_type="DeadlineExceeded", queue_s=wait_s,
        running_path=claimed)
    return True


def retire_rejected(spool: str, slo: dict, path: str, canon: dict,
                    code: str, retry_after_s: float) -> bool:
    """Retire one over-quota/brownout-shed job with a typed, durable
    ``rejected/<job>.json`` (never a silent drop) — the
    :func:`retire_deadline` twin, shared for the same reason."""
    claimed = jobspec.claim_job(spool, path)
    if claimed is None:
        return False
    obs.registry().counter("admission_rejections",
                           tenant=canon["tenant"], code=code).inc()
    obs.emit("admission_rejected", job_id=canon["job_id"],
             tenant=canon["tenant"], code=code,
             retry_after_s=round(retry_after_s, 3))
    slo_count(slo, canon["tenant"], "rejected")
    jobspec.write_rejection(
        spool, canon, code=code, retry_after_s=retry_after_s,
        message=(f"admission rejected ({code}); retry after "
                 f"{retry_after_s}s"), queue_path=claimed)
    return True


def write_slo_report(path: str, slo: dict, *, hosts: int,
                     jobs: int, quiet: bool = False) -> Optional[str]:
    """The serve SLO report: per-tenant tail-latency percentiles,
    written atomically next to the spool — at shutdown AND as periodic
    checkpoints (``quiet=True``: the checkpoint path must not narrate
    every few seconds).  Telemetry discipline: a failed write degrades
    to one stderr line, never fails a finished serve run."""
    doc = {"hosts": int(hosts), "jobs": int(jobs),
           "tenants": slo_summary(slo)}
    try:
        atomic_write(path, json.dumps(doc, sort_keys=True))
    except OSError as e:
        import sys
        sys.stderr.write(f"serve: SLO report write failed: {e}\n")
        return None
    if quiet:
        return path
    from ..instrument import say
    for tenant, ten in doc["tenants"].items():
        q, s = ten.get("queue_s"), ten.get("service_s")
        if q and s:
            say(f"serve SLO [{tenant}]: queue p50 {q['p50']}s "
                f"p99 {q['p99']}s; service p50 {s['p50']}s "
                f"p99 {s['p99']}s over {ten['jobs']} job(s)")
    return path


class ServeServer:
    """One warm device, many tenants (docs/ARCHITECTURE.md §6i)."""

    def __init__(self, spool: str, *, chunk_rows: int = 1 << 22,
                 max_concurrent: int = 4, pack: bool = True,
                 pack_segments: int = DEFAULT_PACK_SEGMENTS,
                 poll_s: float = 0.05, io_procs: int = 1,
                 executor_opts: Optional[dict] = None,
                 slo_report: bool = True,
                 limits: Optional[AdmissionLimits] = None,
                 overload: Optional[OverloadPolicy] = None,
                 series: bool = True):
        self.spool = jobspec.ensure_spool(spool)
        self.chunk_rows = int(chunk_rows)
        self.max_concurrent = max(int(max_concurrent), 1)
        self.pack = bool(pack)
        self.pack_segments = max(int(pack_segments), 2)
        self.poll_s = float(poll_s)
        self.io_procs = int(io_procs)
        self.executor_opts = dict(executor_opts or {})
        self.jobs_served = 0
        #: per-tenant latency accumulators (queue-wait + service time);
        #: fleet workers set ``slo_report=False`` — the scheduler owns
        #: the fleet-wide report, built from the relayed result docs
        self.slo: Dict[str, dict] = {}
        self.slo_report = bool(slo_report)
        #: the overload plane (docs/ARCHITECTURE.md §6m): admission
        #: quotas + DRR fairness (decide_admission's overload keywords)
        #: and the brownout ladder (serve/overload.decide_overload)
        self.limits = limits if limits is not None \
            else resolve_admission_limits()
        self.overload = OverloadTracker(
            overload if overload is not None
            else resolve_overload_policy(
                max_concurrent=self.max_concurrent))
        #: parse-once queue scanner: round cost stays flat as the
        #: backlog deepens (jobspec.QueueCursor)
        self._cursor = jobspec.QueueCursor(self.spool)
        #: filename -> canonicalized spec (queue files are immutable,
        #: so canonicalization — like parsing — is paid once per job)
        self._canon_cache: Dict[str, dict] = {}
        self._poll_round = 0
        self._booted = False
        #: the live telemetry plane (docs/OBSERVABILITY.md): an
        #: obs/series sampler over SPOOL/series.jsonl plus a throttled
        #: atomic SPOOL/status.json every round and periodic SLO-report
        #: checkpoints — a SIGKILL'd server keeps what it measured
        self.series = bool(series)
        self._status_every = status_mod.status_interval_s()
        self._report_every = status_mod.report_interval_s()
        self._last_status: Optional[float] = None
        self._last_report: Optional[float] = None
        #: periodic spool retention GC (serve/retention.py): same
        #: throttle discipline as the status rewrite — a weeks-long
        #: server must not grow its spool without bound
        from .retention import gc_interval_s
        self._gc_every = gc_interval_s()
        self._last_gc: Optional[float] = None
        self._reported_jobs = 0
        self._last_backlog = 0
        self._tenant_backlog: Dict[str, int] = {}
        #: the paged layout's cross-round page pool (packed_flagstat's
        #: pool_holder): ONE resident device allocation for the serve
        #: lifetime — steady state means only new tenants' rows ever
        #: cross the link between dispatches (docs/ARCHITECTURE.md §6l)
        self._pool_holder: Dict[str, object] = {}
        #: the cross-round wire-chunk cache (serve/wirecache.py): one
        #: tenant input packs its flagstat projection once per serve
        #: lifetime however many jobs — packed ingest, degrade-to-solo
        #: re-runs, duplicate submissions — consume it; identity keys
        #: (size + mtime) invalidate rewritten inputs
        from .wirecache import WireChunkCache
        self._wire_cache = WireChunkCache()

    # -- boot ---------------------------------------------------------------

    def boot(self) -> dict:
        """Warm the backend + compile cache once, re-queue any jobs a
        crashed predecessor left under ``running/``, and publish the
        ``serving.json`` receipt (pid + warmup breakdown) clients can
        wait on."""
        from ..platform import warm

        if self._booted:
            return {}
        requeued = jobspec.requeue_running(self.spool)
        t0 = time.perf_counter()
        info = warm()
        info["warm_total_s"] = round(time.perf_counter() - t0, 6)
        info["requeued"] = requeued
        info["startup"] = obs.startup.snapshot()
        obs.emit("serve_boot", **{k: v for k, v in info.items()})
        atomic_write(os.path.join(self.spool, jobspec.SERVING_MARKER),
                     json.dumps({"pid": os.getpid(), **info},
                                sort_keys=True, default=str))
        self._booted = True
        if self.series and obs.series.active() is None:
            obs.series.start_series(
                os.path.join(self.spool, "series.jsonl"),
                source={"role": "serve"})
        return info

    # -- the loop -----------------------------------------------------------

    def run(self, *, max_jobs: Optional[int] = None,
            idle_timeout_s: Optional[float] = None) -> int:
        """Serve until ``max_jobs`` jobs completed, the stop sentinel
        appears, or the queue stays empty for ``idle_timeout_s``.
        Returns the number of jobs served this call."""
        self.boot()
        served_at_entry = self.jobs_served
        idle_since = time.monotonic()
        while True:
            if jobspec.stop_requested(self.spool):
                break
            n = self._round(
                None if max_jobs is None
                else max(max_jobs - (self.jobs_served - served_at_entry),
                         0))
            self._tick_status()
            if n:
                idle_since = time.monotonic()
            if max_jobs is not None and \
                    self.jobs_served - served_at_entry >= max_jobs:
                break
            if n == 0:
                if idle_timeout_s is not None and \
                        time.monotonic() - idle_since >= idle_timeout_s:
                    break
                # deterministic jitter (the retry-backoff helper at
                # exponent 0): many idle servers polling one shared
                # filesystem must not stat it in lockstep, and a
                # seeded delay stays replayable
                self._poll_round += 1
                time.sleep(backoff_delay(
                    f"{self.spool}|idle-poll", 1, self.poll_s,
                    self.poll_s, seed=self._poll_round))
        if self._status_every > 0:
            status_mod.write_status(self.spool, self._status_doc(),
                                    interval_s=self._status_every)
        if self.slo_report and self.jobs_served:
            path = write_slo_report(
                os.path.join(self.spool, SLO_REPORT_FILE), self.slo,
                hosts=1, jobs=self.jobs_served)
            if path:
                obs.emit("serve_report_checkpoint", path=path,
                         jobs=self.jobs_served, reason="final")
        return self.jobs_served - served_at_entry

    # -- live status --------------------------------------------------------

    def _status_doc(self) -> dict:
        """The durable live-state doc (serve/status.py owns the file
        discipline; docs/FLEET_SERVE.md tabulates the rows)."""
        from ..resilience.retry import breaker_snapshot

        tenants: Dict[str, dict] = {}
        for name, ten in slo_summary(self.slo).items():
            tenants[name] = dict(ten)
        # fresh queue-dir count, not the round snapshot: the final
        # exit-time doc must show the drained queue, not the backlog
        # the last round admitted FROM (per-tenant depth stays the
        # round snapshot — attribution needs the spec bodies)
        try:
            backlog = sum(
                1 for n in os.listdir(os.path.join(self.spool,
                                                   jobspec.QUEUE))
                if n.endswith(".json"))
        except OSError:
            backlog = self._last_backlog
        for name, depth in self._tenant_backlog.items():
            tenants.setdefault(name, {})["queued"] = \
                depth if backlog else 0
        for ten in tenants.values():
            ten.setdefault("queued", 0)
        return {"mode": "solo", "warm": self._booted,
                "jobs_served": self.jobs_served,
                "backlog": backlog,
                "max_concurrent": self.max_concurrent,
                "overload": status_mod.overload_doc(self.overload),
                "breakers": breaker_snapshot(),
                "tenants": tenants, "rss_mb": rss_mb()}

    def _tick_status(self) -> None:
        """Once per loop iteration: throttle the status.json rewrite
        and the periodic SLO-report checkpoint (the fix for the
        exit-only report — a kill now loses at most one interval)."""
        now = time.monotonic()
        if self._status_every > 0 and (
                self._last_status is None
                or now - self._last_status >= self._status_every):
            self._last_status = now
            status_mod.write_status(self.spool, self._status_doc(),
                                    interval_s=self._status_every)
        if self.slo_report and self._report_every > 0 and (
                self._last_report is None
                or now - self._last_report >= self._report_every):
            self._last_report = now
            if self.jobs_served != self._reported_jobs:
                self._reported_jobs = self.jobs_served
                path = write_slo_report(
                    os.path.join(self.spool, SLO_REPORT_FILE),
                    self.slo, hosts=1, jobs=self.jobs_served,
                    quiet=True)
                if path:
                    obs.emit("serve_report_checkpoint", path=path,
                             jobs=self.jobs_served, reason="periodic")
        if self._gc_every > 0 and (
                self._last_gc is None
                or now - self._last_gc >= self._gc_every):
            self._last_gc = now
            from .retention import sweep
            try:
                sweep(self.spool)
            except OSError:
                pass  # a failed sweep never takes the serve loop down

    def _snapshot_queue(self) -> tuple:
        """Admission-ready queue snapshot: ``(descriptors, by_id)``
        over the shared cursor-backed canonical snapshot
        (jobspec.snapshot_canon — parse + canonicalization paid once
        per immutable queue file, bad specs failed in place), with the
        overload-era descriptor extras riding only-when-set so a
        vanilla queue decides (and digests) exactly as before."""
        queued = []
        by_id: Dict[str, tuple] = {}
        now = time.time()
        for seq, path, canon in jobspec.snapshot_canon(
                self.spool, self._cursor, self._canon_cache):
            desc = {"job_id": canon["job_id"],
                    "tenant": canon["tenant"],
                    "command": canon["command"], "seq": seq}
            if canon.get("priority") not in (None, "normal"):
                desc["priority"] = canon["priority"]
            if canon.get("deadline_s") is not None:
                desc["deadline_s"] = canon["deadline_s"]
                sub_at = canon.get("submitted_at")
                desc["wait_s"] = max(now - float(sub_at), 0.0) \
                    if isinstance(sub_at, (int, float)) and \
                    not isinstance(sub_at, bool) else 0.0
            queued.append(desc)
            by_id[canon["job_id"]] = (path, canon)
        return queued, by_id

    def _cancel_deadline(self, path: str, canon: dict, wait_s: float,
                         deadline_s: float) -> bool:
        if retire_deadline(self.spool, self.slo, path, canon, wait_s,
                           deadline_s):
            self.jobs_served += 1
            return True
        return False

    def _reject(self, path: str, canon: dict, code: str,
                retry_after_s: float) -> bool:
        if retire_rejected(self.spool, self.slo, path, canon, code,
                           retry_after_s):
            self.jobs_served += 1
            return True
        return False

    def _round(self, budget: Optional[int] = None) -> int:
        """One admission round: snapshot the queue, walk the brownout
        ladder, take the pure admission decision (quotas, deadlines,
        tenant fairness), claim and execute.  Returns jobs completed —
        typed rejections and deadline cancellations included (each
        leaves a durable doc a client is waiting on)."""
        queued, by_id = self._snapshot_queue()
        # live signals for the series sampler / status doc: gauges are
        # max-merged across a fleet, so the fold reports the deepest
        # worker backlog (the pressure signal, not the sum)
        self._last_backlog = len(queued)
        tb: Dict[str, int] = {}
        for d in queued:
            tb[d["tenant"]] = tb.get(d["tenant"], 0) + 1
        self._tenant_backlog = tb
        obs.registry().gauge("serve_backlog").set(len(queued))
        if self.overload.engaged:
            self.overload.update(len(queued))
        if not queued:
            return 0
        max_c = self.max_concurrent if budget is None \
            else min(self.max_concurrent, max(budget, 0))
        level = self.overload.level
        plan = decide_admission(
            queued=queued, running=0, max_concurrent=max_c,
            pack=self.pack and level < 1,
            pack_segments=self.pack_segments,
            fair=self.limits.fair, backlog_cap=self.limits.backlog_cap,
            tenant_quota=self.limits.tenant_quota,
            tenant_slots=self.limits.tenant_slots,
            overload_level=level)
        done = 0
        if not plan["admit"] and not plan.get("cancel") \
                and not plan.get("reject"):
            return 0
        obs.registry().counter("serve_rounds").inc()
        extra = {}
        if plan.get("cancel"):
            extra["cancel"] = plan["cancel"]
        if plan.get("reject"):
            extra["reject"] = plan["reject"]
        obs.emit("admission_selected", admit=plan["admit"],
                 pack_groups=plan["pack_groups"], reason=plan["reason"],
                 inputs=plan["inputs"],
                 input_digest=plan["input_digest"], **extra)
        for c in plan.get("cancel") or ():
            path, canon = by_id[c["job_id"]]
            if self._cancel_deadline(path, canon, c["wait_s"],
                                     c["deadline_s"]):
                done += 1
        for r in plan.get("reject") or ():
            path, canon = by_id[r["job_id"]]
            if self._reject(path, canon, r["code"],
                            r["retry_after_s"]):
                done += 1
        # claim everything admitted up front (a submitter watching the
        # queue sees admission as one atomic batch)
        claimed: Dict[str, tuple] = {}
        for job_id in plan["admit"]:
            path, canon = by_id[job_id]
            running = jobspec.claim_job(self.spool, path)
            if running is not None:
                claimed[job_id] = (running, canon)
        packed_ids = {j for g in plan["pack_groups"] for j in g}
        # the in-flight gauge brackets execution so the sampler thread
        # catches mid-dispatch rows; the loop itself is synchronous
        obs.registry().gauge("serve_inflight").set(len(claimed))
        try:
            for group in plan["pack_groups"]:
                members = [(claimed[j][0], claimed[j][1])
                           for j in group if j in claimed]
                done += self._run_packed(members)
            for job_id in plan["admit"]:
                if job_id in packed_ids or job_id not in claimed:
                    continue
                running, canon = claimed[job_id]
                self._run_solo(running, canon)
                done += 1
        finally:
            obs.registry().gauge("serve_inflight").set(0)
        return done

    # -- execution ----------------------------------------------------------

    def _execute(self, spec: dict):
        """Run one job's command body; returns its result payload."""
        if spec["command"] == "flagstat":
            from ..ops.flagstat import format_report
            from ..parallel.pipeline import streaming_flagstat

            failed, passed = streaming_flagstat(
                spec["input"], chunk_rows=self.chunk_rows,
                io_procs=int(spec["args"].get("io_procs",
                                              self.io_procs)),
                executor_opts=self.executor_opts,
                wire_cache=self._wire_cache)
            return {"report": format_report(failed, passed)}
        if spec["command"] == "flagstat_range":
            # the fleet scheduler's shard sub-job: one unit range of a
            # big input; the exact counter block (not a formatted
            # report) rides the result doc back for the parent merge
            from .scheduler import range_flagstat_counts

            a = spec["args"]
            counts, rows = range_flagstat_counts(
                spec["input"], unit_lo=int(a["unit_lo"]),
                unit_hi=int(a["unit_hi"]),
                unit_rows=int(a["unit_rows"]),
                io_procs=int(a.get("io_procs", self.io_procs)))
            return {"counts": counts.tolist(), "rows": rows}
        if spec["command"] == "call":
            # the variant-calling workload: same executor shape knobs
            # as every co-tenant job (server-owned), plan knobs from
            # the spec; the result doc carries the VCF's sha256 — the
            # identity handle served-mode tests compare against solo
            from ..call.pipeline import streaming_call

            a = spec["args"]
            kw = {}
            if a.get("sample"):
                kw["default_sample"] = str(a["sample"])
            res = streaming_call(
                spec["input"], spec["output"],
                chunk_rows=self.chunk_rows,
                io_procs=int(a.get("io_procs", self.io_procs)),
                stripe_span=a.get("stripe_span"),
                min_depth=a.get("min_depth"),
                min_alt=a.get("min_alt"),
                executor_opts=self.executor_opts, **kw)
            return {k: res[k] for k in
                    ("reads", "admitted", "stripes", "calls",
                     "variants", "genotypes", "samples", "vcf_sha256")}
        return {"rows": self._execute_transform(spec)}

    def _execute_transform(self, spec: dict) -> int:
        from ..models.snptable import SnpTable
        from ..parallel.pipeline import streaming_transform

        args = spec["args"]
        snp_path = args.get("dbsnp_sites")
        snp = SnpTable.from_vcf(snp_path) if snp_path else None
        return streaming_transform(
            spec["input"], spec["output"],
            markdup=bool(args.get("markdup")),
            bqsr=bool(args.get("bqsr")), snp_table=snp,
            realign=bool(args.get("realign")),
            sort=bool(args.get("sort")),
            chunk_rows=self.chunk_rows,
            io_threads=int(args.get("io_threads", 1)),
            io_procs=int(args.get("io_procs", self.io_procs)),
            executor_opts=self.executor_opts)

    def _queue_wait(self, spec: dict) -> Optional[float]:
        """Submit→start wait, when the spec carries its submit stamp
        (jobspec.submit_job writes it; hand-built specs may not)."""
        sub_at = spec.get("submitted_at")
        if isinstance(sub_at, (int, float)) and \
                not isinstance(sub_at, bool):
            return max(time.time() - float(sub_at), 0.0)
        return None

    def _finish(self, running: str, spec: dict, *, ok: bool,
                result=None, error: Optional[BaseException] = None,
                seconds: float = 0.0, compiles: float = 0.0,
                rows=None, dropped: int = 0,
                queue_s: Optional[float] = None) -> None:
        """Publish one job's outcome: durable result doc + the
        ``tenant_job`` event (the per-tenant obs label every sidecar
        consumer splits on).  ``queue_s`` (submit→start wait) and
        ``service_s`` (== ``seconds``, the execution wall) make the
        scheduler's tails a recorded number per tenant."""
        fields = dict(job_id=spec["job_id"], tenant=spec["tenant"],
                      command=spec["command"],
                      status="ok" if ok else "failed",
                      seconds=round(seconds, 6), compiles=int(compiles),
                      service_s=round(seconds, 6))
        if queue_s is not None:
            fields["queue_s"] = round(queue_s, 6)
        if rows is not None:
            fields["rows"] = int(rows)
        if dropped:
            fields["malformed_dropped"] = int(dropped)
        if error is not None:
            fields["error_type"] = type(error).__name__
        obs.emit("tenant_job", **fields)
        obs.registry().counter(
            "serve_jobs", tenant=spec["tenant"],
            status=fields["status"]).inc()
        slo_observe(self.slo, spec["tenant"], queue_s, seconds)
        # the ladder's queue-p99 signal reads the same waits the SLO
        # report does; a served deadlined job is a deadline HIT
        self.overload.observe_wait(queue_s)
        if ok and spec.get("deadline_s") is not None:
            slo_count(self.slo, spec["tenant"], "deadline_hit")
        res = dict(result or {})
        if dropped:
            res["malformed_dropped"] = int(dropped)
        jobspec.write_result(
            self.spool, spec, ok=ok, result=res,
            error=None if error is None else str(error),
            error_type=None if error is None else type(error).__name__,
            seconds=seconds, queue_s=queue_s, service_s=seconds,
            running_path=running)
        self.jobs_served += 1

    def _run_solo(self, running: str, spec: dict) -> None:
        t0 = time.perf_counter()
        queue_s = self._queue_wait(spec)
        compiles0 = obs.registry().counter("compile_count").value
        reset_malformed()
        faults.set_tenant(spec["tenant"])
        # the kill-attribution boundary: if this process dies now, the
        # fleet scheduler charges THIS job, not the whole claimed batch
        jobspec.set_active(self.spool, [spec["job_id"]])
        try:
            with obs.trace.span(
                    f"tenant:{spec['tenant']}:{spec['job_id']}",
                    cat="serve"):
                result = self._execute(spec)
            dropped = malformed_count()   # before the finally resets it
        except (FileNotFoundError, IsADirectoryError, FormatError,
                InjectedFault, ValueError, RuntimeError, OSError) as e:
            # typed, isolated failure: THIS job fails, the loop lives
            self._finish(running, spec, ok=False, error=e,
                         seconds=time.perf_counter() - t0,
                         compiles=obs.registry().counter(
                             "compile_count").value - compiles0,
                         dropped=malformed_count(), queue_s=queue_s)
            return
        finally:
            faults.set_tenant(None)
            reset_malformed()
            jobspec.set_active(self.spool, [])
        self._finish(
            running, spec, ok=True, result=result,
            seconds=time.perf_counter() - t0,
            compiles=obs.registry().counter(
                "compile_count").value - compiles0,
            rows=result.get("rows"), dropped=dropped, queue_s=queue_s)

    def _run_packed(self, members: List[tuple]) -> int:
        """One shared-dispatch group.  On a shared failure, degrade to
        solo re-runs (exact monoid: identical bytes) instead of failing
        every rider."""
        if not members:
            return 0
        specs = [spec for _, spec in members]
        queue_waits = {spec["job_id"]: self._queue_wait(spec)
                       for _, spec in members}
        t0 = time.perf_counter()
        compiles0 = obs.registry().counter("compile_count").value
        reset_malformed()
        # every rider genuinely fate-shares the packed dispatches, so a
        # death here is chargeable to the whole group
        jobspec.set_active(self.spool, [s["job_id"] for s in specs])
        try:
            results, stats = packed_flagstat(
                specs, chunk_rows=self.chunk_rows,
                pack_segments=self.pack_segments,
                executor_opts=self.executor_opts,
                pool_holder=self._pool_holder,
                wire_cache=self._wire_cache)
        except (SharedDispatchError, FileNotFoundError,
                IsADirectoryError, FormatError, InjectedFault,
                ValueError, RuntimeError, OSError) as e:
            obs.emit("serve_pack_degraded",
                     jobs=[s["job_id"] for s in specs],
                     error=f"{type(e).__name__}: {e}"[:200])
            obs.registry().counter("serve_pack_degraded").inc()
            for running, spec in members:
                self._run_solo(running, spec)
            return len(members)
        finally:
            reset_malformed()
            jobspec.set_active(self.spool, [])
        seconds = time.perf_counter() - t0
        compiles = obs.registry().counter(
            "compile_count").value - compiles0
        from ..ops.flagstat import format_report

        for i, (running, spec) in enumerate(members):
            failed, passed = results[spec["job_id"]]
            st = stats.get(spec["job_id"], {})
            # the dispatches were genuinely shared, so per-job wall is
            # the group wall and the compile count lands once (the
            # group head); rows and malformed drops are each tenant's
            # OWN (ingest is sequential per job inside the packer)
            self._finish(running, spec, ok=True,
                         result={"report": format_report(failed,
                                                         passed),
                                 "packed": len(members)},
                         seconds=seconds,
                         compiles=compiles if i == 0 else 0,
                         rows=st.get("rows"),
                         dropped=int(st.get("dropped", 0)),
                         queue_s=queue_waits.get(spec["job_id"]))
        return len(members)
