"""Spool retention GC — bounded disk for a weeks-long serve process.

The spool is an append-mostly ledger: every served job leaves a result
doc under ``done/``/``failed/``/``rejected/``, every fleet run retires
claim tables (``fleet/claims/unit*.json``), ring files
(``fleet/ring/*.ring``) and rotated per-incarnation series sidecars
(``fleet/logs/*.series.jsonl``).  None of that is ever read again once
the SLO report has folded it in — but nothing deleted it either, so a
long-lived server grows without bound.  This module is the collector:

* :func:`decide_retention` — PURE.  Given candidate ``(name, kind,
  age_s)`` rows it returns which to collect, under two floors that make
  the collector safe by construction: a per-kind **count floor** (the
  ``keep_per_kind`` newest of each kind always survive — post-mortems
  keep something to look at) and an **age floor** (nothing younger than
  ``min_age_s`` goes).  Result docs carry two extra guards: a doc is
  never collected unless it is OLDER than the last ``serve_report.json``
  checkpoint (the report provably folded it in) and never while its job
  id is still unacked (queued or running — a requeue may yet rewrite
  it).  Recorded in full (``inputs`` + ``input_digest``) by the
  ``spool_gc`` event; tools/check_executor.py replays it.

* :func:`scan_spool` — enumerate candidates + the checkpoint age + the
  unacked id set from a live spool.

* :func:`sweep` — scan, decide, unlink, emit.  Wired behind
  ``adam-tpu gc SPOOL`` (cli/commands.py) and the periodic serve-loop
  sweeps (serve/server.py, serve/scheduler.py — throttled like the
  status rewrite, ``ADAM_TPU_SERVE_GC_S``).

Deleting is the easy half; the floors are the contract.  A crashed
sweep is harmless: every artifact is independently deletable and the
next sweep re-derives the same decision from what is left.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import obs

#: sweep throttle for the periodic serve-loop GC (seconds; 0 disables)
GC_INTERVAL_ENV = "ADAM_TPU_SERVE_GC_S"
DEFAULT_GC_INTERVAL_S = 600.0
#: age floor: nothing younger than this is ever collected
GC_MIN_AGE_ENV = "ADAM_TPU_SERVE_GC_MIN_AGE_S"
DEFAULT_MIN_AGE_S = 3600.0
#: count floor: the N newest of each kind always survive
GC_KEEP_ENV = "ADAM_TPU_SERVE_GC_KEEP"
DEFAULT_KEEP_PER_KIND = 64

#: candidate kinds, in scan order.  ``result`` rows get the checkpoint
#: + unacked guards; the fleet debris kinds only the two floors.
KINDS = ("result", "claim", "ring", "series")


def _digest(inputs: dict) -> str:
    import hashlib
    return hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]


def gc_interval_s() -> float:
    try:
        return float(os.environ.get(GC_INTERVAL_ENV,
                                    DEFAULT_GC_INTERVAL_S))
    except ValueError:
        return DEFAULT_GC_INTERVAL_S


def _job_id(name: str) -> str:
    """``<seq>-<id>.json`` -> ``<id>`` (jobspec result-doc naming)."""
    base = name.rsplit("/", 1)[-1]
    if base.endswith(".json"):
        base = base[:-5]
    _, _, jid = base.partition("-")
    return jid or base


def decide_retention(*, candidates: Sequence[Sequence],
                     min_age_s: float, keep_per_kind: int,
                     checkpoint_age_s: Optional[float],
                     unacked: Sequence[str]) -> dict:
    """Which spool artifacts a sweep may unlink — PURE.

    ``candidates``: ``[name, kind, age_s]`` rows (kind ∈
    :data:`KINDS`; ``age_s`` seconds since mtime, caller-rounded).
    ``checkpoint_age_s``: age of the last ``serve_report.json``
    checkpoint, or None when no report exists yet (then NO result doc
    is collectable — nothing proves the report folded it in).
    ``unacked``: job ids still queued or running.

    Floors, in order: the ``keep_per_kind`` newest of each kind are
    kept (count floor), anything with ``age_s <= min_age_s`` is kept
    (age floor), and a ``result`` row additionally needs
    ``age_s > checkpoint_age_s`` (older than the last report — the
    checkpoint guard) and its job id absent from ``unacked``.
    """
    canon = sorted((str(n), str(k), float(a)) for n, k, a in candidates)
    inputs = dict(candidates=[list(c) for c in canon],
                  min_age_s=float(min_age_s),
                  keep_per_kind=int(keep_per_kind),
                  checkpoint_age_s=(None if checkpoint_age_s is None
                                    else float(checkpoint_age_s)),
                  unacked=sorted(str(u) for u in unacked))
    unacked_set = set(inputs["unacked"])
    # count floor: rank each kind newest-first (smallest age first;
    # name breaks ties so the decision is total)
    protected: Set[str] = set()
    by_kind: Dict[str, List[Tuple[float, str]]] = {}
    for name, kind, age in canon:
        by_kind.setdefault(kind, []).append((age, name))
    for rows in by_kind.values():
        rows.sort()
        protected.update(n for _, n in rows[:inputs["keep_per_kind"]])
    collect, kept = [], []
    for name, kind, age in canon:
        keep_why = None
        if name in protected:
            keep_why = "count-floor"
        elif age <= inputs["min_age_s"]:
            keep_why = "age-floor"
        elif kind == "result":
            if inputs["checkpoint_age_s"] is None:
                keep_why = "no-checkpoint"
            elif age <= inputs["checkpoint_age_s"]:
                keep_why = "newer-than-checkpoint"
            elif _job_id(name) in unacked_set:
                keep_why = "unacked"
        if keep_why is None:
            collect.append(name)
        else:
            kept.append([name, keep_why])
    reason = (f"collect-{len(collect)}" if collect else "nothing-due")
    return dict(collect=collect, kept=kept, reason=reason,
                inputs=inputs, input_digest=_digest(inputs))


def scan_spool(spool: str, *, now: Optional[float] = None) -> dict:
    """Enumerate GC candidates + guards from a live spool.

    Returns ``{"candidates": [[name, kind, age_s], ...],
    "checkpoint_age_s": float|None, "unacked": [id, ...]}`` with names
    spool-relative (the sweep joins them back).  Rows that vanish
    mid-scan are simply skipped — the spool is live.
    """
    from . import jobspec
    from .server import SLO_REPORT_FILE

    now = time.time() if now is None else float(now)

    def _age(path: str) -> Optional[float]:
        try:
            return round(max(now - os.path.getmtime(path), 0.0), 3)
        except OSError:
            return None

    cands: List[List] = []

    def _add(path: str, kind: str) -> None:
        age = _age(path)
        if age is not None:
            cands.append([os.path.relpath(path, spool), kind, age])

    for sub in (jobspec.DONE, jobspec.FAILED, jobspec.REJECTED):
        for p in _glob.glob(os.path.join(spool, sub, "*.json")):
            _add(p, "result")
    fleet = os.path.join(spool, "fleet")
    for p in _glob.glob(os.path.join(fleet, "claims", "unit*.json")):
        _add(p, "claim")
    for p in _glob.glob(os.path.join(fleet, "ring", "*.ring")):
        _add(p, "ring")
    for p in _glob.glob(os.path.join(fleet, "logs", "*.series.jsonl")):
        _add(p, "series")
    # a batch fleet spool (no serve dirs) keeps the same debris kinds
    # directly at its root — the CLI may point ``gc`` at either layout
    if not os.path.isdir(fleet):
        for p in _glob.glob(os.path.join(spool, "claims",
                                         "unit*.json")):
            _add(p, "claim")
        for p in _glob.glob(os.path.join(spool, "ring", "*.ring")):
            _add(p, "ring")
        for p in _glob.glob(os.path.join(spool, "logs",
                                         "*.series.jsonl")):
            _add(p, "series")

    checkpoint_age = _age(os.path.join(spool, SLO_REPORT_FILE))
    unacked: Set[str] = set()
    for sub in (jobspec.QUEUE, jobspec.RUNNING):
        for p in _glob.glob(os.path.join(spool, sub, "*.json")):
            unacked.add(_job_id(os.path.basename(p)))
    return dict(candidates=cands, checkpoint_age_s=checkpoint_age,
                unacked=sorted(unacked))


def sweep(spool: str, *, min_age_s: Optional[float] = None,
          keep_per_kind: Optional[int] = None,
          dry_run: bool = False,
          now: Optional[float] = None) -> dict:
    """One GC pass: scan, decide, unlink, emit ``spool_gc``.

    Returns the decision dict plus ``removed`` (paths actually
    unlinked — under ``dry_run`` always empty).  The event + the
    ``spool_gc_removed`` counter fire even for an empty collection so
    a quiet sweep is still visible in the ledger replay.
    """
    if min_age_s is None:
        try:
            min_age_s = float(os.environ.get(GC_MIN_AGE_ENV,
                                             DEFAULT_MIN_AGE_S))
        except ValueError:
            min_age_s = DEFAULT_MIN_AGE_S
    if keep_per_kind is None:
        try:
            keep_per_kind = int(os.environ.get(GC_KEEP_ENV,
                                               DEFAULT_KEEP_PER_KIND))
        except ValueError:
            keep_per_kind = DEFAULT_KEEP_PER_KIND
    scan = scan_spool(spool, now=now)
    d = decide_retention(candidates=scan["candidates"],
                         min_age_s=min_age_s,
                         keep_per_kind=keep_per_kind,
                         checkpoint_age_s=scan["checkpoint_age_s"],
                         unacked=scan["unacked"])
    removed: List[str] = []
    if not dry_run:
        for rel in d["collect"]:
            try:
                os.unlink(os.path.join(spool, rel))
                removed.append(rel)
            except OSError:
                pass  # vanished mid-sweep — the spool is live
    obs.emit("spool_gc", spool=spool, collect=len(d["collect"]),
             removed=len(removed), kept=len(d["kept"]),
             dry_run=bool(dry_run), reason=d["reason"],
             inputs=d["inputs"], input_digest=d["input_digest"])
    obs.registry().counter("spool_gc_removed").inc(len(removed))
    d["removed"] = removed
    return d
