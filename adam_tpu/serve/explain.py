"""Per-job causal timeline: join every durable artifact one job touched.

The serve plane records its decisions piecemeal — the result doc says
*what* happened, the event sidecars say *why* (``admission_selected`` /
``placement_selected`` / ``job_requeued`` carry their pure deciders'
full recorded inputs), the series says what the system looked like at
the time, and the trace says where the wall went.  This module is the
offline join: :func:`explain_job` reconstructs one job's causal
timeline — submitted → queued behind N jobs of which tenants →
admission/placement with recorded inputs → retries / degrades /
requeues / steals → rung and breaker context at each step → finish —
from the durable artifacts ALONE, so it works identically on a live
fleet, a crashed one, or a spool copied off a shared filesystem.  The
offline twin of the replay validators (tools/check_executor.py replays
the decisions; ``explain`` narrates them).

Attribution is honest about its certainty:

* **job events** (``admission_selected``, ``placement_selected``,
  ``job_requeued``, ``tenant_job``, ``deadline_missed``,
  ``admission_rejected``, the ``tenant:<t>:<job>`` trace span) name the
  job — exact;
* **window events** (``retry_attempt``, ``degraded_dispatch``,
  ``fault_injected`` carry a site, not a job) attach when they fall
  inside the job's execution window *in the same sidecar*, tagged
  ``attributed="window"`` — the honest ceiling for site-scoped events;
* **context rows** (``overload_state``, ``breaker_state``, series
  samples) describe the plane, not the job — tagged ``"context"``.

Event times are wall-anchored through each sidecar's manifest (its
``time`` stamp minus its relative ``t``), the same trick the trace
plane uses, so rows from different processes land on one timeline.
``adam-tpu explain SPOOL JOB`` and ``tools/explain_run.py`` are the
entrypoints; docs/OBSERVABILITY.md has a worked example.
"""

from __future__ import annotations

import datetime
import glob as _glob
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import jobspec

#: events that name their job directly (exact attribution)
JOB_EVENTS = ("admission_selected", "placement_selected", "job_requeued",
              "tenant_job", "deadline_missed", "admission_rejected",
              "serve_pack_degraded")
#: site-scoped events attributed by execution window (best effort)
WINDOW_EVENTS = ("retry_attempt", "degraded_dispatch", "fault_injected")
#: plane-state events shown as context around the job's window
CONTEXT_EVENTS = ("overload_state", "breaker_state")

#: slack around the job window for window/context attribution — event
#: stamps and the derived submit time round independently
WINDOW_SLOP_S = 0.25


# ---------------------------------------------------------------------------
# artifact readers (every one tolerates missing/torn files)
# ---------------------------------------------------------------------------

def _read_jsonl(path: str) -> List[dict]:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return []
    out = []
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            doc = json.loads(ln)
        except ValueError:
            continue            # torn tail of a crashed writer
        if isinstance(doc, dict):
            out.append(doc)
    return out


def _wall_anchor(rows: Sequence[dict]) -> Optional[float]:
    """Wall time of a sidecar's t=0, from its manifest (``time`` is the
    wall stamp at manifest emit, ``t`` the relative offset)."""
    for r in rows:
        if r.get("event") != "manifest" or not isinstance(
                r.get("time"), str):
            continue
        t_rel = r.get("t") if isinstance(r.get("t"), (int, float)) \
            else 0.0
        for fmt in ("%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%dT%H:%M:%S"):
            try:
                dt = datetime.datetime.strptime(r["time"], fmt)
            except ValueError:
                continue
            if dt.tzinfo is None:
                return time.mktime(dt.timetuple()) - t_rel
            return dt.timestamp() - t_rel
        return None
    return None


def discover_artifacts(spool: str) -> Dict[str, List[str]]:
    """Every joinable durable artifact under a spool: event sidecars
    (published AND in-flight ``.tmp`` — a live or crashed writer's
    lines are exactly the interesting ones), series files (front spool
    + fleet worker sub-spools + shard logs), and trace docs."""
    fleet_logs = os.path.join(spool, "fleet", "logs")
    events: List[str] = []
    for pat in ("*.jsonl", "*.jsonl.tmp"):
        events.extend(_glob.glob(os.path.join(spool, pat)))
        events.extend(_glob.glob(os.path.join(fleet_logs, pat)))
    events = [p for p in events
              if not os.path.basename(p).startswith("series.jsonl")
              and not p.endswith(".series.jsonl")
              and not p.endswith(".series.jsonl.tmp")]
    series = _glob.glob(os.path.join(spool, "series.jsonl"))
    series.extend(_glob.glob(os.path.join(
        spool, "fleet", "workers", "*", "spool", "series.jsonl")))
    series.extend(_glob.glob(os.path.join(fleet_logs,
                                          "*.series.jsonl")))
    traces = _glob.glob(os.path.join(spool, "*.trace.json"))
    traces.extend(_glob.glob(os.path.join(fleet_logs, "*.trace.json")))
    return {"events": sorted(set(events)), "series": sorted(set(series)),
            "traces": sorted(set(traces))}


# ---------------------------------------------------------------------------
# per-event narration
# ---------------------------------------------------------------------------

def _tenant_counts(descs: Sequence[dict]) -> str:
    by: Dict[str, int] = {}
    for d in descs:
        t = str(d.get("tenant", "?"))
        by[t] = by.get(t, 0) + 1
    return ", ".join(f"{t}x{n}" for t, n in sorted(by.items()))


def _narrate_admission(ev: dict, job_id: str) -> Optional[Tuple[str,
                                                                str]]:
    """(kind, summary) when this admission round touched the job."""
    queued = (ev.get("inputs") or {}).get("queued") or []
    mine = next((q for q in queued if q.get("job_id") == job_id), None)
    for c in ev.get("cancel") or ():
        if c.get("job_id") == job_id:
            return ("deadline-cancel",
                    f"admission cancelled it: queued "
                    f"{c.get('wait_s')}s past its "
                    f"{c.get('deadline_s')}s deadline "
                    f"[{ev.get('reason')}]")
    for r in ev.get("reject") or ():
        if r.get("job_id") == job_id:
            return ("admission-reject",
                    f"admission rejected it [{r.get('code')}], retry "
                    f"after {r.get('retry_after_s')}s "
                    f"[{ev.get('reason')}]")
    if job_id in (ev.get("admit") or ()):
        ahead = [q for q in queued
                 if mine is not None and q.get("seq", 0)
                 < mine.get("seq", 0)]
        packed = next((g for g in ev.get("pack_groups") or ()
                       if job_id in g), None)
        s = f"admitted behind {len(ahead)} queued job(s)"
        if ahead:
            s += f" ({_tenant_counts(ahead)})"
        if packed:
            s += f"; packed with {len(packed) - 1} other(s)"
        return ("admission", s + f" [{ev.get('reason')}]")
    if mine is not None:
        return ("admission-skip",
                f"seen queued but not admitted this round "
                f"[{ev.get('reason')}]")
    return None


def _narrate_job_event(ev: dict, job_id: str) -> Optional[Tuple[str,
                                                                str]]:
    kind = ev.get("event")
    if kind == "admission_selected":
        return _narrate_admission(ev, job_id)
    if kind == "placement_selected":
        for jid, w in ev.get("place") or ():
            if jid == job_id:
                return ("placement",
                        f"placed on worker w{w} [{ev.get('reason')}]")
        return None
    if kind == "job_requeued":
        if ev.get("cause") == "steal":
            for jid, src, dst in ev.get("moves") or ():
                if jid == job_id:
                    return ("steal",
                            f"stolen from w{src} to idle w{dst} "
                            f"[{ev.get('reason')}]")
            return None
        if ev.get("job_id") != job_id:
            return None
        return ("requeue",
                f"{ev.get('action')} after {ev.get('cause')} at "
                f"w{ev.get('worker', '?')} [{ev.get('reason')}]")
    if kind == "tenant_job" and ev.get("job_id") == job_id:
        s = (f"finished {ev.get('status')} in "
             f"{ev.get('service_s')}s service")
        if ev.get("queue_s") is not None:
            s += f" after {ev.get('queue_s')}s queued"
        if ev.get("compiles"):
            s += f" ({ev.get('compiles')} compile(s))"
        if ev.get("error_type"):
            s += f" [{ev['error_type']}]"
        return ("finish", s)
    if kind == "deadline_missed" and ev.get("job_id") == job_id:
        return ("deadline-cancel",
                f"cancelled: queued {ev.get('wait_s')}s past its "
                f"{ev.get('deadline_s')}s deadline")
    if kind == "admission_rejected" and ev.get("job_id") == job_id:
        return ("admission-reject",
                f"rejected [{ev.get('code')}], retry after "
                f"{ev.get('retry_after_s')}s")
    if kind == "serve_pack_degraded" and job_id in (ev.get("jobs")
                                                    or ()):
        return ("pack-degrade",
                f"shared dispatch failed ({ev.get('error')}); re-run "
                "solo")
    return None


def _narrate_window(ev: dict) -> Tuple[str, str]:
    kind = ev.get("event")
    if kind == "retry_attempt":
        return ("retry",
                f"retry attempt {ev.get('attempt')} at "
                f"{ev.get('site')} ({ev.get('error_kind')}) -> "
                f"{ev.get('action')} [{ev.get('reason')}]")
    if kind == "degraded_dispatch":
        return ("degrade",
                f"degraded dispatch at {ev.get('site')} after attempt "
                f"{ev.get('attempt')} ({ev.get('error_kind')})")
    return ("fault",
            f"fault injected at {ev.get('site')} occurrence "
            f"{ev.get('occurrence')}: {ev.get('fault')}")


def _narrate_context(ev: dict) -> Tuple[str, str]:
    if ev.get("event") == "overload_state":
        return ("rung",
                f"overload rung -> {ev.get('state')} "
                f"(level {ev.get('prev_level')} -> {ev.get('level')}) "
                f"[{ev.get('reason')}]")
    return ("breaker",
            f"breaker {ev.get('site')} -> {ev.get('state')} "
            f"({ev.get('failures')} recent failure(s)) "
            f"[{ev.get('reason')}]")


# ---------------------------------------------------------------------------
# the join
# ---------------------------------------------------------------------------

def _entry(t: Optional[float], source: str, kind: str, summary: str,
           detail: dict, attributed: str = "job") -> dict:
    return {"t": None if t is None else round(t, 6),
            "source": source, "kind": kind, "summary": summary,
            "attributed": attributed, "detail": detail}


def _result_doc(spool: str, job_id: str
                ) -> Tuple[Optional[dict], Optional[float]]:
    """The job's durable result doc and its finish wall time (the doc
    file's mtime — the only wall stamp a bare spool has)."""
    doc = jobspec.read_result(spool, job_id)
    if doc is None:
        return None, None
    for sub in (jobspec.DONE, jobspec.FAILED, jobspec.REJECTED):
        p = os.path.join(spool, sub, f"{job_id}.json")
        try:
            return doc, os.path.getmtime(p)
        except OSError:
            continue
    return doc, None


def explain_job(spool: str, job_id: str, *,
                events: Sequence[str] = (),
                series: Sequence[str] = (),
                timelines: Sequence[str] = ()) -> dict:
    """One job's causal timeline from durable artifacts alone.

    ``events``/``series``/``timelines`` ADD explicit files to the
    spool auto-discovery (a sidecar written far from the spool via
    ``-metrics PATH``).  Returns ``{"job_id", "found", "tenant",
    "result", "timeline": [...]}`` with the timeline sorted by wall
    time (un-anchorable rows sort last, in sidecar order).
    """
    arts = discover_artifacts(spool)
    ev_paths = list(arts["events"]) + [p for p in events
                                       if p not in arts["events"]]
    se_paths = list(arts["series"]) + [p for p in series
                                       if p not in arts["series"]]
    tr_paths = list(arts["traces"]) + [p for p in timelines
                                       if p not in arts["traces"]]

    doc, finish_wall = _result_doc(spool, job_id)
    tenant = (doc or {}).get("tenant")
    out: List[dict] = []

    # -- event sidecars: job events now, window/context after the
    #    window is known
    parsed = []
    for p in ev_paths:
        rows = _read_jsonl(p)
        if rows:
            parsed.append((os.path.basename(p), _wall_anchor(rows),
                           rows))
    for src, anchor, rows in parsed:
        for ev in rows:
            if ev.get("event") not in JOB_EVENTS:
                continue
            hit = _narrate_job_event(ev, job_id)
            if hit is None:
                continue
            kind, summary = hit
            t_rel = ev.get("t")
            wall = anchor + t_rel if anchor is not None and isinstance(
                t_rel, (int, float)) else None
            out.append(_entry(wall, src, kind, summary, ev))
            if kind == "finish" and wall is not None:
                finish_wall = wall
            if tenant is None and ev.get("tenant"):
                tenant = ev.get("tenant")

    # -- the job's execution window, for window/context attribution
    submit_wall = None
    queue_s = (doc or {}).get("queue_s")
    service_s = (doc or {}).get("service_s") or (doc or {}).get(
        "seconds")
    if finish_wall is not None:
        back = 0.0
        for v in (queue_s, service_s):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                back += float(v)
        submit_wall = finish_wall - back
        out.append(_entry(submit_wall, "derived", "submit",
                          f"submitted (derived: finish - "
                          f"{round(back, 3)}s queue+service)",
                          {"finish_wall": round(finish_wall, 6)}))
    lo = None if submit_wall is None else submit_wall - WINDOW_SLOP_S
    hi = None if finish_wall is None else finish_wall + WINDOW_SLOP_S

    if lo is not None and hi is not None:
        for src, anchor, rows in parsed:
            if anchor is None:
                continue
            # window events only attach when THIS sidecar also ran the
            # job (it holds the job's tenant_job/admission rows) — a
            # neighbor worker's retries are not this job's story
            ran_here = any(e.get("event") in ("tenant_job",
                                              "admission_selected")
                           and _narrate_job_event(e, job_id)
                           for e in rows)
            for ev in rows:
                wall = None
                if isinstance(ev.get("t"), (int, float)):
                    wall = anchor + ev["t"]
                if wall is None or not (lo <= wall <= hi):
                    continue
                if ev.get("event") in WINDOW_EVENTS and ran_here:
                    kind, summary = _narrate_window(ev)
                    out.append(_entry(wall, src, kind, summary, ev,
                                      attributed="window"))
                elif ev.get("event") in CONTEXT_EVENTS:
                    kind, summary = _narrate_context(ev)
                    out.append(_entry(wall, src, kind, summary, ev,
                                      attributed="context"))

    # -- series rows: the plane's shape while the job waited/ran —
    #    only rows where the headline signals changed (the sampler
    #    ticks every second; an unchanged row narrates nothing)
    if lo is not None and hi is not None:
        from ..obs import series as series_mod
        prev = None
        for p in se_paths:
            _, rows = series_mod.read_series(p)
            for r in rows:
                t = r.get("t")
                if not isinstance(t, (int, float)) or not (
                        lo <= t <= hi):
                    continue
                g = (r.get("metrics") or {}).get("gauges") or {}
                sig = (g.get("serve_backlog"), g.get("overload_level"),
                       g.get("serve_inflight"))
                if sig == prev:
                    continue
                prev = sig
                out.append(_entry(
                    t, os.path.basename(os.path.dirname(p)) or
                    os.path.basename(p), "series",
                    f"backlog={int(g.get('serve_backlog', 0))} "
                    f"inflight={int(g.get('serve_inflight', 0))} "
                    f"rung={int(g.get('overload_level', 0))} "
                    f"rss_mb={round(g.get('rss_mb', 0))}",
                    {"source": r.get("source")},
                    attributed="context"))

    # -- trace spans: the exact execution lane
    span_name = None if tenant is None else f"tenant:{tenant}:{job_id}"
    for p in tr_paths:
        from ..obs import trace as trace_mod
        evs = trace_mod.read_trace_events(p) or []
        for ev in evs:
            if ev.get("ph") != "X" or (span_name is not None
                                       and ev.get("name") != span_name):
                continue
            if span_name is None and not str(ev.get("name", "")
                                             ).endswith(f":{job_id}"):
                continue
            ts = ev.get("ts")
            wall = ts / 1e6 if isinstance(ts, (int, float)) else None
            out.append(_entry(
                wall, os.path.basename(p), "execute",
                f"executed {round(ev.get('dur', 0) / 1e6, 3)}s on "
                f"pid {ev.get('pid')} lane {ev.get('tid')}", ev))

    # -- the durable outcome
    if doc is not None:
        if doc.get("rejected"):
            summary = (f"rejected doc [{doc.get('code')}]: retry "
                       f"after {doc.get('retry_after_s')}s")
        elif doc.get("ok"):
            summary = f"result doc: ok in {doc.get('service_s')}s"
        else:
            summary = (f"result doc: failed "
                       f"[{doc.get('error_type')}]: {doc.get('error')}")
        out.append(_entry(finish_wall, "spool", "result", summary, doc))

    out.sort(key=lambda e: (e["t"] is None, e["t"] or 0.0))
    return {"job_id": job_id, "tenant": tenant,
            "found": doc is not None or any(
                e["attributed"] == "job" for e in out),
            "result": doc, "timeline": out}


def render_timeline(doc: dict) -> str:
    """Human view: one line per step, wall-clocked, window/context
    attribution marked (``~`` best-effort, ``·`` plane context)."""
    lines = [f"job {doc['job_id']}"
             + (f" (tenant {doc['tenant']})" if doc.get("tenant")
                else "")
             + (": no durable record found" if not doc["found"]
                else "")]
    mark = {"job": " ", "window": "~", "context": "·"}
    for e in doc["timeline"]:
        if e["t"] is not None:
            stamp = time.strftime("%H:%M:%S",
                                  time.localtime(e["t"]))
            stamp += f".{int((e['t'] % 1) * 1000):03d}"
        else:
            stamp = "--:--:--.---"
        lines.append(f"  {stamp} {mark.get(e['attributed'], ' ')}"
                     f"[{e['source']}] {e['kind']}: {e['summary']}")
    return "\n".join(lines)
