"""``adam_tpu.serve`` — the always-warm, multi-tenant front-end.

Every batch CLI invocation pays cold jax init + XLA compile per run;
the canonical shape ladder (parallel/executor.py) already guarantees the
compiled kernels are reusable across runs, so the only thing missing is
a process that *lives* across runs.  This package is that process:

* :mod:`.jobspec`   — the filesystem job-spec queue (atomic submit,
  durable per-job results, crash-safe re-queue);
* :mod:`.admission` — the pure, replayable admission/batching
  controller (``decide_admission``, the ``decide_plan`` convention:
  recorded inputs + digest, replayed by tools/check_executor.py);
* :mod:`.packed`    — cross-tenant shared dispatches: one fixed-capacity
  flagstat wire buffer packs many tenants' rows, segment prefix-sum
  bounds keep per-tenant counters exact (ops/flagstat.py's segmented
  kernel, the ragged-concat discipline of docs/ARCHITECTURE.md §6g);
* :mod:`.overload`  — the brownout ladder (``decide_overload``): a pure
  overload state machine over backlog depth / queue-wait p99 / RSS
  watermarks that sheds work in deliberate rungs (stop packing →
  reject low-priority → reject all) instead of letting tail latency
  grow without bound (docs/ARCHITECTURE.md §6m);
* :mod:`.server`    — the long-lived loop: warm the backend once
  (platform.warm), admit queued jobs, multiplex them onto one device
  with per-tenant isolation (obs labels, fault/retry scoping, malformed
  budgets — one tenant's failure never touches another's bytes).

docs/ARCHITECTURE.md §6i walks the dataflow.
"""

from .admission import decide_admission  # noqa: F401
from .jobspec import submit_job, wait_result  # noqa: F401
from .overload import decide_overload  # noqa: F401
from .server import ServeServer  # noqa: F401
