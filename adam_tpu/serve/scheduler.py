"""Fleet serve: the fault-tolerant cluster scheduler over always-warm
workers.

PR 10's serve plane multiplexes tenants onto ONE warm device; PR 9's
shard fleet spreads one batch job across worker processes with nobody
queueing behind it.  This module fuses them: one front-door spool, a
fleet of always-warm worker processes (each a full
:class:`~adam_tpu.serve.server.ServeServer` on its own sub-spool, booted
through ``platform.warm()`` and holding the shared compiled shape
ladder), and a pure, replayable cluster scheduler that places queued
jobs — and shards of big jobs via the existing
``shardstream.decide_shard_plan`` — onto whichever hosts are alive.
The DrJAX process-granularity MapReduce shape (arXiv:2403.07128)
applied to a serving loop: placements are the broadcast, each worker's
warm serve loop is the map, result relay (and the exact-monoid counter
merge for sharded jobs) is the reduce — with workers as pipeline
stages, not barrier-synced rounds (arXiv:1908.09291).

Robustness is the core of the design, built on existing machinery:

* **heartbeat leases** — every worker renews a lease file through
  ``shardstream.Heartbeat`` (the ``shard_lease`` fault site fires at
  each renewal); the scheduler reads lease mtimes exactly like the
  shard supervisor: process exit → ``worker_death``, stale lease →
  ``lease_expiry`` + a SIGKILL fence before any reassignment;
* **durable requeue** — a lost worker's claimed jobs (its sub-spool
  ``running/``) and unstarted jobs (``queue/``) move back to the front
  queue by atomic rename, results the worker committed before dying
  relay normally, and the spool's monotonic never-recycled ids mean a
  retried job can never collide with a retired result;
* **poison-job quarantine** — :func:`decide_requeue` (pure) counts the
  worker deaths attributed to each *started* job; past ``max_job_kills``
  the job fails with a typed ``failed/<job>.json`` (``JobQuarantined``)
  instead of grinding the fleet down worker by worker;
* **work stealing** — :func:`decide_steal` (pure, the
  ``decide_shard_speculation`` shape) moves unclaimed queue entries
  from a backlogged worker to an idle one; moves are atomic renames, a
  lost race simply skips, and exactly-once results are structural
  (relay-before-requeue, fence-before-requeue);
* **graceful drain** — stop lets in-flight jobs finish their round,
  relays their results, requeues anything unstarted back to the front
  queue durably, and writes the per-tenant SLO shutdown report.

Every decision follows the ``decide_plan`` convention: PURE, kwonly,
recorded with canonicalized ``inputs`` + ``input_digest``
(``placement_selected`` / ``job_requeued`` events, validated by
tools/check_metrics.py and replayed offline by
tools/check_executor.py).  Cross-tenant packed dispatch happens *per
host* — each worker's own ``decide_admission`` round groups the jobs
placed on it through ``flagstat_kernel_wire32_segmented``, with the
PR 10 degrade-to-solo path intact per worker.

docs/FLEET_SERVE.md walks the placement/requeue/quarantine protocol and
the failure-mode table; tests/test_fleet_serve.py pins the chaos
matrix (SIGKILL any worker mid-job → byte-identical to a one-worker
oracle; a poison job quarantines while neighbors complete).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..checkpoint import atomic_write
from ..resilience import faults
from ..resilience.retry import (RETRY_SEED_ENV, FleetPolicy,
                                backoff_delay, resolve_fleet_policy)
from . import jobspec, status as status_mod
from .admission import decide_admission
from .overload import (AdmissionLimits, OverloadPolicy, OverloadTracker,
                       resolve_admission_limits, resolve_overload_policy,
                       rss_mb)

#: fleet-dir layout (everything lives under ``SPOOL/fleet/``)
FLEET_DIR = "fleet"
CONFIG_FILE = "config.json"
WORKERS_DIR = "workers"
LEASE_DIR = "leases"
LOG_DIR = "logs"
PARTS_DIR = "parts"
SHARDED_DIR = "sharded"


#: sub-job id suffix: ``<parent>.s<k>`` (the spool's id alphabet allows
#: dots, so sub-jobs are first-class spool citizens — they requeue,
#: steal and quarantine through the same machinery as whole jobs)
_SUBJOB_RE = re.compile(r"^(.+)\.s(\d+)$")


class JobQuarantined(RuntimeError):
    """A job was quarantined after killing its worker budget — the
    typed failure the poison ladder writes instead of grinding the
    fleet down (its name lands in ``failed/<job>.json``'s
    ``error_type``)."""


# ---------------------------------------------------------------------------
# the pure decisions
# ---------------------------------------------------------------------------

def _digest(inputs: dict) -> str:
    return hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]


def decide_placement(*, queued: Sequence[dict], workers: Sequence[dict],
                     depth: int, fair: bool = False,
                     tenant_slots: int = 0) -> dict:
    """One scheduler round's placements — PURE.

    ``queued``: front-queue descriptors ``{"job_id", "tenant",
    "command", "seq"}`` (any order; canonicalization sorts by ``seq``).
    ``workers``: ``{"worker", "inflight", "alive"}`` snapshots
    (``inflight`` = queued + running at that host).  FIFO by submit
    order onto the least-loaded alive worker (ties → lowest id), at
    most ``depth`` jobs in flight per worker — jobs past every host's
    depth stay in the front queue (where stealing and later rounds can
    still reorder them onto whoever drains first).  ``fair=True`` (the
    fleet default) replaces the FIFO placement ORDER with the
    deficit-round-robin tenant interleave
    (serve/admission.``_drr_order``, quantum one job): a burst
    tenant's backlog fills at most its round-robin share of the open
    worker depth, so the steady tenant behind it still places this
    round.  ``tenant_slots`` > 0 caps one tenant's placements per
    round (the fleet's in-flight quota — over-slots jobs stay in the
    front queue, they are not shed), in FIFO and DRR order alike.
    Both keywords join the recorded inputs only when engaged, so
    pre-fairness sidecars replay digest-identical.  Returns::

        {"place": [[job_id, worker], ...], "reason": str,
         "inputs": {...}, "input_digest": hex}

    Recorded in full by ``placement_selected``;
    tools/check_executor.py replays the decision offline.
    """
    from .admission import _drr_order

    canon_q = sorted((dict(job_id=str(q["job_id"]),
                           tenant=str(q["tenant"]),
                           command=str(q["command"]), seq=int(q["seq"]))
                      for q in queued), key=lambda q: q["seq"])
    canon_w = sorted((dict(worker=int(w["worker"]),
                           inflight=int(w["inflight"]),
                           alive=bool(w["alive"]))
                      for w in workers), key=lambda w: w["worker"])
    inputs = dict(queued=canon_q, workers=canon_w, depth=int(depth))
    if fair:
        inputs["fair"] = True
    if tenant_slots:
        inputs["tenant_slots"] = int(tenant_slots)
    t_slots = inputs.get("tenant_slots", 0)
    load = {w["worker"]: w["inflight"] for w in canon_w if w["alive"]}
    order = _drr_order(canon_q, len(canon_q), t_slots) \
        if inputs.get("fair") else canon_q
    place: List[List] = []
    taken: Dict[str, int] = {}
    for q in order:
        if not load:
            break
        if t_slots and taken.get(q["tenant"], 0) >= t_slots:
            continue            # over-slots: stays in the front queue
        w = min(load, key=lambda k: (load[k], k))
        if load[w] >= inputs["depth"]:
            break               # every alive worker is at depth
        place.append([q["job_id"], w])
        taken[q["tenant"]] = taken.get(q["tenant"], 0) + 1
        load[w] += 1
    how = "drr" if inputs.get("fair") else "fifo"
    reason = (f"{how} {len(place)}/{len(canon_q)} queued onto "
              f"{len(load)} worker(s) at depth {inputs['depth']}")
    return dict(place=place, reason=reason, inputs=inputs,
                input_digest=_digest(inputs))


def decide_requeue(*, job_id: str, tenant: str, cause: str, kills: int,
                   max_kills: int, started: bool) -> dict:
    """One orphaned job's next action after its worker was lost — PURE.

    ``kills`` counts the worker deaths attributed to this job so far
    (a death is attributed only when the job was *started* — sitting
    claimed in the dead worker's ``running/``; unstarted queue entries
    ride along innocently).  ``action`` is ``requeue`` (back to the
    front queue, durably) or ``quarantine`` (the poison ladder: a job
    that has killed ``max_kills`` workers fails with a typed
    ``failed/<job>.json`` instead of being handed a fresh victim).
    Recorded in full by ``job_requeued``; tools/check_executor.py
    replays it.
    """
    inputs = dict(job_id=str(job_id), tenant=str(tenant),
                  cause=str(cause), kills=int(kills),
                  max_kills=int(max_kills), started=bool(started))
    if inputs["started"] and inputs["kills"] >= inputs["max_kills"]:
        action = "quarantine"
        reason = (f"{inputs['cause']}: killed {inputs['kills']} "
                  f"worker(s) >= budget {inputs['max_kills']} — poison")
    else:
        action = "requeue"
        reason = (f"{inputs['cause']}: requeue "
                  f"({inputs['kills']}/{inputs['max_kills']} "
                  "kill(s) attributed)")
    return dict(action=action, reason=reason, inputs=inputs,
                input_digest=_digest(inputs))


def decide_steal(*, stealable: Sequence[dict],
                 idle: Sequence[int]) -> dict:
    """Whether idle hosts steal queued work from backlogged ones — PURE
    (the ``decide_shard_speculation`` shape: a drained host volunteers,
    the decision hands it the other end of someone's backlog).

    ``stealable``: unclaimed queue entries at busy workers with at
    least TWO jobs in flight — a 1-deep host never donates, since
    moving its only job to an empty neighbor swaps the imbalance
    instead of reducing it (``{"job_id", "worker", "seq"}`` —
    unit-granular, since sharded jobs' range sub-jobs are ordinary
    queue entries).  Each idle worker
    gets at most one steal per decision (gradual rebalance): the
    earliest-seq entry from the most-backlogged donor (ties → lowest
    donor id).  Moves are atomic renames at the call site — a donor
    that claims the job first wins the race and the move is skipped,
    never duplicated.  Recorded by ``job_requeued`` (cause ``steal``).
    """
    canon_s = sorted((dict(job_id=str(s["job_id"]),
                           worker=int(s["worker"]), seq=int(s["seq"]))
                      for s in stealable), key=lambda s: s["seq"])
    inputs = dict(stealable=canon_s,
                  idle=sorted(int(i) for i in idle))
    moves: List[List] = []
    taken: set = set()
    for w in inputs["idle"]:
        cands = [s for s in canon_s
                 if s["job_id"] not in taken and s["worker"] != w]
        if not cands:
            break
        donors: Dict[int, int] = {}
        for s in cands:
            donors[s["worker"]] = donors.get(s["worker"], 0) + 1
        donor = max(donors, key=lambda k: (donors[k], -k))
        s = next(s for s in cands if s["worker"] == donor)
        moves.append([s["job_id"], donor, w])
        taken.add(s["job_id"])
    out = dict(action="steal" if moves else "none", moves=moves,
               reason=(f"{len(moves)} unit(s) to "
                       f"{len(inputs['idle'])} idle worker(s)"
                       if moves else "nothing-stealable"),
               inputs=inputs, input_digest=_digest(inputs))
    return out


def _emit_placement(d: dict, **extra) -> None:
    obs.registry().counter("fleet_placements").inc(len(d["place"]))
    obs.emit("placement_selected", place=d["place"], reason=d["reason"],
             inputs=d["inputs"], input_digest=d["input_digest"], **extra)


def _emit_requeued(cause: str, d: dict, **extra) -> None:
    obs.registry().counter("fleet_requeues", action=d["action"]).inc()
    fields = dict(cause=cause, action=d["action"], reason=d["reason"],
                  inputs=d["inputs"], input_digest=d["input_digest"])
    if cause == "steal":
        fields["moves"] = d["moves"]
    else:
        fields["job_id"] = d["inputs"]["job_id"]
    fields.update(extra)
    obs.emit("job_requeued", **fields)


# ---------------------------------------------------------------------------
# range execution (the sharded-big-job map function, run by workers)
# ---------------------------------------------------------------------------

#: per-worker unit-index cache: a warm serve worker ranges over the
#: same input many times (the shard-split path cuts one big job into
#: many range sub-jobs), so the prescan is paid once per (file state,
#: unit_rows), not once per sub-job
_UNIT_INDEX_CACHE: Dict[Tuple[str, int, int, int], Optional[dict]] = {}


def _range_entry(path: str, unit_rows: int) -> Tuple[str, Optional[dict]]:
    """(entry, unit_index) for a range sub-job over ``path`` — the same
    pure ``decide_shard_entry`` the fleet plan runs, with the prescan
    index memoized per worker process.  Emitted (and decided) only for
    SAM/BAM inputs; Parquet ranges read native row groups."""
    from ..parallel import shardstream
    from ..parallel.ringplane import ENTRY_ENV, decide_shard_entry

    kind = shardstream._input_kind(path)
    if kind not in ("sam", "bam"):
        return "forward", None
    requested = str(os.environ.get(ENTRY_ENV, "auto"))
    index = None
    if requested != "forward":
        try:
            st = os.stat(path)
            key = (os.path.abspath(path), st.st_mtime_ns, st.st_size,
                   int(unit_rows))
        except OSError:
            key = None
        if key is not None and key in _UNIT_INDEX_CACHE:
            index = _UNIT_INDEX_CACHE[key]
        else:
            index = shardstream.build_unit_index(path, int(unit_rows))
            if key is not None:
                _UNIT_INDEX_CACHE[key] = index
    d = decide_shard_entry(kind=kind, requested=requested,
                           index_available=index is not None)
    obs.emit("shard_entry_selected", entry=d["entry"],
             reason=d["reason"], inputs=d["inputs"],
             input_digest=d["input_digest"])
    return d["entry"], index if d["entry"] == "index" else None


def range_flagstat_counts(path: str, *, unit_lo: int, unit_hi: int,
                          unit_rows: int, io_procs: int = 1
                          ) -> Tuple[np.ndarray, int]:
    """The 18x2 flagstat counter block for global units
    ``[unit_lo, unit_hi)`` of ``path`` — the shard fleet's flagstat map
    function (``shardstream._flagstat_runtime``: pad to the canonical
    rung, retry/split/CPU-degrade per unit) re-used inside a warm serve
    worker.  Parquet inputs read only the overlapping row groups;
    SAM/BAM inputs seek to the range via the memoized unit index when
    the shard-entry decision engages; counters are an exact integer
    monoid, so the scheduler's sum over sub-jobs is byte-identical to
    one solo pass."""
    from ..io.dispatch import FLAGSTAT_COLUMNS
    from ..parallel import shardstream

    entry, index = _range_entry(path, int(unit_rows))
    unit_result, ex = shardstream._flagstat_runtime(
        {"unit_rows": int(unit_rows)})
    total = np.zeros((18, 2), np.int64)
    rows = 0
    try:
        for unit, table in shardstream.unit_tables(
                path, list(range(int(unit_lo), int(unit_hi))),
                int(unit_rows), list(FLAGSTAT_COLUMNS), "decoded",
                "flagstat", io_procs=int(io_procs),
                entry=entry, index=index):
            total += unit_result(unit, table)["counts"]
            rows += table.num_rows
    finally:
        ex.finish()
    return total, rows


# ---------------------------------------------------------------------------
# worker entry (``python -m adam_tpu.serve.scheduler --worker FLEET W``)
# ---------------------------------------------------------------------------

def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def worker_spool(fleet_dir: str, worker: int) -> str:
    return os.path.join(fleet_dir, WORKERS_DIR, f"w{worker}", "spool")


def _lease_path(fleet_dir: str, worker: int) -> str:
    return os.path.join(fleet_dir, LEASE_DIR, f"w{worker}.json")


def worker_main(argv: Optional[List[str]] = None) -> int:
    """One fleet-serve worker: heartbeat a lease, warm the backend once,
    and run a full :class:`ServeServer` loop over this worker's private
    sub-spool until the scheduler writes the stop sentinel (or the
    scheduler itself disappears — an orphaned warm jax process must not
    leak)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--worker":
        argv = argv[1:]
    if len(argv) != 2:
        print("usage: python -m adam_tpu.serve.scheduler --worker "
              "FLEET_DIR WORKER_ID", file=sys.stderr)
        return 2
    fleet_dir, worker = argv[0], int(argv[1])
    from ..platform import honor_platform_env
    honor_platform_env()
    try:
        faults.install_from_env()
    except (OSError, ValueError) as e:
        print(f"serve-worker: bad fault plan: {e}", file=sys.stderr)
        return 2
    cfg = _read_json(os.path.join(fleet_dir, CONFIG_FILE)) or {}
    wspool = worker_spool(fleet_dir, worker)
    inc = 0
    try:
        inc = int(os.environ.get(faults.INCARNATION_ENV) or 0)
    except ValueError:
        pass
    from ..parallel.shardstream import Heartbeat
    from .server import ServeServer

    # the lease exists before the expensive warm boot: the scheduler
    # judges a booting worker by its heartbeats, not a boot-grace guess
    hb = Heartbeat(_lease_path(fleet_dir, worker),
                   float(cfg.get("heartbeat_s", 1.0)), inc).start()
    try:
        with obs.metrics_run_from_env(
                argv=["serve-worker", fleet_dir, str(worker)],
                config=dict(fleet_dir=fleet_dir, worker=worker,
                            incarnation=inc),
                command="serve-worker"):
            srv = ServeServer(
                wspool, chunk_rows=int(cfg.get("chunk_rows", 1 << 22)),
                max_concurrent=int(cfg.get("max_concurrent", 4)),
                pack=bool(cfg.get("pack", True)),
                pack_segments=int(cfg.get("pack_segments", 8)),
                poll_s=float(cfg.get("poll_s", 0.05)),
                io_procs=int(cfg.get("io_procs", 1)),
                executor_opts=cfg.get("executor_opts") or {},
                slo_report=False,
                # the FRONT DOOR owns the overload plane: a worker
                # re-resolving ADAM_TPU_SERVE_* from the inherited env
                # would apply the caps a second time — typed-rejecting
                # jobs the scheduler already admitted and placed.
                # Workers keep only the fairness interleave (from the
                # shared config), quotas and the ladder stay off
                limits=AdmissionLimits(fair=bool(cfg.get("fair",
                                                         True))),
                overload=OverloadPolicy(backlog_hi=0),
                series=bool(cfg.get("series", True)))
            sched_pid = int(cfg.get("scheduler_pid") or 0)
            while not jobspec.stop_requested(wspool):
                # short idle re-entries so the orphan check runs even
                # when no jobs arrive (boot() is idempotent)
                srv.run(idle_timeout_s=2.0)
                if jobspec.stop_requested(wspool):
                    break
                if sched_pid:
                    try:
                        os.kill(sched_pid, 0)
                    except OSError:
                        sys.stderr.write(
                            "serve-worker: scheduler gone — exiting "
                            "orphaned loop\n")
                        break
            # final sample + receipt into this worker's sidecar; a
            # killed worker's series keeps its already-fsynced rows
            obs.series.stop_series()
            return 0
    except faults.InjectedFault as e:
        print(f"serve-worker: {type(e).__name__}: {e}", file=sys.stderr)
        return 3
    finally:
        hb.stop()


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------

class _WorkerState:
    def __init__(self, worker: int):
        self.worker = worker
        self.incarnation = 0
        self.restarts = 0
        self.proc: Optional[subprocess.Popen] = None
        self.spawned_at = 0.0
        self.closed = False


def _repo_root() -> str:
    import adam_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(adam_tpu.__file__)))


class FleetServeScheduler:
    """The fleet-serve control plane: spawn always-warm workers, place
    queued jobs, watch leases, fence + requeue + quarantine, steal for
    idle hosts, merge sharded jobs, relay results, drain cleanly."""

    def __init__(self, spool: str, *, hosts: int,
                 chunk_rows: int = 1 << 22, max_concurrent: int = 4,
                 pack: bool = True, pack_segments: int = 8,
                 poll_s: float = 0.05, io_procs: int = 1,
                 worker_depth: int = 4, max_job_kills: int = 2,
                 shard_rows: int = 0, steal: bool = True,
                 policy: Optional[FleetPolicy] = None,
                 env: Optional[dict] = None,
                 executor_opts: Optional[dict] = None,
                 boot_grace_s: float = 60.0,
                 drain_timeout_s: float = 60.0,
                 limits: Optional[AdmissionLimits] = None,
                 overload: Optional[OverloadPolicy] = None,
                 series: bool = True):
        self.spool = jobspec.ensure_spool(spool)
        self.fleet_dir = os.path.join(spool, FLEET_DIR)
        self.hosts = max(int(hosts), 1)
        self.chunk_rows = int(chunk_rows)
        self.max_concurrent = max(int(max_concurrent), 1)
        self.pack = bool(pack)
        self.pack_segments = max(int(pack_segments), 2)
        self.poll_s = float(poll_s)
        self.io_procs = int(io_procs)
        self.worker_depth = max(int(worker_depth), 1)
        self.max_job_kills = max(int(max_job_kills), 1)
        self.shard_rows = int(shard_rows)
        self.steal = bool(steal)
        self.policy = policy or resolve_fleet_policy()
        self.env = dict(env if env is not None else os.environ)
        self.executor_opts = dict(executor_opts or {})
        self.boot_grace_s = max(boot_grace_s, self.policy.lease_ttl_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.states: Dict[int, _WorkerState] = {}
        self.jobs_served = 0
        self.kills: Dict[str, int] = {}
        #: parent job_id -> {"spec", "claim", "parts": {sub_id: doc|None}}
        self._shards: Dict[str, dict] = {}
        #: parents already finished (a FAILED parent can leave straggler
        #: sub-jobs running on healthy workers — their late results must
        #: drop, never relay as client-visible docs or count as served)
        self._retired_parents: set = set()
        self._row_counts: Dict[str, int] = {}
        self._slo: Dict[str, dict] = {}
        self._last_placement_digest: Optional[str] = None
        self._last_admission_digest: Optional[str] = None
        #: the overload plane at the FRONT DOOR (docs/ARCHITECTURE.md
        #: §6m): quotas/deadlines/brownout shed jobs before placement
        #: ever hands them to a warm worker; level >= 1 also stops
        #: shard-splitting (cheaper rounds under pressure)
        self.limits = limits if limits is not None \
            else resolve_admission_limits()
        self.overload = OverloadTracker(
            overload if overload is not None
            else resolve_overload_policy(
                max_concurrent=self.worker_depth * self.hosts))
        self._cursor = jobspec.QueueCursor(self.spool)
        self._canon_cache: Dict[str, dict] = {}
        self._poll_round = 0
        self._booted = False
        #: live telemetry (docs/OBSERVABILITY.md): the scheduler's own
        #: series at SPOOL/series.jsonl (workers write theirs under
        #: their sub-spools), a throttled fleet-wide status.json, and
        #: periodic SLO-report checkpoints — a SIGKILL'd fleet keeps
        #: the tails and the per-worker state it had already measured
        self.series = bool(series)
        self._status_every = status_mod.status_interval_s()
        self._report_every = status_mod.report_interval_s()
        self._last_status: Optional[float] = None
        self._last_report: Optional[float] = None
        #: periodic spool retention GC (serve/retention.py) on the
        #: status-rewrite throttle discipline
        from .retention import gc_interval_s
        self._gc_every = gc_interval_s()
        self._last_gc: Optional[float] = None
        self._reported_jobs = 0
        self._last_backlog = 0
        self._tenant_backlog: Dict[str, int] = {}

    # -- boot ---------------------------------------------------------------

    def boot(self) -> dict:
        if self._booted:
            return {}
        for d in (WORKERS_DIR, LEASE_DIR, LOG_DIR, PARTS_DIR,
                  SHARDED_DIR):
            os.makedirs(os.path.join(self.fleet_dir, d), exist_ok=True)
        requeued = jobspec.requeue_running(self.spool)
        requeued += self._recover_previous_fleet()
        atomic_write(os.path.join(self.fleet_dir, CONFIG_FILE),
                     json.dumps(dict(
                         chunk_rows=self.chunk_rows,
                         max_concurrent=self.max_concurrent,
                         pack=self.pack,
                         pack_segments=self.pack_segments,
                         poll_s=self.poll_s, io_procs=self.io_procs,
                         executor_opts=self.executor_opts,
                         heartbeat_s=self.policy.heartbeat_s,
                         fair=self.limits.fair,
                         series=self.series,
                         scheduler_pid=os.getpid()), sort_keys=True))
        for w in range(self.hosts):
            st = _WorkerState(w)
            self.states[w] = st
            self._spawn(st)
        obs.emit("serve_boot", hosts=self.hosts, requeued=requeued,
                 worker_depth=self.worker_depth,
                 shard_rows=self.shard_rows)
        atomic_write(os.path.join(self.spool, jobspec.SERVING_MARKER),
                     json.dumps(dict(pid=os.getpid(), hosts=self.hosts,
                                     requeued=requeued),
                                sort_keys=True))
        self._booted = True
        if self.series and obs.series.active() is None:
            obs.series.start_series(
                os.path.join(self.spool, "series.jsonl"),
                source={"role": "scheduler"})
        return dict(hosts=self.hosts, requeued=requeued)

    def _recover_previous_fleet(self) -> int:
        """A crashed scheduler leaves jobs scattered across worker
        sub-spools and half-merged shard parents — move every one of
        them back to the front queue (results a dead fleet committed
        relay as-is; sharded parents re-run whole, their orphaned
        sub-jobs and part results are dropped)."""
        n = 0
        wroot = os.path.join(self.fleet_dir, WORKERS_DIR)
        parents: List[str] = []
        sdir = os.path.join(self.fleet_dir, SHARDED_DIR)
        for name in sorted(os.listdir(sdir) if os.path.isdir(sdir)
                           else []):
            if not jobspec._NAME_RE.match(name):
                continue
            try:
                os.rename(os.path.join(sdir, name),
                          os.path.join(self.spool, jobspec.QUEUE, name))
                parents.append(jobspec._NAME_RE.match(name).group(2))
                n += 1
            except OSError:
                pass

        def _orphan_sub(job_id: str) -> bool:
            m = _SUBJOB_RE.match(job_id)
            return bool(m and m.group(1) in parents)

        for wname in sorted(os.listdir(wroot) if os.path.isdir(wroot)
                            else []):
            ws = os.path.join(wroot, wname, "spool")
            for sub in (jobspec.QUEUE, jobspec.RUNNING):
                d = os.path.join(ws, sub)
                for name in sorted(os.listdir(d)
                                   if os.path.isdir(d) else []):
                    m = jobspec._NAME_RE.match(name)
                    if not m:
                        continue
                    src = os.path.join(d, name)
                    if _orphan_sub(m.group(2)):
                        try:
                            os.unlink(src)
                        except OSError:
                            pass
                        continue
                    try:
                        os.rename(src, os.path.join(
                            self.spool, jobspec.QUEUE, name))
                        n += 1
                    except OSError:
                        pass
            for sub in (jobspec.DONE, jobspec.FAILED, jobspec.REJECTED):
                d = os.path.join(ws, sub)
                for name in sorted(os.listdir(d)
                                   if os.path.isdir(d) else []):
                    job_id = name[:-5] if name.endswith(".json") else name
                    src = os.path.join(d, name)
                    if _orphan_sub(job_id) or jobspec.read_result(
                            self.spool, job_id) is not None:
                        try:
                            os.unlink(src)
                        except OSError:
                            pass
                        continue
                    try:
                        os.rename(src, os.path.join(self.spool, sub,
                                                    name))
                    except OSError:
                        pass
            # a dead fleet's stop sentinel must not stop the new one
            try:
                os.unlink(os.path.join(ws, jobspec.STOP_SENTINEL))
            except OSError:
                pass
        # drop stale part results (their parents re-run whole)
        pdir = os.path.join(self.fleet_dir, PARTS_DIR)
        for root, _, names in os.walk(pdir):
            for name in names:
                try:
                    os.unlink(os.path.join(root, name))
                except OSError:
                    pass
        return n

    # -- spawn / env --------------------------------------------------------

    def _worker_env(self, worker: int, incarnation: int) -> dict:
        wenv = dict(self.env)
        wenv[obs.METRICS_ENV] = os.path.join(
            self.fleet_dir, LOG_DIR,
            f"w{worker}-inc{incarnation}.metrics.jsonl")
        wenv[faults.INCARNATION_ENV] = str(incarnation)
        wenv[faults.WORKER_ENV] = str(worker)
        # fleet-serve workers are THIS box's processes: stamp the
        # scheduler's host identity so any shard fleet they spawn
        # resolves same_box from the handshake, not an assumption
        # (parallel/netplane.py; run_fleet's decide_transport inputs)
        from ..parallel import netplane
        wenv.setdefault(netplane.HOST_ID_ENV, netplane.host_identity())
        base = 0
        try:
            base = int(self.env.get(RETRY_SEED_ENV) or 0)
        except ValueError:
            pass
        wenv[RETRY_SEED_ENV] = str(base + 1000 * (worker + 1))
        root = _repo_root()
        wenv["PYTHONPATH"] = root + os.pathsep + \
            wenv.get("PYTHONPATH", "")
        return wenv

    def _spawn(self, st: _WorkerState) -> None:
        # drop the previous incarnation's lease: a respawn must get the
        # boot grace, then live on its OWN heartbeats (the shardstream
        # supervisor's discipline)
        try:
            os.unlink(_lease_path(self.fleet_dir, st.worker))
        except OSError:
            pass
        jobspec.ensure_spool(worker_spool(self.fleet_dir, st.worker))
        for stale in (jobspec.STOP_SENTINEL, jobspec.ACTIVE_MARKER):
            try:
                os.unlink(os.path.join(
                    worker_spool(self.fleet_dir, st.worker), stale))
            except OSError:
                pass
        log_path = os.path.join(
            self.fleet_dir, LOG_DIR,
            f"w{st.worker}-inc{st.incarnation}.log")
        argv = [sys.executable, "-m", "adam_tpu.serve.scheduler",
                "--worker", self.fleet_dir, str(st.worker)]
        with open(log_path, "w") as log:
            st.proc = subprocess.Popen(
                argv, stdout=log, stderr=subprocess.STDOUT,
                env=self._worker_env(st.worker, st.incarnation))
        st.spawned_at = time.monotonic()
        obs.registry().counter("fleet_worker_spawns").inc()

    # -- snapshots ----------------------------------------------------------

    def _listdir(self, path: str) -> List[str]:
        try:
            return sorted(os.listdir(path))
        except OSError:
            return []

    def _worker_inflight(self, worker: int) -> Tuple[List[str],
                                                     List[str]]:
        ws = worker_spool(self.fleet_dir, worker)
        q = [n for n in self._listdir(os.path.join(ws, jobspec.QUEUE))
             if jobspec._NAME_RE.match(n)]
        r = [n for n in self._listdir(os.path.join(ws, jobspec.RUNNING))
             if jobspec._NAME_RE.match(n)]
        return q, r

    def _alive(self, st: _WorkerState) -> bool:
        return (not st.closed and st.proc is not None
                and st.proc.poll() is None)

    # -- placement ----------------------------------------------------------

    def _front_queue(self) -> List[Tuple[int, str, dict]]:
        """Canonicalized front-queue snapshot — the shared
        cursor-backed implementation (jobspec.snapshot_canon: parse +
        canonicalization paid once per immutable queue file,
        hand-tampered bad specs fail themselves, never the
        scheduler)."""
        return jobspec.snapshot_canon(self.spool, self._cursor,
                                      self._canon_cache)

    def _input_rows(self, path: str) -> Optional[int]:
        """Row count for shard-eligibility (cached per input; the
        scheduler pays it once, workers never)."""
        if path in self._row_counts:
            return self._row_counts[path]
        try:
            from ..parallel.shardstream import count_input_rows
            n = int(count_input_rows(path))
        except Exception:  # noqa: BLE001 — sizing is a hint, not fatal
            n = -1
        self._row_counts[path] = n
        return n

    def _maybe_shard(self, seq: int, path: str, canon: dict,
                     alive: int) -> bool:
        """Expand one big flagstat job into per-range sub-jobs via the
        existing pure ``decide_shard_plan`` (event
        ``shard_plan_selected``).  The parent's queue file moves to
        ``fleet/sharded/`` (the durable in-flight claim a crashed
        scheduler requeues from); sub-jobs submit as first-class spool
        jobs and place like any other."""
        if (self.shard_rows <= 0 or alive < 2
                or canon["command"] != "flagstat"
                or _SUBJOB_RE.match(canon["job_id"])):
            return False
        rows = self._input_rows(canon["input"])
        if rows is None or rows < max(self.shard_rows, 2):
            return False
        from ..parallel.shardstream import decide_shard_plan

        unit_rows = max(-(-rows // (2 * alive)), 256)
        n_units = max(-(-rows // unit_rows), 1)
        plan = decide_shard_plan(n_units=n_units, n_hosts=alive,
                                 unit_rows=unit_rows, total_rows=rows,
                                 unit_bins=None)
        # the reason goes out VERBATIM — check_executor replays the
        # decision from its inputs and compares it; the fleet-serve
        # context rides a separate field instead of tainting the replay
        obs.emit("shard_plan_selected", n_hosts=plan["n_hosts"],
                 n_units=plan["n_units"], unit_rows=plan["unit_rows"],
                 assignments=plan["assignments"],
                 reason=plan["reason"], source="fleet-serve",
                 inputs=plan["inputs"],
                 input_digest=plan["input_digest"])
        claim = os.path.join(self.fleet_dir, SHARDED_DIR,
                             os.path.basename(path))
        try:
            os.rename(path, claim)
        except OSError:
            return False        # raced away (shouldn't happen: one
        #                         scheduler owns the front queue)
        parts: Dict[str, Optional[dict]] = {}
        for k, (lo, hi) in enumerate(plan["assignments"]):
            if hi <= lo:
                continue
            sub_id = f"{canon['job_id']}.s{k}"
            jobspec.submit_job(self.spool, {
                "job_id": sub_id, "tenant": canon["tenant"],
                "command": "flagstat_range", "input": canon["input"],
                "output": None,
                "args": {"unit_lo": int(lo), "unit_hi": int(hi),
                         "unit_rows": int(plan["unit_rows"]),
                         **({"io_procs": canon["args"]["io_procs"]}
                            if "io_procs" in canon["args"] else {})}})
            parts[sub_id] = None
        self._shards[canon["job_id"]] = dict(spec=canon, claim=claim,
                                             parts=parts)
        obs.registry().counter("fleet_jobs_sharded").inc()
        return True

    def _shed_round(self, queued: List[Tuple[int, str, dict]]
                    ) -> List[Tuple[int, str, dict]]:
        """The front door's overload pass: run the SAME pure
        ``decide_admission`` the single-host server runs — in
        shed-only mode (every survivor "admits", placement decides who
        actually runs where) — and retire the shed jobs with typed
        docs.  Returns the surviving snapshot."""
        if not (self.limits.backlog_cap or self.limits.tenant_quota
                or self.overload.level >= 2
                or any(c.get("deadline_s") is not None
                       for _, _, c in queued)):
            return queued
        now = time.time()
        desc = []
        for seq, path, canon in queued:
            m = _SUBJOB_RE.match(canon["job_id"])
            if m and m.group(1) in self._shards:
                # a live sharded parent's sub-job (requeued by a worker
                # loss) is NOT new work — shedding it would stall the
                # parent merge forever; the parent was already admitted
                continue
            d = {"job_id": canon["job_id"], "tenant": canon["tenant"],
                 "command": canon["command"], "seq": seq}
            if canon.get("priority") not in (None, "normal"):
                d["priority"] = canon["priority"]
            if canon.get("deadline_s") is not None:
                d["deadline_s"] = canon["deadline_s"]
                sub_at = canon.get("submitted_at")
                d["wait_s"] = max(now - float(sub_at), 0.0) \
                    if isinstance(sub_at, (int, float)) and \
                    not isinstance(sub_at, bool) else 0.0
            desc.append(d)
        plan = decide_admission(
            queued=desc, running=0, max_concurrent=len(desc),
            pack=False, fair=self.limits.fair,
            backlog_cap=self.limits.backlog_cap,
            tenant_quota=self.limits.tenant_quota,
            overload_level=self.overload.level)
        if not plan.get("cancel") and not plan.get("reject"):
            return queued
        if plan["input_digest"] != self._last_admission_digest:
            extra = {}
            if plan.get("cancel"):
                extra["cancel"] = plan["cancel"]
            if plan.get("reject"):
                extra["reject"] = plan["reject"]
            obs.emit("admission_selected", admit=plan["admit"],
                     pack_groups=plan["pack_groups"],
                     reason=plan["reason"], inputs=plan["inputs"],
                     input_digest=plan["input_digest"], **extra)
            self._last_admission_digest = plan["input_digest"]
        # ONE retirement implementation with the single-host loop
        # (server.retire_*): doc shape, events, counters and SLO
        # accounting can never skew between fleet and solo
        from .server import retire_deadline, retire_rejected
        by_id = {c["job_id"]: (path, c) for _, path, c in queued}
        shed = set()
        for c in plan.get("cancel") or ():
            path, canon = by_id[c["job_id"]]
            if retire_deadline(self.spool, self._slo, path, canon,
                               c["wait_s"], c["deadline_s"]):
                self.jobs_served += 1
                shed.add(canon["job_id"])
        for r in plan.get("reject") or ():
            path, canon = by_id[r["job_id"]]
            if retire_rejected(self.spool, self._slo, path, canon,
                               r["code"], r["retry_after_s"]):
                self.jobs_served += 1
                shed.add(canon["job_id"])
        return [(s, p, c) for s, p, c in queued
                if c["job_id"] not in shed]

    def _place_round(self) -> int:
        queued = self._front_queue()
        # live signals for the series sampler / status doc (front-door
        # backlog only; worker sub-spool depths ride the status doc)
        self._last_backlog = len(queued)
        tb: Dict[str, int] = {}
        for _, _, c in queued:
            tb[c["tenant"]] = tb.get(c["tenant"], 0) + 1
        self._tenant_backlog = tb
        obs.registry().gauge("serve_backlog").set(len(queued))
        if self.overload.engaged:
            self.overload.update(len(queued))
        if not queued:
            return 0
        queued = self._shed_round(queued)
        if not queued:
            return 0
        alive = sum(1 for st in self.states.values()
                    if self._alive(st))
        # brownout rung 1 stops shard-splitting: under pressure the
        # fleet serves whole jobs (predictable rounds) instead of
        # multiplying queue entries
        if alive and self.shard_rows > 0 and \
                self.overload.level < 1:
            remaining = []
            for seq, path, canon in queued:
                if not self._maybe_shard(seq, path, canon, alive):
                    remaining.append((seq, path, canon))
            if len(remaining) != len(queued):
                # sub-jobs just joined the queue: re-snapshot so they
                # place this round
                queued = self._front_queue()
            else:
                queued = remaining
        if not queued:
            return 0
        workers = []
        for w, st in sorted(self.states.items()):
            q, r = self._worker_inflight(w)
            workers.append(dict(worker=w, inflight=len(q) + len(r),
                                alive=self._alive(st)))
        d = decide_placement(
            queued=[dict(job_id=c["job_id"], tenant=c["tenant"],
                         command=c["command"], seq=c["seq"])
                    for _, _, c in queued],
            workers=workers, depth=self.worker_depth,
            fair=self.limits.fair,
            tenant_slots=self.limits.tenant_slots)
        if not d["place"]:
            return 0
        # an unchanged queue/worker snapshot re-derives the identical
        # decision — emitting it again would only bloat the sidecar
        if d["input_digest"] != self._last_placement_digest:
            _emit_placement(d)
            self._last_placement_digest = d["input_digest"]
        by_id = {c["job_id"]: (path, c) for _, path, c in queued}
        placed = 0
        for job_id, w in d["place"]:
            path, _canon = by_id[job_id]
            dest = os.path.join(worker_spool(self.fleet_dir, w),
                                jobspec.QUEUE, os.path.basename(path))
            try:
                os.rename(path, dest)
                placed += 1
            except OSError:
                continue
        return placed

    # -- result relay + shard merge -----------------------------------------

    def _observe_slo(self, doc: dict) -> None:
        from .server import slo_observe
        slo_observe(self._slo, doc.get("tenant") or "default",
                    doc.get("queue_s"), doc.get("service_s"))
        # the ladder's queue-p99 signal reads the same relayed waits
        # the SLO report does
        self.overload.observe_wait(doc.get("queue_s"))

    def _relay_results(self) -> int:
        done = 0
        for w in sorted(self.states):
            done += self._relay_worker(w)
        done += self._merge_ready_shards()
        return done

    def _relay_worker(self, worker: int) -> int:
        ws = worker_spool(self.fleet_dir, worker)
        done = 0
        for sub in (jobspec.DONE, jobspec.FAILED, jobspec.REJECTED):
            d = os.path.join(ws, sub)
            for name in self._listdir(d):
                if not name.endswith(".json"):
                    continue
                job_id = name[:-5]
                src = os.path.join(d, name)
                m = _SUBJOB_RE.match(job_id)
                if m and m.group(1) in self._shards:
                    self._collect_part(m.group(1), job_id, src)
                    continue
                if m and m.group(1) in self._retired_parents:
                    # a straggler of an already-failed parent: its
                    # result has nowhere to merge and must not surface
                    # as a client-visible doc (or consume a max_jobs
                    # slot)
                    try:
                        os.unlink(src)
                    except OSError:
                        pass
                    continue
                if jobspec.read_result(self.spool, job_id) is not None:
                    # already served (a requeue/steal race duplicate):
                    # the first durable result wins, extras drop
                    try:
                        os.unlink(src)
                    except OSError:
                        pass
                    continue
                try:
                    os.rename(src, os.path.join(self.spool, sub, name))
                except OSError:
                    continue
                doc = jobspec.read_result(self.spool, job_id) or {}
                self._observe_slo(doc)
                self.kills.pop(job_id, None)
                self.jobs_served += 1
                done += 1
        return done

    def _collect_part(self, parent: str, sub_id: str, src: str) -> None:
        pdir = os.path.join(self.fleet_dir, PARTS_DIR, parent)
        os.makedirs(pdir, exist_ok=True)
        dest = os.path.join(pdir, f"{sub_id}.json")
        try:
            os.rename(src, dest)
        except OSError:
            return
        doc = _read_json(dest)
        state = self._shards.get(parent)
        if state is None or doc is None:
            return
        if sub_id in state["parts"]:
            state["parts"][sub_id] = doc

    def _merge_ready_shards(self) -> int:
        done = 0
        for parent in list(self._shards):
            state = self._shards[parent]
            parts = state["parts"]
            docs = [doc for doc in parts.values() if doc is not None]
            failed = [doc for doc in docs if not doc.get("ok")]
            if failed:
                doc = failed[0]
                self._finish_shard(
                    parent, ok=False,
                    error=(f"shard {doc.get('job_id')} failed: "
                           f"{doc.get('error')}"),
                    error_type=doc.get("error_type") or "RuntimeError")
                done += 1
                continue
            if len(docs) < len(parts):
                continue
            from ..ops.flagstat import (FlagStatMetrics, format_report)

            totals = np.zeros((18, 2), np.int64)
            rows = 0
            queue_ss, service_ss = [], []
            for doc in docs:
                res = doc.get("result") or {}
                totals += np.asarray(res["counts"], np.int64)
                rows += int(res.get("rows") or 0)
                if isinstance(doc.get("queue_s"), (int, float)):
                    queue_ss.append(float(doc["queue_s"]))
                if isinstance(doc.get("service_s"), (int, float)):
                    service_ss.append(float(doc["service_s"]))
            report = format_report(
                FlagStatMetrics.from_counters(totals[:, 1]),
                FlagStatMetrics.from_counters(totals[:, 0]))
            self._finish_shard(
                parent, ok=True,
                result={"report": report, "rows": rows,
                        "sharded": len(parts)},
                queue_s=min(queue_ss) if queue_ss else None,
                service_s=max(service_ss) if service_ss else None)
            done += 1
        return done

    def _finish_shard(self, parent: str, *, ok: bool,
                      result: Optional[dict] = None,
                      error: Optional[str] = None,
                      error_type: Optional[str] = None,
                      queue_s: Optional[float] = None,
                      service_s: Optional[float] = None) -> None:
        state = self._shards.pop(parent)
        self._retired_parents.add(parent)
        jobspec.write_result(self.spool, state["spec"], ok=ok,
                             result=result, error=error,
                             error_type=error_type,
                             queue_s=queue_s, service_s=service_s,
                             running_path=state["claim"])
        doc = jobspec.read_result(self.spool, parent) or {}
        self._observe_slo(doc)
        # a failed parent's stragglers: drop their queue entries so a
        # poison sub-job's siblings do not spin on a retired parent
        if not ok:
            self._drop_subjobs(parent)
        self.jobs_served += 1

    def _drop_subjobs(self, parent: str) -> None:
        dirs = [os.path.join(self.spool, jobspec.QUEUE)]
        for w in self.states:
            dirs.append(os.path.join(worker_spool(self.fleet_dir, w),
                                     jobspec.QUEUE))
        for d in dirs:
            for name in self._listdir(d):
                m = jobspec._NAME_RE.match(name)
                if not m:
                    continue
                sm = _SUBJOB_RE.match(m.group(2))
                if sm and sm.group(1) == parent:
                    try:
                        os.unlink(os.path.join(d, name))
                    except OSError:
                        pass

    # -- loss handling -------------------------------------------------------

    def _check_lease(self, st: _WorkerState, now: float) -> bool:
        lease = _lease_path(self.fleet_dir, st.worker)
        try:
            age = time.time() - os.path.getmtime(lease)
        except OSError:
            return (now - st.spawned_at) > self.boot_grace_s
        if age <= self.policy.lease_ttl_s:
            return False
        obs.registry().counter("fleet_lease_expiries").inc()
        obs.emit("worker_lease_expired", worker=st.worker,
                 age_s=round(age, 3),
                 ttl_s=round(self.policy.lease_ttl_s, 3))
        return True

    def _watch_workers(self) -> None:
        now = time.monotonic()
        for st in list(self.states.values()):
            if st.closed or st.proc is None:
                continue
            rc = st.proc.poll()
            if rc is not None:
                self._handle_worker_loss(st, "worker_death")
            elif self._check_lease(st, now):
                self._handle_worker_loss(st, "lease_expiry")
        if all(st.closed for st in self.states.values()):
            leftover = len(self._front_queue()) + len(self._shards) + \
                sum(len(self._worker_inflight(w)[0]) +
                    len(self._worker_inflight(w)[1])
                    for w in self.states)
            if leftover:
                raise RuntimeError(
                    f"fleet serve failed: all {self.hosts} worker(s) "
                    f"exhausted their restart budgets with {leftover} "
                    "job(s) unserved")

    def _handle_worker_loss(self, st: _WorkerState, cause: str) -> None:
        # fence first: a half-dead worker must not keep writing results
        # after its jobs are handed elsewhere
        if st.proc is not None and st.proc.poll() is None:
            st.proc.kill()
            try:
                st.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        obs.registry().counter("fleet_worker_deaths",
                               cause=cause).inc()
        # whatever the worker committed before dying still counts —
        # relay BEFORE requeue, so a finished job never re-runs
        self._relay_worker(st.worker)
        ws = worker_spool(self.fleet_dir, st.worker)
        # kill attribution is the EXECUTING set (the worker's active
        # marker, written around each run), not the whole claimed
        # batch: a serve round claims several jobs up front, and
        # charging a death to claimed-but-waiting jobs would let one
        # poison job quarantine every innocent sharing its worker
        active = set(jobspec.read_active(ws))
        for sub, claimed in ((jobspec.RUNNING, True),
                             (jobspec.QUEUE, False)):
            d = os.path.join(ws, sub)
            for name in self._listdir(d):
                m = jobspec._NAME_RE.match(name)
                if not m:
                    continue
                src = os.path.join(d, name)
                job_id = m.group(2)
                if jobspec.read_result(self.spool, job_id) is not None:
                    try:        # result landed before the death
                        os.unlink(src)
                    except OSError:
                        pass
                    continue
                sm = _SUBJOB_RE.match(job_id)
                if sm and sm.group(1) in self._retired_parents:
                    try:        # straggler of a failed parent: no
                        os.unlink(src)  # point re-running it
                    except OSError:
                        pass
                    continue
                spec = _read_json(src) or {}
                tenant = str(spec.get("tenant") or "default")
                started = claimed and job_id in active
                kills = self.kills.get(job_id, 0) + (1 if started
                                                     else 0)
                if started:
                    self.kills[job_id] = kills
                dec = decide_requeue(job_id=job_id, tenant=tenant,
                                     cause=cause, kills=kills,
                                     max_kills=self.max_job_kills,
                                     started=started)
                _emit_requeued(cause, dec, worker=st.worker)
                if dec["action"] == "quarantine":
                    self._quarantine(src, job_id, spec, cause, kills)
                    continue
                try:
                    os.rename(src, os.path.join(
                        self.spool, jobspec.QUEUE, name))
                except OSError:
                    pass
        st.restarts += 1
        if st.restarts > self.policy.max_restarts:
            st.closed = True
            obs.registry().counter("fleet_workers_closed").inc()
            return
        st.incarnation += 1
        self._spawn(st)

    def _quarantine(self, src: str, job_id: str, spec: dict,
                    cause: str, kills: int) -> None:
        try:
            canon = jobspec.canon_spec(spec)
        except ValueError:
            canon = {"job_id": job_id,
                     "tenant": str(spec.get("tenant") or "default"),
                     "command": str(spec.get("command")),
                     "input": "", "output": None, "args": {},
                     "submitted_at": None}
        canon["job_id"] = job_id
        err = JobQuarantined(
            f"job {job_id} quarantined: killed {kills} worker(s) "
            f"({cause}) — poison-job budget is "
            f"{self.max_job_kills}")
        jobspec.write_result(self.spool, canon, ok=False,
                             error=str(err),
                             error_type=type(err).__name__,
                             running_path=src)
        obs.registry().counter("fleet_jobs_quarantined").inc()
        self.kills.pop(job_id, None)
        m = _SUBJOB_RE.match(job_id)
        if m and m.group(1) in self._shards:
            # the parent fails through the normal merge path: record
            # the quarantine doc as this part's (failed) result
            doc = jobspec.read_result(self.spool, job_id)
            if doc is not None:
                self._shards[m.group(1)]["parts"][job_id] = doc
        else:
            self.jobs_served += 1

    # -- stealing ------------------------------------------------------------

    def _steal_round(self) -> None:
        if not self.steal:
            return
        stealable, idle = [], []
        for w, st in sorted(self.states.items()):
            if not self._alive(st):
                continue
            q, r = self._worker_inflight(w)
            if not q and not r:
                idle.append(w)
                continue
            if len(q) + len(r) < 2:
                # a 1-deep host is not a donor: moving its only job to
                # an empty neighbor swaps the imbalance instead of
                # reducing it — two booting workers would ping-pong one
                # unclaimed job every poll round, churning renames and
                # spamming steal events that rebalance nothing
                continue
            for name in q:
                m = jobspec._NAME_RE.match(name)
                stealable.append(dict(job_id=m.group(2), worker=w,
                                      seq=int(m.group(1))))
        if not stealable or not idle:
            return
        d = decide_steal(stealable=stealable, idle=idle)
        if d["action"] != "steal":
            return
        _emit_requeued("steal", d)
        by_id = {s["job_id"]: s["seq"] for s in d["inputs"]["stealable"]}
        for job_id, src_w, dst_w in d["moves"]:
            name = f"{by_id[job_id]:08d}-{job_id}.json"
            try:
                os.rename(
                    os.path.join(worker_spool(self.fleet_dir, src_w),
                                 jobspec.QUEUE, name),
                    os.path.join(worker_spool(self.fleet_dir, dst_w),
                                 jobspec.QUEUE, name))
                obs.registry().counter("fleet_jobs_stolen").inc()
            except OSError:
                continue        # the donor claimed it first: skip

    # -- drain / run ---------------------------------------------------------

    def _drain(self) -> None:
        """Stop every worker cleanly: write its stop sentinel, let the
        in-flight round finish, relay what completed, requeue the rest
        durably, kill stragglers past the timeout."""
        for w in self.states:
            try:
                jobspec.request_stop(worker_spool(self.fleet_dir, w))
            except OSError:
                pass
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            self._relay_results()
            if all(st.proc is None or st.proc.poll() is not None
                   for st in self.states.values()):
                break
            time.sleep(0.05)
        for st in self.states.values():
            if st.proc is not None and st.proc.poll() is None:
                st.proc.kill()
                try:
                    st.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        self._relay_results()
        # anything not served goes back to the front queue — durable,
        # never torn: the next boot picks it up exactly where it sat
        for w, st in sorted(self.states.items()):
            ws = worker_spool(self.fleet_dir, w)
            for sub in (jobspec.RUNNING, jobspec.QUEUE):
                d = os.path.join(ws, sub)
                for name in self._listdir(d):
                    if not jobspec._NAME_RE.match(name):
                        continue
                    m = jobspec._NAME_RE.match(name)
                    if jobspec.read_result(self.spool,
                                           m.group(2)) is not None:
                        try:
                            os.unlink(os.path.join(d, name))
                        except OSError:
                            pass
                        continue
                    spec = _read_json(os.path.join(d, name)) or {}
                    dec = decide_requeue(
                        job_id=m.group(2),
                        tenant=str(spec.get("tenant") or "default"),
                        cause="drain",
                        kills=self.kills.get(m.group(2), 0),
                        max_kills=self.max_job_kills, started=False)
                    _emit_requeued("drain", dec, worker=w)
                    try:
                        os.rename(os.path.join(d, name),
                                  os.path.join(self.spool,
                                               jobspec.QUEUE, name))
                    except OSError:
                        pass

    def write_report(self, *, quiet: bool = False) -> Optional[str]:
        # same file name as the single-host server's shutdown report —
        # clients poll one well-known path whatever the fleet size
        from .server import SLO_REPORT_FILE, write_slo_report
        return write_slo_report(
            os.path.join(self.spool, SLO_REPORT_FILE), self._slo,
            hosts=self.hosts, jobs=self.jobs_served, quiet=quiet)

    # -- live status ---------------------------------------------------------

    def _status_doc(self) -> dict:
        """The fleet-wide durable live-state doc: the solo server's
        rows plus per-worker lease health and the active jobs each
        worker would be charged for on a kill
        (docs/FLEET_SERVE.md)."""
        from ..resilience.retry import breaker_snapshot

        now = time.time()
        workers = []
        for w, st in sorted(self.states.items()):
            q, r = self._worker_inflight(w)
            try:
                lease_age = round(now - os.path.getmtime(
                    _lease_path(self.fleet_dir, w)), 3)
            except OSError:
                lease_age = None
            workers.append({"worker": w, "alive": self._alive(st),
                            "incarnation": st.incarnation,
                            "restarts": st.restarts,
                            "lease_age_s": lease_age,
                            "queued": len(q), "running": len(r),
                            "active": jobspec.read_active(
                                worker_spool(self.fleet_dir, w))})
        from .server import slo_summary
        tenants: Dict[str, dict] = {}
        for name, ten in slo_summary(self._slo).items():
            tenants[name] = dict(ten)
        # fresh front-queue count, not the round snapshot: the final
        # exit-time doc must show the drained queue (per-tenant depth
        # stays the snapshot — attribution needs the spec bodies)
        try:
            backlog = sum(
                1 for n in os.listdir(os.path.join(self.spool,
                                                   jobspec.QUEUE))
                if n.endswith(".json"))
        except OSError:
            backlog = self._last_backlog
        for name, depth in self._tenant_backlog.items():
            tenants.setdefault(name, {})["queued"] = \
                depth if backlog else 0
        for ten in tenants.values():
            ten.setdefault("queued", 0)
        return {"mode": "fleet", "warm": self._booted,
                "hosts": self.hosts,
                "jobs_served": self.jobs_served,
                "backlog": backlog,
                "max_concurrent": self.max_concurrent,
                "worker_depth": self.worker_depth,
                "sharded": len(self._shards),
                "overload": status_mod.overload_doc(self.overload),
                "breakers": breaker_snapshot(),
                "tenants": tenants, "workers": workers,
                "rss_mb": rss_mb()}

    def _tick_status(self) -> None:
        """Once per scheduler round: the throttled status.json rewrite
        and the periodic SLO-report checkpoint (the exit-only-report
        fix — a SIGKILL now loses at most one interval of tails)."""
        now = time.monotonic()
        if self._status_every > 0 and (
                self._last_status is None
                or now - self._last_status >= self._status_every):
            self._last_status = now
            status_mod.write_status(self.spool, self._status_doc(),
                                    interval_s=self._status_every)
        if self._report_every > 0 and (
                self._last_report is None
                or now - self._last_report >= self._report_every):
            self._last_report = now
            if self.jobs_served != self._reported_jobs:
                self._reported_jobs = self.jobs_served
                path = self.write_report(quiet=True)
                if path:
                    obs.emit("serve_report_checkpoint", path=path,
                             jobs=self.jobs_served, reason="periodic")
        if self._gc_every > 0 and (
                self._last_gc is None
                or now - self._last_gc >= self._gc_every):
            self._last_gc = now
            from .retention import sweep
            try:
                sweep(self.spool)
            except OSError:
                pass  # a failed sweep never takes the fleet down

    def run(self, *, max_jobs: Optional[int] = None,
            idle_timeout_s: Optional[float] = None) -> int:
        """Serve until ``max_jobs`` results relayed, the front-door stop
        sentinel appears, or the whole fleet idles for
        ``idle_timeout_s``.  Always drains the workers and writes the
        SLO shutdown report on the way out."""
        self.boot()
        served0 = self.jobs_served
        idle_since = time.monotonic()
        try:
            while True:
                n = self._relay_results()
                if n:
                    idle_since = time.monotonic()
                if max_jobs is not None and \
                        self.jobs_served - served0 >= max_jobs:
                    break
                if jobspec.stop_requested(self.spool):
                    break
                self._watch_workers()
                if self._place_round():
                    idle_since = time.monotonic()
                self._steal_round()
                self._tick_status()
                if idle_timeout_s is not None and \
                        time.monotonic() - idle_since >= idle_timeout_s:
                    break
                # deterministic jitter, the serve loop's discipline: N
                # schedulers sharing a filesystem must not poll in
                # lockstep (seeded — replays identical)
                self._poll_round += 1
                time.sleep(backoff_delay(
                    f"{self.spool}|sched-poll", 1, self.poll_s,
                    self.poll_s, seed=self._poll_round))
        finally:
            self._drain()
            path = self.write_report()
            if path:
                obs.emit("serve_report_checkpoint", path=path,
                         jobs=self.jobs_served, reason="final")
            if self._status_every > 0:
                status_mod.write_status(self.spool, self._status_doc(),
                                        interval_s=self._status_every)
        return self.jobs_served - served0


if __name__ == "__main__":
    sys.exit(worker_main())
