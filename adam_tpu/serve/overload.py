"""The brownout ladder: a pure overload state machine for the serve
plane (docs/ARCHITECTURE.md §6m).

When offered load outruns warm capacity, the failure mode is not a
crash — it is an unbounded backlog whose queue-wait tail grows without
limit while every accepted job still "succeeds".  The ladder converts
that into a sequence of deliberate, cheap degradations, walked one rung
per decision and recorded as replayable events:

====  ============  =====================================================
rung  state         sheds
====  ============  =====================================================
0     ``normal``    nothing
1     ``shed_batch``  shared-dispatch packing + fleet shard-splitting
                    (cheaper, more predictable rounds; every accepted
                    byte stays identical — packing is an optimization,
                    never a semantic)
2     ``reject_low``  new low-priority work (typed ``rejected/`` docs
                    with ``retry_after_s``)
3     ``reject_all``  all new work (existing claims still finish)
====  ============  =====================================================

:func:`decide_overload` is PURE (the ``decide_plan`` convention): the
serving loop reads the impure signals ONCE per round — backlog depth,
the recent accepted-job queue-wait p99 it already measures for the SLO
report, and process RSS — and hands them in as plain numbers, so the
recorded ``overload_state`` event replays bit-for-bit offline
(tools/check_executor.py).  Pressure is the max ratio of any engaged
signal over its high watermark; the ladder walks UP one rung when
pressure crosses the next threshold (1x → rung 1, 2x → rung 2, 4x →
rung 3) and walks DOWN one rung only after ``cool_rounds`` consecutive
calm decisions — hysteresis, so a watermark-straddling backlog does not
flap the ladder every round.

The companion breaker for the *backend* half of overload (a storm of
transient dispatch failures, not a deep queue) lives in
resilience/retry.py (:class:`~adam_tpu.resilience.retry.BreakerPolicy`).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Optional

#: ladder rung names, index == level
LEVEL_NAMES = ("normal", "shed_batch", "reject_low", "reject_all")

#: pressure thresholds: level n engages at PRESSURE_STEPS[n-1] times
#: the high watermark (geometric — each rung means "twice as far past
#: capacity as the last")
PRESSURE_STEPS = (1.0, 2.0, 4.0)

#: env knobs (serve CLI flags mirror these; docs/FLEET_SERVE.md)
BACKLOG_HI_ENV = "ADAM_TPU_SERVE_BACKLOG_HI"
QUEUE_P99_HI_ENV = "ADAM_TPU_SERVE_QUEUE_P99_HI_S"
RSS_BUDGET_ENV = "ADAM_TPU_SERVE_RSS_BUDGET_MB"
COOL_ROUNDS_ENV = "ADAM_TPU_SERVE_COOL_ROUNDS"
FAIR_ENV = "ADAM_TPU_SERVE_FAIR"                    # 0/off disables
BACKLOG_CAP_ENV = "ADAM_TPU_SERVE_BACKLOG_CAP"
TENANT_QUOTA_ENV = "ADAM_TPU_SERVE_TENANT_QUOTA"
TENANT_SLOTS_ENV = "ADAM_TPU_SERVE_TENANT_SLOTS"

#: default backlog high watermark as a multiple of ``max_concurrent``
#: when no explicit watermark is configured: eight full admission
#: rounds of queue is "the backlog outran warm capacity"
DEFAULT_BACKLOG_HI_ROUNDS = 8

DEFAULT_COOL_ROUNDS = 3


@dataclass(frozen=True)
class OverloadPolicy:
    """One resolved overload policy per serving loop.  ``backlog_hi``
    <= 0 disables the ladder entirely (the zero-overhead off state);
    ``queue_p99_hi_s``/``rss_budget_mb`` <= 0 disable that signal."""
    backlog_hi: int = 0
    queue_p99_hi_s: float = 0.0
    rss_budget_mb: float = 0.0
    cool_rounds: int = DEFAULT_COOL_ROUNDS


def resolve_overload_policy(backlog_hi: Optional[int] = None,
                            queue_p99_hi_s: Optional[float] = None,
                            rss_budget_mb: Optional[float] = None,
                            cool_rounds: Optional[int] = None,
                            max_concurrent: int = 4) -> OverloadPolicy:
    """Explicit arguments (CLI flags) win; ``ADAM_TPU_SERVE_*`` envs
    fill whatever the caller left unset (the executor's flag/env
    convention, via the shared retry.env_int/env_float coercers); the
    backlog watermark defaults to ``DEFAULT_BACKLOG_HI_ROUNDS *
    max_concurrent``."""
    from ..resilience.retry import env_float, env_int

    return OverloadPolicy(
        backlog_hi=env_int(backlog_hi, BACKLOG_HI_ENV,
                           DEFAULT_BACKLOG_HI_ROUNDS *
                           max(max_concurrent, 1)),
        queue_p99_hi_s=env_float(queue_p99_hi_s, QUEUE_P99_HI_ENV,
                                 0.0),
        rss_budget_mb=env_float(rss_budget_mb, RSS_BUDGET_ENV, 0.0),
        cool_rounds=max(env_int(cool_rounds, COOL_ROUNDS_ENV,
                                DEFAULT_COOL_ROUNDS), 1))


@dataclass(frozen=True)
class AdmissionLimits:
    """The quota half of the overload plane (decide_admission's
    keywords): ``fair`` = deficit-round-robin across tenants (on by
    default), the caps each default 0 = unbounded."""
    fair: bool = True
    backlog_cap: int = 0
    tenant_quota: int = 0
    tenant_slots: int = 0


def resolve_admission_limits(fair: Optional[bool] = None,
                             backlog_cap: Optional[int] = None,
                             tenant_quota: Optional[int] = None,
                             tenant_slots: Optional[int] = None
                             ) -> AdmissionLimits:
    """Explicit arguments win; ``ADAM_TPU_SERVE_*`` envs fill the rest
    (the resolve_retry_policy convention)."""
    from ..resilience.retry import env_int

    if fair is None:
        fair = os.environ.get(FAIR_ENV, "1") not in ("0", "off")
    return AdmissionLimits(
        fair=bool(fair),
        backlog_cap=max(env_int(backlog_cap, BACKLOG_CAP_ENV, 0), 0),
        tenant_quota=max(env_int(tenant_quota, TENANT_QUOTA_ENV, 0),
                         0),
        tenant_slots=max(env_int(tenant_slots, TENANT_SLOTS_ENV, 0),
                         0))


def rss_mb() -> Optional[float]:
    """This process's CURRENT resident set in MB — the one impure
    memory read, taken by the serving loop at the round boundary and
    handed to the pure decider.  Current, not peak: ``ru_maxrss``
    never decreases, so a ladder driven by it could walk up on one
    freed spike and never cool back down.  ``/proc/self/statm`` on
    Linux; the peak (the only portable number) is the fallback where
    /proc does not exist."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") / (1 << 20))
    except Exception:  # noqa: BLE001 — fall through to the peak
        pass
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak / (1 << 20) if sys.platform == "darwin" \
            else peak / 1024.0
    except Exception:  # noqa: BLE001 — a signal, never a crash
        return None


def decide_overload(*, level: int, backlog: int,
                    backlog_hi: int,
                    queue_p99_s: Optional[float] = None,
                    queue_p99_hi_s: float = 0.0,
                    rss_mb: Optional[float] = None,
                    rss_budget_mb: float = 0.0,
                    calm_rounds: int = 0,
                    cool_rounds: int = DEFAULT_COOL_ROUNDS) -> dict:
    """One round's brownout decision — PURE.

    ``level`` is the current rung, ``calm_rounds`` the consecutive
    below-target decisions so far (both carried by the caller between
    rounds and recorded, so the state machine replays).  Signals with
    a <= 0 watermark (or a None reading) are disengaged.  Returns::

        {"level": int, "state": name, "prev_level": int,
         "changed": bool, "calm_rounds": int, "pressure": float,
         "actions": {"pack": bool, "shard_split": bool,
                     "admit_low": bool, "admit_any": bool},
         "reason": str, "inputs": {...}, "input_digest": hex}

    The ladder walks up at most ONE rung per decision and down one
    rung only after ``cool_rounds`` consecutive decisions whose target
    sat below the current rung (hysteresis).  Recorded in full by the
    ``overload_state`` event; tools/check_executor.py replays it.
    """
    inputs = dict(level=int(level), backlog=int(backlog),
                  backlog_hi=int(backlog_hi),
                  queue_p99_s=None if queue_p99_s is None
                  else round(float(queue_p99_s), 3),
                  queue_p99_hi_s=round(float(queue_p99_hi_s), 3),
                  rss_mb=None if rss_mb is None
                  else round(float(rss_mb), 1),
                  rss_budget_mb=round(float(rss_budget_mb), 1),
                  calm_rounds=int(calm_rounds),
                  cool_rounds=max(int(cool_rounds), 1))
    ratios = []
    if inputs["backlog_hi"] > 0:
        ratios.append(("backlog", inputs["backlog"] /
                       inputs["backlog_hi"]))
    if inputs["queue_p99_hi_s"] > 0 and inputs["queue_p99_s"] is not None:
        ratios.append(("queue_p99", inputs["queue_p99_s"] /
                       inputs["queue_p99_hi_s"]))
    if inputs["rss_budget_mb"] > 0 and inputs["rss_mb"] is not None:
        ratios.append(("rss", inputs["rss_mb"] /
                       inputs["rss_budget_mb"]))
    signal, pressure = max(ratios, key=lambda r: r[1]) \
        if ratios else ("none", 0.0)
    pressure = round(pressure, 4)
    target = 0
    for step in PRESSURE_STEPS:
        if pressure >= step:
            target += 1
    cur = max(min(inputs["level"], len(LEVEL_NAMES) - 1), 0)
    calm = inputs["calm_rounds"]
    if target > cur:
        new, calm = cur + 1, 0          # walk up one rung at a time
        reason = (f"{signal} pressure {pressure}x -> "
                  f"{LEVEL_NAMES[new]} (target {LEVEL_NAMES[target]})")
    elif target < cur:
        calm += 1
        if calm >= inputs["cool_rounds"]:
            new, calm = cur - 1, 0      # cooled long enough: step down
            reason = (f"calm {inputs['cool_rounds']} round(s) -> "
                      f"{LEVEL_NAMES[new]}")
        else:
            new = cur
            reason = (f"cooling {calm}/{inputs['cool_rounds']} at "
                      f"{LEVEL_NAMES[cur]}")
    else:
        new, calm = cur, 0
        reason = f"steady at {LEVEL_NAMES[cur]} (pressure {pressure}x)"
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return dict(level=new, state=LEVEL_NAMES[new], prev_level=cur,
                changed=new != cur, calm_rounds=calm,
                pressure=pressure,
                actions=dict(pack=new < 1, shard_split=new < 1,
                             admit_low=new < 2, admit_any=new < 3),
                reason=reason, inputs=inputs, input_digest=digest)


class OverloadTracker:
    """The impure shell around :func:`decide_overload`: holds the rung
    + calm counter between rounds, keeps a bounded window of recent
    accepted-job queue waits for the p99 signal, reads RSS, emits the
    ``overload_state`` event on every rung change and keeps the
    ``overload_level`` gauge current.  Shared by the single-host server
    and the fleet scheduler (docs/FLEET_SERVE.md)."""

    #: queue waits kept for the rolling p99 (enough for a stable tail,
    #: small enough that an hour-old spike eventually ages out)
    WINDOW = 64
    #: samples also age out by TIME: at reject_all nothing new is
    #: served, so a count-only window would freeze at the burst-era
    #: p99 and the ladder could never cool back down — the signal must
    #: decay while the server sheds
    WINDOW_AGE_S = 60.0

    def __init__(self, policy: OverloadPolicy):
        self.policy = policy
        self.level = 0
        self.calm_rounds = 0
        self._waits: list = []      # [(monotonic_ts, wait_s), ...]

    @property
    def engaged(self) -> bool:
        return self.policy.backlog_hi > 0 or \
            self.policy.queue_p99_hi_s > 0 or \
            self.policy.rss_budget_mb > 0

    def observe_wait(self, queue_s) -> None:
        import time

        if isinstance(queue_s, (int, float)) and \
                not isinstance(queue_s, bool) and queue_s >= 0:
            self._waits.append((time.monotonic(), float(queue_s)))
            if len(self._waits) > self.WINDOW:
                del self._waits[:len(self._waits) - self.WINDOW]

    def _queue_p99(self) -> Optional[float]:
        import time

        cut = time.monotonic() - self.WINDOW_AGE_S
        self._waits = [w for w in self._waits if w[0] >= cut]
        if not self._waits:
            return None
        from .server import _pctl
        return _pctl([w[1] for w in self._waits], 99)

    def update(self, backlog: int) -> dict:
        """One round's ladder step: read the signals, take the pure
        decision, record it.  Returns the decision (callers read
        ``actions``/``level``)."""
        from .. import obs

        pol = self.policy
        d = decide_overload(
            level=self.level, backlog=backlog,
            backlog_hi=pol.backlog_hi,
            queue_p99_s=self._queue_p99() if pol.queue_p99_hi_s > 0
            else None,
            queue_p99_hi_s=pol.queue_p99_hi_s,
            rss_mb=rss_mb() if pol.rss_budget_mb > 0 else None,
            rss_budget_mb=pol.rss_budget_mb,
            calm_rounds=self.calm_rounds,
            cool_rounds=pol.cool_rounds)
        self.level = d["level"]
        self.calm_rounds = d["calm_rounds"]
        if d["changed"]:
            obs.registry().counter(
                "overload_transitions",
                state=d["state"]).inc()
            obs.registry().gauge("overload_level").set(d["level"])
            obs.emit("overload_state", level=d["level"],
                     state=d["state"], prev_level=d["prev_level"],
                     changed=True, calm_rounds=d["calm_rounds"],
                     pressure=d["pressure"], actions=d["actions"],
                     reason=d["reason"], inputs=d["inputs"],
                     input_digest=d["input_digest"])
        return d
