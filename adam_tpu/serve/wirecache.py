"""Per-input wire-chunk cache: pack the flagstat projection once per
serve round, however many jobs consume it.

When a serve round runs streaming flagstat and the s2 BQSR count (or a
packed ingest and its degrade-to-solo re-run) over the SAME tenant
input, each consumer used to re-open the file and re-pack the 26-bit
wire words chunk by chunk — the host-side twin of the device-side
triple dispatch the mega-pass collapses (ops/megapass.py).  This module
is the decode-side fix: a bounded, thread-safe cache of packed wire32
chunks keyed by the input's IDENTITY (realpath, size, mtime_ns) plus
the chunk geometry, so the second consumer replays host arrays instead
of decoding bytes.

Correctness discipline:

* identity keys — a rewritten input (new size or mtime) misses and
  re-decodes; stale chunks age out by LRU, they are never served for a
  changed file;
* complete-run gating — a producer that stops early (fault injection,
  admission kill) never marks its entry complete, so partial streams
  can't masquerade as the whole input;
* bounded memory — entries evict LRU once the byte budget
  (``ADAM_TPU_WIRE_CACHE_MB``, default 256; ``0`` disables) is
  exceeded, and an input bigger than the whole budget is simply never
  cached.

Hits and misses are counters (``wire_cache_hits`` /
``wire_cache_misses``, docs/OBSERVABILITY.md) so the collapse is
observable, matching the dispatch_count contract on the device side.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .. import obs

#: byte budget env (MiB); 0/off disables caching entirely
WIRE_CACHE_MB_ENV = "ADAM_TPU_WIRE_CACHE_MB"
DEFAULT_WIRE_CACHE_MB = 256


def _budget_bytes() -> int:
    raw = os.environ.get(WIRE_CACHE_MB_ENV, "")
    try:
        mb = int(raw) if raw else DEFAULT_WIRE_CACHE_MB
    except ValueError:
        mb = DEFAULT_WIRE_CACHE_MB
    return max(mb, 0) << 20


def input_identity(path: str) -> Optional[Tuple[str, int, int]]:
    """(realpath, size, mtime_ns) — None when unstattable (pipes,
    vanished files): such inputs are simply not cacheable."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (os.path.realpath(path), int(st.st_size),
            int(st.st_mtime_ns))


class WireChunkCache:
    """LRU cache of complete packed wire-chunk runs, one entry per
    (input identity, chunk_rows)."""

    def __init__(self, max_bytes: Optional[int] = None):
        self.max_bytes = _budget_bytes() if max_bytes is None \
            else int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, List[np.ndarray]]" = \
            OrderedDict()
        self._bytes = 0

    # -- internals ----------------------------------------------------------

    def _evict_until(self, need: int) -> None:
        # caller holds the lock
        while self._entries and self._bytes + need > self.max_bytes:
            _, old = self._entries.popitem(last=False)
            self._bytes -= sum(c.nbytes for c in old)

    def _get(self, key: tuple) -> Optional[List[np.ndarray]]:
        with self._lock:
            chunks = self._entries.get(key)
            if chunks is not None:
                self._entries.move_to_end(key)
            return chunks

    def _put(self, key: tuple, chunks: List[np.ndarray]) -> None:
        size = sum(c.nbytes for c in chunks)
        if size > self.max_bytes:
            return                          # bigger than the whole budget
        with self._lock:
            if key in self._entries:
                return
            self._evict_until(size)
            self._entries[key] = chunks
            self._bytes += size

    # -- the one public entry ----------------------------------------------

    def chunks(self, path: str, chunk_rows: int,
               produce) -> Iterator[np.ndarray]:
        """Yield ``path``'s packed wire chunks, from cache when a
        complete identical-geometry run is stored, else from
        ``produce()`` (the real decode) while recording a copy.  The
        entry is committed only after the producer is exhausted."""
        ident = None if self.max_bytes <= 0 else input_identity(path)
        if ident is None:
            yield from produce()
            return
        key = ident + (int(chunk_rows),)
        cached = self._get(key)
        reg = obs.registry()
        if cached is not None:
            reg.counter("wire_cache_hits").inc()
            yield from cached
            return
        reg.counter("wire_cache_misses").inc()
        kept: List[np.ndarray] = []
        keep = True
        for w in produce():
            w = np.asarray(w)
            if keep:
                kept.append(w)
                if sum(c.nbytes for c in kept) > self.max_bytes:
                    kept, keep = [], False  # over budget: stream through
            yield w
        if keep and input_identity(path) == ident:
            # identity re-checked at commit: a file rewritten while we
            # streamed it must not publish the torn read
            self._put(key, kept)

    @property
    def stored_bytes(self) -> int:
        with self._lock:
            return self._bytes
