"""Durable live status for the serve plane: ``status.json`` + readers.

Until this PR the serve stack's durable telemetry was all *post-mortem*
(sidecars and ``serve_report.json`` publish at process exit), so a live
or crashed server answered no question about its current state.  This
module is the status half of the live plane (obs/series.py is the
time-series half): every serve round the :class:`ServeServer` / fleet
scheduler throttles an atomic ``status.json`` write into the spool —
warm state, backlog, per-tenant queue depth and SLO window tails, the
brownout rung, breaker states, and (fleet) per-worker lease health with
the active jobs each worker would charge on a kill.

The doc is the WHOLE interface: ``adam-tpu status|top`` and any shared-
filesystem observer render purely from it (plus ``serving.json``, the
report, dir counts and the series tail), so the same view works on a
live fleet, a SIGKILL'd one, or from another host.  Writers degrade on
error (telemetry never takes a server down); readers treat every file
as possibly missing or stale and say so (:func:`liveness`).

Knobs: ``ADAM_TPU_SERVE_STATUS_S`` (status cadence, default 1.0, <=0
disables) and ``ADAM_TPU_SERVE_REPORT_S`` (the periodic
``serve_report.json`` checkpoint cadence, default 5.0, <=0 restores the
old exit-only behavior).  docs/FLEET_SERVE.md tabulates the doc rows.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from ..checkpoint import atomic_write
from ..resilience.retry import env_float
from . import jobspec
from .overload import LEVEL_NAMES

STATUS_FILE = "status.json"
SCHEMA_VERSION = 1
STATUS_INTERVAL_ENV = "ADAM_TPU_SERVE_STATUS_S"
REPORT_INTERVAL_ENV = "ADAM_TPU_SERVE_REPORT_S"
DEFAULT_STATUS_S = 1.0
DEFAULT_REPORT_S = 5.0

#: a status doc older than this many write-intervals from a live pid
#: renders STALE — the loop is wedged (or the clock skewed), either way
#: the doc no longer describes "now"
STALE_INTERVALS = 5.0

#: the spool job-state dirs, in lifecycle order (jobspec owns the names)
SPOOL_STATE_DIRS = (jobspec.QUEUE, jobspec.RUNNING, jobspec.DONE,
                    jobspec.FAILED, jobspec.REJECTED)


def status_interval_s(explicit: Optional[float] = None) -> float:
    return env_float(explicit, STATUS_INTERVAL_ENV, DEFAULT_STATUS_S)


def report_interval_s(explicit: Optional[float] = None) -> float:
    return env_float(explicit, REPORT_INTERVAL_ENV, DEFAULT_REPORT_S)


def overload_doc(tracker) -> dict:
    """The rung as a doc row: numeric level + its name + how close the
    ladder is to stepping down (serve/overload.LEVEL_NAMES)."""
    level = int(getattr(tracker, "level", 0))
    return {"level": level,
            "state": LEVEL_NAMES[min(level, len(LEVEL_NAMES) - 1)],
            "calm_rounds": int(getattr(tracker, "calm_rounds", 0))}


def write_status(spool: str, doc: dict, *,
                 interval_s: Optional[float] = None) -> Optional[str]:
    """Atomically publish ``SPOOL/status.json``.  ``fsync=False``: the
    doc is a freshness signal rewritten every second or so — the rename
    still guarantees readers never see a torn doc, and skipping the
    double fsync keeps the write off the round's critical path.  A
    failed write degrades to one stderr line."""
    out = dict(doc)
    out.setdefault("schema", SCHEMA_VERSION)
    out.setdefault("pid", os.getpid())
    out["written_at"] = round(time.time(), 6)
    if interval_s is not None:
        out["interval_s"] = round(float(interval_s), 6)
    path = os.path.join(spool, STATUS_FILE)
    try:
        atomic_write(path, json.dumps(out, sort_keys=True, default=str),
                     fsync=False)
    except OSError as e:
        import sys
        sys.stderr.write(f"serve: status write failed: {e}\n")
        return None
    return path


def read_status(spool: str) -> Optional[dict]:
    try:
        with open(os.path.join(spool, STATUS_FILE)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def pid_alive(pid) -> bool:
    if not isinstance(pid, int) or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True             # exists, just not ours
    except OSError:
        return False
    return True


def liveness(doc: Optional[dict],
             now: Optional[float] = None) -> str:
    """``LIVE`` / ``STALE`` / ``DEAD`` / ``UNKNOWN`` from the doc alone
    — DEAD means the writing pid is gone (the SIGKILL case), STALE
    means the pid exists but stopped refreshing the doc."""
    if not doc:
        return "UNKNOWN"
    if not pid_alive(doc.get("pid")):
        return "DEAD"
    written = doc.get("written_at")
    if not isinstance(written, (int, float)) or isinstance(written, bool):
        return "STALE"
    interval = doc.get("interval_s")
    if not isinstance(interval, (int, float)) or interval <= 0:
        interval = DEFAULT_STATUS_S
    age = (time.time() if now is None else now) - written
    return "LIVE" if age <= max(STALE_INTERVALS * interval, 5.0) \
        else "STALE"


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def _dir_counts(spool: str) -> Dict[str, int]:
    out = {}
    for d in SPOOL_STATE_DIRS:
        try:
            out[d] = sum(1 for n in os.listdir(os.path.join(spool, d))
                         if n.endswith(".json"))
        except OSError:
            out[d] = 0
    return out


def _series_tail(spool: str) -> Optional[dict]:
    """The last sample of the spool's series, reduced to the headline
    gauges — what a crashed spool still knows about its final seconds
    even when ``status.json`` never got written."""
    from ..obs import series

    _, rows = series.read_series(os.path.join(spool, "series.jsonl"))
    if not rows:
        return None
    last = rows[-1]
    gauges = (last.get("metrics") or {}).get("gauges") or {}
    tail = {"t": last.get("t"), "rows": len(rows),
            "dropped": last.get("dropped", 0)}
    for g in ("serve_backlog", "serve_inflight", "overload_level",
              "rss_mb"):
        if g in gauges:
            tail[g] = gauges[g]
    return tail


def collect_status(spool: str) -> dict:
    """Everything the CLI views render, joined from durable artifacts
    only: the status doc + liveness verdict, the boot receipt
    (``serving.json``), the latest SLO report (exit doc or checkpoint),
    spool dir counts, and the series tail."""
    from .server import SLO_REPORT_FILE

    doc = read_status(spool)
    return {"spool": os.path.abspath(spool),
            "status": doc,
            "liveness": liveness(doc),
            "serving": _read_json(os.path.join(spool,
                                               jobspec.SERVING_MARKER)),
            "report": _read_json(os.path.join(spool, SLO_REPORT_FILE)),
            "counts": _dir_counts(spool),
            "series": _series_tail(spool)}


# ---------------------------------------------------------------------------
# rendering (adam-tpu status / top)
# ---------------------------------------------------------------------------

def _fmt_pct(t: dict, key: str) -> str:
    d = t.get(key)
    if not isinstance(d, dict):
        return "-"
    return f"{d.get('p50', 0):.3f}/{d.get('p99', 0):.3f}"


def _tenant_rows(tenants: Dict[str, dict]) -> List[str]:
    lines = ["  tenant            queued  jobs  queue p50/p99     "
             "service p50/p99   miss  rej"]
    for name in sorted(tenants):
        t = tenants[name] or {}
        lines.append(
            f"  {name:<17} {t.get('queued', 0):>6}  "
            f"{t.get('jobs', 0):>4}  {_fmt_pct(t, 'queue_s'):<17} "
            f"{_fmt_pct(t, 'service_s'):<17} "
            f"{t.get('deadline_missed', 0):>4}  "
            f"{t.get('rejected', 0):>3}")
    return lines


def render_status(view: dict) -> str:
    """The human one-shot view — every number traceable to a durable
    doc field (docs/OBSERVABILITY.md)."""
    doc = view.get("status") or {}
    live = view.get("liveness", "UNKNOWN")
    lines = [f"spool: {view.get('spool')}"]
    mode = doc.get("mode", "?")
    pid = doc.get("pid", "?")
    head = f"state: {live}  mode: {mode}  pid: {pid}"
    if isinstance(doc.get("written_at"), (int, float)):
        head += f"  status_age: {time.time() - doc['written_at']:.1f}s"
    lines.append(head)
    if not doc:
        lines.append("  (no status.json — server never ticked; "
                     "showing spool artifacts only)")
    else:
        ov = doc.get("overload") or {}
        lines.append(
            f"warm: {doc.get('warm')}  jobs_served: "
            f"{doc.get('jobs_served', 0)}  backlog: "
            f"{doc.get('backlog', 0)}  rung: "
            f"{ov.get('state', 'normal')}({ov.get('level', 0)})  "
            f"rss_mb: {round(doc.get('rss_mb') or 0, 1)}")
        brk = doc.get("breakers") or {}
        open_b = {k: v for k, v in brk.items() if v != "closed"}
        if open_b:
            lines.append("breakers: " + ", ".join(
                f"{k}={v}" for k, v in sorted(open_b.items())))
        tenants = doc.get("tenants") or {}
        if tenants:
            lines.extend(_tenant_rows(tenants))
        workers = doc.get("workers")
        if isinstance(workers, list):
            lines.append("  worker  alive  inc  restarts  lease_age  "
                         "queued  running  active")
            for w in workers:
                act = ",".join(w.get("active") or []) or "-"
                lease = w.get("lease_age_s")
                lease_s = f"{lease:.1f}s" if isinstance(
                    lease, (int, float)) else "-"
                lines.append(
                    f"  {str(w.get('worker', '?')):<6}  "
                    f"{str(bool(w.get('alive'))):<5}  "
                    f"{w.get('incarnation', 0):>3}  "
                    f"{w.get('restarts', 0):>8}  {lease_s:>9}  "
                    f"{w.get('queued', 0):>6}  "
                    f"{w.get('running', 0):>7}  {act}")
    counts = view.get("counts") or {}
    lines.append("spool: " + "  ".join(
        f"{d}={counts.get(d, 0)}" for d in SPOOL_STATE_DIRS))
    tail = view.get("series")
    if tail:
        age = time.time() - tail["t"] if isinstance(
            tail.get("t"), (int, float)) else float("nan")
        lines.append(
            f"series: {tail['rows']} row(s), last {age:.1f}s ago"
            + (f", dropped {tail['dropped']}" if tail.get("dropped")
               else ""))
    rep = view.get("report")
    if rep:
        lines.append(f"report: jobs={rep.get('jobs', 0)} "
                     f"hosts={rep.get('hosts', 0)} "
                     f"tenants={len(rep.get('tenants') or {})}")
    return "\n".join(lines)
