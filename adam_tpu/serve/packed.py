"""Cross-tenant shared dispatches: many tenants, one wire buffer.

The PR 8 ragged flagstat concat (docs/ARCHITECTURE.md §6g) packs one
run's variable-length chunks into a fixed-capacity buffer with a
positional row bound; this module is that buffer opened to the request
stream: the capacity slack a lone job would waste is filled with the
NEXT tenant's rows, and a segment prefix sum (the row-offset convention,
one live range per tenant run) keeps the per-tenant counters separable —
``ops/flagstat.flagstat_kernel_wire32_segmented`` folds every tenant's
[18, 2] block from ONE dispatch, the way ragged paged attention packs
variable-length requests into shared TPU dispatches (PAPERS.md,
arXiv:2604.15464).

Byte-identity is structural: the segmented kernel shares
``indicator_masks`` with the solo kernels and sums exact int32
contributions per segment, so a tenant's counters folded across shared
buffers equal its solo run bit-for-bit regardless of how jobs interleave
(tests/test_serve.py pins the matrix).

Isolation: while a tenant's chunks are being decoded and packed, the
fault plane is scoped to that tenant (``faults.set_tenant``); the shared
dispatch itself runs unscoped — and if it fails past the retry ladder,
:class:`SharedDispatchError` tells the server to degrade the group to
solo runs (exact monoid: a re-stream cannot change bytes), so one bad
shared dispatch never takes down the tenants riding in it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..resilience import faults


class SharedDispatchError(RuntimeError):
    """A shared (multi-tenant) dispatch failed past the retry ladder;
    carries the original error.  The server's response is degradation,
    not failure: re-run each member solo."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(f"shared dispatch failed: "
                         f"{type(cause).__name__}: {cause}")


def packed_flagstat(specs: List[dict], *, chunk_rows: int = 1 << 22,
                    pack_segments: int = 8,
                    executor_opts: Optional[dict] = None,
                    pool_holder: Optional[dict] = None,
                    wire_cache=None
                    ) -> Tuple[Dict[str, Tuple[object, object]],
                               Dict[str, dict]]:
    """Run N flagstat jobs through shared fixed-capacity dispatches.

    ``specs``: canonical job specs (jobspec.canon_spec) in admission
    order.  Returns ``(results, stats)``: ``results[job_id]`` is the
    exact ``(failed, passed)`` pair ``streaming_flagstat`` returns per
    job, ``stats[job_id]`` carries that job's ``rows`` and its OWN
    ``dropped`` malformed-record count (ingest is sequential per job,
    so the delta brackets attribute drops to the tenant that owns them
    — the per-tenant accounting contract).  One buffer capacity (the
    executor plan's chunk_rows) and one segment width = ONE compiled
    shape for the whole serve lifetime.

    Under the PAGED layout (``-paged``/``ADAM_TPU_PAGED``,
    docs/ARCHITECTURE.md §6l) the shared buffer becomes page-RESIDENT
    continuous batching: tenants' rows land in free pages of one
    persistent device pool, only the live pages of each round cross the
    link (the unpaged path re-ships the full capacity, slack included),
    the segmented kernel reads the page table, and a flushed round
    frees its pages for the next tenant without touching neighbors.
    ``pool_holder`` (the server's cross-round dict) keeps the pool
    resident across packed_flagstat calls — the steady state where
    host→device transfer between dispatches is only ever new rows.

    ``wire_cache`` (the server's cross-round
    :class:`.wirecache.WireChunkCache`) makes each tenant input's wire
    pack once-per-round: a degrade-to-solo re-run, a duplicate job on
    the same input, or the s2 count pass replaying the same round's
    planes hits the packed host chunks instead of re-decoding the file.
    """
    import jax
    import jax.numpy as jnp

    from ..errors import malformed_count
    from ..ops.flagstat import (FlagStatMetrics,
                                flagstat_kernel_wire32_segmented,
                                flagstat_kernel_wire32_segmented_paged)
    from ..parallel.executor import StreamExecutor
    from ..parallel.pagedbuf import PagePool
    from ..parallel.pipeline import flagstat_wire_chunks

    ex = StreamExecutor(1, chunk_rows, **(executor_opts or {}))
    # the shared buffer is its own pass: one frozen plan, one
    # executor_bucket_selected event, one compiled (capacity, S) shape
    pex = ex.begin_pass("serve_pack", bytes_per_row=4.0,
                        paged_capable=True)
    cap = pex.chunk_rows
    n_seg = max(int(pack_segments), 2)
    paged = pex.layout == "paged"
    pool = None
    table_len = 0
    if paged:
        holder = pool_holder if pool_holder is not None else {}
        pool = holder.get("serve_pack")
        if pool is None or pool.page_rows != pex.page_rows or \
                pool.pool_pages < cap // pex.page_rows + 1:
            pool = holder["serve_pack"] = PagePool(
                "serve_pack", max(pex.pool_pages,
                                  cap // pex.page_rows + 1),
                pex.page_rows, planes=(("wire", np.uint32),))
        pool.bind(pex.dispatch_put)
        table_len = cap // pool.page_rows

    totals = {s["job_id"]: np.zeros((18, 2), np.int64) for s in specs}
    stats = {s["job_id"]: {"rows": 0, "dropped": 0} for s in specs}

    def _host_counts(buf, bounds):
        # degraded CPU fallback for ONE buffer: same exact integer
        # kernel on the CPU backend (the solo path's discipline)
        with jax.default_device(jax.devices("cpu")[0]):
            return np.asarray(flagstat_kernel_wire32_segmented(
                jnp.asarray(buf), jnp.asarray(bounds)))

    shipped: List[int] = []     # paged: page ids shipped this round,
    #                             in logical (fill) order

    def _ship_upto(have: int, final: bool = False) -> None:
        """Paged: ship every full page of the host mirror up to
        ``have`` (and the partial tail page when ``final``) into free
        pool pages — new rows cross the link AS THEY ARRIVE, page by
        page, mid-stream; nothing re-ships at flush time."""
        # page writes are SHARED infrastructure (like the unpaged
        # flush transfer): a tenant-scoped fault must not fire on a
        # write its neighbors ride in
        prev = faults.current_tenant()
        faults.set_tenant(None)
        try:
            while True:
                n = have // pool.page_rows - len(shipped)
                if n <= 0:
                    # the partial tail ships one whole page at flush;
                    # rows past the bound are garbage the segmented
                    # fold never reads
                    if not (final and
                            len(shipped) * pool.page_rows < have):
                        break
                    n = 1
                ids = pool.alloc(n)
                if ids is None:     # misconfigured pool: the server
                    #                 degrades the group to solo runs
                    raise SharedDispatchError(RuntimeError(
                        "page pool exhausted mid-round"))
                lo = len(shipped) * pool.page_rows
                try:
                    pool.write(ids,
                               wire=buf[lo:lo + n * pool.page_rows])
                except BaseException:
                    # a failed write must not leak pages from the
                    # server's CROSS-ROUND pool (it is never resized on
                    # free count — a leak would shrink packed capacity
                    # for the server's remaining lifetime)
                    pool.free(ids)
                    raise
                shipped.extend(ids)
        finally:
            faults.set_tenant(prev)

    def _flush(buf, segments):
        """Dispatch one filled buffer; fold each segment's [18, 2] block
        into its job's totals.  ``segments``: [(job_id, rows), ...] in
        fill order."""
        if not segments:
            return
        counts = np.cumsum([0] + [r for _, r in segments])
        live = int(counts[-1])
        bounds = np.full(n_seg + 1, live, np.int32)
        bounds[:len(counts)] = counts.astype(np.int32)
        # tenants share the dispatch; a tenant-scoped fault must not
        # fire here (it would hit its neighbors) — the server scopes
        # ingest, the dispatch runs unscoped
        prev = faults.current_tenant()
        faults.set_tenant(None)
        try:
            pex.note_ragged(live, cap)
            bounds_dev = jnp.asarray(bounds)
            n_pages = 0
            if paged:
                _ship_upto(live, final=True)
                n_pages = len(shipped)
                ptable = pool.table(shipped, table_len)
                counts_dev = pex.dispatch(
                    "pack-count",
                    lambda attempt, tab=ptable, host=buf, b=bounds_dev:
                        flagstat_kernel_wire32_segmented_paged(
                            pool.device("wire"), jnp.asarray(tab), b)
                        if attempt == 1 else
                        flagstat_kernel_wire32_segmented(
                            jnp.asarray(host), b),
                    fallback=lambda e, host=buf, b=bounds:
                        _host_counts(host, b))
            else:
                dev = pex.dispatch_put(
                    "pack-wire", lambda attempt: jax.device_put(buf),
                    nbytes=buf.nbytes)
                counts_dev = pex.dispatch(
                    "pack-count",
                    lambda attempt, dev=dev, host=buf, b=bounds_dev:
                        flagstat_kernel_wire32_segmented(
                            dev if attempt == 1 else jnp.asarray(host),
                            b),
                    fallback=lambda e, host=buf, b=bounds:
                        _host_counts(host, b))
            out = np.asarray(counts_dev).astype(np.int64)
        except SharedDispatchError:
            raise
        except Exception as e:  # noqa: BLE001 — the server degrades
            raise SharedDispatchError(e) from e
        finally:
            faults.set_tenant(prev)
            if paged and shipped:
                # the flushed round's rows are consumed: its pages free
                # for the NEXT tenant without touching neighbors (the
                # dispatch is already enqueued — single-stream FIFO
                # orders any recycling scatter after the fold)
                pool.free(shipped)
                shipped.clear()
        for s, (job_id, rows) in enumerate(segments):
            totals[job_id] += out[s]
        obs.chunk_processed("serve_pack", live, bytes_in=4 * live)
        fields = dict(capacity=int(cap), live_rows=live,
                      segments=len(segments),
                      jobs=sorted({j for j, _ in segments}))
        if paged:
            fields.update(paged=True, pages=n_pages)
        obs.emit("serve_pack_dispatch", **fields)

    # sequential fill in admission order: job j's tail shares its last
    # buffer with job j+1's head — the capacity slack IS the next
    # tenant's admission ticket
    buf = np.empty(cap, np.uint32)      # slack past the bound is
    #                                     positionally dead (never read)
    have = 0
    segments: List[Tuple[str, int]] = []

    def _seg_add(job_id: str, rows: int) -> None:
        if segments and segments[-1][0] == job_id:
            segments[-1] = (job_id, segments[-1][1] + rows)
        else:
            segments.append((job_id, rows))

    def _ingest_all() -> None:
        nonlocal buf, have, segments
        for spec in specs:
            job_id = spec["job_id"]
            with obs.trace.span(f"tenant:{spec['tenant']}:{job_id}",
                                cat="serve"):
                faults.set_tenant(spec["tenant"])
                dropped0 = malformed_count()
                try:
                    chunks = flagstat_wire_chunks(
                        spec["input"], chunk_rows=cap,
                        io_procs=int(spec["args"].get("io_procs", 1)),
                        wire_cache=wire_cache)
                    for w in chunks:
                        w = np.asarray(w, np.uint32)
                        stats[job_id]["rows"] += int(w.size)
                        while w.size:
                            # a full segment table flushes early even
                            # with row capacity left: S is a compiled
                            # constant
                            if have == cap or \
                                    (len(segments) == n_seg and
                                     segments[-1][0] != job_id):
                                _flush(buf, segments)
                                buf = np.empty(cap, np.uint32)
                                have, segments = 0, []
                            take = min(cap - have, int(w.size))
                            buf[have:have + take] = w[:take]
                            _seg_add(job_id, take)
                            have += take
                            w = w[take:]
                            if paged:
                                # continuous batching: this tenant's
                                # rows land in free pages AS THEY
                                # ARRIVE — the flush dispatches
                                # resident pages, it does not transfer
                                # them
                                _ship_upto(have)
                            if have == cap:
                                _flush(buf, segments)
                                buf = np.empty(cap, np.uint32)
                                have, segments = 0, []
                finally:
                    faults.set_tenant(None)
                    stats[job_id]["dropped"] = \
                        malformed_count() - dropped0
        if segments:
            _flush(buf, segments)

    try:
        _ingest_all()
    finally:
        if paged and shipped:
            # an error path left pages allocated: release them so the
            # server's persistent pool serves the next round at full
            # capacity (the degrade-to-solo path re-streams anyway)
            pool.free(shipped)
            shipped.clear()
    ex.finish()

    out: Dict[str, Tuple[object, object]] = {}
    for spec in specs:
        t = totals[spec["job_id"]]
        out[spec["job_id"]] = (FlagStatMetrics.from_counters(t[:, 1]),
                               FlagStatMetrics.from_counters(t[:, 0]))
    return out, stats
