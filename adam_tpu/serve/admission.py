"""The pure admission/batching controller — the autotuner grown into a
scheduler.

Each serve round, the server snapshots its queue and asks ONE pure
function which jobs run now, which of them share dispatches, and — the
overload half (docs/ARCHITECTURE.md §6m) — which are shed before they
ever occupy a warm worker:

* **FIFO admission** bounded by ``max_concurrent`` — submit order is the
  default fairness story (no clocks, no sizes-as-priorities);
* **deficit-round-robin across tenants** (``fair=True``, the serve
  default) — a burst tenant's 50-job backlog no longer starves the
  steady tenant behind it: tenants take turns (quantum = one job per
  tenant per cycle, the DRR special case where every job costs one
  slot), ordered by each tenant's earliest queued seq so the
  interleave is deterministic and replayable;
* **bounded admission** — ``backlog_cap`` caps the total queue a round
  will retain and ``tenant_quota`` caps one tenant's queued share;
  everything past a cap is REJECTED with a typed, durable
  ``rejected/<job>.json`` carrying ``retry_after_s`` (never a silent
  drop, never a torn spool), and ``tenant_slots`` caps one tenant's
  admissions per round (the in-flight quota — over-slots jobs simply
  wait, they are not shed);
* **deadlines** — a queued job whose recorded wait exceeds its spec's
  ``deadline_s`` is CANCELLED (typed ``DeadlineExceeded`` failure doc)
  instead of wasting a warm dispatch on a result nobody is waiting
  for;
* **brownout shedding** — ``overload_level`` (serve/overload.py's pure
  ladder) >= 2 rejects queued low-priority work, >= 3 rejects all
  queued work; level 1 (cheaper rounds) is applied by the CALLER
  passing ``pack=False``, so the recorded inputs show exactly what the
  round did;
* **cross-tenant pack groups** — admitted flagstat jobs co-dispatch
  through the shared fixed-capacity wire buffer (serve/packed.py), at
  most ``pack_segments`` tenants per group.

:func:`decide_admission` follows the ``decide_plan`` convention
(parallel/executor.py): PURE, canonicalized inputs recorded verbatim in
the ``admission_selected`` event plus their digest, replayed offline by
tools/check_executor.py.  Every overload-era input joins the recorded
``inputs`` ONLY when engaged (the tenant/shard-scoping precedent in
resilience.faults), so pre-overload sidecars replay digest-identical.
The queue snapshot it decides from carries only (job_id, tenant,
command, seq) plus — only when set — (priority, deadline_s, wait_s);
admission never reads a byte of input data, so the decision is cheap
and the replay needs no files.
"""

from __future__ import annotations

import hashlib
import json
from typing import Iterable

#: compiled segment width of the shared flagstat dispatch buffer — the
#: segmented kernel (ops/flagstat.flagstat_kernel_wire32_segmented)
#: compiles per (capacity, S), so the server pads every group to this
DEFAULT_PACK_SEGMENTS = 8

#: commands the shared-dispatch packer can co-schedule (transform runs
#: a multi-pass dataflow with its own spills — it multiplexes between
#: jobs, not inside a dispatch)
PACKABLE_COMMANDS = ("flagstat",)

#: typed rejection codes (the ``code`` field of ``rejected/<job>.json``
#: and the ``admission_rejected`` event) with their ``retry_after_s``
#: floors — each a pure function of the decision inputs below
REJECT_CODES = ("over_backlog", "tenant_quota", "brownout_low",
                "brownout_all")

#: retry_after_s bounds: deterministic, pure, and bounded — a client
#: must never be told to wait forever, and the hint scales with how
#: far over the cap the queue sits so a storm naturally spreads out
RETRY_AFTER_MIN_S = 1.0
RETRY_AFTER_MAX_S = 30.0


def _retry_after(code: str, excess: int) -> float:
    """Pure ``retry_after_s`` hint for one rejection: scales with how
    deep past the cap the queue sits (``excess`` = position beyond the
    cap, 1-based), clipped to [RETRY_AFTER_MIN_S, RETRY_AFTER_MAX_S]."""
    base = {"over_backlog": 1.0, "tenant_quota": 2.0,
            "brownout_low": 5.0, "brownout_all": 10.0}[code]
    return round(min(max(base + 0.5 * max(excess - 1, 0),
                         RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S), 3)


def _drr_order(jobs: list, slots: int, tenant_slots: int) -> list:
    """Deficit-round-robin interleave: tenants (ordered by earliest
    queued seq) take turns releasing their next job in seq order —
    quantum one job per tenant per cycle, the DRR special case where
    every job costs one admission slot.  ``tenant_slots`` > 0 caps one
    tenant's take per round (the in-flight quota)."""
    order: list = []
    per: dict = {}
    for q in jobs:                 # jobs arrive seq-sorted, so first
        t = q["tenant"]            # sighting order == earliest-seq order
        if t not in per:
            per[t] = []
            order.append(t)
        per[t].append(q)
    admit: list = []
    idx = {t: 0 for t in order}
    taken = {t: 0 for t in order}
    while len(admit) < slots:
        progressed = False
        for t in order:
            if len(admit) >= slots:
                break
            if tenant_slots and taken[t] >= tenant_slots:
                continue
            if idx[t] < len(per[t]):
                admit.append(per[t][idx[t]])
                idx[t] += 1
                taken[t] += 1
                progressed = True
        if not progressed:
            break
    return admit


def decide_admission(*, queued: Iterable[dict], running: int,
                     max_concurrent: int, pack: bool = True,
                     pack_segments: int = DEFAULT_PACK_SEGMENTS,
                     fair: bool = False, backlog_cap: int = 0,
                     tenant_quota: int = 0, tenant_slots: int = 0,
                     overload_level: int = 0) -> dict:
    """One serve round's admission plan — PURE.

    ``queued``: compact descriptors ``{"job_id", "tenant", "command",
    "seq"}`` (any order; canonicalization sorts by ``seq``), each
    optionally carrying ``priority`` (recorded only when not
    ``"normal"``) and ``deadline_s`` + ``wait_s`` (recorded only when
    the spec set a deadline; ``wait_s`` is the caller's measured
    submit→now wait — the one clock read, taken at the impure boundary
    and recorded so the replay is exact).  ``running``: jobs already
    executing (occupied slots).  Returns::

        {"admit": [job_id, ...],          # start these, in order
         "pack_groups": [[job_id, ...]],  # co-dispatched subsets
         "cancel": [{job_id, tenant, wait_s, deadline_s}, ...],
         "reject": [{job_id, tenant, code, retry_after_s}, ...],
         "reason": str,
         "inputs": {...}, "input_digest": hex}

    ``cancel``/``reject`` list the jobs to retire from the queue with
    typed docs BEFORE any admission happens (a cancelled or rejected
    job never occupies a slot); both keys are present only when
    non-empty, and every overload-era keyword joins the recorded
    ``inputs`` only when engaged — with the defaults this function is
    bit-for-bit the pre-overload FIFO decider, so old sidecars replay
    digest-identical.  Every ``pack_groups`` member also appears in
    ``admit``; groups hold >= 2 jobs (singletons run solo).
    """
    canon = []
    for q in queued:
        c = dict(job_id=str(q["job_id"]), tenant=str(q["tenant"]),
                 command=str(q["command"]), seq=int(q["seq"]))
        # only-when-set: a descriptor without a deadline or a
        # non-default priority canonicalizes exactly as it always did
        if q.get("priority") not in (None, "normal"):
            c["priority"] = str(q["priority"])
        if q.get("deadline_s") is not None:
            c["deadline_s"] = round(float(q["deadline_s"]), 3)
            c["wait_s"] = round(float(q.get("wait_s") or 0.0), 3)
        canon.append(c)
    canon.sort(key=lambda q: q["seq"])
    inputs = dict(queued=canon, running=int(running),
                  max_concurrent=int(max_concurrent), pack=bool(pack),
                  pack_segments=int(pack_segments))
    # only-when-engaged: pre-overload sidecars must digest identically
    if fair:
        inputs["fair"] = True
    if backlog_cap:
        inputs["backlog_cap"] = int(backlog_cap)
    if tenant_quota:
        inputs["tenant_quota"] = int(tenant_quota)
    if tenant_slots:
        inputs["tenant_slots"] = int(tenant_slots)
    if overload_level:
        inputs["overload_level"] = int(overload_level)

    reasons = []
    remaining = list(canon)

    # 1. deadlines: a job that already waited past its deadline is
    # cancelled, never dispatched
    cancel = [dict(job_id=q["job_id"], tenant=q["tenant"],
                   wait_s=q["wait_s"], deadline_s=q["deadline_s"])
              for q in remaining
              if "deadline_s" in q and q["wait_s"] > q["deadline_s"]]
    if cancel:
        gone = {c["job_id"] for c in cancel}
        remaining = [q for q in remaining if q["job_id"] not in gone]
        reasons.append(f"cancelled {len(cancel)} past-deadline job(s)")

    # 2. shedding, harshest rung first: brownout-all > brownout-low >
    # tenant quota > backlog cap
    reject: list = []

    def _shed(job, code, excess):
        reject.append(dict(job_id=job["job_id"], tenant=job["tenant"],
                           code=code,
                           retry_after_s=_retry_after(code, excess)))

    lvl = inputs.get("overload_level", 0)
    if lvl >= 3:
        for k, q in enumerate(remaining):
            _shed(q, "brownout_all", k + 1)
        remaining = []
    elif lvl >= 2:
        keep = []
        shed_n = 0
        for q in remaining:
            if q.get("priority") == "low":
                shed_n += 1
                _shed(q, "brownout_low", shed_n)
            else:
                keep.append(q)
        remaining = keep
    quota = inputs.get("tenant_quota", 0)
    if quota:
        seen: dict = {}
        keep = []
        for q in remaining:
            n = seen.get(q["tenant"], 0) + 1
            seen[q["tenant"]] = n
            if n > quota:
                _shed(q, "tenant_quota", n - quota)
            else:
                keep.append(q)
        remaining = keep
    cap = inputs.get("backlog_cap", 0)
    if cap and len(remaining) > cap:
        if inputs.get("fair"):
            # retain the capped backlog in DRR order, not seq order: a
            # pure-FIFO cut would hand a burst tenant every retained
            # slot and convert the steady tenant's new jobs into 100%
            # typed rejections — the exact starvation the fairness
            # rung exists to prevent, made worse
            keep_ids = {q["job_id"]
                        for q in _drr_order(remaining, cap, 0)}
        else:
            keep_ids = {q["job_id"] for q in remaining[:cap]}
        shed_n = 0
        keep = []
        for q in remaining:
            if q["job_id"] in keep_ids:
                keep.append(q)
            else:
                shed_n += 1
                _shed(q, "over_backlog", shed_n)
        remaining = keep
    if reject:
        reasons.append(f"rejected {len(reject)} job(s) "
                       f"({'+'.join(sorted({r['code'] for r in reject}))})")

    # 3. admission into the free slots: DRR interleave when fair,
    # plain FIFO otherwise (the pre-overload behavior, bit-for-bit);
    # the per-round tenant cap applies to BOTH orders — a quota the
    # operator set must never silently depend on the fairness flag
    slots = max(inputs["max_concurrent"] - inputs["running"], 0)
    t_slots = inputs.get("tenant_slots", 0)
    if inputs.get("fair"):
        admitted = _drr_order(remaining, slots, t_slots)
        tenants = len({q["tenant"] for q in remaining})
        reasons.append(f"drr {len(admitted)}/{len(remaining)} queued "
                       f"into {slots} slot(s) across {tenants} "
                       "tenant(s)")
    elif t_slots:
        admitted, taken = [], {}
        for q in remaining:
            if len(admitted) >= slots:
                break
            if taken.get(q["tenant"], 0) >= t_slots:
                continue            # over-slots: waits, not shed
            taken[q["tenant"]] = taken.get(q["tenant"], 0) + 1
            admitted.append(q)
        reasons.append(f"fifo {len(admitted)}/{len(canon)} queued into "
                       f"{slots} slot(s) (tenant_slots {t_slots})")
    else:
        admitted = remaining[:slots]
        reasons.append(f"fifo {len(admitted)}/{len(canon)} queued into "
                       f"{slots} slot(s)")
    admit = [q["job_id"] for q in admitted]

    pack_groups: list = []
    if inputs["pack"]:
        packable = [q["job_id"] for q in admitted
                    if q["command"] in PACKABLE_COMMANDS]
        width = max(inputs["pack_segments"], 2)
        for lo in range(0, len(packable), width):
            group = packable[lo:lo + width]
            if len(group) >= 2:
                pack_groups.append(group)
        if pack_groups:
            reasons.append(
                f"packed {sum(len(g) for g in pack_groups)} flagstat "
                f"job(s) into {len(pack_groups)} shared dispatch "
                f"group(s)")
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    out = dict(admit=admit, pack_groups=pack_groups,
               reason="; ".join(reasons), inputs=inputs,
               input_digest=digest)
    if cancel:
        out["cancel"] = cancel
    if reject:
        out["reject"] = reject
    return out
