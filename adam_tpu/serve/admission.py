"""The pure admission/batching controller — the autotuner grown into a
scheduler.

Each serve round, the server snapshots its queue and asks ONE pure
function which jobs run now and which of them share dispatches:

* **FIFO admission** bounded by ``max_concurrent`` — submit order is the
  only fairness story that is both starvation-free and replayable (no
  clocks, no sizes-as-priorities that would let a huge tenant starve a
  small one at decision time);
* **cross-tenant pack groups** — admitted flagstat jobs co-dispatch
  through the shared fixed-capacity wire buffer (serve/packed.py), at
  most ``pack_segments`` tenants per group (the segmented kernel's
  compiled segment width); a lone flagstat job runs solo, since a
  one-tenant "shared" buffer is just the ragged path with extra steps.

:func:`decide_admission` follows the ``decide_plan`` convention
(parallel/executor.py): PURE, canonicalized inputs recorded verbatim in
the ``admission_selected`` event plus their digest, replayed offline by
tools/check_executor.py.  The queue snapshot it decides from carries
only (job_id, tenant, command, seq) — admission never reads a byte of
input data, so the decision is cheap and the replay needs no files.
"""

from __future__ import annotations

import hashlib
import json
from typing import Iterable

#: compiled segment width of the shared flagstat dispatch buffer — the
#: segmented kernel (ops/flagstat.flagstat_kernel_wire32_segmented)
#: compiles per (capacity, S), so the server pads every group to this
DEFAULT_PACK_SEGMENTS = 8

#: commands the shared-dispatch packer can co-schedule (transform runs
#: a multi-pass dataflow with its own spills — it multiplexes between
#: jobs, not inside a dispatch)
PACKABLE_COMMANDS = ("flagstat",)


def decide_admission(*, queued: Iterable[dict], running: int,
                     max_concurrent: int, pack: bool = True,
                     pack_segments: int = DEFAULT_PACK_SEGMENTS) -> dict:
    """One serve round's admission plan — PURE.

    ``queued``: compact descriptors ``{"job_id", "tenant", "command",
    "seq"}`` (any order; canonicalization sorts by ``seq``).
    ``running``: jobs already executing (occupied slots).  Returns::

        {"admit": [job_id, ...],          # start these, in order
         "pack_groups": [[job_id, ...]],  # co-dispatched subsets
         "reason": str,
         "inputs": {...}, "input_digest": hex}

    Every ``pack_groups`` member also appears in ``admit``; groups hold
    >= 2 jobs (singletons run solo).  The recorded inputs replay the
    decision bit-for-bit (tools/check_executor.py).
    """
    canon = sorted((dict(job_id=str(q["job_id"]), tenant=str(q["tenant"]),
                         command=str(q["command"]), seq=int(q["seq"]))
                    for q in queued), key=lambda q: q["seq"])
    inputs = dict(queued=canon, running=int(running),
                  max_concurrent=int(max_concurrent), pack=bool(pack),
                  pack_segments=int(pack_segments))
    slots = max(inputs["max_concurrent"] - inputs["running"], 0)
    admitted = inputs["queued"][:slots]
    admit = [q["job_id"] for q in admitted]
    reasons = [f"fifo {len(admit)}/{len(canon)} queued into "
               f"{slots} slot(s)"]
    pack_groups: list = []
    if inputs["pack"]:
        packable = [q["job_id"] for q in admitted
                    if q["command"] in PACKABLE_COMMANDS]
        width = max(inputs["pack_segments"], 2)
        for lo in range(0, len(packable), width):
            group = packable[lo:lo + width]
            if len(group) >= 2:
                pack_groups.append(group)
        if pack_groups:
            reasons.append(
                f"packed {sum(len(g) for g in pack_groups)} flagstat "
                f"job(s) into {len(pack_groups)} shared dispatch "
                f"group(s)")
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return dict(admit=admit, pack_groups=pack_groups,
                reason="; ".join(reasons), inputs=inputs,
                input_digest=digest)
