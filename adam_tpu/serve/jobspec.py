"""Filesystem job-spec queue for the serve front-end.

The transport is deliberately the dumbest durable thing that works
everywhere the CLI works: a spool directory of JSON files.  Submission
is atomic (write tmp, hard-link into the queue — a name collision loses
the race and retries the next sequence number), results are atomic
(tmp+rename, the sidecar discipline), and a server crash loses nothing:
jobs found under ``running/`` at boot re-queue, because every job is a
pure function of its spec (the streaming commands it wraps are
idempotent over their inputs and rewrite their outputs whole).

Spool layout::

    SPOOL/queue/<seq>-<job_id>.json    submitted, waiting
    SPOOL/running/<seq>-<job_id>.json  claimed by the server
    SPOOL/done/<job_id>.json           result document (ok)
    SPOOL/failed/<job_id>.json         result document (typed failure)
    SPOOL/rejected/<job_id>.json       typed admission rejection
                                       (over-quota / brownout shed;
                                       carries ``retry_after_s``)
    SPOOL/serving.json                 server boot receipt (pid + warmup)
    SPOOL/stop                         sentinel: drain and exit

Job spec (canonicalized by :func:`canon_spec`)::

    {"job_id": str, "tenant": str,
     "command": "flagstat" | "transform" | "call",
     "input": str, "output": str | null, "args": {...},
     "priority": "low" | "normal" | "high",   # admission shed order
     "deadline_s": float | null}              # cancel if queued longer

``args`` forwards a whitelisted subset of the underlying streaming
call's keywords (:data:`FLAGSTAT_ARGS` / :data:`TRANSFORM_ARGS`) — the
server, not the client, owns executor shape knobs, so every tenant's
jobs land on the one canonical shape ladder and cross-job compile-cache
hits are structural.
"""

from __future__ import annotations

import json
import os
import re
from typing import Iterator, Optional, Tuple

from ..checkpoint import atomic_write

QUEUE, RUNNING, DONE, FAILED = "queue", "running", "done", "failed"
#: typed admission rejections (over-quota / brownout shed) — a result
#: class of its own so a rejected job is never confused with a job that
#: RAN and failed; docs carry ``retry_after_s`` and clients (``adam-tpu
#: submit -wait``) may transparently resubmit once after that delay
REJECTED = "rejected"
STOP_SENTINEL = "stop"
SERVING_MARKER = "serving.json"

#: which claimed job(s) the server is EXECUTING right now (a claimed
#: batch sits in ``running/`` while the loop works through it one
#: entry at a time) — the fleet scheduler's kill-attribution boundary:
#: a worker death charges only the jobs named here; claimed-but-waiting
#: jobs requeue innocently (serve/scheduler.py, the poison ladder)
ACTIVE_MARKER = "active.json"

COMMANDS = ("flagstat", "transform", "flagstat_range", "call")

#: per-command arg whitelists — the spec's ``args`` may set only these
#: (anything else is a validation error, not a silent drop)
FLAGSTAT_ARGS = ("io_procs",)
TRANSFORM_ARGS = ("markdup", "bqsr", "dbsnp_sites", "realign", "sort",
                  "io_procs", "io_threads")
#: the variant-calling workload (call/pipeline.streaming_call): knob
#: args only — the plan knobs ride the spec so ``decide_call_plan``
#: runs server-side with the tenant's explicit values, while executor
#: shape knobs stay server-owned like every other command
CALL_ARGS = ("io_procs", "stripe_span", "min_depth", "min_alt",
             "sample")
#: ``flagstat_range`` is the fleet scheduler's shard sub-job (one unit
#: range of a big input; serve/scheduler.py sums the exact counter
#: monoid back into the parent's report) — first-class in the spool so
#: sub-jobs requeue/steal/quarantine through the same machinery
FLAGSTAT_RANGE_ARGS = ("io_procs", "unit_lo", "unit_hi", "unit_rows")

_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,80}$")
_NAME_RE = re.compile(r"^(\d{8,})-(.+)\.json$")

#: high-water sequence hint, max-merged on every successful submit so
#: enqueueing stays O(in-flight), not O(every job ever served) — the
#: hard-link race below is what actually guarantees uniqueness
_SEQ_FILE = ".seq"


#: which priorities a spec may carry; the brownout ladder's level-2
#: rung sheds ``low`` first (serve/overload.py)
PRIORITIES = ("low", "normal", "high")


def spool_dirs(spool: str) -> Tuple[str, ...]:
    return tuple(os.path.join(spool, d)
                 for d in (QUEUE, RUNNING, DONE, FAILED, REJECTED))


def ensure_spool(spool: str) -> str:
    for d in spool_dirs(spool):
        os.makedirs(d, exist_ok=True)
    return spool


def canon_spec(spec: dict) -> dict:
    """Validate + canonicalize one job spec (what queue files hold and
    what results echo back).  Raises ``ValueError`` on anything a server
    round could not execute — bad submissions fail at submit time, on
    the client, never inside the serve loop."""
    if not isinstance(spec, dict):
        raise ValueError("job spec must be a JSON object")
    cmd = spec.get("command")
    if cmd not in COMMANDS:
        raise ValueError(f"job spec: unknown command {cmd!r} "
                         f"(want one of {', '.join(COMMANDS)})")
    tenant = spec.get("tenant", "default")
    if not (isinstance(tenant, str) and _ID_RE.match(tenant)):
        raise ValueError(f"job spec: bad tenant {tenant!r} "
                         "(want [A-Za-z0-9._-]{1,80})")
    job_id = spec.get("job_id")
    if job_id is not None and not (isinstance(job_id, str)
                                   and _ID_RE.match(job_id)):
        raise ValueError(f"job spec: bad job_id {job_id!r}")
    inp = spec.get("input")
    if not (isinstance(inp, str) and inp):
        raise ValueError("job spec: missing input path")
    output = spec.get("output")
    if cmd in ("transform", "call"):
        if not (isinstance(output, str) and output):
            raise ValueError(f"job spec: {cmd} needs an output path")
    elif output is not None:
        raise ValueError(f"job spec: {cmd} takes no output path")
    args = spec.get("args") or {}
    if not isinstance(args, dict):
        raise ValueError("job spec: args must be an object")
    allowed = {"flagstat": FLAGSTAT_ARGS, "transform": TRANSFORM_ARGS,
               "flagstat_range": FLAGSTAT_RANGE_ARGS,
               "call": CALL_ARGS}[cmd]
    unknown = sorted(set(args) - set(allowed))
    if unknown:
        raise ValueError(f"job spec: unknown {cmd} args {unknown} "
                         f"(allowed: {', '.join(allowed)})")
    if cmd == "flagstat_range":
        # the range args are REQUIRED, not merely allowed — a spec
        # missing them would otherwise detonate inside the serve loop
        # instead of failing itself at validation time
        for field in ("unit_lo", "unit_hi", "unit_rows"):
            v = args.get(field)
            if not (isinstance(v, int) and not isinstance(v, bool)
                    and v >= (1 if field == "unit_rows" else 0)):
                raise ValueError(
                    f"job spec: flagstat_range needs int arg "
                    f"{field!r} (got {v!r})")
    if cmd == "call":
        # knob args, when present, must be positive ints (sample a
        # non-empty string) — a bad knob fails at submit time, never
        # inside the serve loop
        for field in ("io_procs", "stripe_span", "min_depth",
                      "min_alt"):
            v = args.get(field)
            if v is not None and not (isinstance(v, int)
                                      and not isinstance(v, bool)
                                      and v >= 1):
                raise ValueError(
                    f"job spec: call arg {field!r} must be a "
                    f"positive int (got {v!r})")
        sample = args.get("sample")
        if sample is not None and not (isinstance(sample, str)
                                       and sample):
            raise ValueError(
                f"job spec: call arg 'sample' must be a non-empty "
                f"string (got {sample!r})")
    # submit time rides the spec so the server can report queue-wait
    # per tenant; absent/garbage degrades to "unknown", never an error
    sub_at = spec.get("submitted_at")
    sub_at = float(sub_at) if isinstance(sub_at, (int, float)) \
        and not isinstance(sub_at, bool) else None
    priority = spec.get("priority", "normal")
    if priority is None:
        priority = "normal"
    if priority not in PRIORITIES:
        raise ValueError(f"job spec: bad priority {priority!r} "
                         f"(want one of {', '.join(PRIORITIES)})")
    deadline = spec.get("deadline_s")
    if deadline is not None:
        if not (isinstance(deadline, (int, float))
                and not isinstance(deadline, bool) and deadline > 0):
            raise ValueError(f"job spec: deadline_s must be a positive "
                             f"number of seconds (got {deadline!r})")
        deadline = float(deadline)
    return {"job_id": job_id, "tenant": tenant, "command": cmd,
            "input": inp, "output": output, "args": dict(args),
            "submitted_at": sub_at, "priority": priority,
            "deadline_s": deadline}


_AUTO_ID_RE = re.compile(r"^job(\d{8,})\.json$")


def _live_max_seq(spool: str) -> int:
    """Highest sequence among IN-FLIGHT jobs (queue + running names
    carry it as their prefix) — bounded by concurrency, cheap."""
    seq = 0
    for d in (QUEUE, RUNNING):
        try:
            names = os.listdir(os.path.join(spool, d))
        except OSError:
            continue
        for name in names:
            m = _NAME_RE.match(name)
            if m:
                seq = max(seq, int(m.group(1)))
    return seq


def _max_seq(spool: str) -> int:
    """Highest sequence number the spool has EVER assigned: in-flight
    names plus retired auto-id results (``done/jobNNNNNNNN.json``) —
    scanning only the live queue would recycle seq 1 the moment the
    queue drains, and a recycled auto job_id would let a waiting client
    read the PREVIOUS job's result document.  Full-scan fallback for
    spools without a ``.seq`` hint; normal submits read the hint and
    scan only the in-flight dirs."""
    seq = _live_max_seq(spool)
    for d in (DONE, FAILED, REJECTED):
        try:
            names = os.listdir(os.path.join(spool, d))
        except OSError:
            continue
        for name in names:
            m = _AUTO_ID_RE.match(name)
            if m:
                seq = max(seq, int(m.group(1)))
    return seq


def _read_seq_hint(spool: str) -> Optional[int]:
    try:
        with open(os.path.join(spool, _SEQ_FILE)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _write_seq_hint(spool: str, seq: int) -> None:
    """Max-merge the high-water hint (atomic tmp+rename; a racing
    writer can only lose a few numbers, and the hard-link submit race
    re-resolves those — the hint is a scan-avoidance optimization,
    never the uniqueness authority)."""
    try:
        cur = _read_seq_hint(spool) or 0
        path = os.path.join(spool, _SEQ_FILE)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(max(cur, seq)))
        os.replace(tmp, path)
    except OSError:
        pass


def _result_exists(spool: str, job_id: str) -> bool:
    return any(os.path.exists(os.path.join(spool, d, f"{job_id}.json"))
               for d in (DONE, FAILED, REJECTED))


def _id_in_flight(spool: str, job_id: str) -> bool:
    suffix = f"-{job_id}.json"
    for d in (QUEUE, RUNNING):
        try:
            names = os.listdir(os.path.join(spool, d))
        except OSError:
            continue
        if any(n.endswith(suffix) and _NAME_RE.match(n) for n in names):
            return True
    return False


def submit_job(spool: str, spec: dict) -> str:
    """Atomically enqueue one job; returns its ``job_id``.

    The sequence number (submit order — what FIFO admission orders by)
    is high-water+1 — the ``.seq`` hint max-merged with the in-flight
    names (a hintless spool pays one full scan); a concurrent submitter
    that claims the same number loses the hard-link race and retries
    the next one, so two clients can never clobber each other's specs.

    Input/output paths resolve to absolute HERE, on the submitting
    side: the server's cwd is not the client's, and a relative
    ``sample.bam`` must mean the client's file, not whatever same-named
    file sits next to the server."""
    ensure_spool(spool)
    spec = canon_spec(spec)
    spec["input"] = os.path.abspath(spec["input"])
    if spec["output"] is not None:
        spec["output"] = os.path.abspath(spec["output"])
    if spec["job_id"] and (_result_exists(spool, spec["job_id"]) or
                           _id_in_flight(spool, spec["job_id"])):
        raise ValueError(
            f"job_id {spec['job_id']!r} already has a result or a "
            "queued/running job in this spool (pick a fresh id — "
            "results key by job_id)")
    qdir = os.path.join(spool, QUEUE)
    hint = _read_seq_hint(spool)
    seq = max(hint, _live_max_seq(spool)) if hint is not None \
        else _max_seq(spool)
    import time as _time
    spec["submitted_at"] = round(_time.time(), 6)
    while True:
        seq += 1
        job_id = spec["job_id"] or f"job{seq:08d}"
        final = os.path.join(qdir, f"{seq:08d}-{job_id}.json")
        tmp = final + f".tmp{os.getpid()}"
        doc = dict(spec, job_id=job_id, seq=seq)
        with open(tmp, "w") as f:
            f.write(json.dumps(doc, sort_keys=True))
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, final)     # fails if the name exists: no clobber
        except FileExistsError:
            os.unlink(tmp)
            if spec["job_id"]:
                raise ValueError(
                    f"job_id {spec['job_id']!r} already queued at "
                    f"seq {seq}")
            continue
        os.unlink(tmp)
        _write_seq_hint(spool, seq)
        return job_id


def iter_queue(spool: str) -> Iterator[Tuple[int, str, dict]]:
    """Queued jobs in submit order: yields ``(seq, path, spec)``.
    Unreadable/torn files (a submitter mid-write crashed before the
    atomic link — impossible — or manual tampering) are skipped, not
    fatal: one bad file must not wedge the queue."""
    qdir = os.path.join(spool, QUEUE)
    try:
        names = os.listdir(qdir)
    except OSError:
        return
    # numeric order, not lexicographic: past seq 99,999,999 the name
    # grows a digit and a string sort would serve it out of order
    matched = sorted(((int(m.group(1)), n)
                      for n in names
                      for m in (_NAME_RE.match(n),) if m))
    for _, name in matched:
        path = os.path.join(qdir, name)
        m = _NAME_RE.match(name)
        try:
            with open(path) as f:
                spec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(spec, dict):
            yield int(m.group(1)), path, spec


class QueueCursor:
    """Parse-once queue scanner: the poll-loop twin of
    :func:`iter_queue`.

    Every serve/placement round snapshots the queue; a naive rescan
    re-opens and re-parses EVERY queued spec each round, making round
    cost O(backlog) precisely when the backlog is deepest (the overload
    regime the brownout ladder exists for).  Queue files are immutable
    once hard-linked (submit never rewrites; claims RENAME the file
    away), so a name seen once never needs re-parsing: this cursor
    keeps a name-keyed spec cache, parses only names it has not seen,
    and evicts names that left the directory.  When the directory
    mtime is unchanged (and old enough to be outside coarse-timestamp
    races) the previous listing is reused wholesale.

    ``parsed_total`` counts file parses since construction — the
    flat-round-cost pin in tests/test_serve.py reads it.
    """

    #: reuse the cached listing only when the dir mtime is at least
    #: this old — inside the window a same-ns submit could hide
    _MTIME_SETTLE_S = 2.0

    def __init__(self, spool: str):
        self.spool = spool
        self._specs: dict = {}          # name -> (seq, spec) | None (bad)
        self._last_mtime_ns: Optional[int] = None
        self._last_names: list = []
        self.parsed_total = 0

    def snapshot(self) -> list:
        """Queued jobs in submit order: ``[(seq, path, spec), ...]`` —
        the :func:`iter_queue` contract, amortized O(new entries)."""
        import time as _time

        qdir = os.path.join(self.spool, QUEUE)
        try:
            st = os.stat(qdir)
        except OSError:
            return []
        if (self._last_mtime_ns is not None
                and st.st_mtime_ns == self._last_mtime_ns):
            names = self._last_names
        else:
            try:
                names = os.listdir(qdir)
            except OSError:
                return []
            # trust this listing for mtime-keyed reuse ONLY when it
            # was taken outside the settle window: a listing taken
            # moments after a submit could miss a second submit
            # landing in the same coarse mtime tick, and the age test
            # at reuse time cannot detect that — the listing, not the
            # mtime, must be older than the window
            self._last_mtime_ns = st.st_mtime_ns \
                if _time.time() - st.st_mtime > self._MTIME_SETTLE_S \
                else None
            self._last_names = names
            for gone in set(self._specs) - set(names):
                del self._specs[gone]
        out = []
        for name in names:
            m = _NAME_RE.match(name)
            if not m:
                continue
            if name not in self._specs:
                self.parsed_total += 1
                try:
                    with open(os.path.join(qdir, name)) as f:
                        spec = json.load(f)
                except OSError:
                    # TRANSIENT (fd exhaustion, a racing claim): do
                    # NOT cache — caching would starve an intact
                    # queued job forever; the next round retries, the
                    # iter_queue discipline
                    continue
                except ValueError:
                    spec = None     # torn/tampered content: the file
                #                     is immutable, so this is final
                self._specs[name] = (int(m.group(1)), spec) \
                    if isinstance(spec, dict) else None
            ent = self._specs[name]
            if ent is not None:
                out.append((ent[0], os.path.join(qdir, name), ent[1]))
        out.sort(key=lambda e: e[0])
        return out


def snapshot_canon(spool: str, cursor: QueueCursor,
                   canon_cache: dict) -> list:
    """Cursor-backed CANONICALIZED queue snapshot: ``[(seq, path,
    canon), ...]`` with canonicalization paid once per immutable queue
    file (``canon_cache``, name-keyed, evicted with the listing) and
    hand-tampered bad specs retired in place with their own typed
    failure doc — ONE implementation for the serve loop and the fleet
    front door, so the bad-spec discipline can never skew between
    them.

    The failure doc keys by the FILENAME-derived id (via the name
    regex — a fixed slice would mangle 9-digit seqs), never the file's
    own ``job_id`` field: a filename cannot carry a path separator,
    but a hand-written job_id like ``../../x`` could walk the result
    write out of the spool."""
    out = []
    live = set()
    for seq, path, spec in cursor.snapshot():
        name = os.path.basename(path)
        live.add(name)
        if name not in canon_cache:
            try:
                canon_cache[name] = canon_spec(spec)
            except ValueError as e:
                m = _NAME_RE.match(name)
                bad = {"job_id": m.group(2), "tenant": "default",
                       "command": str(spec.get("command")),
                       "input": "", "output": None, "args": {},
                       "submitted_at": None, "priority": "normal",
                       "deadline_s": None}
                claimed = claim_job(spool, path)
                write_result(spool, bad, ok=False, error=str(e),
                             error_type="ValueError",
                             running_path=claimed)
                canon_cache[name] = {}
                continue
        canon = canon_cache[name]
        if not canon:
            continue            # failed canonicalization above
        out.append((seq, path, dict(canon, seq=seq)))
    for gone in [n for n in canon_cache if n not in live]:
        del canon_cache[gone]
    return out


def claim_job(spool: str, queue_path: str) -> Optional[str]:
    """Move a queued job to ``running/`` (atomic rename).  Returns the
    running path, or None when another server instance claimed it
    first."""
    dest = os.path.join(spool, RUNNING, os.path.basename(queue_path))
    try:
        os.rename(queue_path, dest)
    except OSError:
        return None
    return dest


def requeue_running(spool: str) -> int:
    """Boot-time crash recovery: any job still under ``running/`` was
    claimed by a server that died mid-job — move it back to the queue
    (jobs are idempotent; see module docstring).  Returns the count."""
    rdir = os.path.join(spool, RUNNING)
    n = 0
    try:
        names = os.listdir(rdir)
    except OSError:
        return 0
    for name in sorted(names):
        if _NAME_RE.match(name):
            try:
                os.rename(os.path.join(rdir, name),
                          os.path.join(spool, QUEUE, name))
                n += 1
            except OSError:
                pass
    return n


def write_result(spool: str, spec: dict, *, ok: bool,
                 result: Optional[dict] = None,
                 error: Optional[str] = None,
                 error_type: Optional[str] = None,
                 seconds: Optional[float] = None,
                 queue_s: Optional[float] = None,
                 service_s: Optional[float] = None,
                 running_path: Optional[str] = None) -> str:
    """Publish one job's durable result document (atomic tmp+rename)
    and retire its running-claim file.  ``done/`` and ``failed/`` key by
    job_id — the client polls one well-known name.  ``queue_s`` /
    ``service_s`` stamp the per-tenant SLO split (submit→start wait and
    execution wall) into the doc the client reads."""
    doc = {"job_id": spec["job_id"], "tenant": spec["tenant"],
           "command": spec["command"], "ok": bool(ok),
           "seconds": None if seconds is None else round(seconds, 6),
           "result": result or {}}
    if queue_s is not None:
        doc["queue_s"] = round(float(queue_s), 6)
    if service_s is not None:
        doc["service_s"] = round(float(service_s), 6)
    if error is not None:
        doc["error"] = str(error)[:500]
    if error_type is not None:
        doc["error_type"] = error_type
    dest = os.path.join(spool, DONE if ok else FAILED,
                        f"{spec['job_id']}.json")
    atomic_write(dest, json.dumps(doc, sort_keys=True))
    if running_path:
        try:
            os.unlink(running_path)
        except OSError:
            pass
    return dest


def write_rejection(spool: str, spec: dict, *, code: str,
                    retry_after_s: float, message: str,
                    queue_path: Optional[str] = None) -> str:
    """Publish one job's durable TYPED rejection (over-quota or
    brownout shed — the job never ran) to ``rejected/<job>.json`` and
    retire its claimed queue file.  Never a silent drop, never a torn
    spool: the doc lands atomically BEFORE the queue entry goes away,
    so a crash between the two leaves a duplicate doc, not a lost job."""
    doc = {"job_id": spec["job_id"], "tenant": spec["tenant"],
           "command": spec["command"], "ok": False, "rejected": True,
           "code": str(code),
           "retry_after_s": round(float(retry_after_s), 3),
           "error": str(message)[:500],
           "error_type": "AdmissionRejected"}
    dest = os.path.join(spool, REJECTED, f"{spec['job_id']}.json")
    atomic_write(dest, json.dumps(doc, sort_keys=True))
    if queue_path:
        try:
            os.unlink(queue_path)
        except OSError:
            pass
    return dest


def read_result(spool: str, job_id: str) -> Optional[dict]:
    for d in (DONE, FAILED, REJECTED):
        path = os.path.join(spool, d, f"{job_id}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return None


def wait_result(spool: str, job_id: str, timeout_s: float = 60.0,
                poll_s: float = 0.05,
                max_poll_s: Optional[float] = None) -> dict:
    """Poll for a job's result document; raises ``TimeoutError`` when
    the server never publishes one in time.

    The poll interval backs off exponentially from ``poll_s`` to
    ``max_poll_s`` (default: 20x ``poll_s``, capped at 1 s) — a client
    waiting on a deeply backlogged server must not hammer the result
    directories at a fixed busy-poll rate, but the first few polls stay
    tight so a warm fast job still returns promptly."""
    import time

    if max_poll_s is None:
        max_poll_s = min(max(poll_s * 20.0, poll_s), 1.0)
    deadline = time.monotonic() + timeout_s
    delay = max(poll_s, 1e-4)
    while True:
        doc = read_result(spool, job_id)
        if doc is not None:
            return doc
        now = time.monotonic()
        if now >= deadline:
            raise TimeoutError(
                f"no result for job {job_id!r} within {timeout_s}s "
                f"(is a server running on {spool!r}?)")
        time.sleep(min(delay, max(deadline - now, 0.0)))
        delay = min(delay * 2.0, max_poll_s)


def set_active(spool: str, job_ids) -> None:
    """Publish the executing-job set (atomic; survives a SIGKILL so the
    fleet scheduler can read it off a corpse).  An empty set clears the
    marker — between jobs nothing is chargeable."""
    path = os.path.join(spool, ACTIVE_MARKER)
    ids = sorted(str(j) for j in job_ids)
    if not ids:
        try:
            os.unlink(path)
        except OSError:
            pass
        return
    atomic_write(path, json.dumps(ids))


def read_active(spool: str) -> list:
    """The job ids the (possibly dead) server was executing — ``[]``
    when the marker is absent or unreadable (attribution then errs
    innocent: a requeue costs a re-run, a wrong quarantine costs a
    tenant its job)."""
    try:
        with open(os.path.join(spool, ACTIVE_MARKER)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    return [str(j) for j in doc] if isinstance(doc, list) else []


def request_stop(spool: str) -> None:
    """Drop the stop sentinel: a running server drains its current round
    and exits cleanly."""
    with open(os.path.join(spool, STOP_SENTINEL), "w") as f:
        f.write("stop\n")


def stop_requested(spool: str) -> bool:
    return os.path.exists(os.path.join(spool, STOP_SENTINEL))
