"""Field projections — the columnar-discipline API.

Re-designs the reference's ``projections/`` package: ``Projection``/``Filter``
build a projected schema from a field subset (Projection.scala:10-41) and
per-record field enumerations name every projectable field
(ADAMRecordField.scala:28-71 and siblings).  Here each record's fields are a
namespace over its Arrow schema, and a projection resolves to the column list
handed to the Parquet reader (io/parquet.load_table) — plus one packing-aware
twist: the eleven ADAMRecord flag booleans (adam.avdl:31-43) are virtual
fields that resolve to the packed ``flags`` column (schema.FLAG_FIELDS).
"""

from __future__ import annotations

from typing import Iterable, List

import pyarrow as pa

from . import schema as S


class _FieldNamespace:
    """Attribute-per-field view over one record schema; iterating yields all
    concrete column names (the reference's FieldEnumeration)."""

    def __init__(self, record: str, arrow_schema: pa.Schema, virtual=()):
        self._record = record
        self._schema = arrow_schema
        self._virtual = dict(virtual)
        for name in arrow_schema.names:
            setattr(self, name, name)
        for name, target in self._virtual.items():
            setattr(self, name, name)

    @property
    def record(self) -> str:
        return self._record

    @property
    def arrow_schema(self) -> pa.Schema:
        return self._schema

    def __iter__(self):
        return iter(self._schema.names)

    def resolve(self, fields: Iterable[str]) -> List[str]:
        """Field names -> concrete column names, virtual flag fields folded
        into their backing column, order preserved, duplicates dropped."""
        out: List[str] = []
        for f in fields:
            col = self._virtual.get(f, f)
            if col not in self._schema.names:
                raise ValueError(
                    f"unknown field {f!r} for record {self._record!r}")
            if col not in out:
                out.append(col)
        return out


_FLAG_VIRTUALS = {name: "flags" for name in S.FLAG_FIELDS}

#: ADAMRecordField (projections/ADAMRecordField.scala:28-71) — 39 reference
#: fields; the 11 booleans resolve to the packed ``flags`` column.
ADAMRecordField = _FieldNamespace("read", S.READ_SCHEMA, _FLAG_VIRTUALS)
ADAMPileupField = _FieldNamespace("pileup", S.PILEUP_SCHEMA)
ADAMVariantField = _FieldNamespace("variant", S.VARIANT_SCHEMA)
ADAMGenotypeField = _FieldNamespace("genotype", S.GENOTYPE_SCHEMA)
ADAMVariantDomainField = _FieldNamespace("variantdomain",
                                         S.VARIANT_DOMAIN_SCHEMA)
ADAMNucleotideContigField = _FieldNamespace("contig", S.CONTIG_SCHEMA)

_NAMESPACES = {ns.record: ns for ns in (
    ADAMRecordField, ADAMPileupField, ADAMVariantField, ADAMGenotypeField,
    ADAMVariantDomainField, ADAMNucleotideContigField)}

#: ADAMVariantAnnotations (projections/ADAMVariantAnnotationFields.scala:21-28)
#: — the extension registry pairing each variant-annotation record with the
#: dataset suffix it is stored under; compute_variants/vcf2adam write the
#: ``.vd`` dataset and variantcontext.load_variant_contexts reads it back.
ADAMVariantAnnotations = {"variantdomain": ".vd"}


def annotation_extension(record: str) -> str:
    """File extension for a registered variant-annotation record."""
    return ADAMVariantAnnotations[record]


def annotation_namespace(record: str) -> _FieldNamespace:
    """Field namespace for a registered variant-annotation record."""
    if record not in ADAMVariantAnnotations:
        raise KeyError(f"{record!r} is not a registered variant annotation")
    return _NAMESPACES[record]


def namespace_for(record: str) -> _FieldNamespace:
    return _NAMESPACES[record]


def projection(*fields: str, record: str = "read") -> List[str]:
    """Columns to read for the requested fields (Projection.scala:25-33)."""
    return _NAMESPACES[record].resolve(fields)


def filtered(*excluded: str, record: str = "read") -> List[str]:
    """Complement projection: every column except the excluded fields
    (Projection's filter form, Projection.scala:35-41).

    Virtual flag fields cannot be excluded — dropping one would drop the
    shared packed ``flags`` column and take the other ten booleans with it;
    exclude ``"flags"`` itself to drop them all.
    """
    ns = _NAMESPACES[record]
    virtual = [f for f in excluded if f in ns._virtual]
    if virtual:
        raise ValueError(
            f"cannot exclude virtual flag field(s) {virtual}: they share "
            "the packed 'flags' column; exclude 'flags' to drop all of them")
    drop = set(ns.resolve(excluded))
    return [c for c in ns if c not in drop]


def project_schema(columns: Iterable[str], record: str = "read") -> pa.Schema:
    """Projected Arrow schema for the column subset."""
    full = _NAMESPACES[record].arrow_schema
    return pa.schema([full.field(c) for c in columns])
