"""Backend-platform selection under the axon TPU plugin.

The one environment quirk every entry point must handle: the axon PJRT
plugin registers itself regardless of ``JAX_PLATFORMS``, so forcing the CPU
backend takes BOTH the env var and ``jax.config.update("jax_platforms",
"cpu")`` before the backend initializes.  Round 1 lost a driver evidence
artifact because one entry point (``__graft_entry__.dryrun_multichip``) had
its own drifted copy of this workaround — this module is now the single
implementation, shared by tests/conftest.py, the CLI, bench.py, and the
driver entry points.
"""

from __future__ import annotations

import os
import re

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=(\d+)")


def shard_map(f, *, mesh, in_specs, out_specs, **kw):
    """``jax.shard_map`` on any installed jax.

    The API graduated out of ``jax.experimental.shard_map`` (top-level
    since ~0.6); older jaxlibs only ship the experimental name.  One
    compat indirection here keeps every kernel call site on the modern
    spelling — this is the same single-implementation discipline that
    created this module (a drifted per-file workaround cost round 1 an
    evidence artifact).
    """
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

        if "check_vma" in kw:   # the experimental API's older spelling
            kw["check_rep"] = kw.pop("check_vma")
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def ensure_host_device_count(n_devices: int) -> None:
    """Guarantee >= ``n_devices`` virtual CPU devices via ``XLA_FLAGS``.

    Replaces an existing smaller ``--xla_force_host_platform_device_count``
    rather than skipping on a substring hit (a pre-set smaller count would
    otherwise make a multi-device caller fail).  Must run before the jax
    backend initializes.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = _COUNT_RE.search(flags)
    if m is None:
        flags = (flags +
                 f" --xla_force_host_platform_device_count={n_devices}")
    elif int(m.group(1)) < n_devices:
        flags = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = flags.strip()


def enable_compilation_cache(path: str | None = None) -> None:
    """Persist compiled XLA executables across processes and runs.

    The reference pays JVM warmup once per command; this framework's
    analog cost is XLA compilation — tens of seconds per pipeline run
    (and 20-40 s/kernel through the tunnel's remote AOT compiler), all
    fully repeated on every CLI invocation without a persistent cache.
    One config flag removes it for every run after the first.

    Resolution order: explicit arg > ADAM_TPU_COMPILE_CACHE (``0``/empty
    disables; a path force-enables on any backend) >
    JAX_COMPILATION_CACHE_DIR (jax reads it natively; we leave it
    alone) > ``~/.cache/adam_tpu/xla``.  Failures are non-fatal — the
    cache is an optimization, never a dependency.

    Default-on only for non-CPU backends: XLA:CPU AOT reload emits an
    ERROR-level machine-feature-drift warning per cached executable
    (compile-time tuning flags like +prefer-no-scatter never match the
    host detector's list) and genuinely risks SIGILL when one cache dir
    crosses heterogeneous machines (shared home dirs).  The compile
    the cache saves most is the tunnel's remote AOT anyway.

    The default-on decision gates on the ACTUAL initialized backend, not
    on platform-config string absence: a CPU-only jax install with no
    ``JAX_PLATFORMS`` set used to pass the old "not forced to cpu" check
    and enable the persistent cache anyway (round-5 advisor).  An
    explicitly forced CPU platform still short-circuits here; otherwise
    the config update is DEFERRED to the first backend-compile event —
    the backend is initialized by then, so ``jax.default_backend()`` is
    a free read, never an init trigger.  Cost of the deferral: the very
    first compile of a run misses the cache config (it would have been
    the cache's own first miss on a cold cache anyway).
    """
    install_compile_metrics()   # count hits/misses/compile-seconds even
    #                             when the cache itself ends up disabled
    if path is None:
        env = os.environ.get("ADAM_TPU_COMPILE_CACHE")
        if env is not None:
            if env in ("", "0", "off"):
                return
            path = env
        elif os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            return
        else:
            # fast veto WITHOUT touching the backend (deciding about a
            # cache must never dial a dead tunnel): an explicitly forced
            # CPU platform needs no deferral machinery at all
            try:
                import jax

                plat = jax.config.jax_platforms or \
                    os.environ.get("JAX_PLATFORMS", "")
            except Exception:  # noqa: BLE001
                plat = os.environ.get("JAX_PLATFORMS", "")
            if (plat or "").split(",")[0].strip() == "cpu":
                return
            _defer_default_cache(os.path.join(
                os.path.expanduser("~"), ".cache", "adam_tpu", "xla"))
            return
    _apply_cache_config(path)


def _apply_cache_config(path: str) -> None:
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # default threshold (1 s) skips most of this pipeline's kernels —
        # dozens of 0.1-0.9 s compiles that add up to the actual warmup
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.1)
    except Exception:  # noqa: BLE001 — never fail a run over a cache
        pass


#: the deferred default-cache path (at most one pending decision) — a
#: list so tests can reset it without reaching into closures
_PENDING_DEFAULT_CACHE: list = []
_DEFER_LISTENER_INSTALLED = False


def _defer_default_cache(path: str) -> None:
    """Arm the deferred default-on decision: on the first backend
    compile, check the now-initialized backend and enable the cache for
    non-CPU backends only.  jax.monitoring listeners cannot be
    unregistered, so the callback consults the pending list and becomes
    a no-op once the decision is made."""
    global _DEFER_LISTENER_INSTALLED
    _PENDING_DEFAULT_CACHE[:] = [path]
    if _DEFER_LISTENER_INSTALLED:
        return
    try:
        from jax import monitoring

        def on_duration(event: str, duration: float, **kw) -> None:
            if event.endswith("backend_compile_duration"):
                apply_pending_default_cache()

        monitoring.register_event_duration_secs_listener(on_duration)
        _DEFER_LISTENER_INSTALLED = True
    except Exception:  # noqa: BLE001 — cache is an optimization only
        _PENDING_DEFAULT_CACHE.clear()


def apply_pending_default_cache() -> None:
    """Resolve a deferred default-cache decision against the initialized
    backend (called from the compile listener; safe to call directly —
    e.g. after an explicit backend init — and idempotent)."""
    try:
        # two threads can finish their first compiles concurrently; the
        # loser of the pop must no-op, not raise out of jax's listener
        path = _PENDING_DEFAULT_CACHE.pop()
    except IndexError:
        return
    try:
        import jax

        if jax.default_backend() == "cpu":
            return          # CPU-only install: never default-enable
    except Exception:  # noqa: BLE001
        return
    _apply_cache_config(path)


def warm() -> dict:
    """Pre-pay the cold-start tolls NOW, not on the first tenant's job.

    The serve front-end (adam_tpu/serve) calls this once at boot: it
    initializes the jax backend, resolves the deferred default
    compilation-cache decision (:func:`enable_compilation_cache`'s
    listener path would otherwise wait for the first real compile —
    i.e. the first tenant's job would pay the un-cached compile), and
    runs one tiny jit dispatch so the dispatch machinery is hot.
    Returns the measured breakdown (also recorded in obs.startup)::

        {"backend": str, "n_devices": int, "backend_init_s": float,
         "warm_dispatch_s": float, "cache_resolved": bool}

    Safe to call repeatedly — a warm backend just re-measures cheap
    reads (and the startup marks keep their first values).  Never
    raises: a broken backend returns the error string instead, and the
    caller (which is about to run real jobs that will surface the same
    problem loudly) decides what to do.
    """
    import time as _time

    from .obs import startup

    out: dict = {"backend": None, "n_devices": 0,
                 "backend_init_s": 0.0, "warm_dispatch_s": 0.0,
                 "cache_resolved": False}
    try:
        t0 = _time.perf_counter()
        with startup.phase("backend_init"):
            import jax

            out["backend"] = jax.default_backend()
        out["n_devices"] = len(jax.devices())
        out["backend_init_s"] = round(_time.perf_counter() - t0, 6)
        # the deferred default-cache decision normally resolves on the
        # first compile event; the backend is initialized now, so
        # resolve it eagerly — the warm dispatch below then compiles
        # WITH the cache config in place
        apply_pending_default_cache()
        out["cache_resolved"] = not _PENDING_DEFAULT_CACHE
        t0 = _time.perf_counter()
        import jax.numpy as jnp

        jax.block_until_ready(
            jax.jit(lambda x: x + 1)(jnp.zeros((8,), jnp.int32)))
        out["warm_dispatch_s"] = round(_time.perf_counter() - t0, 6)
        startup.mark_at("first_dispatch")
    except Exception as e:  # noqa: BLE001 — warming is best-effort
        out["error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def axis_size(axis_name):
    """``jax.lax.axis_size`` on any installed jax (older releases spell
    it ``core.axis_frame``); concrete int under shard_map tracing."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    from jax._src import core

    return core.axis_frame(axis_name)


def pallas_tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` / legacy ``pltpu.TPUCompilerParams``."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


_COMPILE_METRICS_INSTALLED = False


def install_compile_metrics() -> None:
    """Route jax.monitoring compile events into the obs registry.

    Compilation is this framework's JVM-warmup analog, so it is telemetry
    of the first order: persistent-cache hits/misses
    (``/jax/compilation_cache/*``) become ``compile_cache_hits`` /
    ``compile_cache_misses`` counters, and every backend-compile duration
    (``/jax/core/compile/backend_compile_duration``) accumulates into
    ``compile_count`` / ``compile_seconds``.  Idempotent and non-fatal:
    listeners cannot be unregistered, so the callbacks consult the
    live registry accessor (test resets keep working) and any
    registration failure degrades to no telemetry, never a broken run.
    """
    global _COMPILE_METRICS_INSTALLED
    if _COMPILE_METRICS_INSTALLED:
        return
    try:
        from jax import monitoring

        from .obs.registry import registry

        def on_event(event: str, **kw) -> None:
            if "/compilation_cache/cache_hits" in event:
                registry().counter("compile_cache_hits").inc()
            elif "/compilation_cache/cache_misses" in event:
                registry().counter("compile_cache_misses").inc()

        from .obs import startup

        def on_duration(event: str, duration: float, **kw) -> None:
            if event.endswith("backend_compile_duration"):
                registry().counter("compile_count").inc()
                registry().counter("compile_seconds").inc(duration)
                # first-write-wins: only the run's FIRST compile lands
                # in the startup_seconds breakdown
                startup.note_first_compile(duration)

        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
        _COMPILE_METRICS_INSTALLED = True
    except Exception:  # noqa: BLE001 — telemetry never fails a run
        pass


def force_cpu(n_devices: int | None = None) -> None:
    """Force the CPU backend; optionally ensure n virtual devices.

    Safe to call repeatedly; must be called before the first backend touch
    (a backend that already initialized to TPU cannot be switched).
    """
    if n_devices is not None:
        ensure_host_device_count(n_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # the env var alone is not enough under the axon plugin; config wins
    jax.config.update("jax_platforms", "cpu")


def honor_platform_env() -> None:
    """Make ``JAX_PLATFORMS=cpu adam-tpu ...`` actually run on CPU.

    Harmless if jax is already imported or the var is unset.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def is_tpu_backend() -> bool:
    """True when the active backend executes on TPU hardware.

    The axon PJRT plugin can surface the backend name as "axon" while the
    devices themselves report a TPU device_kind, so a bare
    ``default_backend() == "tpu"`` check misfires there (it would route the
    streaming flagstat off its Pallas fast path, or worse, run the Mosaic
    interpreter on real chunks).  Single shared predicate for every
    fast-path gate.
    """
    from .obs import startup

    # the first call through here usually IS the backend init (every
    # streaming pass gates on it before compiling anything) — time it
    # into the cold-start breakdown; later calls re-measure a cached
    # backend read in microseconds and lose the first-write race
    with startup.phase("backend_init"):
        import jax

        backend = jax.default_backend()
    if backend in ("tpu", "axon"):
        return True
    try:
        return any("tpu" in getattr(d, "device_kind", "").lower()
                   for d in jax.devices())
    except RuntimeError:
        return False
