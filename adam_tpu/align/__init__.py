from .smithwaterman import (SWAlignment, SWParams, smith_waterman,
                            sw_score_batch)

__all__ = ["SWAlignment", "SWParams", "smith_waterman", "sw_score_batch"]
