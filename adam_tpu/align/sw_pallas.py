"""Pallas TPU kernel for batched Smith-Waterman scoring.

The DP the reference scaffolded (SmithWatermanGapScoringFromFn.scala:24-64,
never finished — SURVEY.md §2.2) runs here as a VMEM-resident row recurrence:
the H row lives in lanes (the y axis), each x position is one loop step, and
the in-row insertion chain closes with a log-step Hillis-Steele max-plus scan
(`roll` + max) instead of a serial sweep.  Nothing but the [B, Ly] row block
and the running best score ever leaves registers/VMEM, so scoring B pairs
costs O(B·Lx·Ly / lanes) VPU ops with zero HBM traffic for the matrix —
the matrix the jnp path (`smithwaterman._fill`) materializes.

Score-only by design: batch scoring is the filter/rank path (which candidate
aligns best); the full traceback for the chosen pair goes through
``smithwaterman.smith_waterman`` host-side, mirroring how the realigner
splits device-chosen offsets from host cigar rewriting.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..packing import _round_up
from .smithwaterman import SWParams

NEG = -3e38  # effectively -inf for the masked scan lanes


def _sw_body(xs_ref, ys_ref, xlen_ref, ylen_ref, best_ref, *,
             n_rows: int, w_match: float, w_mismatch: float,
             w_insert: float, w_delete: float):
    ys = ys_ref[:]                                     # [B, Ly] int32
    xlen = xlen_ref[:]                                 # [B, 1]
    ylen = ylen_ref[:]                                 # [B, 1]
    B, Ly = ys.shape
    # Mosaic's tpu.iota is integer-only (the f32 form verifies in the
    # interpreter but is rejected at real TPU lowering — caught by
    # tools/aot_check.py); build the float lane index by converting
    jvec = jax.lax.broadcasted_iota(jnp.int32, (B, Ly), 1).astype(
        jnp.float32)
    j_alive = jax.lax.broadcasted_iota(jnp.int32, (B, Ly), 1) < ylen
    lane0 = jax.lax.broadcasted_iota(jnp.int32, (B, Ly), 1) == 0

    def row(i, carry):
        h_prev, best, xs_c = carry
        xc = xs_c[:, :1]                               # current x char [B, 1]
        alive = i < xlen                               # [B, 1]
        sub = jnp.where(ys == xc, w_match, w_mismatch)
        # diagonal needs H[i-1][j-1]: shift the previous row right one lane,
        # zero fills the j=0 boundary (first column of H is all 0)
        h_shift = jnp.where(lane0, 0.0, pltpu.roll(h_prev, 1, axis=1))
        diag = h_shift + sub
        up = h_prev + w_delete
        cand = jnp.maximum(jnp.maximum(diag, up), 0.0)
        cand = jnp.where(j_alive & alive, cand, 0.0)
        # insertion chain: H[i][j] = max_k<=j cand[k] + w_insert*(j-k),
        # i.e. a max-plus prefix scan, done in log2(Ly) roll+max steps
        a = cand - jvec * w_insert
        d = 1
        while d < Ly:
            idx = jax.lax.broadcasted_iota(jnp.int32, (B, Ly), 1)
            a = jnp.maximum(a, jnp.where(idx < d, NEG,
                                         pltpu.roll(a, d, axis=1)))
            d *= 2
        h = jnp.maximum(cand, jnp.where(j_alive, a + jvec * w_insert, 0.0))
        best = jnp.maximum(best, jnp.max(h, axis=1, keepdims=True))
        return h, best, pltpu.roll(xs_c, shift=xs_c.shape[1] - 1, axis=1)

    init = (jnp.zeros((B, Ly), jnp.float32), jnp.zeros((B, 1), jnp.float32),
            xs_ref[:])
    _, best, _ = jax.lax.fori_loop(0, n_rows, row, init)
    best_ref[:] = best


@functools.partial(jax.jit, static_argnames=("p", "n_rows", "interpret"))
def _sw_padded(xs, ys, xlen, ylen, p: SWParams, n_rows: int,
               interpret=False):
    B, Lx = xs.shape
    Ly = ys.shape[1]
    kernel = functools.partial(
        _sw_body, n_rows=n_rows, w_match=p.w_match, w_mismatch=p.w_mismatch,
        w_insert=p.w_insert, w_delete=p.w_delete)
    best = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, 1), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xs, ys, xlen, ylen)
    return best[:, 0]


def sw_score_batch_pallas(xs_u8, x_lens, ys_u8, y_lens,
                          p: SWParams = SWParams(), *,
                          interpret: bool = False):
    """Best local-alignment score per pair, Pallas-backed.

    xs_u8 [N, Lx], ys_u8 [N, Ly] padded code arrays, lengths [N].  Returns
    scores [N] float32 — same values as ``sw_score_batch(...)[0]``.
    ``interpret=True`` runs on any backend (the CPU-mesh CI path).
    """
    N, Lx = xs_u8.shape
    Ly = ys_u8.shape[1]
    Np = _round_up(max(N, 8), 8)
    Lyp = _round_up(max(Ly, 128), 128)
    # x pads with one extra lane so the roll never re-exposes lane 0
    Lxp = _round_up(max(Lx + 1, 128), 128)

    xs_p = jnp.zeros((Np, Lxp), jnp.int32).at[:N, :Lx].set(
        jnp.asarray(xs_u8).astype(jnp.int32))
    ys_p = jnp.full((Np, Lyp), -1, jnp.int32).at[:N, :Ly].set(
        jnp.asarray(ys_u8).astype(jnp.int32))
    xlen_p = jnp.zeros((Np, 1), jnp.int32).at[:N, 0].set(
        jnp.asarray(x_lens, jnp.int32))
    ylen_p = jnp.zeros((Np, 1), jnp.int32).at[:N, 0].set(
        jnp.asarray(y_lens, jnp.int32))

    # rows >= the true Lx are provably dead (x_lens <= Lx): don't pay the
    # per-row scan for the lane padding
    best = _sw_padded(xs_p, ys_p, xlen_p, ylen_p, p, n_rows=Lx,
                      interpret=interpret)
    return best[:N]
