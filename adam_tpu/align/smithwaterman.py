"""Smith-Waterman local alignment, vectorized for TPU.

The reference ships only an abstract scaffold (algorithms/smithwaterman/
SmithWaterman.scala:21-34): the scoring-matrix fill exists but its inner loop
runs ``for (j <- i until y)`` — upper-triangular only — and indexes one past
the end of both strings (SmithWatermanGapScoringFromFn.scala:44-51); no
``trackback`` implementation or call site exists anywhere.  This module is
the completed algorithm, designed tensor-first:

* The DP fill is O(|x|) ``lax.scan`` steps, each a fully vectorized row
  update.  The within-row insertion chain ``H[i,j] = max(cand[j],
  H[i,j-1] + w_ins)`` — the recurrence that usually forces a scalar inner
  loop — is a max-plus prefix maximum, computed in one shot as
  ``cummax(cand - j*w_ins) + j*w_ins``.  That keeps each step a wide VPU op
  instead of a length-|y| dependency chain.
* Scores/end positions are available batch-wise on device (``sw_score_batch``
  via ``vmap``) without materializing matrices; full traceback materializes
  the [|x|+1, |y|+1] score matrix and walks it on host (traceback is an
  O(|x|+|y|) pointer chase — sequential by nature and never the hot loop;
  realignment's consensus sweep handles the batched case).

Cell preference on score ties is diagonal > up (gap in y) > left (gap in x),
so alignments favor M runs; the reference never defined one (its fill keeps
the value only).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class SWParams:
    """Constant gap scoring (SmithWatermanConstantGapScoring.scala:21-40)."""
    w_match: float = 1.0
    w_mismatch: float = -1.0 / 3.0
    w_insert: float = -1.0 / 3.0   # gap in x (consumes y)
    w_delete: float = -1.0 / 3.0   # gap in y (consumes x)


@dataclass
class SWAlignment:
    score: float
    x_start: int          # 0-based start of the aligned window in x
    y_start: int
    cigar_x: str          # x against y: M = diag, I = consumes x, D = consumes y
    cigar_y: str          # mirror (I and D swapped)
    aligned_x: str        # x window with '_' at gaps
    aligned_y: str


def _fill(x_u8, y_u8, x_len, y_len, p: SWParams):
    """Return the full [Lx+1, Ly+1] local-alignment score matrix.

    x_u8 [Lx], y_u8 [Ly] padded int8 codes; positions >= the lengths are
    masked out of play (their candidates pinned to 0, the local-alignment
    floor), so padding never changes the matrix inside the live region.
    """
    Lx, Ly = x_u8.shape[0], y_u8.shape[0]
    j = jnp.arange(Ly + 1, dtype=jnp.float32)
    j_alive = j[1:] <= y_len  # column j consumes y[j-1]

    def row(h_prev, xi):
        xc, i = xi
        alive = (i <= x_len)
        sub = jnp.where(xc == y_u8, p.w_match, p.w_mismatch)
        diag = h_prev[:-1] + sub
        up = h_prev[1:] + p.w_delete
        cand = jnp.maximum(jnp.maximum(diag, up), 0.0)
        cand = jnp.where(j_alive & alive, cand, 0.0)
        # insertion chain via max-plus prefix max
        chain = jax.lax.cummax(cand - j[1:] * p.w_insert) + j[1:] * p.w_insert
        h = jnp.concatenate([jnp.zeros((1,), jnp.float32),
                             jnp.maximum(cand, jnp.where(j_alive, chain, 0.0))])
        return h, h

    h0 = jnp.zeros((Ly + 1,), jnp.float32)
    xs = (x_u8, jnp.arange(1, Lx + 1))
    _, rows = jax.lax.scan(row, h0, xs)
    return jnp.concatenate([h0[None, :], rows], axis=0)


def _score_end(x_u8, y_u8, x_len, y_len, p: SWParams):
    m = _fill(x_u8, y_u8, x_len, y_len, p)
    flat = jnp.argmax(m)
    return m.max(), flat // m.shape[1], flat % m.shape[1]


@partial(jax.jit, static_argnames=("p",))
def sw_score_batch(xs_u8, x_lens, ys_u8, y_lens, p: SWParams = SWParams()):
    """Batched best-local-alignment (score, end_x, end_y) — no matrices kept.

    xs_u8 [N, Lx], ys_u8 [N, Ly] padded; lengths [N].  This is the device
    path for filtering/scoring many pairs at once.
    """
    return jax.vmap(lambda x, xl, yv, yl: _score_end(x, yv, xl, yl, p))(
        xs_u8, x_lens, ys_u8, y_lens)


def _encode(s: str) -> np.ndarray:
    """Raw bytes as codes: equality on codes is exactly equality on
    characters, for any alphabet (IUPAC codes, lowercase soft-masking)."""
    return np.frombuffer(s.encode(), np.uint8).copy()


def _rle(ops: str) -> str:
    out = []
    i = 0
    while i < len(ops):
        j = i
        while j < len(ops) and ops[j] == ops[i]:
            j += 1
        out.append(f"{j - i}{ops[i]}")
        i = j
    return "".join(out)


def smith_waterman(x: str, y: str, p: SWParams = SWParams()) -> SWAlignment:
    """Align two strings locally; full cigars + gapped alignment strings."""
    if not x or not y:
        return SWAlignment(0.0, 0, 0, "", "", "", "")
    xv, yv = _encode(x), _encode(y)
    m = np.asarray(_fill(jnp.asarray(xv), jnp.asarray(yv),
                         jnp.int32(len(x)), jnp.int32(len(y)), p))
    i, j = np.unravel_index(np.argmax(m), m.shape)
    score = float(m[i, j])
    # the max-plus cummax in _fill leaves float-epsilon residue whose
    # magnitude scales with j*|w_insert| (the shifted operand), so cell
    # provenance is re-derived with a tolerance that scales with the
    # matrix — a fixed eps breaks down once f32 ulp at j/3 exceeds it
    eps = 1e-4 + 1e-6 * float(np.abs(m).max())
    ops_x, ax, ay = [], [], []
    while i > 0 and j > 0 and m[i, j] > eps:
        sub = p.w_match if xv[i - 1] == yv[j - 1] else p.w_mismatch
        if abs(m[i, j] - (m[i - 1, j - 1] + sub)) <= eps:
            ops_x.append("M"); ax.append(x[i - 1]); ay.append(y[j - 1])
            i, j = i - 1, j - 1
        elif abs(m[i, j] - (m[i - 1, j] + p.w_delete)) <= eps:
            ops_x.append("I"); ax.append(x[i - 1]); ay.append("_")
            i -= 1
        elif abs(m[i, j] - (m[i, j - 1] + p.w_insert)) <= eps:
            ops_x.append("D"); ax.append("_"); ay.append(y[j - 1])
            j -= 1
        else:  # numerical dead end: stop rather than emit a wrong op
            break
    ops_x.reverse(); ax.reverse(); ay.reverse()
    sx = "".join(ops_x)
    sy = sx.replace("I", "d").replace("D", "I").replace("d", "D")
    return SWAlignment(score, i, j, _rle(sx), _rle(sy),
                       "".join(ax), "".join(ay))
