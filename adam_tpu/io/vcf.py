"""VCF import/export.

Re-designs ``converters/VariantContextConverter.scala`` (bidirectional
ADAM <-> VCF, :44-575) without the Broad VariantContext/tribble stack: VCF
text parses directly into the three Arrow tables (variants, genotypes,
variant domains) and serializes back with the standard header lines the
reference builds in ``util/VcfHeaderUtils.scala:34-131``.

Field mapping (VariantContextConverter.convertVariants :126-300):
  * one variant row per ALT allele; 0-based positions;
  * variantType by ref/alt length (SNP/MNP/Insertion/Deletion, :207-226);
  * INFO: AF (per-allele), NS -> numberOfSamplesWithData, DP ->
    totalSiteMapCounts, MQ -> siteRmsMapQuality, MQ0 -> siteMapQZeroCounts,
    BQ -> rmsBaseQuality;
  * FILTER "." -> filtersRun=false, PASS -> empty filters.
Genotypes (convertGenotypes :351-449): one row per sample per haplotype
(GT entry), with phasing flags, GQ/DP/HQ/PL fields.
Domains (convertDomains :474-504): DB/H2/H3/1000G INFO flags.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from ..models.dictionary import SequenceDictionary, SequenceRecord
from .. import schema as S


def _variant_type(ref: str, alt: str) -> str:
    if len(ref) == len(alt):
        return "SNP" if len(ref) == 1 else "MNP"
    return "Insertion" if len(alt) > len(ref) else "Deletion"


#: VCF SVTYPE code <-> StructuralVariantType enum (adam.avdl:137-146)
_SV_TYPE_OF_CODE = {
    "DEL": "Deletion", "INS": "Insertion", "DUP": "Duplication",
    "INV": "Inversion", "CNV": "CopyNumberVariation",
    "DUP:TANDEM": "TandemDuplication", "DEL:ME": "MobileElementDeletion",
    "INS:ME": "MobileElementInsertion",
}
_SV_CODE_OF_TYPE = {v: k for k, v in _SV_TYPE_OF_CODE.items()}


def _int_or_none(s: Optional[str]) -> Optional[int]:
    """VCF integer value; '.' (the missing value) and malformed -> None."""
    if not s or s == ".":
        return None
    try:
        return int(s)
    except ValueError:
        return None


def _sv_fields(info_d: Dict[str, str]) -> Dict[str, object]:
    """INFO SVTYPE/SVLEN/END/IMPRECISE/CIPOS/CIEND -> ADAMVariant sv*
    columns (adam.avdl:190-216; VariantContextConverter carries them via
    the symbolic-allele path, :207-226).

    SVTYPE codes outside the StructuralVariantType enum (e.g. BND) are kept
    as their raw code so the write path can round-trip them — the reference
    would drop them at its enum boundary; a superset costs nothing here.
    """
    if "SVTYPE" not in info_d:
        return {}
    out: Dict[str, object] = {
        "svType": _SV_TYPE_OF_CODE.get(info_d["SVTYPE"],
                                       info_d["SVTYPE"] or None),
        "svIsPrecise": "IMPRECISE" not in info_d,
    }
    svlen = _int_or_none(info_d.get("SVLEN", "").split(",")[0])
    if svlen is not None:
        out["svLength"] = svlen
    end = _int_or_none(info_d.get("END"))
    if end is not None:
        out["svEnd"] = end - 1
    for key, lo, hi in (("CIPOS", "svConfidenceIntervalStartLow",
                         "svConfidenceIntervalStartHigh"),
                        ("CIEND", "svConfidenceIntervalEndLow",
                         "svConfidenceIntervalEndHigh")):
        parts = info_d.get(key, "").split(",")
        if len(parts) == 2:
            plo, phi = _int_or_none(parts[0]), _int_or_none(parts[1])
            if plo is not None and phi is not None:
                out[lo], out[hi] = plo, phi
    return out


def _info_dict(info: str) -> Dict[str, str]:
    out = {}
    if info == ".":
        return out
    for item in info.split(";"):
        if "=" in item:
            k, v = item.split("=", 1)
            out[k] = v
        else:
            out[item] = ""
    return out


class VcfStream:
    """Streaming VCF parser: iterate ``(variants, genotypes, domains)``
    Arrow-table chunks of ~``chunk_rows`` variant rows each, holding only
    one chunk of rows in memory (``read_vcf`` loads whole files; 1000G-
    scale VCFs need this form).  ``seq_dict`` and ``samples`` are complete
    once iteration finishes (contigs can appear mid-body via interning,
    exactly like the whole-file parser).
    """

    def __init__(self, path_or_file, chunk_rows: int = 1 << 18):
        self._source = path_or_file
        self._chunk_rows = chunk_rows
        self.samples: List[str] = []
        self._contigs: List[SequenceRecord] = []
        self._contig_by_name: Dict[str, SequenceRecord] = {}

    @property
    def seq_dict(self) -> SequenceDictionary:
        return SequenceDictionary(self._contigs)

    def _open_lines(self):
        if hasattr(self._source, "read"):
            return iter(self._source.read().splitlines()), None
        if not isinstance(self._source, (str, bytes)) and \
                hasattr(self._source, "__iter__"):
            # a line iterator (e.g. bcf.iter_bcf_vcf_lines) — one-shot:
            # a second __iter__ pass will see it exhausted
            return iter(self._source), None
        p = str(self._source)
        if p.endswith((".gz", ".bgz")):
            import gzip
            f = gzip.open(p, "rt")
            return (ln.rstrip("\n") for ln in f), f
        f = open(p, "rt")
        return (ln.rstrip("\n") for ln in f), f

    def __iter__(self):
        lines, close_me = self._open_lines()
        # a fresh pass re-reads the header: reset the interned state or a
        # second iteration would duplicate contigs and shift referenceIds
        self._contigs = []
        self._contig_by_name = {}
        self.samples = []
        contigs = self._contigs
        contig_by_name = self._contig_by_name
        v_rows, g_rows, d_rows = [], [], []
        samples = self.samples

        def intern_contig(name: str) -> SequenceRecord:
            rec = contig_by_name.get(name)
            if rec is None:
                rec = SequenceRecord(len(contigs), name, 0)
                contigs.append(rec)
                contig_by_name[name] = rec
            return rec

        def tables():
            return (_rows_to_table(v_rows, S.VARIANT_SCHEMA),
                    _rows_to_table(g_rows, S.GENOTYPE_SCHEMA),
                    _rows_to_table(d_rows, S.VARIANT_DOMAIN_SCHEMA))

        try:
            for line in lines:
                if line.startswith("##"):
                    if line.startswith("##contig=<"):
                        fields = dict(kv.split("=", 1)
                                      for kv in line[10:].rstrip(">").split(",")
                                      if "=" in kv)
                        rec = SequenceRecord(
                            len(contigs), fields.get("ID", f"c{len(contigs)}"),
                            int(fields.get("length", 0)))
                        contigs.append(rec)
                        contig_by_name[rec.name] = rec
                    continue
                if line.startswith("#CHROM"):
                    samples[:] = line.split("\t")[9:]  # mutate in place:
                    #          self.samples must see the header
                    continue
                if not line.strip():
                    continue
                f = line.split("\t")
                chrom, pos1, vid, ref, alts, qual, filt, info = f[:8]
                fmt = f[8].split(":") if len(f) > 8 else []
                pos = int(pos1) - 1
                info_d = _info_dict(info)
                contig = intern_contig(chrom)
                refid = contig.id
                alt_list = [a for a in alts.split(",") if a != "."]
                afs = info_d.get("AF", "").split(",") if "AF" in info_d else []
                sv = _sv_fields(info_d)

                for ai, alt in enumerate(alt_list):
                    # symbolic ALT (<DEL>, <DUP:TANDEM>) -> Complex with no base
                    # string; breakend notation -> SV (convertType :207-218)
                    if alt.startswith("<"):
                        vtype, vseq = "Complex", None
                    elif "[" in alt or "]" in alt:
                        vtype, vseq = "SV", alt
                    else:
                        vtype, vseq = _variant_type(ref, alt), alt
                    v_rows.append(sv | {
                        "referenceId": refid, "referenceName": chrom,
                        "referenceLength": contig.length or None,
                        "referenceUrl": contig.url,
                        "position": pos, "referenceAllele": ref, "variant": vseq,
                        "variantType": vtype,
                        "id": vid if vid != "." else None,
                        "quality": int(float(qual)) if qual != "." else None,
                        "filters": None if filt in (".", "PASS") else filt,
                        "filtersRun": filt != ".",
                        "alleleFrequency": float(afs[ai]) if ai < len(afs) else None,
                        "rmsBaseQuality": int(info_d["BQ"]) if "BQ" in info_d else None,
                        "siteRmsMappingQuality": int(info_d["MQ"]) if "MQ" in info_d else None,
                        "siteMapQZeroCounts": int(info_d["MQ0"]) if "MQ0" in info_d else None,
                        "totalSiteMapCounts": int(info_d["DP"]) if "DP" in info_d else None,
                        "numberOfSamplesWithData": int(info_d["NS"]) if "NS" in info_d else None,
                    })
                d_rows.append({
                    "referenceId": refid, "position": pos, "referenceAllele": ref,
                    "variant": alt_list[0] if alt_list else None,
                    "inDbSNP": "DB" in info_d, "inHM2": "H2" in info_d,
                    "inHM3": "H3" in info_d, "in1000G": "1000G" in info_d,
                })

                alleles = [ref] + alts.split(",")
                for si, sample in enumerate(samples):
                    if 9 + si >= len(f):
                        continue
                    sd = dict(zip(fmt, f[9 + si].split(":")))
                    gt = sd.get("GT", ".")
                    phased = "|" in gt
                    idxs = gt.replace("|", "/").split("/")
                    hq = sd.get("HQ", "").split(",") if "HQ" in sd else []
                    for hi, ix in enumerate(idxs):
                        if ix == ".":
                            continue
                        allele = alleles[int(ix)]
                        g_rows.append({
                            "referenceId": refid, "referenceName": chrom,
                            "position": pos, "sampleId": sample,
                            "ploidy": len(idxs), "haplotypeNumber": hi,
                            "allele": allele, "isReference": allele == ref,
                            "referenceAllele": ref,
                            "alleleVariantType": (
                                "SNP" if allele == ref else
                                "Complex" if allele.startswith("<") else
                                "SV" if ("[" in allele or "]" in allele) else
                                _variant_type(ref, allele)),
                            "genotypeQuality": int(sd["GQ"]) if sd.get("GQ", "").isdigit() else None,
                            "depth": int(sd["DP"]) if sd.get("DP", "").isdigit() else None,
                            "phredLikelihoods": sd.get("PL"),
                            "phredPosteriorLikelihoods": sd.get("GP"),
                            "ploidyStateGenotypeLikelihoods": sd.get("GQL"),
                            "rmsMapQuality": (int(sd["MQ"])
                                              if sd.get("MQ", "").isdigit()
                                              else None),
                            "haplotypeQuality": (int(hq[hi])
                                                 if hi < len(hq) and hq[hi].isdigit()
                                                 else None),
                            "isPhased": phased,
                            # phasing extras only carry when the call IS phased
                            # (VariantContextConverter :404-411)
                            "phaseSetId": sd.get("PS") if phased else None,
                            "phaseQuality": (int(sd["PQ"])
                                             if phased and sd.get("PQ", "").isdigit()
                                             else None),
                        })
                # flush on EITHER table: multi-sample VCFs grow g_rows
                # ~samples x ploidy faster than v_rows, and the bound must
                # hold for 2504-sample cohorts
                if max(len(v_rows), len(g_rows)) >= self._chunk_rows:
                    yield tables()
                    v_rows, g_rows, d_rows = [], [], []
            if v_rows or g_rows or d_rows:
                yield tables()
        finally:
            if close_me is not None:
                close_me.close()


def _rows_to_table(rows, schema):
    cols = {name: [r.get(name) for r in rows] for name in schema.names}
    return pa.Table.from_pydict(cols, schema=schema)


def read_vcf(path_or_file) -> Tuple[pa.Table, pa.Table, pa.Table,
                                    SequenceDictionary]:
    """Parse VCF -> (variants, genotypes, domains, sequence dictionary).

    Dispatches on extension like the reference's adamLoad
    (AdamContext.scala:129-137): ``.bcf`` decodes through the binary codec
    (io/bcf.py), ``.vcf.gz``/``.vcf.bgz`` decompress first (BGZF is plain
    concatenated gzip members), bare paths parse as text.  The whole-file
    form of :class:`VcfStream`.
    """
    if not hasattr(path_or_file, "read") and \
            str(path_or_file).endswith(".bcf"):
        from .bcf import read_bcf
        return read_bcf(str(path_or_file))
    stream = VcfStream(path_or_file)
    chunks = list(stream)
    if not chunks:
        return (_rows_to_table([], S.VARIANT_SCHEMA),
                _rows_to_table([], S.GENOTYPE_SCHEMA),
                _rows_to_table([], S.VARIANT_DOMAIN_SCHEMA),
                stream.seq_dict)
    vs, gs, ds = zip(*chunks)
    return (pa.concat_tables(vs), pa.concat_tables(gs),
            pa.concat_tables(ds), stream.seq_dict)


def write_vcf(variants: pa.Table, genotypes: pa.Table, path_or_file,
              seq_dict: Optional[SequenceDictionary] = None) -> None:
    """Serialize variant/genotype tables to VCF text (adam2vcf path;
    header lines follow VcfHeaderUtils.scala:34-131).  ``.vcf.gz``/``.bgz``
    paths BGZF-compress; ``.bcf`` paths binary-encode (io/bcf.py) — export
    forms the reference never had.

    Path targets land durably (checkpoint.atomic_write tmp+fsync+rename,
    GL003 discipline): a crash mid-emit leaves the old file or none, never
    a torn VCF.  File-like targets are the caller's to make durable."""
    if hasattr(path_or_file, "write"):
        out = path_or_file
    elif str(path_or_file).endswith((".gz", ".bgz", ".bcf")):
        import io as _io
        buf = _io.StringIO()
        write_vcf(variants, genotypes, buf, seq_dict)
        p = str(path_or_file)
        if p.endswith(".bcf"):
            from .bcf import write_bcf
            write_bcf(buf.getvalue(), p)
        else:
            from ..checkpoint import atomic_np_write
            from .bam import _BGZF_EOF, _bgzf_block
            data = buf.getvalue().encode()

            def _write_bgzf(fh):
                for i in range(0, len(data), 60000):
                    fh.write(_bgzf_block(data[i:i + 60000]))
                fh.write(_BGZF_EOF)

            atomic_np_write(p, _write_bgzf)
        return
    else:
        # durable-write discipline: buffer the text and land it with
        # tmp+fsync+rename — a crash mid-emit never leaves a torn VCF
        import io as _io

        from ..checkpoint import atomic_write
        buf = _io.StringIO()
        write_vcf(variants, genotypes, buf, seq_dict)
        atomic_write(str(path_or_file), buf.getvalue())
        return
    sample_order: List[str] = []
    for sid in genotypes.column("sampleId").to_pylist():
        if sid not in sample_order:
            sample_order.append(sid)
    _write_vcf_header(out, variants, sample_order, seq_dict)
    _write_vcf_records(out, variants, genotypes, sample_order)


def _write_vcf_header(out, variants: pa.Table, sample_order: List[str],
                      seq_dict: Optional[SequenceDictionary]) -> None:
    """The ## metadata block + contig lines + #CHROM line with a FIXED
    sample column order (VcfHeaderUtils.scala:34-131); split out so the
    streaming adam2vcf can emit it once before windowed data lines."""
    out.write("##fileformat=VCFv4.1\n")
    out.write('##INFO=<ID=NS,Number=1,Type=Integer,Description="Number of Samples With Data">\n')
    out.write('##INFO=<ID=DP,Number=1,Type=Integer,Description="Total Depth">\n')
    out.write('##INFO=<ID=AF,Number=A,Type=Float,Description="Allele Frequency">\n')
    out.write('##INFO=<ID=BQ,Number=1,Type=Integer,Description="RMS Base Quality">\n')
    out.write('##INFO=<ID=MQ,Number=1,Type=Integer,Description="RMS Mapping Quality">\n')
    out.write('##INFO=<ID=MQ0,Number=1,Type=Integer,Description="Number of MapQ=0 Reads">\n')
    out.write('##INFO=<ID=SVTYPE,Number=1,Type=String,Description="Type of structural variant">\n')
    out.write('##INFO=<ID=SVLEN,Number=.,Type=Integer,Description="Difference in length between REF and ALT alleles">\n')
    out.write('##INFO=<ID=END,Number=1,Type=Integer,Description="End position of the variant">\n')
    out.write('##INFO=<ID=IMPRECISE,Number=0,Type=Flag,Description="Imprecise structural variation">\n')
    out.write('##INFO=<ID=CIPOS,Number=2,Type=Integer,Description="Confidence interval around POS">\n')
    out.write('##INFO=<ID=CIEND,Number=2,Type=Integer,Description="Confidence interval around END">\n')
    out.write('##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n')
    out.write('##FORMAT=<ID=GQ,Number=1,Type=Integer,Description="Genotype Quality">\n')
    out.write('##FORMAT=<ID=DP,Number=1,Type=Integer,Description="Read Depth">\n')
    out.write('##FORMAT=<ID=HQ,Number=2,Type=Integer,Description="Haplotype Quality">\n')
    out.write('##FORMAT=<ID=PL,Number=G,Type=Integer,Description="Phred-scaled Genotype Likelihoods">\n')
    out.write('##FORMAT=<ID=GP,Number=G,Type=Float,Description="Phred-scaled Genotype Posteriors">\n')
    out.write('##FORMAT=<ID=GQL,Number=.,Type=String,Description="Ploidy-state Genotype Likelihoods">\n')
    out.write('##FORMAT=<ID=MQ,Number=1,Type=Integer,Description="RMS Mapping Quality">\n')
    out.write('##FORMAT=<ID=PS,Number=1,Type=String,Description="Phase Set">\n')
    out.write('##FORMAT=<ID=PQ,Number=1,Type=Integer,Description="Phasing Quality">\n')
    if seq_dict is None:
        # rebuild contig lines from the denormalized variant columns
        seen: Dict[str, int] = {}
        for v in variants.select(["referenceName",
                                  "referenceLength"]).to_pylist():
            if v["referenceName"] is not None and \
                    v["referenceName"] not in seen:
                seen[v["referenceName"]] = v["referenceLength"] or 0
        seq_dict = SequenceDictionary(
            SequenceRecord(i, n, l) for i, (n, l) in
            enumerate(seen.items()))
    for rec in seq_dict:
        out.write(f"##contig=<ID={rec.name},length={rec.length}>\n")

    header = ["#CHROM", "POS", "ID", "REF", "ALT", "QUAL", "FILTER",
              "INFO"]
    if sample_order:
        header += ["FORMAT"] + sample_order
    out.write("\t".join(header) + "\n")


def _write_vcf_records(out, variants: pa.Table, genotypes: pa.Table,
                       sample_order: List[str]) -> None:
    """Emit the data lines for one (variants, genotypes) slice with a FIXED
    global sample column order — the slice-local body of :func:`write_vcf`,
    callable per genome window by the streaming adam2vcf."""
    g_by_site: Dict[Tuple, List[dict]] = {}
    for g in genotypes.to_pylist():
        g_by_site.setdefault((g["referenceName"], g["position"]),
                             []).append(g)

    v_by_site: Dict[Tuple, List[dict]] = {}
    for v in variants.to_pylist():
        v_by_site.setdefault((v["referenceName"], v["position"]),
                             []).append(v)
    # reference-only sites (ALT=".") exist only in the genotype table
    for (chrom, pos), gs in g_by_site.items():
        v_by_site.setdefault((chrom, pos), [])

    for (chrom, pos), vs in sorted(v_by_site.items(),
                                   key=lambda kv: (kv[0][0] or "",
                                                   kv[0][1])):
        site_genotypes = g_by_site.get((chrom, pos), [])
        ref = vs[0]["referenceAllele"] if vs else \
            site_genotypes[0]["referenceAllele"]
        # reference-allele variant rows (computed site stats) never
        # appear in ALT — only true alternate alleles do
        alt_vs = [v for v in vs if not v.get("isReference")]
        # Complex (symbolic) alleles carry no base string; rebuild the
        # symbolic ALT from the SV type (the base string is likewise
        # unrecoverable in the reference, convertType :244-252)
        alts = [v["variant"] if v["variant"] is not None else
                "<%s>" % _SV_CODE_OF_TYPE.get(v.get("svType") or "UNK",
                                              v.get("svType") or "UNK")
                for v in alt_vs]
        vs = alt_vs or vs
        if not vs:
            vs = [{key: None for key in
                   ("id", "quality", "filters", "numberOfSamplesWithData",
                    "totalSiteMapCounts", "alleleFrequency",
                    "siteRmsMappingQuality", "siteMapQZeroCounts")} |
                  {"filtersRun": False}]
        info_parts = []
        if vs[0]["numberOfSamplesWithData"] is not None:
            info_parts.append(f"NS={vs[0]['numberOfSamplesWithData']}")
        if vs[0]["totalSiteMapCounts"] is not None:
            info_parts.append(f"DP={vs[0]['totalSiteMapCounts']}")
        afs = [v["alleleFrequency"] for v in vs]
        if any(a is not None for a in afs):
            info_parts.append(
                "AF=" + ",".join("." if a is None else f"{a:g}"
                                 for a in afs))
        if vs[0].get("rmsBaseQuality") is not None:
            info_parts.append(f"BQ={vs[0]['rmsBaseQuality']}")
        if vs[0]["siteRmsMappingQuality"] is not None:
            info_parts.append(f"MQ={vs[0]['siteRmsMappingQuality']}")
        if vs[0]["siteMapQZeroCounts"] is not None:
            info_parts.append(f"MQ0={vs[0]['siteMapQZeroCounts']}")
        if vs[0].get("svType") is not None:
            # unmapped codes (BND etc.) were kept raw — emit verbatim
            info_parts.append(
                "SVTYPE="
                f"{_SV_CODE_OF_TYPE.get(vs[0]['svType'], vs[0]['svType'])}")
            if vs[0].get("svIsPrecise") is False:
                info_parts.append("IMPRECISE")
            if vs[0].get("svLength") is not None:
                info_parts.append(f"SVLEN={vs[0]['svLength']}")
            if vs[0].get("svEnd") is not None:
                info_parts.append(f"END={vs[0]['svEnd'] + 1}")
            if vs[0].get("svConfidenceIntervalStartLow") is not None:
                info_parts.append(
                    f"CIPOS={vs[0]['svConfidenceIntervalStartLow']},"
                    f"{vs[0]['svConfidenceIntervalStartHigh']}")
            if vs[0].get("svConfidenceIntervalEndLow") is not None:
                info_parts.append(
                    f"CIEND={vs[0]['svConfidenceIntervalEndLow']},"
                    f"{vs[0]['svConfidenceIntervalEndHigh']}")
        filt = "." if not vs[0]["filtersRun"] else \
            (vs[0]["filters"] or "PASS")
        row = [chrom, str(pos + 1), vs[0]["id"] or ".", ref,
               ",".join(alts) or ".",
               str(vs[0]["quality"]) if vs[0]["quality"] is not None else ".",
               filt, ";".join(info_parts) or "."]

        site_gs = g_by_site.get((chrom, pos), [])
        if sample_order:
            # per-site FORMAT: GT plus whichever fields any sample
            # carries (the reference round-trips GQ/DP/HQ/PL/GP/GQL/
            # MQ/PS/PQ, VariantContextConverter.scala:362-449)
            field_of = {"GQ": "genotypeQuality", "DP": "depth",
                        "HQ": "haplotypeQuality",
                        "PL": "phredLikelihoods",
                        "GP": "phredPosteriorLikelihoods",
                        "GQL": "ploidyStateGenotypeLikelihoods",
                        "MQ": "rmsMapQuality", "PS": "phaseSetId",
                        "PQ": "phaseQuality"}
            keys = [k for k, fld in field_of.items()
                    if any(g.get(fld) is not None for g in site_gs)]
            row.append(":".join(["GT"] + keys))
            alleles = [ref] + alts
            for sample in sample_order:
                gs = sorted((g for g in site_gs
                             if g["sampleId"] == sample),
                            key=lambda g: g["haplotypeNumber"] or 0)
                if not gs:
                    row.append("./.")
                    continue
                sep = "|" if gs[0]["isPhased"] else "/"
                calls = [str(alleles.index(g["allele"]))
                         if g["allele"] in alleles else "." for g in gs]
                # pad half-calls back to declared ploidy ("0/." etc.)
                ploidy = gs[0]["ploidy"] or len(calls)
                calls += ["."] * (ploidy - len(calls))
                cols = [sep.join(calls)]
                for k in keys:
                    if k == "HQ":  # one value per haplotype
                        hqs = [g.get("haplotypeQuality") for g in gs]
                        cols.append(
                            ",".join("." if h is None else str(h)
                                     for h in hqs)
                            if any(h is not None for h in hqs) else ".")
                        continue
                    v = gs[0].get(field_of[k])
                    cols.append("." if v is None else str(v))
                row.append(":".join(cols))
        out.write("\t".join(row) + "\n")
