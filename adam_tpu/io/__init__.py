"""IO layer: format codecs + streaming.  Shared row-conversion helper lives
here so the SAM and BAM streamed parsers build identical chunk tables."""

import pyarrow as pa

from .. import schema as S


def read_rows_to_table(rows) -> pa.Table:
    """Row dicts -> an Arrow table over READ_SCHEMA."""
    cols = {name: [] for name in S.READ_SCHEMA.names}
    for row in rows:
        for name in S.READ_SCHEMA.names:
            cols[name].append(row.get(name))
    return pa.Table.from_pydict(cols, schema=S.READ_SCHEMA)
