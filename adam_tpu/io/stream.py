"""Streaming read input: format dispatch for chunked pipelines.

The reference's pipelines are streaming by construction — Spark partitions
flow through executors without ever materializing the dataset on one node
(rdd/AdamContext.scala:122-161).  The round-1 build loaded every input into
one in-memory Arrow table; this module is the streaming counterpart of
``io/dispatch.load_reads``: one API that yields bounded Arrow table chunks
from SAM, BAM, or Parquet, with the dictionaries available up front (from
the header when there is one).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import pyarrow as pa

from ..models.dictionary import RecordGroupDictionary, SequenceDictionary

DEFAULT_CHUNK_ROWS = 1 << 20


class ReadStream:
    """A chunked read source: iterate for ``pa.Table`` chunks.

    ``seq_dict``/``rg_dict`` are None for Parquet datasets (reconstruct from
    the denormalized columns, as the reference does,
    AdamContext.scala:175-236); for SAM/BAM they come from the header before
    the first chunk.  ``rg_dict`` may still gain groups while a SAM stream is
    consumed (RG tags without header lines register lazily).
    """

    def __init__(self, chunks: Iterator[pa.Table],
                 seq_dict: Optional[SequenceDictionary],
                 rg_dict: Optional[RecordGroupDictionary]):
        self._chunks = chunks
        self.seq_dict = seq_dict
        self.rg_dict = rg_dict

    def __iter__(self) -> Iterator[pa.Table]:
        return iter(self._chunks)


def _projected(chunks, columns, filters):
    for table in chunks:
        if columns is not None:
            table = table.select(list(columns))
        if filters is not None:
            table = table.filter(filters)
        if table.num_rows:
            yield table


def open_read_stream(path: str, *, columns: Optional[Sequence[str]] = None,
                     filters=None,
                     chunk_rows: int = DEFAULT_CHUNK_ROWS,
                     io_procs: int = 1,
                     stringency: str = "strict") -> ReadStream:
    """Open SAM/BAM/Parquet reads as a bounded-memory chunk stream.

    ``io_procs > 1`` inflates BGZF (.bam) across a process pool — the
    byte stream is identical, decode just stops being one-core-bound.
    ``stringency`` applies to SAM text parsing (strict/lenient/silent,
    Bam2Adam.scala:46-47); BAM and Parquet are binary formats whose
    decode is structurally strict.

    When an I/O-ledger pass scope is active (``obs.ioledger.pass_scope``
    — the streaming passes set one around their stream opens), the
    source's on-disk bytes count as that pass's decoded input; outside a
    scope this records nothing."""
    from ..obs import ioledger

    p = str(path)
    ioledger.record_input(p)
    if p.endswith(".bam"):
        from .fastbam import open_bam_arrow_stream
        sd, rg, gen = open_bam_arrow_stream(p, chunk_rows=chunk_rows,
                                            io_procs=io_procs)
        return ReadStream(_projected(gen, columns, filters), sd, rg)
    if p.endswith(".sam"):
        from .sam import open_sam_stream
        sd, rg, gen = open_sam_stream(p, chunk_rows=chunk_rows,
                                      stringency=stringency)
        return ReadStream(_projected(gen, columns, filters), sd, rg)
    from . import parquet as pqio
    gen = pqio.iter_tables(p, columns=columns, filters=filters,
                           chunk_rows=chunk_rows)
    return ReadStream(gen, None, None)
