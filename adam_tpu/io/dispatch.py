"""Format dispatch by file extension — the reference's ``adamLoad``
(rdd/AdamContext.scala:106-161,318-332): .sam/.bam -> SAM parsing, .vcf ->
VCF, anything else -> Parquet dataset."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import pyarrow as pa

from ..models.dictionary import RecordGroupDictionary, SequenceDictionary
from . import parquet as pqio
from .sam import read_sam

#: columns the flagstat command projects — the 13-field projection of
#: cli/FlagStat.scala:50-57 collapses to 4 columns once the 11 flag booleans
#: fold into the packed ``flags`` word (projections.ADAMRecordField).
def _flagstat_columns():
    from ..projections import projection
    return tuple(projection(
        "readPaired", "properPair", "readMapped", "mateMapped",
        "readNegativeStrand", "firstOfPair", "secondOfPair",
        "primaryAlignment", "failedVendorQualityChecks", "duplicateRead",
        "mapq", "referenceId", "mateReferenceId"))


FLAGSTAT_COLUMNS = _flagstat_columns()


def load_reads(path: str, *, columns: Optional[Sequence[str]] = None,
               filters=None, stringency: str = "strict"
               ) -> Tuple[pa.Table, Optional[SequenceDictionary],
                          Optional[RecordGroupDictionary]]:
    """Load reads from SAM or Parquet; returns (table, seq_dict, rg_dict).

    Dictionaries come from the header for SAM; for Parquet they are
    reconstructed from the denormalized columns on demand (the reference
    rebuilds them by scanning and deduplicating, AdamContext.scala:175-236).
    """
    p = str(path)
    if p.endswith(".sam") or p.endswith(".bam"):
        if p.endswith(".bam"):
            # native Arrow decoder when built; pure-Python codec otherwise
            from .. import schema as S
            from .fastbam import open_bam_arrow_stream
            sd, rg, gen = open_bam_arrow_stream(p)
            tables = list(gen)
            table = pa.concat_tables(tables) if tables else \
                pa.Table.from_pydict({n: [] for n in S.READ_SCHEMA.names},
                                     schema=S.READ_SCHEMA)
        else:
            table, sd, rg = read_sam(p, stringency=stringency)
        if columns is not None:
            table = table.select([c for c in columns])
        if filters is not None:
            table = table.filter(filters)
        return table, sd, rg
    table = pqio.load_table(p, columns=columns, filters=filters)
    return table, None, None


def remap_reference_ids(table: pa.Table, id_map) -> pa.Table:
    """Rewrite referenceId/mateReferenceId through ``id_map`` — the
    reference's broadcast remap (rich/RichRDDReferenceRecords.scala:26-48);
    identity maps are skipped, like the reference.  Vectorized: one
    sorted-key binary search (searchsorted) replaces the per-row dict
    walk (this sits on streaming compare's per-bucket path)."""
    if all(k == v for k, v in id_map.items()):
        return table
    import numpy as np
    keys = np.fromiter(id_map.keys(), np.int64, len(id_map))
    vals_map = np.fromiter(id_map.values(), np.int64, len(id_map))
    order = np.argsort(keys)
    skeys, svals = keys[order], vals_map[order]
    # searchsorted, NOT a dense LUT over the key span: nonoverlapping_hash
    # contig ids reach ~2^30, so a span-sized arange would allocate
    # gigabytes for a map of a few dozen entries
    for col in ("referenceId", "mateReferenceId"):
        if col not in table.column_names:
            continue
        arr = table.column(col)
        vals = arr.to_numpy(zero_copy_only=False)
        nulls = np.isnan(vals) if vals.dtype.kind == "f" else \
            np.zeros(len(vals), bool)
        v = np.where(nulls, skeys[0], vals).astype(np.int64)
        idx = np.searchsorted(skeys, v)
        idx_c = np.minimum(idx, len(skeys) - 1)
        hit = skeys[idx_c] == v
        new = np.where(hit, svals[idx_c], v)   # unmapped ids pass through
        # hand pyarrow the int64 array: its checked cast raises loudly on
        # an id past int32 instead of silently wrapping
        table = table.set_column(
            table.column_names.index(col), col,
            pa.array(new, pa.int32(),
                     mask=nulls if nulls.any() else None))
    return table


def load_reads_union(paths):
    """Load several read files into one table with reconciled contig ids
    (AdamContext.loadAdamFromPaths :364-383): each file's dictionary maps
    onto the accumulated one via SequenceDictionary.map_to, its ids are
    rewritten, and the tables concatenate."""
    acc_dict = None
    tables = []
    rg = None
    for p in paths:
        table, sd, rgd = load_reads(p)
        if sd is None:
            sd = sequence_dictionary_from_reads(table)
        if acc_dict is None:
            acc_dict = sd
        else:
            id_map = sd.map_to(acc_dict)
            table = remap_reference_ids(table, id_map)
            acc_dict = acc_dict + sd.remap(id_map)
        rg = rg or rgd
        tables.append(table)
    return pa.concat_tables(tables), acc_dict, rg


def record_group_dictionary_from_reads(table: pa.Table) -> RecordGroupDictionary:
    """Rebuild record groups from the denormalized recordGroup* columns
    (the reference reconstructs them by scan+dedup the same way it does the
    sequence dictionary, AdamContext.scala:175-236)."""
    from ..models.dictionary import RecordGroup
    cols = ("recordGroupName", "recordGroupId", "recordGroupSequencingCenter",
            "recordGroupDescription", "recordGroupRunDateEpoch",
            "recordGroupFlowOrder", "recordGroupKeySequence",
            "recordGroupLibrary", "recordGroupPredictedMedianInsertSize",
            "recordGroupPlatform", "recordGroupPlatformUnit",
            "recordGroupSample")
    if not all(c in table.column_names for c in cols):
        return RecordGroupDictionary()
    sub = table.select(cols).to_pydict()
    seen = {}
    for i in range(table.num_rows):
        name = sub["recordGroupName"][i]
        if name is None or name in seen:
            continue
        seen[name] = RecordGroup(
            id=name, index=sub["recordGroupId"][i] or 0,
            sequencing_center=sub["recordGroupSequencingCenter"][i],
            description=sub["recordGroupDescription"][i],
            run_date_epoch=sub["recordGroupRunDateEpoch"][i],
            flow_order=sub["recordGroupFlowOrder"][i],
            key_sequence=sub["recordGroupKeySequence"][i],
            library=sub["recordGroupLibrary"][i],
            predicted_median_insert_size=sub["recordGroupPredictedMedianInsertSize"][i],
            platform=sub["recordGroupPlatform"][i],
            platform_unit=sub["recordGroupPlatformUnit"][i],
            sample=sub["recordGroupSample"][i])
    return RecordGroupDictionary(seen.values())


def sequence_dictionary_from_reads(table: pa.Table) -> SequenceDictionary:
    """Rebuild the sequence dictionary from denormalized read fields
    (AdamContext.scala:175-236: scan + dedup of
    referenceId/Name/Length/Url and the mate variants)."""
    from ..models.dictionary import SequenceRecord
    cols = ("referenceId", "referenceName", "referenceLength", "referenceUrl")
    mate_cols = ("mateReferenceId", "mateReference", "mateReferenceLength",
                 "mateReferenceUrl")
    seen = {}
    for cset in (cols, mate_cols):
        if not all(c in table.column_names for c in cset):
            continue
        sub = table.select(cset).to_pydict()
        ids, names, lens, urls = (sub[c] for c in cset)
        for i, n, l, u in zip(ids, names, lens, urls):
            if i is None or n is None:
                continue
            seen[(i, n)] = SequenceRecord(i, n, l or 0, u)
    return SequenceDictionary(seen.values())
