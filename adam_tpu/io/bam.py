"""BAM binary format: BGZF + BAM record codec.

The reference leans on samtools-jar + hadoop-bam for BAM decoding
(pom.xml:299-345, AdamContext.adamBamLoad :122-137).  This module implements
the format natively: BGZF block decompression, the BAM header (SAM spec
section 4.2), and the alignment record codec — producing the same Arrow
reads table as the SAM parser, via the same converter semantics
(SAMRecordConverter.scala:25-146).

A writer is included (round-trip tests + bam export).  The hot-path C++
version of this decoder lives in ``native/``; this pure-Python codec is the
reference implementation and fallback.
"""

from __future__ import annotations

import gzip
import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ..models.dictionary import (RecordGroup, RecordGroupDictionary,
                                 SequenceDictionary, SequenceRecord)
from .. import schema as S

_BAM_MAGIC = b"BAM\x01"
#: 4-bit seq codes (SAM spec 4.2.3)
SEQ_CODE = "=ACMGRSVTWYHKDBN"
_CIGAR_OPS = "MIDNSHP=X"
_MAPQ_UNKNOWN = 255


def _decompress_bgzf(data: bytes) -> bytes:
    """BGZF is a series of gzip members; decompress them all."""
    out = []
    pos = 0
    while pos < len(data):
        d = zlib.decompressobj(wbits=31)
        out.append(d.decompress(data[pos:]))
        consumed = len(data) - pos - len(d.unused_data)
        if consumed <= 0:
            break
        pos += consumed
    return b"".join(out)


def _parse_tag_value(data: bytes, off: int) -> Tuple[str, str, object, int]:
    """One optional field -> (tag, sam_type, value, new_offset)."""
    tag = data[off:off + 2].decode()
    typ = chr(data[off + 2])
    off += 3
    if typ == "A":
        return tag, "A", chr(data[off]), off + 1
    int_types = {"c": ("b", 1), "C": ("B", 1), "s": ("<h", 2), "S": ("<H", 2),
                 "i": ("<i", 4), "I": ("<I", 4)}
    if typ in int_types:
        fmt, size = int_types[typ]
        return tag, "i", struct.unpack_from(fmt, data, off)[0], off + size
    if typ == "f":
        return tag, "f", struct.unpack_from("<f", data, off)[0], off + 4
    if typ in "ZH":
        end = data.index(b"\x00", off)
        return tag, typ, data[off:end].decode(), end + 1
    if typ == "B":
        sub = chr(data[off])
        n = struct.unpack_from("<i", data, off + 1)[0]
        fmt, size = {"c": ("b", 1), "C": ("B", 1), "s": ("<h", 2),
                     "S": ("<H", 2), "i": ("<i", 4), "I": ("<I", 4),
                     "f": ("<f", 4)}[sub]
        vals = [struct.unpack_from(fmt, data, off + 5 + i * size)[0]
                for i in range(n)]
        value = sub + "," + ",".join(str(v) for v in vals)
        return tag, "B", value, off + 5 + n * size
    raise ValueError(f"unknown BAM tag type {typ!r}")


def load_decompressed(path) -> bytes:
    with open(path, "rb") as f:
        raw = f.read()
    return _decompress_bgzf(raw) if raw[:2] == b"\x1f\x8b" else raw


def parse_header(data: bytes, path="<bytes>"
                 ) -> Tuple[SequenceDictionary, RecordGroupDictionary, int]:
    """BAM header -> (seq dict, record groups, first-record offset)."""
    from ..errors import FormatError
    if data[:4] != _BAM_MAGIC:
        raise FormatError(f"{path}: not a BAM file")
    l_text = struct.unpack_from("<i", data, 4)[0]
    text = data[8:8 + l_text].decode("utf-8", "replace").rstrip("\x00")
    off = 8 + l_text
    n_ref = struct.unpack_from("<i", data, off)[0]
    off += 4
    refs: List[SequenceRecord] = []
    for i in range(n_ref):
        l_name = struct.unpack_from("<i", data, off)[0]
        name = data[off + 4:off + 4 + l_name - 1].decode()
        l_ref = struct.unpack_from("<i", data, off + 4 + l_name)[0]
        refs.append(SequenceRecord(i, name, l_ref))
        off += 8 + l_name
    rg_dict = RecordGroupDictionary.from_sam_header_lines(
        l for l in text.splitlines() if l.startswith("@RG"))
    return SequenceDictionary(refs), rg_dict, off


def _bgzf_member_size(buf, off: int):
    """Parse one BGZF member header at ``off`` -> total member size, or
    None when the BSIZE ('BC') extra subfield is absent / header truncated.
    """
    if off + 18 > len(buf):
        return None
    if buf[off] != 0x1F or buf[off + 1] != 0x8B or not (buf[off + 3] & 4):
        return None
    xlen = buf[off + 10] | (buf[off + 11] << 8)
    p, end = off + 12, off + 12 + xlen
    if end > len(buf):
        return None
    while p + 4 <= end:
        si1, si2 = buf[p], buf[p + 1]
        slen = buf[p + 2] | (buf[p + 3] << 8)
        if si1 == 66 and si2 == 67 and slen == 2:  # 'B','C'
            return (buf[p + 4] | (buf[p + 5] << 8)) + 1
        p += 4 + slen
    return None


def _iter_decompressed_bgzf(f, chunk_bytes: int):
    """Threaded BGZF decompression: members are independent deflate blocks,
    and ``zlib.decompress`` releases the GIL, so a thread pool inflates a
    batch of members in parallel (~8x one thread)."""
    import os as _os
    from concurrent.futures import ThreadPoolExecutor

    from ..errors import FormatError

    def inflate(view):
        # strip 12-byte header + extra field; trailing 8 bytes are crc+isize
        xlen = view[10] | (view[11] << 8)
        isize = int.from_bytes(view[-4:], "little")
        return zlib.decompress(bytes(view[12 + xlen:-8]), wbits=-15,
                               bufsize=isize or 1)

    with ThreadPoolExecutor(min(8, _os.cpu_count() or 1)) as pool:
        buf = bytearray()
        eof = False
        target = chunk_bytes
        while not eof or buf:
            while not eof and len(buf) < target:
                raw = f.read(chunk_bytes)
                if not raw:
                    eof = True
                else:
                    buf += raw
            members = []
            off = 0
            while True:
                size = _bgzf_member_size(buf, off)
                if size is None or off + size > len(buf):
                    break
                members.append(memoryview(buf)[off:off + size])
                off += size
            if not members:
                if buf and eof:
                    raise FormatError(
                        f"{len(buf)} trailing bytes form no BGZF member")
                if not eof:
                    # one member larger than the current window: widen it
                    target = max(target * 2, len(buf) + chunk_bytes)
                    continue
                break
            target = chunk_bytes
            chunk = b"".join(pool.map(inflate, members))
            del members  # release memoryviews before compacting
            del buf[:off]
            if chunk:
                yield chunk


def iter_decompressed(path, chunk_bytes: int = 1 << 24, procs: int = 1):
    """Stream a (possibly BGZF-compressed) file as decompressed byte chunks.

    The whole-file :func:`load_decompressed` holds the full decompressed BAM
    in memory; this generator bounds host RSS for multi-GB inputs.  BGZF
    inputs (the normal case) decompress member-parallel across a thread
    pool; plain whole-file gzip falls back to sequential streaming.

    ``procs > 1`` inflates member-aligned compressed segments across a
    process pool instead (``io/bgzf_procs``) — byte-identical stream,
    process-level decode parallelism.
    """
    if procs > 1:
        from .bgzf_procs import iter_decompressed_procs
        yield from iter_decompressed_procs(path, procs,
                                           chunk_bytes=chunk_bytes)
        return
    with open(path, "rb") as f:
        head = f.read(18)
        f.seek(0)
        if head[:2] != b"\x1f\x8b":
            while True:
                raw = f.read(chunk_bytes)
                if not raw:
                    return
                yield raw
        if _bgzf_member_size(head, 0) is not None:
            yield from _iter_decompressed_bgzf(f, chunk_bytes)
            return
        d = zlib.decompressobj(wbits=31)
        while True:
            raw = f.read(chunk_bytes)
            if not raw:
                break
            out = [d.decompress(raw)]
            # a raw chunk can close several gzip members; chain through them
            while d.eof:
                leftover = d.unused_data
                d = zlib.decompressobj(wbits=31)
                if not leftover:
                    break
                out.append(d.decompress(leftover))
            chunk = b"".join(out)
            if chunk:
                yield chunk


def _iter_bgzf_members(path, chunk_bytes: int = 1 << 24, start: int = 0):
    """Yield ``(file_off, member_size, payload)`` per BGZF member from
    byte ``start`` — members are self-delimiting, so a mid-file start
    works as long as it lands ON a member boundary (a BGZF virtual
    offset's file half).  Incomplete trailing bytes end the walk; the
    record layer decides whether that is truncation."""
    with open(path, "rb") as f:
        if start:
            f.seek(start)
        buf = bytearray()
        off = start
        eof = False
        while True:
            size = _bgzf_member_size(buf, 0)
            while not eof and (size is None or size > len(buf)):
                raw = f.read(chunk_bytes)
                if not raw:
                    eof = True
                else:
                    buf += raw
                    size = _bgzf_member_size(buf, 0)
            if size is None or size > len(buf):
                return
            view = bytes(buf[:size])
            xlen = view[10] | (view[11] << 8)
            isize = int.from_bytes(view[-4:], "little")
            yield off, size, zlib.decompress(view[12 + xlen:-8],
                                             wbits=-15, bufsize=isize or 1)
            del buf[:size]
            off += size


def scan_bam_units(path, unit_rows: Optional[int] = None):
    """Length-walk a BGZF BAM — total rows plus the BGZF virtual offset
    of each unit's first record — WITHOUT building Arrow rows.

    The walk hops ``block_size`` fields (4 bytes read per record, no
    field decode, no Python row objects), so counting a file costs one
    inflate pass instead of a full decode.  With ``unit_rows`` set it
    also emits ``voffs[k] = [member_file_off, intra_member_off]`` for
    unit ``k`` — the seek target :func:`open_bam_stream_at` enters at,
    which is what collapses a shard's re-decode bytes to ~0.

    Returns ``None`` when the file is not BGZF (plain gzip / raw BAM
    has no member boundaries to seek to); raises FormatError on the
    same corrupt/truncated shapes the decoder would.
    """
    import bisect

    from ..errors import FormatError
    with open(path, "rb") as f:
        head = f.read(18)
    if head[:2] != b"\x1f\x8b" or _bgzf_member_size(head, 0) is None:
        return None
    gen = _iter_bgzf_members(path)
    mem_starts: List[int] = []      # global decompressed start per member
    mem_offs: List[int] = []        # file offset per member
    buf = bytearray()
    base = 0                        # global offset of buf[0]
    eof = False

    def fill(need_end: int) -> None:
        nonlocal eof
        while not eof and base + len(buf) < need_end:
            got = next(gen, None)
            if got is None:
                eof = True
            else:
                foff, _size, payload = got
                mem_starts.append(base + len(buf))
                mem_offs.append(foff)
                buf.extend(payload)

    pos = None                      # global offset of the next record
    while pos is None:
        try:
            if len(buf) >= 4:
                _, _, first = parse_header(bytes(buf), path)
                pos = first
        except (struct.error, IndexError):
            pass
        if pos is None:
            if eof:
                raise FormatError(f"{path}: truncated BAM header")
            fill(base + len(buf) + 1)

    total = 0
    voffs: List[List[int]] = []
    while True:
        fill(pos + 4)
        end_g = base + len(buf)
        if pos >= end_g:
            if pos > end_g:
                raise FormatError(
                    f"{path}: {pos - end_g} byte(s) short of a complete "
                    "record (truncated file?)")
            break
        if pos + 4 > end_g:
            raise FormatError(
                f"{path}: {end_g - pos} trailing bytes form no complete "
                "record (truncated file?)")
        block_size = struct.unpack_from("<i", buf, pos - base)[0]
        if block_size < 32:
            from ..errors import FormatError as _FE
            raise _FE(f"corrupt BAM record: block_size {block_size} at "
                      f"decompressed byte {pos}")
        if unit_rows and total % unit_rows == 0:
            i = bisect.bisect_right(mem_starts, pos) - 1
            voffs.append([mem_offs[i], pos - mem_starts[i]])
        total += 1
        pos += 4 + block_size
        # bound memory: drop members wholly behind the cursor
        if pos - base > (1 << 25):
            i = bisect.bisect_right(mem_starts, pos) - 1
            if i > 0:
                cut = mem_starts[i]
                del buf[:cut - base]
                base = cut
                del mem_starts[:i]
                del mem_offs[:i]
    return dict(total_rows=total,
                unit_rows=int(unit_rows) if unit_rows else None,
                voffs=voffs if unit_rows else None)


def open_bam_stream_at(path, member_off: int, intra_off: int, *,
                       chunk_rows: int = 1 << 20,
                       chunk_bytes: int = 1 << 24, io_procs: int = 1,
                       on_bytes=None):
    """:func:`open_bam_stream`, entered at a BGZF virtual offset.

    The header still parses from byte 0 (seq/RG dictionaries live
    there), then decoding seeks straight to ``member_off`` and skips
    ``intra_off`` decompressed bytes — everything between the header
    and the target member is never read, which is the entire point.
    ``io_procs > 1`` inflates the seeked tail through the
    ``io/bgzf_procs`` segment pool (member-aligned, byte-identical).
    ``on_bytes`` (when given) receives the COMPRESSED size of every
    member/segment actually inflated, so the I/O ledger can charge what
    this reader truly cost instead of the whole file.
    """
    from ..errors import FormatError

    hdr_iter = _iter_bgzf_members(path, chunk_bytes)
    hbuf = bytearray()
    seq_dict = rg_dict = None
    for _foff, size, payload in hdr_iter:
        hbuf += payload
        if on_bytes is not None:
            on_bytes(size)
        try:
            seq_dict, rg_dict, _first = parse_header(bytes(hbuf), path)
            break
        except (struct.error, IndexError):
            continue
    hdr_iter.close()
    if seq_dict is None:
        raise FormatError(f"{path}: truncated BAM header")

    def pieces():
        if io_procs > 1:
            from .bgzf_procs import iter_decompressed_procs
            yield from iter_decompressed_procs(
                path, io_procs, chunk_bytes=chunk_bytes,
                start=member_off, on_segment=on_bytes)
            return
        for _foff, size, payload in _iter_bgzf_members(
                path, chunk_bytes, start=member_off):
            if on_bytes is not None:
                on_bytes(size)
            yield payload

    def gen():
        from ..resilience import faults as _faults
        it = pieces()
        buf = bytearray()
        off = intra_off
        rows = []
        exhausted = False
        while True:
            parsed = _parse_record(buf, off, seq_dict, rg_dict)
            if parsed is None:
                if exhausted:
                    break
                if off and off <= len(buf):
                    del buf[:off]
                    off = 0
                got = next(it, None)
                if got is None:
                    exhausted = True
                else:
                    buf += got
                continue
            # same per-parsed-record injection discipline as the
            # forward decoder; occurrences count from THIS entry point
            _faults.fire("input_record")
            row, off = parsed
            rows.append(row)
            if len(rows) >= chunk_rows:
                yield _rows_to_table(rows)
                rows = []
        if off < len(buf):
            raise FormatError(
                f"{path}: {len(buf) - off} trailing bytes form no "
                "complete record (truncated file?)")
        if rows:
            yield _rows_to_table(rows)

    return seq_dict, rg_dict, gen()


def parse_tag_region(data, p: int, end: int):
    """Walk a record's optional-field region -> (attr strings, MD, RG).

    Shared by the pure-Python record parser and the native decoder's
    float-tag fallback (C cannot reproduce Python's float repr).
    """
    attrs = []
    md = None
    rg_name = None
    while p < end:
        tag, typ, value, p = _parse_tag_value(data, p)
        if tag == "MD":
            md = str(value)
        elif tag == "RG":
            rg_name = str(value)
        else:
            attrs.append(f"{tag}:{typ}:{value}")
    return attrs, md, rg_name


def _parse_record(data, off: int, seq_dict, rg_dict):
    """Parse ONE complete alignment record at ``off``.

    Returns (row_dict, record_end) or None when the buffer ends before the
    record does (streaming callers append more bytes and retry).
    """
    n = len(data)
    if off + 4 > n:
        return None
    block_size = struct.unpack_from("<i", data, off)[0]
    if block_size < 32:  # below the fixed-field floor: corrupt, not partial
        from ..errors import FormatError
        raise FormatError(
            f"corrupt BAM record: block_size {block_size} at byte {off}")
    rec_end = off + 4 + block_size
    if rec_end > n:
        return None
    (ref_id, pos, l_read_name, mapq, _bin, n_cigar, flag, l_seq,
     next_ref, next_pos, _tlen) = struct.unpack_from("<iiBBHHHiiii",
                                                     data, off + 4)
    p = off + 36
    read_name = data[p:p + l_read_name - 1].decode()
    p += l_read_name
    cigar_parts = []
    for ci in range(n_cigar):
        v = struct.unpack_from("<I", data, p + ci * 4)[0]
        cigar_parts.append(f"{v >> 4}{_CIGAR_OPS[v & 0xF]}")
    p += n_cigar * 4
    seq_bytes = data[p:p + (l_seq + 1) // 2]
    seq_chars = []
    for i in range(l_seq):
        b = seq_bytes[i // 2]
        code = (b >> 4) if i % 2 == 0 else (b & 0xF)
        seq_chars.append(SEQ_CODE[code])
    p += (l_seq + 1) // 2
    quals = data[p:p + l_seq]
    p += l_seq
    qual = None if (l_seq == 0 or quals[:1] == b"\xff") else \
        "".join(chr(q + 33) for q in quals)

    attrs, md, rg_name = parse_tag_region(data, p, rec_end)

    row = dict(
        readName=read_name if read_name != "*" else None,
        flags=flag,
        sequence="".join(seq_chars) if l_seq else None,
        qual=qual,
        cigar="".join(cigar_parts) or None,
        mismatchingPositions=md,
        attributes="\t".join(attrs) if attrs else None,
    )
    if ref_id >= 0:
        rec = seq_dict[ref_id]
        row.update(referenceId=ref_id, referenceName=rec.name,
                   referenceLength=rec.length, referenceUrl=rec.url)
        if pos >= 0:
            row["start"] = pos
        if mapq != _MAPQ_UNKNOWN:
            row["mapq"] = mapq
    if next_ref >= 0:
        rec = seq_dict[next_ref]
        row.update(mateReferenceId=next_ref, mateReference=rec.name,
                   mateReferenceLength=rec.length,
                   mateReferenceUrl=rec.url)
        if next_pos >= 0:
            row["mateAlignmentStart"] = next_pos
    if rg_name is not None and rg_name in rg_dict:
        g = rg_dict[rg_name]
        row.update(
            recordGroupName=g.id, recordGroupId=g.index,
            recordGroupSequencingCenter=g.sequencing_center,
            recordGroupDescription=g.description,
            recordGroupRunDateEpoch=g.run_date_epoch,
            recordGroupFlowOrder=g.flow_order,
            recordGroupKeySequence=g.key_sequence,
            recordGroupLibrary=g.library,
            recordGroupPredictedMedianInsertSize=g.predicted_median_insert_size,
            recordGroupPlatform=g.platform,
            recordGroupPlatformUnit=g.platform_unit,
            recordGroupSample=g.sample)
    return row, rec_end


def _rows_to_table(rows) -> pa.Table:
    from . import read_rows_to_table
    return read_rows_to_table(rows)


def stream_header(byte_iter, path):
    """Accumulate streamed bytes until the BAM header parses.

    Returns (seq_dict, rg_dict, first_record_offset, buffer) where ``buffer``
    is a bytearray already holding the consumed bytes.
    """
    from ..errors import FormatError

    buf = bytearray()
    for piece in byte_iter:
        buf += piece
        try:
            sd, rg, off = parse_header(bytes(buf), path)
            return sd, rg, off, buf
        except (struct.error, IndexError):
            continue  # header larger than the bytes so far
    try:
        sd, rg, off = parse_header(bytes(buf), path)
        return sd, rg, off, buf
    except (struct.error, IndexError) as e:
        raise FormatError(f"{path}: truncated BAM header") from e


def open_bam_stream(path, chunk_rows: int = 1 << 20,
                    chunk_bytes: int = 1 << 24, io_procs: int = 1):
    """(seq_dict, rg_dict, generator of Arrow tables) over a streamed BAM.

    Host memory stays bounded by chunk size: bytes decompress incrementally
    (``iter_decompressed``) and records parse as they complete, never
    materializing the whole file.
    """
    from ..errors import FormatError

    byte_iter = iter_decompressed(path, chunk_bytes, procs=io_procs)
    seq_dict, rg_dict, off, buf = stream_header(byte_iter, path)

    def gen():
        nonlocal buf, off
        from ..resilience import faults as _faults
        rows = []
        exhausted = False
        while True:
            parsed = _parse_record(buf, off, seq_dict, rg_dict)
            if parsed is None:
                if exhausted:
                    break
                # compact consumed bytes, then pull more input
                if off:
                    del buf[:off]
                    off = 0
                piece = next(byte_iter, None)
                if piece is None:
                    exhausted = True
                else:
                    buf += piece
                continue
            # input_record injection site — fired once per PARSED record
            # (never on buffer-refill iterations), so occurrence N means
            # the Nth record regardless of chunking, matching read_bam.
            # An 'error' fault raises InjectedFormatError: like a
            # genuinely undecodable BAM record, it is fatal-typed (the
            # binary decoder has no stringency drop path — that exists
            # only for SAM text), so the CLI exits with one clean line.
            _faults.fire("input_record")
            row, off = parsed
            rows.append(row)
            if len(rows) >= chunk_rows:
                yield _rows_to_table(rows)
                rows = []
        if off < len(buf):
            raise FormatError(
                f"{path}: {len(buf) - off} trailing bytes form no complete "
                "record (truncated file?)")
        if rows:
            yield _rows_to_table(rows)

    return seq_dict, rg_dict, gen()


def read_bam(path) -> Tuple[pa.Table, SequenceDictionary,
                            RecordGroupDictionary]:
    """Parse a BAM file into (reads table, seq dict, record groups)."""
    data = load_decompressed(path)
    seq_dict, rg_dict, off = parse_header(data, path)
    from ..resilience import faults as _faults
    rows = []
    while off < len(data):
        parsed = _parse_record(data, off, seq_dict, rg_dict)
        if parsed is None:
            from ..errors import FormatError
            raise FormatError(f"{path}: truncated record at byte {off}")
        # fired once per parsed record (occurrence N = Nth record), the
        # same counting as the streaming decoder
        _faults.fire("input_record")
        row, off = parsed
        rows.append(row)
    return _rows_to_table(rows), seq_dict, rg_dict


# ----------------------------------------------------------------------
# writer (round-trip testing + export)
# ----------------------------------------------------------------------

_SEQ_TO_CODE = {c: i for i, c in enumerate(SEQ_CODE)}
_CIGAR_TO_CODE = {c: i for i, c in enumerate(_CIGAR_OPS)}


def _bgzf_block(payload: bytes) -> bytes:
    comp = zlib.compressobj(6, zlib.DEFLATED, -15)
    deflated = comp.compress(payload) + comp.flush()
    bsize = len(deflated) + 25 + 1
    header = (b"\x1f\x8b\x08\x04\x00\x00\x00\x00\x00\xff" +
              struct.pack("<HBBHH", 6, 66, 67, 2, bsize - 1))
    return header + deflated + struct.pack("<II", zlib.crc32(payload),
                                           len(payload))


_BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")


#: rows serialized per slice — bounds write_bam's Python-object footprint
_WRITE_SLICE_ROWS = 1 << 16


def write_bam(table: pa.Table, seq_dict: SequenceDictionary, path,
              rg_dict: Optional[RecordGroupDictionary] = None) -> None:
    """Serialize a reads table as BGZF-compressed BAM.

    Rows stream out in ``_WRITE_SLICE_ROWS`` slices so the per-row Python
    serializer never materializes the whole table as boxed objects — a
    multi-GB table writes in bounded memory.
    """
    import io as _io
    from .sam import write_sam
    # header text: reuse the SAM writer's header
    buf = _io.StringIO()
    write_sam(table.slice(0, 0), seq_dict, buf, rg_dict)
    text = buf.getvalue().encode()

    body = bytearray()
    body += _BAM_MAGIC
    body += struct.pack("<i", len(text))
    body += text
    recs = list(seq_dict)
    body += struct.pack("<i", len(recs))
    for rec in recs:
        name = rec.name.encode() + b"\x00"
        body += struct.pack("<i", len(name)) + name + \
            struct.pack("<i", rec.length)

    # stream through a temp file + rename: a mid-serialization error must
    # not leave a truncated BGZF (no EOF marker) under the target name
    tmp_path = f"{path}.tmp"
    out = open(tmp_path, "wb")

    def drain(final: bool = False) -> None:
        nonlocal body
        lo = 0
        while len(body) - lo >= 0xFF00 or (final and lo < len(body)):
            out.write(_bgzf_block(bytes(body[lo:lo + 0xFF00])))
            lo += 0xFF00
        del body[:lo]

    import os as _os
    try:
        for slice_lo in range(0, max(table.num_rows, 1), _WRITE_SLICE_ROWS):
            for row in table.slice(slice_lo, _WRITE_SLICE_ROWS).to_pylist():
                name = (row.get("readName") or "*").encode() + b"\x00"
                seq = row.get("sequence") or ""
                qual = row.get("qual")
                from ..util.mdtag import parse_cigar
                cigar = parse_cigar(row.get("cigar")) if row.get("cigar") else []
                rec = bytearray()
                ref_id = row.get("referenceId") if row.get("referenceId") is not None else -1
                pos = row.get("start") if row.get("start") is not None else -1
                mate_ref = row.get("mateReferenceId") \
                    if row.get("mateReferenceId") is not None else -1
                mate_pos = row.get("mateAlignmentStart") \
                    if row.get("mateAlignmentStart") is not None else -1
                mapq = row.get("mapq") if row.get("mapq") is not None else _MAPQ_UNKNOWN
                rec += struct.pack("<iiBBHHHiiii", ref_id, pos, len(name), mapq,
                                   0, len(cigar), row.get("flags") or 0, len(seq),
                                   mate_ref, mate_pos, 0)
                rec += name
                for length, op in cigar:
                    rec += struct.pack("<I", (length << 4) | _CIGAR_TO_CODE[op])
                packed = bytearray()
                for i in range(0, len(seq), 2):
                    hi = _SEQ_TO_CODE.get(seq[i].upper(), 15) << 4
                    lo = _SEQ_TO_CODE.get(seq[i + 1].upper(), 15) \
                        if i + 1 < len(seq) else 0
                    packed.append(hi | lo)
                rec += bytes(packed)
                rec += bytes((ord(c) - 33 for c in qual)) if qual \
                    else b"\xff" * len(seq)
                if row.get("mismatchingPositions") is not None:
                    rec += b"MDZ" + row.get("mismatchingPositions").encode() + b"\x00"
                if row.get("recordGroupName") is not None:
                    rec += b"RGZ" + row.get("recordGroupName").encode() + b"\x00"
                for field in (row.get("attributes") or "").split("\t"):
                    if not field:
                        continue
                    tag, typ, value = field.split(":", 2)
                    if typ == "i":
                        iv = int(value)
                        # values beyond int32 came from unsigned BAM tags
                        rec += tag.encode() + (b"i" + struct.pack("<i", iv)
                                               if iv < (1 << 31)
                                               else b"I" + struct.pack("<I", iv))
                    elif typ == "f":
                        rec += tag.encode() + b"f" + struct.pack("<f", float(value))
                    elif typ == "A":
                        rec += tag.encode() + b"A" + value[:1].encode()
                    else:  # Z/H/B all serialize as text
                        rec += tag.encode() + b"Z" + value.encode() + b"\x00"
                body += struct.pack("<i", len(rec)) + bytes(rec)
            drain()
        drain(final=True)
        out.write(_BGZF_EOF)
        out.close()
        _os.replace(tmp_path, path)
    except BaseException:
        out.close()
        _os.unlink(tmp_path)
        raise
