"""BCF2.2 binary VCF codec — both directions, no htslib.

The reference dispatches ``.bcf`` through hadoop-bam's ``VCFInputFormat``
(rdd/AdamContext.scala:129-137), i.e. it gets the binary codec from a JVM
dependency jar.  Here the codec is native to the framework, like the BAM
(BGZF) codec in ``io/bam.py`` whose block helpers it reuses: a BCF file is
the VCF header text plus binary-encoded records, the whole stream
BGZF-compressed.

Decode strategy: reconstruct exact VCF text lines from the binary records
and feed them through :func:`io.vcf.read_vcf` — one converter owns the
VCF->Arrow field mapping (VariantContextConverter.scala:44-575), and the
binary layer stays a pure transport codec.  Encode is the inverse
(VCF text -> binary), which gives a dependency-free round-trip test and a
``.bcf`` export path the reference never had.

Layout (per the samtools BCFv2.2 spec):
  magic "BCF\\2\\2" | l_text u32 | header text (NUL-terminated) |
  records: l_shared u32, l_indiv u32,
    shared: CHROM i32, POS i32, rlen i32, QUAL f32,
            n_info u16 | n_allele u16, n_sample u24 | n_fmt u8,
            ID (typed str), alleles (n_allele typed str),
            FILTER (typed int vector), n_info x (typed int key, typed value)
    indiv:  n_fmt x (typed int key, typed descriptor, n_sample * values)
Dictionary-of-strings: implicit "PASS" at index 0, then every
FILTER/INFO/FORMAT ID in header order (IDX= overrides); contigs index in
##contig order.
"""

from __future__ import annotations

import io
import re
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .bam import _BGZF_EOF, _bgzf_block, _decompress_bgzf

_MAGIC = b"BCF\x02\x02"

# type codes
_BT_INT8, _BT_INT16, _BT_INT32, _BT_FLOAT, _BT_CHAR = 1, 2, 3, 5, 7
_MISSING = {_BT_INT8: -0x80, _BT_INT16: -0x8000, _BT_INT32: -0x80000000}
_EOV = {_BT_INT8: -0x7F, _BT_INT16: -0x7FFF, _BT_INT32: -0x7FFFFFFF}
_MISSING_FLOAT_BITS = 0x7F800001
_EOV_FLOAT_BITS = 0x7F800002
_INT_FMT = {_BT_INT8: "<b", _BT_INT16: "<h", _BT_INT32: "<i"}


# --------------------------------------------------------------------------
# header dictionaries
# --------------------------------------------------------------------------

_HDR_RE = re.compile(r"##(FILTER|INFO|FORMAT|contig)=<(.*)>\s*$")


def _split_meta(body: str) -> Dict[str, str]:
    """Split `ID=DP,Number=1,Description="a,b"` honoring quoted commas."""
    out: Dict[str, str] = {}
    for m in re.finditer(r'(\w+)=("(?:[^"\\]|\\.)*"|[^,]*)', body):
        v = m.group(2)
        out[m.group(1)] = v[1:-1] if v.startswith('"') else v
    return out


class _HeaderDicts:
    """String and contig dictionaries + declared INFO/FORMAT types."""

    def __init__(self, header_text: str):
        self.strings: List[str] = ["PASS"]
        self.contigs: List[str] = []
        # INFO and FORMAT are distinct namespaces in the spec: the same ID
        # may be declared with different Types in each (e.g. INFO DP Integer
        # vs FORMAT DP String), so each context keeps its own type map
        self.info_types: Dict[str, str] = {}
        self.fmt_types: Dict[str, str] = {"GT": "String"}
        str_idx = {"PASS": 0}
        for line in header_text.splitlines():
            m = _HDR_RE.match(line)
            if not m:
                continue
            kind, meta = m.group(1), _split_meta(m.group(2))
            name = meta.get("ID", "")
            if kind == "contig":
                idx = int(meta["IDX"]) if "IDX" in meta else len(self.contigs)
                while len(self.contigs) <= idx:
                    self.contigs.append("")
                self.contigs[idx] = name
            else:
                if kind == "INFO":
                    self.info_types.setdefault(name,
                                               meta.get("Type", "String"))
                elif kind == "FORMAT":
                    self.fmt_types.setdefault(name,
                                              meta.get("Type", "String"))
                if name not in str_idx:
                    idx = int(meta["IDX"]) if "IDX" in meta else \
                        len(self.strings)
                    while len(self.strings) <= idx:
                        self.strings.append("")
                    self.strings[idx] = name
                    str_idx[name] = idx
        self.string_idx = str_idx
        self.contig_idx = {c: i for i, c in enumerate(self.contigs)}


# --------------------------------------------------------------------------
# typed-value primitives
# --------------------------------------------------------------------------

def _read_desc(buf: bytes, off: int) -> Tuple[int, int, int]:
    b = buf[off]
    off += 1
    btype, length = b & 0xF, b >> 4
    if length == 15:
        vals, off = _read_value(buf, off)
        # the extended length must be a concrete non-negative int: a
        # MISSING/EOV sentinel here is file corruption, and letting the
        # None/Ellipsis flow on turns into a baffling TypeError downstream
        if not isinstance(vals, list) or not vals or \
                not isinstance(vals[0], int) or vals[0] < 0:
            raise ValueError("corrupt BCF typed descriptor: extended length "
                             f"is {vals!r}, not a non-negative int")
        length = vals[0]
    return length, btype, off


def _read_value(buf: bytes, off: int):
    """One typed value -> (list of python values | str, new offset)."""
    length, btype, off = _read_desc(buf, off)
    if btype == _BT_CHAR:
        s = buf[off:off + length].decode("latin-1")
        return s, off + length
    if btype == 0:
        return [], off
    if btype == _BT_FLOAT:
        out = []
        for i in range(length):
            bits = struct.unpack_from("<I", buf, off + 4 * i)[0]
            if bits == _EOV_FLOAT_BITS:
                out.append(Ellipsis)
            elif bits == _MISSING_FLOAT_BITS:
                out.append(None)
            else:
                out.append(struct.unpack_from("<f", buf, off + 4 * i)[0])
        return out, off + 4 * length
    fmt = _INT_FMT[btype]
    size = struct.calcsize(fmt)
    out = []
    for i in range(length):
        v = struct.unpack_from(fmt, buf, off + size * i)[0]
        out.append(Ellipsis if v == _EOV[btype]
                   else None if v == _MISSING[btype] else v)
    return out, off + size * length


def _enc_desc(length: int, btype: int) -> bytes:
    if length < 15:
        return bytes([(length << 4) | btype])
    return bytes([0xF0 | btype]) + _enc_ints([length])


def _enc_ints(vals: List[Optional[int]], width: Optional[int] = None
              ) -> bytes:
    """Typed int vector; None -> MISSING, pad to ``width`` with EOV."""
    width = width if width is not None else len(vals)
    concrete = [v for v in vals if v is not None]
    lo = min(concrete, default=0)
    hi = max(concrete, default=0)
    # reserve the bottom of each range for MISSING/EOV sentinels
    if -120 <= lo and hi <= 127:
        btype = _BT_INT8
    elif -32000 <= lo and hi <= 32767:
        btype = _BT_INT16
    else:
        btype = _BT_INT32
    fmt = _INT_FMT[btype]
    out = [_enc_desc(width, btype)]
    padded = list(vals) + [Ellipsis] * (width - len(vals))
    for v in padded:
        out.append(struct.pack(
            fmt, _EOV[btype] if v is Ellipsis
            else _MISSING[btype] if v is None else v))
    return b"".join(out)


def _enc_floats(vals: List[Optional[float]], width: Optional[int] = None
                ) -> bytes:
    width = width if width is not None else len(vals)
    out = [_enc_desc(width, _BT_FLOAT)]
    padded = list(vals) + [Ellipsis] * (width - len(vals))
    for v in padded:
        if v is Ellipsis:
            out.append(struct.pack("<I", _EOV_FLOAT_BITS))
        elif v is None:
            out.append(struct.pack("<I", _MISSING_FLOAT_BITS))
        else:
            out.append(struct.pack("<f", v))
    return b"".join(out)


def _enc_str(s: str, width: Optional[int] = None) -> bytes:
    data = s.encode("latin-1")
    width = width if width is not None else len(data)
    return _enc_desc(width, _BT_CHAR) + data.ljust(width, b"\x00")


# --------------------------------------------------------------------------
# decode: BCF -> VCF text -> Arrow (via io.vcf.read_vcf)
# --------------------------------------------------------------------------

def _fmt_float(v: float) -> str:
    # shortest decimal string that round-trips the stored float32 — %g's six
    # significant digits silently lose precision the storage still carries
    return str(np.float32(v))


def _vals_to_text(vals, btype_hint=None) -> str:
    if isinstance(vals, str):
        return vals if vals else "."
    shown = [v for v in vals if v is not Ellipsis]
    if not shown:
        return "."
    return ",".join(
        "." if v is None else _fmt_float(v) if isinstance(v, float)
        else str(v) for v in shown)


def bcf_to_vcf_text(path_or_bytes) -> str:
    """Decode a BCF file to equivalent VCF text (header + records)."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        raw = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            raw = f.read()
    data = _decompress_bgzf(raw) if raw[:2] == b"\x1f\x8b" else raw
    if data[:5] != _MAGIC:
        raise ValueError(
            f"not a BCFv2 file (magic {data[:5]!r}); plain VCF text should "
            "go through io.vcf.read_vcf")
    (l_text,) = struct.unpack_from("<I", data, 5)
    text = data[9:9 + l_text].split(b"\x00", 1)[0].decode()
    dicts = _HeaderDicts(text)
    lines = [text.rstrip("\n")]

    off = 9 + l_text
    while off + 8 <= len(data):
        l_shared, l_indiv = struct.unpack_from("<II", data, off)
        off += 8
        shared = data[off:off + l_shared]
        indiv = data[off + l_shared:off + l_shared + l_indiv]
        off += l_shared + l_indiv
        lines.append(_decode_record(shared, indiv, dicts))
    return "\n".join(lines) + "\n"


def _decode_record(shared: bytes, indiv: bytes, dicts: _HeaderDicts) -> str:
    chrom_i, pos, _rlen = struct.unpack_from("<iii", shared, 0)
    (qual_bits,) = struct.unpack_from("<I", shared, 12)
    (n_ai,) = struct.unpack_from("<I", shared, 16)
    (n_fs,) = struct.unpack_from("<I", shared, 20)
    n_info, n_allele = n_ai & 0xFFFF, n_ai >> 16
    n_sample, n_fmt = n_fs & 0xFFFFFF, n_fs >> 24
    qual = "." if qual_bits == _MISSING_FLOAT_BITS else \
        _fmt_float(struct.unpack("<f", struct.pack("<I", qual_bits))[0])

    p = 24
    vid, p = _read_value(shared, p)
    alleles = []
    for _ in range(n_allele):
        a, p = _read_value(shared, p)
        alleles.append(a)
    filt_idx, p = _read_value(shared, p)
    if isinstance(filt_idx, str):  # 0 filters encode as an empty vector
        filt_idx = []
    filt = ";".join(dicts.strings[i] for i in filt_idx
                    if i is not None and i is not Ellipsis) or "."

    info_parts = []
    for _ in range(n_info):
        key_v, p = _read_value(shared, p)
        key = dicts.strings[key_v[0]]
        vals, p = _read_value(shared, p)
        if (not isinstance(vals, str) and len(vals) == 0) or \
                dicts.info_types.get(key) == "Flag":
            info_parts.append(key)
        else:
            info_parts.append(f"{key}={_vals_to_text(vals)}")

    cols = [dicts.contigs[chrom_i], str(pos + 1),
            vid if vid else ".", alleles[0] if alleles else ".",
            ",".join(alleles[1:]) or ".", qual, filt,
            ";".join(info_parts) or "."]

    if n_fmt:
        p = 0
        fmt_keys: List[str] = []
        sample_cols: List[List[str]] = [[] for _ in range(n_sample)]
        for _ in range(n_fmt):
            key_v, p = _read_value(indiv, p)
            key = dicts.strings[key_v[0]]
            fmt_keys.append(key)
            length, btype, p = _read_desc(indiv, p)
            for s in range(n_sample):
                if btype == _BT_CHAR:
                    raw_s = indiv[p:p + length].decode("latin-1")
                    p += length
                    sample_cols[s].append(raw_s.rstrip("\x00") or ".")
                    continue
                vals = []
                if btype == _BT_FLOAT:
                    for i in range(length):
                        bits = struct.unpack_from("<I", indiv, p + 4 * i)[0]
                        vals.append(Ellipsis if bits == _EOV_FLOAT_BITS
                                    else None
                                    if bits == _MISSING_FLOAT_BITS else
                                    struct.unpack_from("<f", indiv,
                                                       p + 4 * i)[0])
                    p += 4 * length
                else:
                    fmt = _INT_FMT[btype]
                    size = struct.calcsize(fmt)
                    for i in range(length):
                        v = struct.unpack_from(fmt, indiv, p + size * i)[0]
                        vals.append(Ellipsis if v == _EOV[btype]
                                    else None if v == _MISSING[btype] else v)
                    p += size * length
                if key == "GT":
                    sample_cols[s].append(_decode_gt(vals))
                else:
                    sample_cols[s].append(_vals_to_text(vals))
        cols.append(":".join(fmt_keys))
        cols += [":".join(s) for s in sample_cols]
    return "\t".join(cols)


def _decode_gt(vals) -> str:
    alleles = [v for v in vals if v is not Ellipsis]
    if not alleles:
        return "."
    # the phase bit lives on EACH non-first allele (htslib convention), so
    # each separator reflects its own allele's bit — 0/1|2 stays mixed-phase;
    # missing alleles encode as 0 (unphased) or 1 (phased)
    def show(v):
        return "." if (v is None or v >> 1 == 0) else str((v >> 1) - 1)
    out = [show(alleles[0])]
    for v in alleles[1:]:
        out.append("|" if (v is not None and v & 1) else "/")
        out.append(show(v))
    return "".join(out)


def read_bcf(path_or_bytes):
    """BCF -> (variants, genotypes, domains, seq_dict), via read_vcf."""
    from .vcf import read_vcf
    return read_vcf(io.StringIO(bcf_to_vcf_text(path_or_bytes)))


# --------------------------------------------------------------------------
# encode: VCF text -> BCF
# --------------------------------------------------------------------------

def _sniff_type(raw: str) -> str:
    vals = [v for v in raw.split(",") if v != "."]
    if all(re.fullmatch(r"-?\d+", v) for v in vals) and vals:
        return "Integer"
    try:
        [float(v) for v in vals]
        return "Float" if vals else "String"
    except ValueError:
        return "String"


def _complete_header(lines: List[str], records: List[str]) -> List[str]:
    """Append synthetic declarations for anything records use that the
    header doesn't declare, so the BCF dictionaries are total."""
    declared_contigs = set()
    declared_strs = {"PASS"}
    types: Dict[str, str] = {}
    for ln in lines:
        m = _HDR_RE.match(ln)
        if m:
            meta = _split_meta(m.group(2))
            (declared_contigs if m.group(1) == "contig"
             else declared_strs).add(meta.get("ID", ""))
            if m.group(1) in ("INFO", "FORMAT"):
                types[meta.get("ID", "")] = meta.get("Type", "String")
    extra: List[str] = []

    def declare(kind: str, name: str, typ: str = "String",
                number: str = ".") -> None:
        if kind == "FILTER":
            extra.append(f'##FILTER=<ID={name},Description="">')
        else:
            extra.append(f'##{kind}=<ID={name},Number={number},Type={typ},'
                         'Description="">')
        declared_strs.add(name)

    for rec in records:
        f = rec.split("\t")
        if f[0] not in declared_contigs:
            extra.append(f"##contig=<ID={f[0]}>")
            declared_contigs.add(f[0])
        if len(f) > 6 and f[6] not in (".", "PASS"):
            for name in f[6].split(";"):
                if name not in declared_strs:
                    declare("FILTER", name)
        if len(f) > 7 and f[7] != ".":
            for part in f[7].split(";"):
                if "=" in part:
                    k, v = part.split("=", 1)
                    if k not in declared_strs:
                        declare("INFO", k, _sniff_type(v))
                elif part not in declared_strs:
                    declare("INFO", part, "Flag", "0")
        if len(f) > 8:
            keys = f[8].split(":")
            sample_fields = [s.split(":") for s in f[9:]]
            for ki, k in enumerate(keys):
                if k in declared_strs:
                    continue
                vals = [sf[ki] for sf in sample_fields if len(sf) > ki]
                declare("FORMAT", k,
                        "String" if k == "GT"
                        else _sniff_type(",".join(vals) or "."))
    # synthetic lines go before #CHROM
    return lines[:-1] + extra + lines[-1:]


def _enc_info_value(raw: str, typ: str) -> bytes:
    if typ == "Flag":
        return b"\x00"  # length-0 value (htslib convention for flags)
    vals = raw.split(",")
    if typ == "Integer":
        return _enc_ints([None if v == "." else int(v) for v in vals])
    if typ == "Float":
        return _enc_floats([None if v == "." else float(v) for v in vals])
    return _enc_str(raw)


def _enc_gt_block(gts: List[str]) -> bytes:
    parsed = []
    for gt in gts:
        # keep each allele's own separator: 0/1|2 sets the phase bit on the
        # third allele only (phased-missing ".|1" != "./1" likewise)
        toks = re.split(r"([/|])", gt) if gt != "." else ["."]
        vals = []
        for i in range(0, len(toks), 2):
            a = toks[i]
            core = 0 if a == "." else (int(a) + 1) << 1
            phased = i > 0 and toks[i - 1] == "|"
            vals.append(core | (1 if phased else 0))
        parsed.append(vals)
    width = max(len(v) for v in parsed)
    out = [_enc_desc(width, _BT_INT8)]
    for vals in parsed:
        padded = vals + [Ellipsis] * (width - len(vals))
        out.append(b"".join(
            struct.pack("<b", _EOV[_BT_INT8] if v is Ellipsis else v)
            for v in padded))
    return b"".join(out)


def _enc_fmt_block(raws: List[str], typ: str) -> bytes:
    """One FORMAT field across samples: shared descriptor + padded values."""
    if typ == "Integer":
        per = [[None if v == "." else int(v)
                for v in r.split(",")] if r != "." else [None]
               for r in raws]
        width = max(len(v) for v in per)
        flat = [v for vals in per for v in vals if v is not None]
        lo, hi = min(flat, default=0), max(flat, default=0)
        btype = _BT_INT8 if -120 <= lo and hi <= 127 else \
            _BT_INT16 if -32000 <= lo and hi <= 32767 else _BT_INT32
        fmt = _INT_FMT[btype]
        out = [_enc_desc(width, btype)]
        for vals in per:
            padded = vals + [Ellipsis] * (width - len(vals))
            out.append(b"".join(struct.pack(
                fmt, _EOV[btype] if v is Ellipsis
                else _MISSING[btype] if v is None else v) for v in padded))
        return b"".join(out)
    if typ == "Float":
        per = [[None if v == "." else float(v)
                for v in r.split(",")] if r != "." else [None]
               for r in raws]
        width = max(len(v) for v in per)
        out = [_enc_desc(width, _BT_FLOAT)]
        for vals in per:
            padded = vals + [Ellipsis] * (width - len(vals))
            for v in padded:
                out.append(struct.pack("<I", _EOV_FLOAT_BITS)
                           if v is Ellipsis else
                           struct.pack("<I", _MISSING_FLOAT_BITS)
                           if v is None else struct.pack("<f", v))
        return b"".join(out)
    data = [r.encode("latin-1") for r in raws]
    width = max((len(d) for d in data), default=1) or 1
    return (_enc_desc(width, _BT_CHAR) +
            b"".join(d.ljust(width, b"\x00") for d in data))


def _enc_record(line: str, dicts: _HeaderDicts, n_sample: int) -> bytes:
    f = line.split("\t")
    chrom, pos1, vid, ref, alts, qual, filt, info = f[:8]
    alleles = [ref] + [a for a in alts.split(",") if a != "."]
    qual_b = struct.pack("<I", _MISSING_FLOAT_BITS) if qual == "." else \
        struct.pack("<f", float(qual))
    info_parts = [] if info == "." else info.split(";")
    fmt_keys = f[8].split(":") if len(f) > 8 and n_sample else []

    shared = [struct.pack("<iii", dicts.contig_idx[chrom], int(pos1) - 1,
                          len(ref)), qual_b,
              struct.pack("<I", len(info_parts) | (len(alleles) << 16)),
              struct.pack("<I", n_sample | (len(fmt_keys) << 24)),
              _enc_str("" if vid == "." else vid)]
    for a in alleles:
        shared.append(_enc_str(a))
    if filt == ".":
        shared.append(_enc_ints([]))
    else:
        shared.append(_enc_ints([dicts.string_idx[x]
                                 for x in filt.split(";")]))
    for part in info_parts:
        if "=" in part:
            k, v = part.split("=", 1)
        else:
            k, v = part, ""
        shared.append(_enc_ints([dicts.string_idx[k]]))
        shared.append(_enc_info_value(v, dicts.info_types.get(k, "String")))
    shared_b = b"".join(shared)

    indiv = []
    for ki, key in enumerate(fmt_keys):
        cols = []
        for s in range(n_sample):
            sf = f[9 + s].split(":") if len(f) > 9 + s else []
            cols.append(sf[ki] if ki < len(sf) else ".")
        indiv.append(_enc_ints([dicts.string_idx[key]]))
        if key == "GT":
            indiv.append(_enc_gt_block(cols))
        else:
            indiv.append(_enc_fmt_block(cols,
                                        dicts.fmt_types.get(key, "String")))
    indiv_b = b"".join(indiv)
    return struct.pack("<II", len(shared_b), len(indiv_b)) + \
        shared_b + indiv_b


def vcf_text_to_bcf_bytes(vcf_text: str) -> bytes:
    """Encode VCF text as a BGZF-compressed BCF2.2 byte stream."""
    all_lines = [ln for ln in vcf_text.splitlines() if ln.strip()]
    header = [ln for ln in all_lines if ln.startswith("#")]
    records = [ln for ln in all_lines if not ln.startswith("#")]
    if not header or not header[-1].startswith("#CHROM"):
        raise ValueError("VCF text lacks a #CHROM header line")
    header = _complete_header(header, records)
    text = "\n".join(header) + "\n"
    dicts = _HeaderDicts(text)
    n_sample = max(len(header[-1].split("\t")) - 9, 0)

    body = io.BytesIO()
    tb = text.encode() + b"\x00"
    body.write(_MAGIC + struct.pack("<I", len(tb)) + tb)
    for rec in records:
        body.write(_enc_record(rec, dicts, n_sample))
    raw = body.getvalue()

    out = []
    for i in range(0, len(raw), 60000):
        out.append(_bgzf_block(raw[i:i + 60000]))
    out.append(_BGZF_EOF)
    return b"".join(out)


def write_bcf(vcf_text: str, path) -> None:
    with open(path, "wb") as fh:
        fh.write(vcf_text_to_bcf_bytes(vcf_text))


def iter_bcf_vcf_lines(path: str, chunk_bytes: int = 1 << 24):
    """Streaming BCF -> VCF text lines: BGZF members decompress
    incrementally (io/bam.iter_decompressed) and records decode from a
    bounded buffer — ``read_bcf``/``bcf_to_vcf_text`` buffer whole files;
    cohort-scale BCFs need this form.  Yields the header lines first, then
    one record line per site; plug into ``vcf.VcfStream`` for chunked
    Arrow tables.
    """
    from .bam import iter_decompressed

    it = iter_decompressed(path, chunk_bytes)
    buf = bytearray()
    off = 0
    exhausted = False

    def fill(target: int) -> bool:
        """Ensure ``target`` unconsumed bytes; compacts ONCE per refill —
        a per-record front delete would memmove the whole window per
        record (quadratic: ~160k records per 16 MB window)."""
        nonlocal exhausted, off
        if len(buf) - off >= target:
            return True
        if off:
            del buf[:off]
            off = 0
        while not exhausted and len(buf) < target:
            piece = next(it, None)
            if piece is None:
                exhausted = True
            else:
                buf.extend(piece)
        return len(buf) >= target

    if not fill(9):
        raise ValueError("truncated BCF header")
    if bytes(buf[off:off + 5]) != _MAGIC:
        raise ValueError(
            f"not a BCFv2 file (magic {bytes(buf[off:off + 5])!r}); plain "
            "VCF text should go through io.vcf.read_vcf")
    (l_text,) = struct.unpack_from("<I", buf, off + 5)
    if not fill(9 + l_text):
        raise ValueError("truncated BCF header text")
    text = bytes(buf[off + 9:off + 9 + l_text]).split(b"\x00", 1)[0] \
        .decode()
    dicts = _HeaderDicts(text)
    yield from text.rstrip("\n").split("\n")
    off += 9 + l_text

    while True:
        if not fill(8):
            if len(buf) - off:
                raise ValueError(f"{len(buf) - off} trailing bytes form "
                                 "no complete BCF record (truncated "
                                 "file?)")
            return
        l_shared, l_indiv = struct.unpack_from("<II", buf, off)
        if not fill(8 + l_shared + l_indiv):
            raise ValueError("truncated BCF record")
        shared = bytes(buf[off + 8:off + 8 + l_shared])
        indiv = bytes(buf[off + 8 + l_shared:off + 8 + l_shared + l_indiv])
        off += 8 + l_shared + l_indiv
        yield _decode_record(shared, indiv, dicts)
