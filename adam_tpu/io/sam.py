"""SAM text import/export.

The reference gets SAM/BAM parsing from samtools-jar + hadoop-bam and converts
each ``SAMRecord`` to an Avro ``ADAMRecord`` in
``converters/SAMRecordConverter.scala:25-146``.  We parse SAM text directly
into Arrow columns matching :data:`adam_tpu.schema.READ_SCHEMA`.

Field semantics follow SAMRecordConverter:
  * reference fields only set when the read has a reference (rname != "*");
    start = SAM POS - 1 (0-based), unset when POS == 0
    (SAMRecordConverter.scala:36-54).
  * mate fields analogous (:57-72).
  * MD tag is lifted out of the attributes into ``mismatchingPositions``;
    the remaining tags are flattened "TAG:TYPE:VALUE" joined by tabs
    (:110-121, AttributeUtils.scala:26-103).
  * record-group metadata denormalized into each read (:123-141).

One deliberate divergence: the reference only decodes flag booleans when the
whole SAM flag word is non-zero (SAMRecordConverter.scala:75-101), so a read
with flags == 0 is recorded as unmapped/non-primary — a bug.  We keep the SAM
flag word itself (schema.FLAG_* bits), so flags == 0 means mapped, forward,
primary, as the SAM spec defines.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

import pyarrow as pa

from ..models.dictionary import (RecordGroup, RecordGroupDictionary,
                                 SequenceDictionary)
from .. import schema as S

_MAPQ_UNKNOWN = 255


def _parse_sam_line(line: str, seq_dict, rg_dict) -> Optional[dict]:
    """One SAM body line -> row dict (None for blank lines)."""
    line = line.rstrip("\n")
    if not line:
        return None
    f = line.split("\t")
    qname, flag, rname, pos, mapq, cigar, rnext, pnext, _tlen, seq, qual = f[:11]
    flag = int(flag)
    row = {
        "readName": qname if qname != "*" else None,
        "flags": flag,
        "sequence": seq if seq != "*" else None,
        "qual": qual if qual != "*" else None,
        "cigar": cigar if cigar != "*" else None,
    }
    if rname != "*":
        rec = seq_dict.get(rname)
        row["referenceName"] = rname
        row["referenceId"] = rec.id if rec else None
        if rec:
            row["referenceLength"] = rec.length
            row["referenceUrl"] = rec.url
        if int(pos) != 0:
            row["start"] = int(pos) - 1
        if int(mapq) != _MAPQ_UNKNOWN:
            row["mapq"] = int(mapq)
    mate_rname = rname if rnext == "=" else rnext
    if mate_rname != "*":
        rec = seq_dict.get(mate_rname)
        row["mateReference"] = mate_rname
        row["mateReferenceId"] = rec.id if rec else None
        if rec:
            row["mateReferenceLength"] = rec.length
            row["mateReferenceUrl"] = rec.url
        if int(pnext) > 0:
            row["mateAlignmentStart"] = int(pnext) - 1
    attrs = []
    rg: Optional[RecordGroup] = None
    for tag_field in f[11:]:
        tag, typ, value = tag_field.split(":", 2)
        if tag == "MD":
            row["mismatchingPositions"] = value
        elif tag == "RG":
            rg = rg_dict.get(value)
            if rg is None:
                # tolerate RG tags without a header line: register so each
                # distinct group still gets a distinct dense index
                rg = RecordGroup(id=value, index=len(rg_dict))
                rg_dict.add(rg)
        else:
            attrs.append(f"{tag}:{typ}:{value}")
    if attrs:
        row["attributes"] = "\t".join(attrs)
    if rg is not None:
        row.update(
            recordGroupName=rg.id, recordGroupId=rg.index,
            recordGroupSequencingCenter=rg.sequencing_center,
            recordGroupDescription=rg.description,
            recordGroupRunDateEpoch=rg.run_date_epoch,
            recordGroupFlowOrder=rg.flow_order,
            recordGroupKeySequence=rg.key_sequence,
            recordGroupLibrary=rg.library,
            recordGroupPredictedMedianInsertSize=rg.predicted_median_insert_size,
            recordGroupPlatform=rg.platform,
            recordGroupPlatformUnit=rg.platform_unit,
            recordGroupSample=rg.sample,
        )
    return row


def _rows_to_table(rows) -> pa.Table:
    from . import read_rows_to_table
    return read_rows_to_table(rows)


def open_sam_stream(path_or_file, chunk_rows: int = 1 << 20,
                    stringency: str = "strict"):
    """(seq_dict, rg_dict, generator of Arrow tables) over a streamed SAM.

    Lines parse as they are read; host memory is bounded by ``chunk_rows``
    (the whole-file :func:`read_sam` is this stream concatenated).
    ``stringency`` follows samtools semantics (Bam2Adam.scala:46-47):
    strict raises on a malformed record, lenient warns and drops it,
    silent drops it quietly; the level is validated here, up front, not
    at the first malformed record.
    """
    from ..errors import ValidationStringency
    if stringency not in (ValidationStringency.STRICT,
                          ValidationStringency.LENIENT,
                          ValidationStringency.SILENT):
        raise ValueError(f"unknown validation stringency {stringency!r} "
                         "(want strict/lenient/silent)")
    close = False
    if hasattr(path_or_file, "read"):
        f = path_or_file
    else:
        f = open(path_or_file, "rt")
        close = True
    header_lines = []
    first_body: Optional[str] = None
    for line in f:
        if line.startswith("@"):
            header_lines.append(line)
        else:
            first_body = line
            break
    seq_dict = SequenceDictionary.from_sam_header_lines(header_lines)
    rg_dict = RecordGroupDictionary.from_sam_header_lines(header_lines)

    def gen():
        try:
            rows: List[dict] = []
            lines = ([first_body] if first_body is not None else [])
            from ..errors import handle_malformed
            for line in itertools.chain(lines, f):
                try:
                    row = _parse_sam_line(line, seq_dict, rg_dict)
                except (ValueError, IndexError) as e:
                    handle_malformed(
                        stringency,
                        f"malformed SAM record {line.rstrip()[:80]!r}: {e}",
                        e)
                    continue
                if row is None:
                    continue
                rows.append(row)
                if len(rows) >= chunk_rows:
                    yield _rows_to_table(rows)
                    rows = []
            if rows:
                yield _rows_to_table(rows)
        finally:
            if close:
                f.close()

    return seq_dict, rg_dict, gen()


def scan_sam_units(path, unit_rows: Optional[int] = None):
    """Byte-walk a SAM file — total body rows plus the byte offset of
    each unit's first record — without building any row objects.

    Also answers whether mid-file entry is SAFE: the body parser
    lazily registers ``RG:Z:`` values missing from the header
    (:func:`_parse_sam_line`), and lazy indices depend on encounter
    order — a shard entering mid-file would assign different dense
    ``recordGroupId``s than a forward decode.  ``safe`` is True only
    when every body RG value is declared by a header ``@RG`` line, so
    entry order cannot matter.  Callers treat ``safe=False`` as
    index-unavailable and fall back to forward decode.
    """
    rg_ids = set()
    total = 0
    offsets: List[int] = []
    safe = True
    with open(path, "rb") as f:
        off = 0
        in_header = True
        for line in f:
            this_off = off
            off += len(line)
            if in_header:
                if line.startswith(b"@"):
                    if line.startswith(b"@RG"):
                        for field in line.rstrip(b"\n").split(b"\t"):
                            if field.startswith(b"ID:"):
                                rg_ids.add(field[3:])
                    continue
                in_header = False
            if not line.rstrip(b"\n"):
                continue        # blank: the parser drops it too
            if unit_rows and total % unit_rows == 0:
                offsets.append(this_off)
            tab_rg = line.find(b"\tRG:Z:")
            if tab_rg >= 0:
                rest = line[tab_rg + 6:]
                end = len(rest)
                for stop in (b"\t", b"\n"):
                    cut = rest.find(stop)
                    if 0 <= cut < end:
                        end = cut
                if rest[:end] not in rg_ids:
                    safe = False
            total += 1
    return dict(total_rows=total,
                unit_rows=int(unit_rows) if unit_rows else None,
                offsets=offsets if unit_rows else None, safe=safe)


def open_sam_stream_at(path, offset: int, *, chunk_rows: int = 1 << 20,
                       stringency: str = "strict", on_bytes=None):
    """:func:`open_sam_stream`, entered at a byte offset.

    The header still parses from byte 0 (dictionaries live there);
    body decoding seeks straight to ``offset`` — a line boundary from
    :func:`scan_sam_units`.  Only call this when the scan reported
    ``safe`` (no lazy RG registration in play).  ``on_bytes`` (when
    given) receives the size of every line actually read, so the I/O
    ledger charges what this reader truly cost, not the whole file.
    """
    from ..errors import ValidationStringency
    if stringency not in (ValidationStringency.STRICT,
                          ValidationStringency.LENIENT,
                          ValidationStringency.SILENT):
        raise ValueError(f"unknown validation stringency {stringency!r} "
                         "(want strict/lenient/silent)")
    header_lines: List[str] = []
    hdr_bytes = 0
    with open(path, "rb") as f:
        for line in f:
            if not line.startswith(b"@"):
                break
            header_lines.append(line.decode())
            hdr_bytes += len(line)
    if on_bytes is not None:
        on_bytes(hdr_bytes)
    seq_dict = SequenceDictionary.from_sam_header_lines(header_lines)
    rg_dict = RecordGroupDictionary.from_sam_header_lines(header_lines)

    def gen():
        from ..errors import handle_malformed
        rows: List[dict] = []
        with open(path, "rb") as f:
            f.seek(offset)
            for bline in f:
                if on_bytes is not None:
                    on_bytes(len(bline))
                line = bline.decode("utf-8", "replace")
                try:
                    row = _parse_sam_line(line, seq_dict, rg_dict)
                except (ValueError, IndexError) as e:
                    handle_malformed(
                        stringency,
                        f"malformed SAM record {line.rstrip()[:80]!r}: {e}",
                        e)
                    continue
                if row is None:
                    continue
                rows.append(row)
                if len(rows) >= chunk_rows:
                    yield _rows_to_table(rows)
                    rows = []
        if rows:
            yield _rows_to_table(rows)

    return seq_dict, rg_dict, gen()


def read_sam(path_or_file, stringency: str = "strict"
             ) -> Tuple[pa.Table, SequenceDictionary, RecordGroupDictionary]:
    """Parse a SAM text file into (reads table, seq dict, record groups)."""
    seq_dict, rg_dict, gen = open_sam_stream(path_or_file,
                                             stringency=stringency)
    tables = list(gen)
    table = pa.concat_tables(tables) if tables \
        else _rows_to_table([])
    return table, seq_dict, rg_dict


def write_sam(table: pa.Table, seq_dict: SequenceDictionary, path_or_file,
              rg_dict: Optional[RecordGroupDictionary] = None) -> None:
    """Serialize a reads table back to SAM text (inverse of :func:`read_sam`)."""
    close = False
    if hasattr(path_or_file, "write"):
        out = path_or_file
    else:
        out = open(path_or_file, "wt")
        close = True
    try:
        out.write("@HD\tVN:1.0\tSO:unsorted\n")
        for line in seq_dict.to_sam_header_lines():
            out.write(line + "\n")
        if rg_dict:
            for g in rg_dict:
                parts = [f"@RG\tID:{g.id}"]
                for code, val in (("CN", g.sequencing_center), ("DS", g.description),
                                  ("FO", g.flow_order), ("KS", g.key_sequence),
                                  ("LB", g.library), ("PI", g.predicted_median_insert_size),
                                  ("PL", g.platform), ("PU", g.platform_unit),
                                  ("SM", g.sample)):
                    if val is not None:
                        parts.append(f"{code}:{val}")
                out.write("\t".join(parts) + "\n")
        d = table.to_pydict()
        n = table.num_rows
        for i in range(n):
            flag = d["flags"][i] or 0
            rname = d["referenceName"][i] or "*"
            start = d["start"][i]
            mate_ref = d["mateReference"][i] or "*"
            if mate_ref != "*" and mate_ref == rname:
                mate_ref = "="
            mate_start = d["mateAlignmentStart"][i]
            fields = [
                d["readName"][i] or "*",
                str(flag),
                rname,
                str(start + 1 if start is not None else 0),
                str(d["mapq"][i] if d["mapq"][i] is not None else _MAPQ_UNKNOWN),
                d["cigar"][i] or "*",
                mate_ref,
                str(mate_start + 1 if mate_start is not None else 0),
                "0",
                d["sequence"][i] or "*",
                d["qual"][i] or "*",
            ]
            if d["mismatchingPositions"][i] is not None:
                fields.append(f"MD:Z:{d['mismatchingPositions'][i]}")
            if d["recordGroupName"][i] is not None:
                fields.append(f"RG:Z:{d['recordGroupName'][i]}")
            if d["attributes"][i]:
                fields.extend(d["attributes"][i].split("\t"))
            out.write("\t".join(fields) + "\n")
    finally:
        if close:
            out.close()
