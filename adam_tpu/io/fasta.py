"""FASTA import -> ADAMNucleotideContig records.

Re-designs ``converters/FastaConverter.scala:27-166`` (line-number-keyed
Spark FASTA assembly) as a bounded-buffer chunk parse: the file reads in
fixed-size byte chunks and contigs emit as soon as their last line is seen,
so host RSS is bounded by (largest single contig + one IO chunk) rather
than the whole file — the reference gets the same bound from Spark
partitioning.  ``>name description`` headers, sequence lines concatenated,
sequential contig ids.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import pyarrow as pa

from .. import schema as S

#: bytes per read() chunk of the streaming parser
_CHUNK_BYTES = 8 << 20


def iter_fasta(path_or_file, chunk_bytes: int = _CHUNK_BYTES
               ) -> Iterator[Tuple[str, Optional[str], str]]:
    """Yield ``(name, description, sequence)`` per contig, reading the
    file in ``chunk_bytes`` pieces.  Peak memory: one contig's sequence
    pieces + one IO chunk."""
    f = path_or_file if hasattr(path_or_file, "read") \
        else open(path_or_file, "rt")
    owns = f is not path_or_file
    try:
        name: Optional[str] = None
        desc: Optional[str] = None
        pieces: list = []
        started = False
        carry = ""
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            chunk = carry + chunk
            lines = chunk.split("\n")
            carry = lines.pop()          # last piece may be mid-line
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                if line.startswith(">"):
                    if started:
                        yield name or "", desc, "".join(pieces)
                    header = line[1:].split(None, 1)
                    name = header[0] if header else ""
                    desc = header[1] if len(header) > 1 else None
                    pieces = []
                    started = True
                else:
                    if not started:      # headerless: anonymous contig
                        name, desc, started = "", None, True
                    pieces.append(line.upper())
                    if len(pieces) >= 4096:
                        # compact: per-line str objects cost ~2x their
                        # payload; long contigs would otherwise hold
                        # millions of them
                        pieces = ["".join(pieces)]
        last = carry.strip()
        if last:
            if last.startswith(">"):
                if started:
                    yield name or "", desc, "".join(pieces)
                header = last[1:].split(None, 1)
                yield (header[0] if header else ""), \
                    (header[1] if len(header) > 1 else None), ""
                return
            if not started:
                name, desc, started = "", None, True
            pieces.append(last.upper())
        if started:
            yield name or "", desc, "".join(pieces)
    finally:
        if owns:
            f.close()


def contig_batches(path_or_file, url: Optional[str] = None,
                   batch_bytes: int = 256 << 20,
                   start_id: int = 0) -> Iterator[pa.Table]:
    """CONTIG_SCHEMA tables of whole contigs, flushed every
    ``batch_bytes`` of sequence — the bounded-memory unit the streaming
    ``fasta2adam`` writes per part."""
    names, descs, seqs = [], [], []
    held = 0
    next_id = start_id

    def flush():
        nonlocal names, descs, seqs, held
        t = pa.Table.from_pydict({
            "contigName": names,
            "contigId": list(range(next_id - len(names), next_id)),
            "description": descs,
            "sequence": seqs,
            "sequenceLength": [len(s) for s in seqs],
            "url": [url] * len(names),
        }, schema=S.CONTIG_SCHEMA)
        names, descs, seqs = [], [], []
        held = 0
        return t

    for name, desc, seq in iter_fasta(path_or_file):
        names.append(name)
        descs.append(desc)
        seqs.append(seq)
        held += len(seq)
        next_id += 1
        if held >= batch_bytes:
            yield flush()
    if names or next_id == start_id:
        yield flush()


def read_fasta(path_or_file, url: Optional[str] = None) -> pa.Table:
    """Whole-file form (small references / tests); the chunked parser
    underneath keeps intermediate copies bounded."""
    if url is None and not hasattr(path_or_file, "read"):
        url = str(path_or_file)
    tables = list(contig_batches(path_or_file, url=url))
    return tables[0] if len(tables) == 1 else pa.concat_tables(tables)
