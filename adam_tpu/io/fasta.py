"""FASTA import -> ADAMNucleotideContig records.

Re-designs ``converters/FastaConverter.scala:27-166`` (line-number-keyed
Spark FASTA assembly) as a simple host parse: ``>name description`` headers,
sequence lines concatenated, sequential contig ids.
"""

from __future__ import annotations

from typing import Optional, Tuple

import pyarrow as pa

from .. import schema as S


def read_fasta(path_or_file, url: Optional[str] = None) -> pa.Table:
    if hasattr(path_or_file, "read"):
        text = path_or_file.read()
    else:
        url = url or str(path_or_file)
        with open(path_or_file, "rt") as f:
            text = f.read()
    names, descs, seqs = [], [], []
    cur: list = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith(">"):
            header = line[1:].split(None, 1)
            names.append(header[0] if header else "")
            descs.append(header[1] if len(header) > 1 else None)
            cur = []
            seqs.append(cur)
        else:
            if not names:  # headerless FASTA: single anonymous contig
                names.append("")
                descs.append(None)
                cur = []
                seqs.append(cur)
            cur.append(line.upper())
    joined = ["".join(s) for s in seqs]
    return pa.Table.from_pydict({
        "contigName": names,
        "contigId": list(range(len(names))),
        "description": descs,
        "sequence": joined,
        "sequenceLength": [len(s) for s in joined],
        "url": [url] * len(names),
    }, schema=S.CONTIG_SCHEMA)
