"""Multi-process BGZF inflate: N worker processes, one compressed segment
range each (VERDICT r4, next-round #7).

``io/bam.iter_decompressed`` already thread-parallelizes member inflate
(zlib releases the GIL), but one process tops out around one core of
Python-side glue; the 10 M reads/s ingest model needs ~8 cores of decode
(round-3 finding: ~450 k reads/s/core).  This module is the process-level
axis, re-designing ``cli/Bam2Adam.scala:56-97`` (reader thread + N writer
threads over a blocking queue) as: a cheap no-inflate SEGMENTER pass that
hops BGZF member headers (BSIZE extra subfield, SAM spec 4.1) to cut the
compressed byte range into member-aligned segments, then a process pool
that inflates whole segments independently, with results consumed in
input order.

Order preservation is structural, not scheduled: segments are contiguous
compressed ranges, workers never see partial members, and the parent
yields segment payloads in segment order — so the concatenated output is
byte-identical to the sequential walk for ANY process count (pinned by
``tests/test_io_procs.py``).  Record straddling across segment
boundaries needs no special handling because records are parsed
downstream from the *joined* byte stream, exactly as with the
single-process iterator.

Workers are ``spawn``ed, not forked: the parent typically holds a live
JAX/XLA runtime whose internal threads do not survive fork.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import zlib
from collections import deque
from typing import Iterator, List, Tuple

#: default compressed bytes per segment — ~64 MiB decompressed, so
#: in-flight host RSS is bounded by ``depth x ~4x this``
SEGMENT_BYTES = 16 << 20


def _member_size(buf, off: int):
    """BGZF member header at ``off`` -> total member size, or None.

    Same parse as ``io/bam._bgzf_member_size``; duplicated here so worker
    processes import nothing beyond the stdlib (spawn cost, and no
    pyarrow/numpy in the inflate workers).
    """
    if off + 18 > len(buf):
        return None
    if buf[off] != 0x1F or buf[off + 1] != 0x8B or not (buf[off + 3] & 4):
        return None
    xlen = buf[off + 10] | (buf[off + 11] << 8)
    p, end = off + 12, off + 12 + xlen
    if end > len(buf):
        return None
    while p + 4 <= end:
        si1, si2 = buf[p], buf[p + 1]
        slen = buf[p + 2] | (buf[p + 3] << 8)
        if si1 == 66 and si2 == 67 and slen == 2:  # 'B','C'
            return (buf[p + 4] | (buf[p + 5] << 8)) + 1
        p += 4 + slen
    return None


def iter_segments(path: str, segment_bytes: int = SEGMENT_BYTES,
                  start: int = 0) -> Iterator[Tuple[int, int]]:
    """Member-aligned compressed (offset, size) segments of a BGZF file,
    yielded as the scan discovers them.

    One sequential buffered pass over the COMPRESSED bytes, no inflate:
    each member header names its own size (BSIZE), so the scan hops
    header to header.  Lazy on purpose — on a multi-GB input the pool
    starts inflating the first segments while the tail is still being
    scanned.  ``start`` (a member-aligned file offset — the file half of
    a BGZF virtual offset) begins the walk mid-file: the index-assisted
    shard entry (``io/bam.open_bam_stream_at``) never scans the bytes it
    seeks past.  Raises ValueError on non-BGZF input (first yield) or a
    truncated trailing member (mid-iteration, like the sequential
    iterator's FormatError).
    """
    window = 4 << 20
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        buf = b""
        base = start        # file offset of buf[0]
        off = start         # current member's file offset
        seg_start = start
        while off < size:
            # keep a full worst-case header (12 + xlen <= 64 KiB + slack)
            if off - base + (1 << 17) > len(buf) and base + len(buf) < size:
                f.seek(off)
                buf = f.read(window)
                base = off
            m = _member_size(buf, off - base)
            if m is None:
                raise ValueError(
                    f"{path}: no BGZF member at offset {off}")
            off += m
            if off - seg_start >= segment_bytes:
                yield (seg_start, off - seg_start)
                seg_start = off
        if off != size:
            raise ValueError(f"{path}: trailing garbage after {off}")
        if seg_start < size:
            yield (seg_start, size - seg_start)


def scan_segments(path: str, segment_bytes: int = SEGMENT_BYTES
                  ) -> List[Tuple[int, int]]:
    """Eager form of :func:`iter_segments` (tests, tooling)."""
    return list(iter_segments(path, segment_bytes))


def _inflate_segment(path: str, off: int, size: int) -> bytes:
    """Worker: inflate every member in [off, off+size) of ``path``."""
    with open(path, "rb") as f:
        f.seek(off)
        buf = f.read(size)
    out = []
    p = 0
    while p < len(buf):
        m = _member_size(buf, p)
        if m is None or p + m > len(buf):
            raise ValueError(f"{path}: segment [{off},{off + size}) is not "
                             f"member-aligned at +{p}")
        xlen = buf[p + 10] | (buf[p + 11] << 8)
        isize = int.from_bytes(buf[p + m - 4:p + m], "little")
        out.append(zlib.decompress(buf[p + 12 + xlen:p + m - 8], wbits=-15,
                                   bufsize=isize or 1))
        p += m
    return b"".join(out)


def iter_decompressed_procs(path: str, procs: int,
                            segment_bytes: int = 0,
                            depth: int = 0,
                            chunk_bytes: int = 1 << 24,
                            start: int = 0,
                            on_segment=None) -> Iterator[bytes]:
    """Decompressed byte chunks of a BGZF file, inflated by ``procs``
    worker processes; concatenation is byte-identical to
    ``io/bam.iter_decompressed``.  Non-BGZF inputs (plain gzip, raw)
    fall back to the sequential iterator (which honors ``chunk_bytes``).

    Yielded chunks are one decompressed segment each; segments default
    to ~``chunk_bytes/4`` of compressed bytes (BGZF compresses BAM ~4x),
    so the caller's per-chunk memory expectation carries over.  At most
    ``depth`` (default ``procs + 2``) segments are in flight, so host
    RSS stays bounded by ~``depth x chunk_bytes`` regardless of how far
    inflate outruns the consumer.

    ``start`` begins the walk at a member-aligned file offset (the
    index-assisted shard entry); a non-zero start requires real BGZF —
    there is no sequential fallback that could honor a seek.
    ``on_segment`` (when given) receives each segment's COMPRESSED size
    as it is yielded, so callers can charge the I/O ledger with bytes
    actually inflated rather than the whole file.
    """
    from .bam import iter_decompressed

    if procs <= 1 and not start:
        yield from iter_decompressed(path, chunk_bytes)
        return
    if not segment_bytes:
        # module attr read at call time, so tests can shrink segments
        segment_bytes = min(SEGMENT_BYTES, max(1 << 16, chunk_bytes // 4))
    it = iter_segments(path, segment_bytes, start=start)
    try:
        first = next(it, None)
    except ValueError:
        if start:
            raise       # a seek into a non-BGZF file has no fallback
        # not BGZF (plain gzip / raw): the sequential iterator handles it
        yield from iter_decompressed(path, chunk_bytes)
        return
    if first is None:
        return

    if procs <= 1:
        # seeked single-process walk: inflate segments inline
        seg = first
        while seg is not None:
            data = _inflate_segment(path, *seg)
            if on_segment is not None:
                on_segment(seg[1])
            if data:
                yield data
            seg = next(it, None)
        return

    depth = depth or procs + 2
    ctx = mp.get_context("spawn")
    with ctx.Pool(processes=procs) as pool:
        pending: deque = deque()
        pending.append((first[1],
                        pool.apply_async(_inflate_segment,
                                         (path, *first))))
        try:
            # prime the window lazily: the scan overlaps the inflate pool
            while pending:
                while len(pending) < depth:
                    nxt = next(it, None)
                    if nxt is None:
                        break
                    pending.append((nxt[1],
                                    pool.apply_async(_inflate_segment,
                                                     (path, *nxt))))
                nbytes, fut = pending.popleft()
                data = fut.get()
                if on_segment is not None:
                    on_segment(nbytes)
                if data:
                    yield data
        finally:
            pool.terminate()
