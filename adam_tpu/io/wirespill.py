"""ReadBatch wire-format spill: sequence/qual as padded byte planes.

The legacy streaming transform spills raw Parquet ROWS and re-packs the
two base-level string columns (``sequence``, ``qual``) on every
re-stream: a ragged offsets+data gather per column per chunk
(packing._string_column_to_padded).  The fused transform's stream 1
spills those columns already in the ReadBatch WIRE LAYOUT instead — one
fixed-width byte row per read, padded to the canonical length bucket —
so a re-streaming pass rebuilds the device planes with a reshape + LUT
(no ragged gather) and the output pass reconstructs the original
strings with an exact prefix slice.

Losslessness is structural, not alphabet-dependent: the wire columns
hold the ORIGINAL BYTES verbatim (never the int8 codes), lengths ride
in sidecar int32 columns (-1 encodes null, 0 the empty string), so any
IUPAC/lowercase/odd byte round-trips exactly — pinned by the
tests/test_fusion.py roundtrip property tests.

Schema mapping (column order preserved):

* ``sequence`` -> ``__wire_seq`` (binary, every row exactly the wire
  width) at the same column index; ``__wire_seq_len`` appended;
* ``qual`` -> ``__wire_qual`` / ``__wire_qual_len`` likewise.

Every chunk of one spill uses the same wire width (the caller passes
the run's growing length bucket), so the Parquet dataset carries one
unified schema and a re-read chunk's plane rebuild is a single
``data.reshape(n, W)``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

WIRE_SEQ = "__wire_seq"
WIRE_QUAL = "__wire_qual"
WIRE_SEQ_LEN = "__wire_seq_len"
WIRE_QUAL_LEN = "__wire_qual_len"

#: Arrow binary columns carry int32 offsets: one wire plane must stay
#: under 2^31 bytes or the offset arithmetic would wrap SILENTLY (a
#: 2^20-row chunk of 2048-padded long reads crosses it).  to_wire
#: builds chunked columns above this; _wire_pair refuses outright.
MAX_WIRE_PLANE_BYTES = (1 << 31) - (1 << 16)

#: the wire plane columns a count-only projection needs (plus scalars)
WIRE_COLUMNS = (WIRE_SEQ, WIRE_QUAL, WIRE_SEQ_LEN, WIRE_QUAL_LEN)


def is_wire_table(table: pa.Table) -> bool:
    return WIRE_SEQ in table.column_names


def _string_bytes(col) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Arrow string/binary column -> (data uint8, offsets int32,
    lens int32 with -1 for null)."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if isinstance(arr, pa.ChunkedArray):  # zero-chunk edge case
        arr = pa.concat_arrays(arr.chunks) if arr.num_chunks \
            else pa.array([], pa.binary())
    n = len(arr)
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], np.int32, count=n + 1,
                            offset=arr.offset * 4) if n else \
        np.zeros(1, np.int32)
    data = np.frombuffer(bufs[2], np.uint8) if len(bufs) > 2 and \
        bufs[2] is not None else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if n and arr.null_count:
        lens = np.where(np.asarray(arr.is_null()), -1, lens)
    return data, offsets, lens


def _padded_matrix(data: np.ndarray, offsets: np.ndarray,
                   lens: np.ndarray, width: int) -> np.ndarray:
    """[n, width] uint8 byte matrix: each row's original bytes then
    zero padding (null rows all-zero)."""
    n = len(lens)
    out = np.zeros((n, width), np.uint8)
    if n == 0 or data.size == 0:
        return out
    real = np.maximum(lens, 0)
    if int(real.max(initial=0)) > width:
        raise ValueError(
            f"string length {int(real.max())} exceeds wire width {width}")
    # dense fast path: uniform non-null rows ARE the matrix
    L0 = int(real[0])
    if L0 and not (lens < 0).any() and data.size == n * L0 and \
            int(offsets[0]) == 0 and int(offsets[-1]) == data.size and \
            bool((real == L0).all()):
        out[:, :L0] = data.reshape(n, L0)
        return out
    pos = np.arange(width, dtype=np.int32)[None, :]
    mask = pos < real[:, None]
    pos_in_row = np.minimum(pos, np.maximum(real[:, None] - 1, 0))
    src = np.minimum(offsets[:-1, None] + pos_in_row,
                     np.int32(max(data.size - 1, 0)))
    np.copyto(out, np.where(mask, data[src], 0))
    return out


def _wire_pair(col, width: int) -> Tuple[pa.Array, pa.Array]:
    """One string column -> (wire binary array of uniform ``width``
    rows, int32 length array with -1 for null)."""
    data, offsets, lens = _string_bytes(col)
    n = len(lens)
    if n * width > MAX_WIRE_PLANE_BYTES:
        # int32 offsets would wrap silently past 2 GiB — the caller
        # (to_wire) slices rows to stay under the cap, so reaching this
        # is a bug, and corrupting the spill is the one wrong answer
        raise ValueError(
            f"wire plane {n} rows x {width} B exceeds the 2 GiB "
            "int32-offset cap")
    mat = _padded_matrix(data, offsets, lens, width)
    wire_offsets = (np.arange(n + 1, dtype=np.int32) * width)
    wire = pa.Array.from_buffers(
        pa.binary(), n,
        [None, pa.py_buffer(wire_offsets), pa.py_buffer(mat.tobytes())])
    return wire, pa.array(lens, pa.int32())


def to_wire(table: pa.Table, width: int) -> pa.Table:
    """Replace ``sequence``/``qual`` with wire plane columns (same
    indices; length sidecars appended).  ``width`` must hold every
    read of the run (the transform passes its canonical length
    bucket).  A chunk whose padded plane would cross the 2 GiB
    int32-offset cap is built in row slices and carried as chunked
    columns — same values, no silent offset wrap."""
    rows_cap = max(MAX_WIRE_PLANE_BYTES // max(width, 1), 1)

    def wire_col(name):
        col = table.column(name)
        if table.num_rows <= rows_cap:
            w, ln = _wire_pair(col, width)
            return w, ln
        parts = [_wire_pair(col.slice(lo, rows_cap), width)
                 for lo in range(0, table.num_rows, rows_cap)]
        return (pa.chunked_array([p[0] for p in parts]),
                pa.chunked_array([p[1] for p in parts]))

    seq_wire, seq_len = wire_col("sequence")
    qual_wire, qual_len = wire_col("qual")
    out = table.set_column(table.column_names.index("sequence"),
                           WIRE_SEQ, seq_wire)
    out = out.set_column(out.column_names.index("qual"),
                         WIRE_QUAL, qual_wire)
    out = out.append_column(WIRE_SEQ_LEN, seq_len)
    return out.append_column(WIRE_QUAL_LEN, qual_len)


def _wire_matrix(table: pa.Table, name: str) -> np.ndarray:
    """[n, W] uint8 matrix straight off the wire column's data buffer."""
    data, offsets, lens = _string_bytes(table.column(name))
    n = table.num_rows
    if n == 0:
        return np.zeros((0, 0), np.uint8)
    W = int(lens[0]) if len(lens) else 0
    if W and data.size == n * W and int(offsets[0]) == 0 and \
            bool((lens == W).all()):
        return data.reshape(n, W).copy()
    # defensive ragged fallback (a hand-edited spill); rebuild densely
    width = int(np.maximum(lens, 0).max(initial=0))
    return _padded_matrix(data, offsets, lens, max(width, 1))


def _rebuild_string(mat: np.ndarray, lens: np.ndarray) -> pa.Array:
    """Wire matrix + true lengths -> the exact original string column
    (prefix bytes verbatim, nulls where ``lens < 0``)."""
    n = len(lens)
    nulls = lens < 0
    real = np.maximum(lens, 0)
    offsets = np.zeros(n + 1, np.int32)
    np.cumsum(real, out=offsets[1:])
    W = mat.shape[1] if mat.ndim == 2 else 0
    keep = np.arange(W, dtype=np.int32)[None, :] < real[:, None]
    data = mat[keep].tobytes() if W else b""
    buffers = [None, pa.py_buffer(offsets), pa.py_buffer(data)]
    null_count = int(nulls.sum())
    if null_count:
        buffers[0] = pa.py_buffer(
            np.packbits(~nulls, bitorder="little").tobytes())
    return pa.Array.from_buffers(pa.string(), n, buffers,
                                 null_count=null_count)


def from_wire(table: pa.Table) -> pa.Table:
    """Exact inverse of :func:`to_wire` (original column names, order,
    values, and nulls)."""
    seq_lens = np.asarray(table.column(WIRE_SEQ_LEN).combine_chunks()
                          .to_numpy(zero_copy_only=False)).astype(np.int64)
    qual_lens = np.asarray(table.column(WIRE_QUAL_LEN).combine_chunks()
                           .to_numpy(zero_copy_only=False)).astype(np.int64)
    seq = _rebuild_string(_wire_matrix(table, WIRE_SEQ), seq_lens)
    qual = _rebuild_string(_wire_matrix(table, WIRE_QUAL), qual_lens)
    out = table.set_column(table.column_names.index(WIRE_SEQ),
                           "sequence", seq)
    out = out.set_column(out.column_names.index(WIRE_QUAL), "qual", qual)
    return out.drop_columns([WIRE_SEQ_LEN, WIRE_QUAL_LEN])


def pack_reads_wire(table: pa.Table, *, bucket_len: int,
                    pad_rows_to: int = 1,
                    max_cigar_ops: Optional[int] = None):
    """:func:`packing.pack_reads` over a WIRE-format chunk: the base/qual
    planes come from a reshape + one LUT pass over the wire matrices (no
    ragged gather), producing bit-identical planes to packing a
    reconstructed string table (padding beyond each read's length is
    BASE_PAD / QUAL_PAD exactly as pack_reads emits)."""
    from .. import schema as S
    from ..packing import (MAX_CIGAR_OPS, QUAL_PAD, ReadBatch, _BASE_LUT,
                           _OFFSET_LUTS, _int_column, _round_up,
                           pack_cigars)

    n = table.num_rows
    n_pad = _round_up(max(n, 1), pad_rows_to)
    seq_lens = np.asarray(table.column(WIRE_SEQ_LEN).combine_chunks()
                          .to_numpy(zero_copy_only=False)).astype(np.int64)
    qual_lens = np.asarray(table.column(WIRE_QUAL_LEN).combine_chunks()
                           .to_numpy(zero_copy_only=False)).astype(np.int64)
    if int(np.maximum(seq_lens, 0).max(initial=0)) > bucket_len or \
            int(np.maximum(qual_lens, 0).max(initial=0)) > bucket_len:
        raise ValueError("wire read length exceeds bucket "
                         f"{bucket_len}")

    def plane(name, lens, lut, pad_value):
        mat = _wire_matrix(table, name)
        out = np.full((n_pad, bucket_len), pad_value, np.int8)
        W = min(mat.shape[1], bucket_len) if mat.size else 0
        if W:
            real = np.maximum(lens, 0)
            dec = lut[mat[:, :W]]
            keep = np.arange(W, dtype=np.int32)[None, :] < real[:, None]
            out[:n, :W] = np.where(keep, dec, pad_value)
        return out

    bases = plane(WIRE_SEQ, seq_lens, _BASE_LUT, S.BASE_PAD)
    quals = plane(WIRE_QUAL, qual_lens, _OFFSET_LUTS[33], QUAL_PAD)
    read_len = np.zeros(n_pad, np.int32)
    read_len[:n] = np.maximum(seq_lens, 0).astype(np.int32)
    ops, lens_c, n_ops = pack_cigars(
        table.column("cigar"), n_pad,
        max_cigar_ops if max_cigar_ops is not None else MAX_CIGAR_OPS)
    return ReadBatch(
        flags=_int_column(table, "flags", n_pad, null_value=0),
        refid=_int_column(table, "referenceId", n_pad),
        start=_int_column(table, "start", n_pad),
        mapq=_int_column(table, "mapq", n_pad),
        mate_refid=_int_column(table, "mateReferenceId", n_pad),
        mate_start=_int_column(table, "mateAlignmentStart", n_pad),
        read_group=_int_column(table, "recordGroupId", n_pad),
        valid=np.arange(n_pad) < n,
        row_index=np.where(np.arange(n_pad) < n,
                           np.arange(n_pad), -1).astype(np.int32),
        read_len=read_len, bases=bases, quals=quals,
        cigar_ops=ops, cigar_lens=lens_c, n_cigar=n_ops)


def pack_reads_ragged_wire(table: pa.Table, *, pad_rows_to: int = 1,
                           pad_bases_to: int = 1, with_cigar: bool = True,
                           max_cigar_ops: Optional[int] = None):
    """:func:`packing.pack_reads_ragged` over a WIRE-format chunk.

    The wire matrices are row-padded byte planes; gathering each row's
    true-length prefix (one boolean take per plane) yields exactly the
    concatenated layout — the length sidecars already ARE the per-read
    lengths whose prefix sum becomes ``row_offsets``.  Bit-identical to
    flattening ``pack_reads_wire``'s padded planes (the ragged
    differential pinned in tests/test_ragged.py)."""
    from .. import schema as S
    from ..packing import (MAX_CIGAR_OPS, QUAL_PAD, RaggedBatch, _BASE_LUT,
                           _OFFSET_LUTS, _int_column, _ragged_walk,
                           _ranges_within, _round_up, pack_cigars)

    n = table.num_rows
    n_pad = _round_up(max(n, 1), pad_rows_to)
    seq_lens = np.asarray(table.column(WIRE_SEQ_LEN).combine_chunks()
                          .to_numpy(zero_copy_only=False)).astype(np.int64)
    qual_lens = np.asarray(table.column(WIRE_QUAL_LEN).combine_chunks()
                           .to_numpy(zero_copy_only=False)).astype(np.int64)
    read_len = np.zeros(n_pad, np.int32)
    read_len[:n] = np.maximum(seq_lens, 0).astype(np.int32)
    T = int(read_len.sum())
    t_pad = _round_up(max(T, 1), max(int(pad_bases_to), 1))
    row_offsets, row_of, pos_of = _ragged_walk(read_len, t_pad)

    def flat(name, lens, lut, pad_value):
        mat = _wire_matrix(table, name)
        out = np.full(t_pad, pad_value, np.int8)
        if not mat.size:
            return out
        W = mat.shape[1]
        # decode only each read's true-length prefix; the qual plane
        # clips to the sequence length (flat planes share the sequence
        # offsets — bytes past read_len are never consumed by a kernel),
        # and a row whose own column is shorter leaves its tail at
        # pad_value — exactly the padded packer's QUAL_PAD tail
        eff = np.minimum(np.maximum(lens, 0),
                         np.minimum(read_len[:n], W)).astype(np.int64)
        src_rows = np.repeat(np.arange(n, dtype=np.int64), eff)
        pos = _ranges_within(eff)
        out[row_offsets[:-1][:n][src_rows] + pos] = lut[mat[src_rows, pos]]
        return out

    bases_flat = flat(WIRE_SEQ, seq_lens, _BASE_LUT, S.BASE_PAD)
    quals_flat = flat(WIRE_QUAL, qual_lens, _OFFSET_LUTS[33], QUAL_PAD)
    kw: dict = {}
    if with_cigar:
        ops, lens_c, n_ops = pack_cigars(
            table.column("cigar"), n_pad,
            max_cigar_ops if max_cigar_ops is not None else MAX_CIGAR_OPS)
        kw.update(cigar_ops=ops, cigar_lens=lens_c, n_cigar=n_ops)
    return RaggedBatch(
        flags=_int_column(table, "flags", n_pad, null_value=0),
        refid=_int_column(table, "referenceId", n_pad),
        start=_int_column(table, "start", n_pad),
        mapq=_int_column(table, "mapq", n_pad),
        mate_refid=_int_column(table, "mateReferenceId", n_pad),
        mate_start=_int_column(table, "mateAlignmentStart", n_pad),
        read_group=_int_column(table, "recordGroupId", n_pad),
        valid=np.arange(n_pad) < n,
        row_index=np.where(np.arange(n_pad) < n,
                           np.arange(n_pad), -1).astype(np.int32),
        read_len=read_len, row_offsets=row_offsets,
        bases_flat=bases_flat, quals_flat=quals_flat,
        row_of=row_of, pos_of=pos_of, **kw)
