"""Fast path: BAM file -> ReadBatch without Arrow materialization.

Uses the native packer (native/packer.c) when built, falling back to the
pure-Python codec.  This is the input pipeline for device-only workloads
(flagstat, markdup scoring, BQSR pass 1): scalar columns, decoded bases,
quals and cigars land directly in the padded SoA tensors the kernels
consume.  Header parsing (dictionaries) stays in Python — it is tiny.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..models.dictionary import RecordGroupDictionary, SequenceDictionary
from ..packing import ReadBatch, _round_up
from .bam import (iter_decompressed, load_decompressed, parse_header,
                  stream_header)

try:
    import adam_tpu_native as _native
except ImportError:  # pragma: no cover - toolchain-less environments
    _native = None


def native_available() -> bool:
    return _native is not None


def native_unavailable_reason() -> str:
    """The precise environment-limitation test (the
    tests/_mp_support.py discipline): non-empty — the reason — ONLY
    when the native packer failed to load because the built extension
    artifact targets a different CPython ABI than the running
    interpreter (e.g. a ``cpython-312`` .so under a 3.10 runtime).
    Everything else — no artifact built at all, a matching-ABI
    artifact that still failed to import — returns "" and the caller's
    test fails with the real cause; the skip is a precise condition,
    not a blanket."""
    if _native is not None:
        return ""
    import importlib.machinery
    import sys as _sys

    suffixes = tuple(importlib.machinery.EXTENSION_SUFFIXES)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return ""
    built = [n for n in names
             if n.startswith("adam_tpu_native.")
             and n.endswith((".so", ".pyd", ".dylib"))]
    if not built:
        return ""               # never built: a real toolchain failure
    if any(n[len("adam_tpu_native"):] in suffixes for n in built):
        return ""               # right ABI present yet unloadable: real
    tag = "cp%d%d" % _sys.version_info[:2]
    return (f"native packer artifact {built[0]} targets a different "
            f"CPython ABI than this interpreter ({tag}, expects "
            f"adam_tpu_native{suffixes[0]})")


def bam_to_read_batch(path, *, pad_rows_to: int = 1,
                      bucket_len: int = 0, max_cigar_ops: int = 0
                      ) -> Tuple[ReadBatch, SequenceDictionary,
                                 RecordGroupDictionary]:
    """Decode + pack a whole BAM in one native pass."""
    if _native is None:
        # fallback path never touches the file twice: read_bam does the one
        # decompression + parse
        from ..packing import pack_cigars, pack_reads
        from ..util.mdtag import parse_cigar
        from .bam import read_bam
        table, sd, rg = read_bam(path)
        cig_ops = max_cigar_ops or max(
            (len(parse_cigar(c)) for c in table.column("cigar").to_pylist()
             if c), default=1)
        return pack_reads(table, pad_rows_to=pad_rows_to,
                          bucket_len=bucket_len,
                          max_cigar_ops=max(cig_ops, 1)), sd, rg

    data = load_decompressed(path)
    seq_dict, rg_dict, first = parse_header(data, path)

    n, max_len, max_cig = _native.scan(data, first)
    L = bucket_len or _round_up(max(int(max_len), 1), 128)
    C = max_cigar_ops or max(int(max_cig), 1)
    n_pad = _round_up(max(n, 1), pad_rows_to)

    cols = _alloc_cols(n_pad, L, C)
    packed = _native.pack(
        data, first, cols["flags"][:n], cols["refid"][:n], cols["start"][:n],
        cols["mapq"][:n], cols["mate_refid"][:n], cols["mate_start"][:n],
        cols["read_len"][:n], cols["bases"][:n].reshape(-1),
        cols["quals"][:n].reshape(-1), cols["cigar_ops"][:n].reshape(-1),
        cols["cigar_lens"][:n].reshape(-1), cols["n_cigar"][:n], L, C)
    if packed != n:
        raise ValueError(f"packed {packed} of {n} records")

    batch = ReadBatch(
        valid=np.arange(n_pad) < n,
        row_index=np.where(np.arange(n_pad) < n, np.arange(n_pad),
                           -1).astype(np.int32),
        read_group=np.full(n_pad, -1, np.int32),  # RG tags stay in the
        **cols)                                   # Arrow path
    return batch, seq_dict, rg_dict


def _alloc_cols(n_pad: int, L: int, C: int) -> dict:
    return dict(
        flags=np.zeros(n_pad, np.int32),
        refid=np.full(n_pad, -1, np.int32),
        start=np.full(n_pad, -1, np.int32),
        mapq=np.full(n_pad, -1, np.int32),
        mate_refid=np.full(n_pad, -1, np.int32),
        mate_start=np.full(n_pad, -1, np.int32),
        read_len=np.zeros(n_pad, np.int32),
        bases=np.full((n_pad, L), -1, np.int8),
        quals=np.full((n_pad, L), -1, np.int8),
        cigar_ops=np.full((n_pad, C), -1, np.int8),
        cigar_lens=np.zeros((n_pad, C), np.int32),
        n_cigar=np.zeros(n_pad, np.int32),
    )


def _string_array(n, offsets, data_bytes, validity=None):
    """Arrow string array zero-copy over C-filled offsets + data blob."""
    import pyarrow as pa

    buffers = [None, pa.py_buffer(offsets[:n + 1]), pa.py_buffer(data_bytes)]
    null_count = 0
    if validity is not None:
        valid = validity[:n].astype(bool)
        null_count = int(n - valid.sum())
        if null_count:
            buffers[0] = pa.py_buffer(
                np.packbits(valid, bitorder="little").tobytes())
    return pa.Array.from_buffers(pa.string(), n, buffers[:2] + [buffers[2]],
                                 null_count=null_count)


def _arrow_chunk_table(n, fixed, offs, vals, blobs, needs_py, seq_dict,
                       rg_dict):
    """Assemble one READ_SCHEMA Arrow table from decode_arrow outputs."""
    import pyarrow as pa
    import pyarrow.compute as pc

    from .. import schema as S

    flags, refid, start, mapq, mref, mstart = (a[:n] for a in fixed)
    (name_o, seq_o, qual_o, cig_o, md_o, rg_o, attr_o, raw_o) = offs
    (name_v, seq_v, qual_v, cig_v, md_v, rg_v, attr_v) = vals
    (name_b, seq_b, qual_b, cig_b, md_b, rg_b, attr_b, raw_b) = blobs

    attributes = _string_array(n, attr_o, attr_b, attr_v)
    flagged = np.flatnonzero(needs_py[:n])
    if len(flagged):
        # rare float-tagged records: Python re-formats from the raw region
        from .bam import parse_tag_region
        out = attributes.to_pylist()
        for i in flagged:
            attrs, _, _ = parse_tag_region(raw_b, int(raw_o[i]),
                                           int(raw_o[i + 1]))
            out[int(i)] = "\t".join(attrs) if attrs else None
        attributes = pa.array(out, pa.string())

    has_ref = refid >= 0
    has_mref = mref >= 0
    ref_ids = pa.array(refid, mask=~has_ref)
    mref_ids = pa.array(mref, mask=~has_mref)
    ref_names = pa.array([r.name for r in seq_dict], pa.string())
    ref_lens = pa.array([r.length for r in seq_dict], pa.int64())
    ref_urls = pa.array([r.url for r in seq_dict], pa.string())

    def take(values, ids):
        return pc.take(values, ids)

    rg_names = _string_array(n, rg_o, rg_b, rg_v)
    enc = pc.dictionary_encode(rg_names)
    rgs = [rg_dict.get(v) if v is not None else None
           for v in enc.dictionary.to_pylist()]

    def rg_col(getter, typ):
        vals_ = pa.array([None if g is None else getter(g) for g in rgs], typ)
        return pc.take(vals_, enc.indices)

    cols = {
        "referenceName": take(ref_names, ref_ids),
        "referenceId": ref_ids,
        "start": pa.array(start.astype(np.int64),
                          mask=~(has_ref & (start >= 0))),
        "mapq": pa.array(mapq, mask=~(has_ref & (mapq != 255))),
        "readName": _string_array(n, name_o, name_b, name_v),
        "sequence": _string_array(n, seq_o, seq_b, seq_v),
        "mateReference": take(ref_names, mref_ids),
        "mateAlignmentStart": pa.array(mstart.astype(np.int64),
                                       mask=~(has_mref & (mstart >= 0))),
        "cigar": _string_array(n, cig_o, cig_b, cig_v),
        "qual": _string_array(n, qual_o, qual_b, qual_v),
        "recordGroupName": rg_col(lambda g: g.id, pa.string()),
        "recordGroupId": rg_col(lambda g: g.index, pa.int32()),
        "flags": pa.array(flags.astype(np.uint32)),
        "mismatchingPositions": _string_array(n, md_o, md_b, md_v),
        "attributes": attributes,
        "recordGroupSequencingCenter":
            rg_col(lambda g: g.sequencing_center, pa.string()),
        "recordGroupDescription":
            rg_col(lambda g: g.description, pa.string()),
        "recordGroupRunDateEpoch":
            rg_col(lambda g: g.run_date_epoch, pa.int64()),
        "recordGroupFlowOrder": rg_col(lambda g: g.flow_order, pa.string()),
        "recordGroupKeySequence":
            rg_col(lambda g: g.key_sequence, pa.string()),
        "recordGroupLibrary": rg_col(lambda g: g.library, pa.string()),
        "recordGroupPredictedMedianInsertSize":
            rg_col(lambda g: g.predicted_median_insert_size, pa.int32()),
        "recordGroupPlatform": rg_col(lambda g: g.platform, pa.string()),
        "recordGroupPlatformUnit":
            rg_col(lambda g: g.platform_unit, pa.string()),
        "recordGroupSample": rg_col(lambda g: g.sample, pa.string()),
        "mateReferenceId": mref_ids,
        "referenceLength": take(ref_lens, ref_ids),
        "referenceUrl": take(ref_urls, ref_ids),
        "mateReferenceLength": take(ref_lens, mref_ids),
        "mateReferenceUrl": take(ref_urls, mref_ids),
    }
    return pa.Table.from_pydict(
        {nm: cols[nm] for nm in S.READ_SCHEMA.names}, schema=S.READ_SCHEMA)


def open_bam_arrow_stream(path, *, chunk_rows: int = 1 << 20,
                          chunk_bytes: int = 1 << 24, io_procs: int = 1):
    """(seq_dict, rg_dict, generator of Arrow tables) — native fast path.

    The C decoder (native/packer.c decode_arrow) emits string columns as
    offsets+data blobs that pyarrow wraps zero-copy; measured ~50x the pure
    Python record parser.  Falls back to ``open_bam_stream`` without the
    extension.  ``io_procs > 1`` inflates BGZF across a process pool
    (byte-identical stream — io/bgzf_procs).
    """
    from .bam import open_bam_stream

    if _native is None:
        return open_bam_stream(path, chunk_rows=chunk_rows,
                               chunk_bytes=chunk_bytes, io_procs=io_procs)
    byte_iter = iter_decompressed(path, chunk_bytes, procs=io_procs)
    seq_dict, rg_dict, off, buf = stream_header(byte_iter, path)

    def decode(buf, off):
        cr = chunk_rows
        fixed = [np.empty(cr, np.int32) for _ in range(6)]
        offs = [np.empty(cr + 1, np.int32) for _ in range(8)]
        vals = [np.empty(cr, np.uint8) for _ in range(7)]
        needs_py = np.zeros(cr, np.uint8)
        n, next_off, *blobs = _native.decode_arrow(
            buf, off, cr, *fixed, *offs, *vals, needs_py)
        table = None if n == 0 else _arrow_chunk_table(
            n, fixed, offs, vals, blobs, needs_py, seq_dict, rg_dict)
        return n, next_off, table

    return seq_dict, rg_dict, _stream_records(path, byte_iter, buf, off,
                                              chunk_bytes, decode)


def open_bam_batch_stream(path, *, chunk_rows: int = 1 << 20,
                          pad_rows_to: int = 1, bucket_len: int = 0,
                          max_cigar_ops: int = 0, chunk_bytes: int = 1 << 24):
    """(seq_dict, rg_dict, generator of ReadBatch) over a streamed BAM.

    The streaming input pipeline for device workloads: BGZF blocks
    decompress incrementally, ``scan_chunk``/``pack_chunk`` (native) walk at
    most ``chunk_rows`` records per step, and each chunk packs straight into
    the fixed-shape SoA tensors.  Host RSS stays bounded by
    chunk_rows × row width — never the file size.

    Row-length buckets and cigar-slot budgets grow monotonically across
    chunks (rounded to 128 lanes), so a long run of same-shape chunks reuses
    one compiled kernel.
    """
    from ..errors import FormatError

    if _native is None:
        # pure-Python fallback: Arrow chunks -> pack_reads
        from ..packing import pack_reads
        from .bam import open_bam_stream
        sd, rg, tables = open_bam_stream(path, chunk_rows=chunk_rows,
                                         chunk_bytes=chunk_bytes)

        def gen_py():
            L = bucket_len
            C = max_cigar_ops or 1
            for table in tables:
                from ..util.mdtag import parse_cigar
                C = max(C, max((len(parse_cigar(c))
                                for c in table.column("cigar").to_pylist()
                                if c), default=1))
                # grow the bucket before packing — a later chunk may hold a
                # longer read than anything seen so far
                chunk_max = max((len(s) for s
                                 in table.column("sequence").to_pylist()
                                 if s), default=1)
                L = max(L, _round_up(chunk_max, 128))
                batch = pack_reads(table, pad_rows_to=pad_rows_to,
                                   bucket_len=L, max_cigar_ops=C)
                yield batch

        return sd, rg, gen_py()

    byte_iter = iter_decompressed(path, chunk_bytes)
    seq_dict, rg_dict, off, buf = stream_header(byte_iter, path)

    def gen():
        nonlocal buf, off
        L_sticky = bucket_len
        C_sticky = max_cigar_ops
        exhausted = False
        # incremental scan state: resume from scan_off instead of re-walking
        # the whole accumulated buffer after every appended byte piece
        n, max_len, max_cig, scan_off = 0, 0, 0, off
        while True:
            dn, dml, dmc, scan_off = _native.scan_chunk(
                buf, scan_off, chunk_rows - n)
            n += dn
            max_len = max(max_len, dml)
            max_cig = max(max_cig, dmc)
            if n < chunk_rows and not exhausted:
                if off:
                    del buf[:off]
                    scan_off -= off
                    off = 0
                piece = next(byte_iter, None)
                if piece is None:
                    exhausted = True
                else:
                    buf += piece
                continue
            if n == 0:
                if off < len(buf):
                    raise FormatError(
                        f"{path}: {len(buf) - off} trailing bytes form no "
                        "complete record (truncated file?)")
                return
            next_off = scan_off
            n_pad = _round_up(n, pad_rows_to)
            L_sticky = max(L_sticky, _round_up(max(int(max_len), 1), 128))
            C_sticky = max(C_sticky, int(max_cig), 1)
            cols = _alloc_cols(n_pad, L_sticky, C_sticky)
            packed, new_off = _native.pack_chunk(
                buf, off, cols["flags"][:n], cols["refid"][:n],
                cols["start"][:n], cols["mapq"][:n], cols["mate_refid"][:n],
                cols["mate_start"][:n], cols["read_len"][:n],
                cols["bases"][:n].reshape(-1), cols["quals"][:n].reshape(-1),
                cols["cigar_ops"][:n].reshape(-1),
                cols["cigar_lens"][:n].reshape(-1), cols["n_cigar"][:n],
                L_sticky, C_sticky)
            if packed != n or new_off != next_off:
                raise ValueError(
                    f"pack_chunk consumed {packed}/{n} records")
            off = scan_off = new_off
            n_chunk, n = n, 0
            max_len, max_cig = 0, 0
            yield ReadBatch(
                valid=np.arange(n_pad) < n_chunk,
                row_index=np.where(np.arange(n_pad) < n_chunk,
                                   np.arange(n_pad), -1).astype(np.int32),
                read_group=np.full(n_pad, -1, np.int32),
                **cols)

    return seq_dict, rg_dict, gen()


def _stream_records(path, byte_iter, buf0, off0, chunk_bytes, decode):
    """Shared bounded-buffer driver for the native chunk decoders: fill the
    window, call ``decode(buf, off)`` -> (n, next_offset, result), widen the
    window when one record exceeds it, raise on trailing garbage, trim the
    consumed prefix.  Yields each non-empty ``result``."""
    from ..errors import FormatError

    buf, off = buf0, off0
    exhausted = False
    target = chunk_bytes
    while True:
        while not exhausted and len(buf) - off < target:
            piece = next(byte_iter, None)
            if piece is None:
                exhausted = True
            else:
                buf += piece
        n, next_off, result = decode(buf, off)
        if n == 0:
            if exhausted:
                if off < len(buf):
                    raise FormatError(
                        f"{path}: {len(buf) - off} trailing bytes form "
                        "no complete record (truncated file?)")
                return
            target *= 2  # one record larger than the buffer window
            continue
        target = chunk_bytes  # a widened window resets after success
        off = next_off
        if off:
            del buf[:off]
            off = 0
        yield result


def open_bam_wire32_stream(path, *, chunk_rows: int = 1 << 22,
                           chunk_bytes: int = 1 << 24, io_procs: int = 1):
    """Generator of uint32 flagstat wire-word chunks straight from BAM
    bytes — the 4 fields flagstat consumes live at fixed offsets in each
    record, so the native walk emits the wire with NO name/seq/qual/cigar
    decode (the Arrow path decodes everything, ~30 bytes of string work
    per 4-byte word).  Field semantics are pinned to the Arrow path by a
    differential test.  Returns None without the native extension; the
    caller falls back to the Arrow path.
    """
    if _native is None or not hasattr(_native, "flagstat_wire_chunk"):
        return None
    # I/O ledger: the native walk decodes the whole BAM once — count its
    # on-disk bytes against the active pass scope (no-op outside one)
    from ..obs import ioledger
    ioledger.record_input(path)
    byte_iter = iter_decompressed(path, chunk_bytes, procs=io_procs)
    _sd, _rg, off0, buf0 = stream_header(byte_iter, path)

    def decode(buf, off):
        out = np.empty(chunk_rows, np.uint32)
        n, next_off = _native.flagstat_wire_chunk(buf, off, chunk_rows, out)
        return n, next_off, out[:n]

    return _stream_records(path, byte_iter, buf0, off0, chunk_bytes,
                           decode)
