"""Fast path: BAM file -> ReadBatch without Arrow materialization.

Uses the native packer (native/packer.c) when built, falling back to the
pure-Python codec.  This is the input pipeline for device-only workloads
(flagstat, markdup scoring, BQSR pass 1): scalar columns, decoded bases,
quals and cigars land directly in the padded SoA tensors the kernels
consume.  Header parsing (dictionaries) stays in Python — it is tiny.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..models.dictionary import RecordGroupDictionary, SequenceDictionary
from ..packing import ReadBatch, _round_up
from .bam import (iter_decompressed, load_decompressed, parse_header,
                  stream_header)

try:
    import adam_tpu_native as _native
except ImportError:  # pragma: no cover - toolchain-less environments
    _native = None


def native_available() -> bool:
    return _native is not None


def bam_to_read_batch(path, *, pad_rows_to: int = 1,
                      bucket_len: int = 0, max_cigar_ops: int = 0
                      ) -> Tuple[ReadBatch, SequenceDictionary,
                                 RecordGroupDictionary]:
    """Decode + pack a whole BAM in one native pass."""
    if _native is None:
        # fallback path never touches the file twice: read_bam does the one
        # decompression + parse
        from ..packing import pack_cigars, pack_reads
        from ..util.mdtag import parse_cigar
        from .bam import read_bam
        table, sd, rg = read_bam(path)
        cig_ops = max_cigar_ops or max(
            (len(parse_cigar(c)) for c in table.column("cigar").to_pylist()
             if c), default=1)
        return pack_reads(table, pad_rows_to=pad_rows_to,
                          bucket_len=bucket_len,
                          max_cigar_ops=max(cig_ops, 1)), sd, rg

    data = load_decompressed(path)
    seq_dict, rg_dict, first = parse_header(data, path)

    n, max_len, max_cig = _native.scan(data, first)
    L = bucket_len or _round_up(max(int(max_len), 1), 128)
    C = max_cigar_ops or max(int(max_cig), 1)
    n_pad = _round_up(max(n, 1), pad_rows_to)

    cols = _alloc_cols(n_pad, L, C)
    packed = _native.pack(
        data, first, cols["flags"][:n], cols["refid"][:n], cols["start"][:n],
        cols["mapq"][:n], cols["mate_refid"][:n], cols["mate_start"][:n],
        cols["read_len"][:n], cols["bases"][:n].reshape(-1),
        cols["quals"][:n].reshape(-1), cols["cigar_ops"][:n].reshape(-1),
        cols["cigar_lens"][:n].reshape(-1), cols["n_cigar"][:n], L, C)
    if packed != n:
        raise ValueError(f"packed {packed} of {n} records")

    batch = ReadBatch(
        valid=np.arange(n_pad) < n,
        row_index=np.where(np.arange(n_pad) < n, np.arange(n_pad),
                           -1).astype(np.int32),
        read_group=np.full(n_pad, -1, np.int32),  # RG tags stay in the
        **cols)                                   # Arrow path
    return batch, seq_dict, rg_dict


def _alloc_cols(n_pad: int, L: int, C: int) -> dict:
    return dict(
        flags=np.zeros(n_pad, np.int32),
        refid=np.full(n_pad, -1, np.int32),
        start=np.full(n_pad, -1, np.int32),
        mapq=np.full(n_pad, -1, np.int32),
        mate_refid=np.full(n_pad, -1, np.int32),
        mate_start=np.full(n_pad, -1, np.int32),
        read_len=np.zeros(n_pad, np.int32),
        bases=np.full((n_pad, L), -1, np.int8),
        quals=np.full((n_pad, L), -1, np.int8),
        cigar_ops=np.full((n_pad, C), -1, np.int8),
        cigar_lens=np.zeros((n_pad, C), np.int32),
        n_cigar=np.zeros(n_pad, np.int32),
    )


def open_bam_batch_stream(path, *, chunk_rows: int = 1 << 20,
                          pad_rows_to: int = 1, bucket_len: int = 0,
                          max_cigar_ops: int = 0, chunk_bytes: int = 1 << 24):
    """(seq_dict, rg_dict, generator of ReadBatch) over a streamed BAM.

    The streaming input pipeline for device workloads: BGZF blocks
    decompress incrementally, ``scan_chunk``/``pack_chunk`` (native) walk at
    most ``chunk_rows`` records per step, and each chunk packs straight into
    the fixed-shape SoA tensors.  Host RSS stays bounded by
    chunk_rows × row width — never the file size.

    Row-length buckets and cigar-slot budgets grow monotonically across
    chunks (rounded to 128 lanes), so a long run of same-shape chunks reuses
    one compiled kernel.
    """
    from ..errors import FormatError

    if _native is None:
        # pure-Python fallback: Arrow chunks -> pack_reads
        from ..packing import pack_reads
        from .bam import open_bam_stream
        sd, rg, tables = open_bam_stream(path, chunk_rows=chunk_rows,
                                         chunk_bytes=chunk_bytes)

        def gen_py():
            L = bucket_len
            C = max_cigar_ops or 1
            for table in tables:
                from ..util.mdtag import parse_cigar
                C = max(C, max((len(parse_cigar(c))
                                for c in table.column("cigar").to_pylist()
                                if c), default=1))
                # grow the bucket before packing — a later chunk may hold a
                # longer read than anything seen so far
                chunk_max = max((len(s) for s
                                 in table.column("sequence").to_pylist()
                                 if s), default=1)
                L = max(L, _round_up(chunk_max, 128))
                batch = pack_reads(table, pad_rows_to=pad_rows_to,
                                   bucket_len=L, max_cigar_ops=C)
                yield batch

        return sd, rg, gen_py()

    byte_iter = iter_decompressed(path, chunk_bytes)
    seq_dict, rg_dict, off, buf = stream_header(byte_iter, path)

    def gen():
        nonlocal buf, off
        L_sticky = bucket_len
        C_sticky = max_cigar_ops
        exhausted = False
        # incremental scan state: resume from scan_off instead of re-walking
        # the whole accumulated buffer after every appended byte piece
        n, max_len, max_cig, scan_off = 0, 0, 0, off
        while True:
            dn, dml, dmc, scan_off = _native.scan_chunk(
                buf, scan_off, chunk_rows - n)
            n += dn
            max_len = max(max_len, dml)
            max_cig = max(max_cig, dmc)
            if n < chunk_rows and not exhausted:
                if off:
                    del buf[:off]
                    scan_off -= off
                    off = 0
                piece = next(byte_iter, None)
                if piece is None:
                    exhausted = True
                else:
                    buf += piece
                continue
            if n == 0:
                if off < len(buf):
                    raise FormatError(
                        f"{path}: {len(buf) - off} trailing bytes form no "
                        "complete record (truncated file?)")
                return
            next_off = scan_off
            n_pad = _round_up(n, pad_rows_to)
            L_sticky = max(L_sticky, _round_up(max(int(max_len), 1), 128))
            C_sticky = max(C_sticky, int(max_cig), 1)
            cols = _alloc_cols(n_pad, L_sticky, C_sticky)
            packed, new_off = _native.pack_chunk(
                buf, off, cols["flags"][:n], cols["refid"][:n],
                cols["start"][:n], cols["mapq"][:n], cols["mate_refid"][:n],
                cols["mate_start"][:n], cols["read_len"][:n],
                cols["bases"][:n].reshape(-1), cols["quals"][:n].reshape(-1),
                cols["cigar_ops"][:n].reshape(-1),
                cols["cigar_lens"][:n].reshape(-1), cols["n_cigar"][:n],
                L_sticky, C_sticky)
            if packed != n or new_off != next_off:
                raise ValueError(
                    f"pack_chunk consumed {packed}/{n} records")
            off = scan_off = new_off
            n_chunk, n = n, 0
            max_len, max_cig = 0, 0
            yield ReadBatch(
                valid=np.arange(n_pad) < n_chunk,
                row_index=np.where(np.arange(n_pad) < n_chunk,
                                   np.arange(n_pad), -1).astype(np.int32),
                read_group=np.full(n_pad, -1, np.int32),
                **cols)

    return seq_dict, rg_dict, gen()
