"""Fast path: BAM file -> ReadBatch without Arrow materialization.

Uses the native packer (native/packer.c) when built, falling back to the
pure-Python codec.  This is the input pipeline for device-only workloads
(flagstat, markdup scoring, BQSR pass 1): scalar columns, decoded bases,
quals and cigars land directly in the padded SoA tensors the kernels
consume.  Header parsing (dictionaries) stays in Python — it is tiny.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..models.dictionary import RecordGroupDictionary, SequenceDictionary
from ..packing import ReadBatch, _round_up
from .bam import load_decompressed, parse_header

try:
    import adam_tpu_native as _native
except ImportError:  # pragma: no cover - toolchain-less environments
    _native = None


def native_available() -> bool:
    return _native is not None


def bam_to_read_batch(path, *, pad_rows_to: int = 1,
                      bucket_len: int = 0, max_cigar_ops: int = 0
                      ) -> Tuple[ReadBatch, SequenceDictionary,
                                 RecordGroupDictionary]:
    """Decode + pack a whole BAM in one native pass."""
    if _native is None:
        # fallback path never touches the file twice: read_bam does the one
        # decompression + parse
        from ..packing import pack_cigars, pack_reads
        from ..util.mdtag import parse_cigar
        from .bam import read_bam
        table, sd, rg = read_bam(path)
        cig_ops = max_cigar_ops or max(
            (len(parse_cigar(c)) for c in table.column("cigar").to_pylist()
             if c), default=1)
        return pack_reads(table, pad_rows_to=pad_rows_to,
                          bucket_len=bucket_len,
                          max_cigar_ops=max(cig_ops, 1)), sd, rg

    data = load_decompressed(path)
    seq_dict, rg_dict, first = parse_header(data, path)

    n, max_len, max_cig = _native.scan(data, first)
    L = bucket_len or _round_up(max(int(max_len), 1), 128)
    C = max_cigar_ops or max(int(max_cig), 1)
    n_pad = _round_up(max(n, 1), pad_rows_to)

    cols = dict(
        flags=np.zeros(n_pad, np.int32),
        refid=np.full(n_pad, -1, np.int32),
        start=np.full(n_pad, -1, np.int32),
        mapq=np.full(n_pad, -1, np.int32),
        mate_refid=np.full(n_pad, -1, np.int32),
        mate_start=np.full(n_pad, -1, np.int32),
        read_len=np.zeros(n_pad, np.int32),
        bases=np.full((n_pad, L), -1, np.int8),
        quals=np.full((n_pad, L), -1, np.int8),
        cigar_ops=np.full((n_pad, C), -1, np.int8),
        cigar_lens=np.zeros((n_pad, C), np.int32),
        n_cigar=np.zeros(n_pad, np.int32),
    )
    packed = _native.pack(
        data, first, cols["flags"][:n], cols["refid"][:n], cols["start"][:n],
        cols["mapq"][:n], cols["mate_refid"][:n], cols["mate_start"][:n],
        cols["read_len"][:n], cols["bases"][:n].reshape(-1),
        cols["quals"][:n].reshape(-1), cols["cigar_ops"][:n].reshape(-1),
        cols["cigar_lens"][:n].reshape(-1), cols["n_cigar"][:n], L, C)
    if packed != n:
        raise ValueError(f"packed {packed} of {n} records")

    batch = ReadBatch(
        valid=np.arange(n_pad) < n,
        row_index=np.where(np.arange(n_pad) < n, np.arange(n_pad),
                           -1).astype(np.int32),
        read_group=np.full(n_pad, -1, np.int32),  # RG tags stay in the
        **cols)                                   # Arrow path
    return batch, seq_dict, rg_dict
