"""Parquet storage with projection + predicate pushdown.

The reference reads Parquet through ParquetInputFormat + AvroReadSupport with
an optional projected schema and pushdown predicate
(rdd/AdamContext.scala:139-161) and writes through ParquetOutputFormat
(rdd/AdamRDDFunctions.scala:37-56).  pyarrow gives us both natively: column
projection = ``columns=``, predicate pushdown = row-group filtering via
``filters=``.

Datasets are directories of part files, like the reference's Hadoop output
(part-r-00000.parquet ...), so shards can be written independently per host.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from .. import schema as S

#: the reference's LocusPredicate (predicates/LocusPredicate.scala:28-36):
#: mapped ∧ primary ∧ !failedVendorQualityChecks ∧ !duplicateRead, expressed
#: over the packed flags word.
LOCUS_PREDICATE_MASK = (S.FLAG_UNMAPPED | S.FLAG_SECONDARY |
                        S.FLAG_QC_FAIL | S.FLAG_DUPLICATE)


def locus_predicate():
    import pyarrow.compute as pc
    field = pc.field("flags")
    return (pc.bit_wise_and(field, pa.scalar(LOCUS_PREDICATE_MASK, pa.uint32()))
            == pa.scalar(0, pa.uint32()))


def save_table(table: pa.Table, path: str, *, compression: str = "zstd",
               row_group_size: int = 1 << 20, n_parts: int = 1) -> None:
    """Write a dataset directory of Parquet part files (adamSave analog)."""
    os.makedirs(path, exist_ok=True)
    rows = table.num_rows
    per = max(1, (rows + n_parts - 1) // max(n_parts, 1))
    part = 0
    for lo in range(0, max(rows, 1), per):
        chunk = table.slice(lo, per)
        pq.write_table(chunk, os.path.join(path, f"part-r-{part:05d}.parquet"),
                       compression=compression, row_group_size=row_group_size)
        part += 1


def load_table(path: str, *, columns: Optional[Sequence[str]] = None,
               filters=None) -> pa.Table:
    """Read a Parquet file or dataset directory with optional projection
    (column subset) and pushdown predicate (pyarrow filter expression)."""
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if f.endswith(".parquet"))
        import pyarrow.dataset as ds
        dataset = ds.dataset(paths, format="parquet")
        return dataset.to_table(columns=list(columns) if columns else None,
                                filter=filters)
    if filters is not None:
        import pyarrow.dataset as ds
        return ds.dataset(path, format="parquet").to_table(
            columns=list(columns) if columns else None, filter=filters)
    return pq.read_table(path, columns=list(columns) if columns else None)
