"""Parquet storage with projection + predicate pushdown.

The reference reads Parquet through ParquetInputFormat + AvroReadSupport with
an optional projected schema and pushdown predicate
(rdd/AdamContext.scala:139-161) and writes through ParquetOutputFormat
(rdd/AdamRDDFunctions.scala:37-56).  pyarrow gives us both natively: column
projection = ``columns=``, predicate pushdown = row-group filtering via
``filters=``.

Datasets are directories of part files, like the reference's Hadoop output
(part-r-00000.parquet ...), so shards can be written independently per host.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from .. import schema as S
from ..resilience import faults as _faults

#: the reference's LocusPredicate (predicates/LocusPredicate.scala:28-36):
#: mapped ∧ primary ∧ !failedVendorQualityChecks ∧ !duplicateRead, expressed
#: over the packed flags word.
LOCUS_PREDICATE_MASK = (S.FLAG_UNMAPPED | S.FLAG_SECONDARY |
                        S.FLAG_QC_FAIL | S.FLAG_DUPLICATE)


def locus_predicate():
    import pyarrow.compute as pc
    field = pc.field("flags")
    return (pc.bit_wise_and(field, pa.scalar(LOCUS_PREDICATE_MASK, pa.uint32()))
            == pa.scalar(0, pa.uint32()))


def rows_for_block_size(table: pa.Table, block_bytes: int) -> int:
    """Approximate row-group row count for a byte-denominated block size
    (the reference's ``-parquet_block_size``, ParquetArgs.scala:22-31, is
    bytes; our writers rotate row groups by rows)."""
    rows = max(table.num_rows, 1)
    bytes_per_row = max(table.nbytes / rows, 1.0)
    return max(int(block_bytes / bytes_per_row), 1)


def save_table(table: pa.Table, path: str, *, compression: str = "zstd",
               row_group_size: int = 1 << 20, n_parts: int = 1,
               page_size: int | None = None,
               use_dictionary: bool = True) -> None:
    """Write a dataset directory of Parquet part files (adamSave analog)."""
    os.makedirs(path, exist_ok=True)
    rows = table.num_rows
    per = max(1, (rows + n_parts - 1) // max(n_parts, 1))
    part = 0
    for lo in range(0, max(rows, 1), per):
        chunk = table.slice(lo, per)
        pq.write_table(chunk, os.path.join(path, f"part-r-{part:05d}.parquet"),
                       compression=compression, row_group_size=row_group_size,
                       data_page_size=page_size,
                       use_dictionary=use_dictionary)
        part += 1


def iter_tables(path: str, *, columns: Optional[Sequence[str]] = None,
                filters=None, chunk_rows: int = 1 << 20):
    """Stream a Parquet file/dataset as Arrow tables of ~chunk_rows each.

    Projection and predicate push down into the scan; host memory stays
    bounded by the chunk size instead of the dataset size.
    """
    import pyarrow.dataset as ds
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if f.endswith(".parquet"))
        dataset = ds.dataset(paths, format="parquet")
    else:
        dataset = ds.dataset(path, format="parquet")
    for batch in dataset.to_batches(
            columns=list(columns) if columns else None, filter=filters,
            batch_size=chunk_rows):
        if batch.num_rows:
            yield pa.Table.from_batches([batch])


class DatasetWriter:
    """Incremental Parquet dataset writer: one part file per ``write`` call
    group, bounded memory (the streaming counterpart of :func:`save_table`).

    The reference's executors each write their own part file
    (AdamRDDFunctions.scala:37-56 via ParquetOutputFormat); here each flushed
    chunk becomes a part, named in write order so readers see file order ==
    stream order.

    ``part_rows`` rotates to a new part file after that many rows, but rows
    stream into the OPEN part as row groups every ``row_group_size`` rows —
    memory stays bounded by the row-group size even when one part holds the
    whole dataset (transform -coalesce 1).
    """

    def __init__(self, path: str, *, compression: str = "zstd",
                 row_group_size: int = 1 << 20,
                 part_rows: int = 1 << 20,
                 page_size: int | None = None,
                 use_dictionary: bool = True,
                 row_group_bytes: int | None = None,
                 io_pass: str | None = None,
                 io_kind: str = "spilled"):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.compression = compression
        self.row_group_size = row_group_size
        self.part_rows = part_rows
        self.page_size = page_size
        self.use_dictionary = use_dictionary
        #: byte-denominated row-group target (the reference's
        #: -parquet_block_size); resolved to rows from the first flushed
        #: chunk's observed bytes/row
        self.row_group_bytes = row_group_bytes
        #: I/O-ledger attribution (obs.ioledger): a spill writer names
        #: the pass that pays for it (``io_pass="p1"``) and its on-disk
        #: bytes are counted at close — from ``os.stat`` of the parts
        #: this writer produced, so ledger totals reconcile with ``du``.
        #: ``io_pass=None`` (the default — outputs, converters) records
        #: nothing.
        self.io_pass = io_pass
        self.io_kind = io_kind
        self._part_paths: list[str] = []
        self._part = 0
        self._part_row_count = 0
        self._writer: Optional[pq.ParquetWriter] = None
        self._pending: list[pa.Table] = []
        self._pending_rows = 0
        self._schema: Optional[pa.Schema] = None
        self.rows_written = 0

    def write(self, table: pa.Table) -> None:
        self._schema = table.schema
        self._pending.append(table)
        self._pending_rows += table.num_rows
        if self._pending_rows >= min(self.row_group_size, self.part_rows):
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        chunk = pa.concat_tables(self._pending)
        self._pending = []
        self._pending_rows = 0
        if self.row_group_bytes is not None:
            self.row_group_size = rows_for_block_size(
                chunk, self.row_group_bytes)
            self.row_group_bytes = None
        # split across part-file boundaries
        part_path = None
        while chunk.num_rows:
            part_path = os.path.join(
                self.path, f"part-r-{self._part:05d}.parquet")
            if self._writer is None:
                self._writer = pq.ParquetWriter(
                    part_path,
                    chunk.schema, compression=self.compression,
                    data_page_size=self.page_size,
                    use_dictionary=self.use_dictionary)
                self._part_paths.append(part_path)
            room = self.part_rows - self._part_row_count
            head = chunk.slice(0, room)
            self._writer.write_table(head,
                                     row_group_size=self.row_group_size)
            self.rows_written += head.num_rows
            self._part_row_count += head.num_rows
            chunk = chunk.slice(head.num_rows)
            if self._part_row_count >= self.part_rows:
                self._writer.close()
                self._writer = None
                self._part += 1
                self._part_row_count = 0
        if part_path is not None:
            # spill_write injection site: a truncate/corrupt fault tears
            # the just-flushed part and 'dies' — resume must treat the
            # partial spill as absent or rebuild it (pinned by the
            # crash-consistency tests)
            _faults.fire("spill_write", path=part_path)

    def close(self) -> None:
        self.flush()
        if self._writer is None and self.rows_written == 0 and \
                self._schema is not None:
            # an all-empty stream still yields a schema-bearing dataset
            # (save_table writes one empty part the same way) — a
            # part-less directory reads back as a 0-column table and
            # breaks every downstream consumer
            empty_path = os.path.join(self.path, "part-r-00000.parquet")
            self._writer = pq.ParquetWriter(
                empty_path,
                self._schema, compression=self.compression,
                data_page_size=self.page_size,
                use_dictionary=self.use_dictionary)
            self._part_paths.append(empty_path)
            self._writer.write_table(self._schema.empty_table())
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self.io_pass is not None and self._part_paths:
            from ..obs import ioledger
            nbytes = 0
            for p in self._part_paths:
                try:
                    nbytes += os.path.getsize(p)
                except OSError:
                    pass
            ioledger.record(self.io_kind, nbytes, self.io_pass)
            self._part_paths = []   # idempotent close: count parts once

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if not any(exc):
            self.close()


def load_table(path: str, *, columns: Optional[Sequence[str]] = None,
               filters=None) -> pa.Table:
    """Read a Parquet file or dataset directory with optional projection
    (column subset) and pushdown predicate (pyarrow filter expression)."""
    if os.path.isdir(path):
        paths = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if f.endswith(".parquet"))
        import pyarrow.dataset as ds
        dataset = ds.dataset(paths, format="parquet")
        return dataset.to_table(columns=list(columns) if columns else None,
                                filter=filters)
    if filters is not None:
        import pyarrow.dataset as ds
        return ds.dataset(path, format="parquet").to_table(
            columns=list(columns) if columns else None, filter=filters)
    return pq.read_table(path, columns=list(columns) if columns else None)
