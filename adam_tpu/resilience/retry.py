"""Scoped retry/degradation policy engine for per-chunk device dispatch.

The recovery ladder between "chunk raised" and "job restarts" (SURVEY
§5's lineage re-execution, rebuilt at chunk granularity):

1. **retry** — transient device errors (preemption, interconnect
   ``DATA_LOSS``, ``UNAVAILABLE``) re-dispatch the same chunk with
   exponential backoff + deterministic jitter, at most ``budget``
   attempts;
2. **split** — ``RESOURCE_EXHAUSTED`` halves the chunk/bucket along the
   existing ladder rungs and re-dispatches the halves (every consumer is
   an exact monoid or per-row map, so re-chunking never changes bytes —
   the ``reread`` contract);
3. **CPU fallback** — a budget-exhausted (persistent) device failure
   re-runs that chunk's kernels on the CPU backend, byte-identical by
   construction (exact integer kernels), and flags the dispatch
   ``degraded`` — a streaming run finishes instead of dying;
4. **raise** — fatal errors (anything not recognizably transient)
   propagate immediately; bounded retries never mask a real bug.

Every decision is :func:`decide_retry` — PURE, recorded in full in the
``retry_attempt`` event (``inputs`` + ``input_digest``, the executor's
``decide_plan`` convention) so tools/check_resilience.py replays a
recorded run's policy offline.  Degraded dispatches additionally emit
``degraded_dispatch`` and set the ``degraded`` gauge.

Above the per-chunk ladder sits the **backend circuit breaker**
(docs/ARCHITECTURE.md §6m): one transient-retry exhaustion is a bad
chunk, N of them inside a sliding window is a backend STORM — and
paying ``budget`` retries + backoff per chunk during a storm multiplies
the outage.  Per dispatch site, the breaker counts exhaustions; past
``threshold`` in ``window_s`` it TRIPS OPEN (``breaker_state`` event,
``breaker_open`` gauge) and every subsequent dispatch short-circuits —
straight to the byte-identical degraded-CPU fallback when the site has
one, or a typed :class:`BreakerOpen` otherwise — with zero device
attempts and zero backoff sleeps.  After ``cooldown_s`` it goes
HALF-OPEN: exactly one probe dispatch is let through; success closes
the breaker (counters reset), failure re-opens it for another cooldown.
:func:`decide_breaker` is PURE and its transitions replay offline
through tools/check_executor.py.

Policy knobs: ``-retry_budget`` on the streaming CLI commands, the
``ADAM_TPU_RETRY_*`` envs, and the ``ADAM_TPU_BREAKER*`` envs
(docs/RESILIENCE.md).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .. import obs
from . import faults

RETRY_BUDGET_ENV = "ADAM_TPU_RETRY_BUDGET"
RETRY_BACKOFF_ENV = "ADAM_TPU_RETRY_BACKOFF_S"
RETRY_SPLIT_ENV = "ADAM_TPU_RETRY_SPLIT"            # 0/off disables
RETRY_FALLBACK_ENV = "ADAM_TPU_RETRY_CPU_FALLBACK"  # 0/off disables
RETRY_SEED_ENV = "ADAM_TPU_RETRY_SEED"

#: attempts per chunk, retries included (1 = no retries)
DEFAULT_BUDGET = 3
DEFAULT_BACKOFF_S = 0.05
DEFAULT_BACKOFF_CAP_S = 2.0


def env_int(explicit, name: str, default: int) -> int:
    """Explicit-argument-wins / env-fills-unset / garbage-falls-to-
    default int coercion — THE resolver rule, shared by every policy
    resolver here and in serve/overload.py."""
    if explicit is not None:
        return int(explicit)
    try:
        return int(os.environ[name]) if os.environ.get(name) \
            else default
    except ValueError:
        return default


def env_float(explicit, name: str, default: float) -> float:
    """:func:`env_int`'s float twin."""
    if explicit is not None:
        return float(explicit)
    try:
        return float(os.environ[name]) if os.environ.get(name) \
            else default
    except ValueError:
        return default

#: XLA status codes (and message substrings) worth re-dispatching: the
#: transient set production TPU jobs see across preemption, interconnect
#: flaps, and coordinator churn
_TRANSIENT_MARKS = ("DATA_LOSS", "UNAVAILABLE", "PREEMPT",
                    "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
                    "INTERNAL", "CONNECTION RESET", "SOCKET CLOSED")
_OOM_MARKS = ("RESOURCE_EXHAUSTED", "OUT OF MEMORY", "OOM")


@dataclass(frozen=True)
class RetryPolicy:
    """One resolved policy per run scope (executor / realign engine)."""
    budget: int = DEFAULT_BUDGET
    backoff_s: float = DEFAULT_BACKOFF_S
    backoff_cap_s: float = DEFAULT_BACKOFF_CAP_S
    split: bool = True
    cpu_fallback: bool = True
    seed: int = 0


def resolve_retry_policy(budget: Optional[int] = None,
                         backoff_s: Optional[float] = None,
                         split: Optional[bool] = None,
                         cpu_fallback: Optional[bool] = None,
                         seed: Optional[int] = None) -> RetryPolicy:
    """Explicit arguments (CLI flags) win; ``ADAM_TPU_RETRY_*`` envs fill
    whatever the caller left unset (the executor's flag/env convention)."""
    env = os.environ

    def _bool(v, name):
        if v is not None:
            return bool(v)
        return env.get(name, "1") not in ("0", "off")

    return RetryPolicy(
        budget=max(env_int(budget, RETRY_BUDGET_ENV, DEFAULT_BUDGET),
                   1),
        backoff_s=max(env_float(backoff_s, RETRY_BACKOFF_ENV,
                                DEFAULT_BACKOFF_S), 0.0),
        backoff_cap_s=DEFAULT_BACKOFF_CAP_S,
        split=_bool(split, RETRY_SPLIT_ENV),
        cpu_fallback=_bool(cpu_fallback, RETRY_FALLBACK_ENV),
        seed=env_int(seed, RETRY_SEED_ENV, 0))


# ---------------------------------------------------------------------------
# fleet-scoped policy (the shard-stream supervisor's knobs)
# ---------------------------------------------------------------------------

FLEET_RESTARTS_ENV = "ADAM_TPU_FLEET_MAX_RESTARTS"
FLEET_LEASE_TTL_ENV = "ADAM_TPU_FLEET_LEASE_TTL_S"
FLEET_HEARTBEAT_ENV = "ADAM_TPU_FLEET_HEARTBEAT_S"
FLEET_REDISTRIBUTE_ENV = "ADAM_TPU_FLEET_REDISTRIBUTE"   # 0/off disables
FLEET_SPECULATE_ENV = "ADAM_TPU_FLEET_SPECULATE"         # 1/on enables
FLEET_SPECULATE_FACTOR_ENV = "ADAM_TPU_FLEET_SPECULATE_FACTOR"
FLEET_STEAL_ENV = "ADAM_TPU_FLEET_STEAL"                 # 1/on enables


@dataclass(frozen=True)
class FleetPolicy:
    """One resolved recovery policy per fleet run (the shard-stream
    supervisor, parallel/shardstream.py) — the fleet-scoped rung of the
    same ladder :class:`RetryPolicy` runs per chunk INSIDE each worker.

    ``max_restarts`` bounds respawned incarnations per shard (the
    elastic supervisor's convention); past it, ``redistribute`` lets the
    dead shard's remaining range shrink-to-fit across survivors.
    ``lease_ttl_s`` is how stale a worker's heartbeat lease may go
    before the supervisor declares the worker lost (a hung worker shows
    no exit code — the lease is what converts "silent" into "dead").
    ``speculate`` (off by default) enables deadline-based speculative
    reassignment of the slowest shard's tail range to an idle survivor;
    the per-unit commit merge deduplicates, so speculation can never
    double-count.  ``steal`` (off by default) enables unit-granular
    work stealing: an idle worker pulls single pending units off the
    claim table (parallel/ringplane.py, ``O_EXCL`` create = one winner)
    instead of waiting for a lease expiry or a whole-shard speculative
    copy — the straggler's tail drains across survivors while the
    straggler still runs.  The same merge dedup backstops it.
    """
    max_restarts: int = 2
    lease_ttl_s: float = 10.0
    heartbeat_s: float = 1.0
    redistribute: bool = True
    speculate: bool = False
    speculate_factor: float = 3.0
    steal: bool = False


def resolve_fleet_policy(max_restarts: Optional[int] = None,
                         lease_ttl_s: Optional[float] = None,
                         heartbeat_s: Optional[float] = None,
                         redistribute: Optional[bool] = None,
                         speculate: Optional[bool] = None,
                         speculate_factor: Optional[float] = None,
                         steal: Optional[bool] = None) -> FleetPolicy:
    """Explicit arguments (CLI flags) win; ``ADAM_TPU_FLEET_*`` envs fill
    whatever the caller left unset (the executor's flag/env convention).
    The heartbeat defaults to a third of the lease TTL so one missed
    renewal never expires a healthy worker."""
    env = os.environ

    def _bool(v, name, default):
        if v is not None:
            return bool(v)
        raw = env.get(name)
        if raw is None:
            return default
        return raw not in ("0", "off", "")

    ttl = max(env_float(lease_ttl_s, FLEET_LEASE_TTL_ENV, 10.0), 0.1)
    hb = env_float(heartbeat_s, FLEET_HEARTBEAT_ENV, ttl / 3.0)
    return FleetPolicy(
        max_restarts=max(env_int(max_restarts, FLEET_RESTARTS_ENV, 2),
                         0),
        lease_ttl_s=ttl,
        heartbeat_s=min(max(hb, 0.05), ttl),
        redistribute=_bool(redistribute, FLEET_REDISTRIBUTE_ENV, True),
        speculate=_bool(speculate, FLEET_SPECULATE_ENV, False),
        speculate_factor=max(
            env_float(speculate_factor, FLEET_SPECULATE_FACTOR_ENV,
                      3.0),
            1.0),
        steal=_bool(steal, FLEET_STEAL_ENV, False))


# ---------------------------------------------------------------------------
# the backend circuit breaker
# ---------------------------------------------------------------------------

BREAKER_ENV = "ADAM_TPU_BREAKER"                    # 0/off disables
BREAKER_THRESHOLD_ENV = "ADAM_TPU_BREAKER_THRESHOLD"
BREAKER_WINDOW_ENV = "ADAM_TPU_BREAKER_WINDOW_S"
BREAKER_COOLDOWN_ENV = "ADAM_TPU_BREAKER_COOLDOWN_S"

#: exhaustions inside the window before the breaker trips — one bad
#: chunk retries normally; a third budget-exhausted chunk in half a
#: minute is a storm
DEFAULT_BREAKER_THRESHOLD = 3
DEFAULT_BREAKER_WINDOW_S = 30.0
DEFAULT_BREAKER_COOLDOWN_S = 5.0

BREAKER_STATES = ("closed", "open", "half_open")


class BreakerOpen(RuntimeError):
    """A dispatch was refused because the site's circuit breaker is
    open (a transient-failure storm is in progress) and the site has no
    byte-identical CPU fallback to degrade to.  Typed — the serve loop
    writes it into ``failed/<job>.json`` as ``error_type: BreakerOpen``
    and the client may retry after the cooldown."""

    def __init__(self, site: str, cooldown_s: float):
        self.site = site
        self.cooldown_s = cooldown_s
        super().__init__(
            f"circuit breaker open for site {site!r} (transient-"
            f"failure storm); retry after ~{cooldown_s}s")


@dataclass(frozen=True)
class BreakerPolicy:
    """One resolved breaker policy per process (all sites share it;
    state is per site)."""
    enabled: bool = True
    threshold: int = DEFAULT_BREAKER_THRESHOLD
    window_s: float = DEFAULT_BREAKER_WINDOW_S
    cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S


def resolve_breaker_policy(enabled: Optional[bool] = None,
                           threshold: Optional[int] = None,
                           window_s: Optional[float] = None,
                           cooldown_s: Optional[float] = None
                           ) -> BreakerPolicy:
    """Explicit arguments win; ``ADAM_TPU_BREAKER*`` envs fill whatever
    the caller left unset (the resolve_retry_policy convention)."""
    if enabled is None:
        enabled = os.environ.get(BREAKER_ENV, "1") not in ("0", "off")
    return BreakerPolicy(
        enabled=bool(enabled),
        threshold=max(env_int(threshold, BREAKER_THRESHOLD_ENV,
                              DEFAULT_BREAKER_THRESHOLD), 1),
        window_s=max(env_float(window_s, BREAKER_WINDOW_ENV,
                               DEFAULT_BREAKER_WINDOW_S), 0.1),
        cooldown_s=max(env_float(cooldown_s, BREAKER_COOLDOWN_ENV,
                                 DEFAULT_BREAKER_COOLDOWN_S), 0.0))


#: (env 4-tuple) -> resolved policy: the per-dispatch hot path pays
#: four dict lookups and a tuple compare, not string parsing + a
#: dataclass build per chunk (tests that monkeypatch the envs still
#: see their change — the key is the env values themselves)
_BREAKER_POLICY_CACHE: dict = {}


def _breaker_policy_cached() -> BreakerPolicy:
    key = (os.environ.get(BREAKER_ENV),
           os.environ.get(BREAKER_THRESHOLD_ENV),
           os.environ.get(BREAKER_WINDOW_ENV),
           os.environ.get(BREAKER_COOLDOWN_ENV))
    pol = _BREAKER_POLICY_CACHE.get(key)
    if pol is None:
        _BREAKER_POLICY_CACHE.clear()   # envs changed: one live entry
        pol = _BREAKER_POLICY_CACHE[key] = resolve_breaker_policy()
    return pol


def decide_breaker(*, state: str, failures: int, threshold: int,
                   open_elapsed_s: Optional[float] = None,
                   cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S,
                   probe_ok: Optional[bool] = None) -> dict:
    """One breaker transition — PURE.

    ``state`` is the current breaker state, ``failures`` the
    exhaustions currently inside the sliding window (the caller prunes
    the window — the one clock use, at the impure boundary),
    ``open_elapsed_s`` how long the breaker has been open (None unless
    open), ``probe_ok`` the half-open probe's outcome (None unless a
    probe finished).  Returns the next state with the canonicalized
    inputs + digest (``breaker_state`` event; tools/check_executor.py
    replays it)."""
    inputs = dict(state=str(state), failures=int(failures),
                  threshold=int(threshold),
                  open_elapsed_s=None if open_elapsed_s is None
                  else round(float(open_elapsed_s), 3),
                  cooldown_s=round(float(cooldown_s), 3),
                  probe_ok=None if probe_ok is None else bool(probe_ok))
    cur = inputs["state"]
    new, reason = cur, f"steady:{cur}"
    if cur == "closed":
        if inputs["failures"] >= inputs["threshold"]:
            new = "open"
            reason = (f"tripped: {inputs['failures']} transient "
                      f"exhaustion(s) >= threshold "
                      f"{inputs['threshold']} in window — storm")
    elif cur == "open":
        if inputs["open_elapsed_s"] is not None and \
                inputs["open_elapsed_s"] >= inputs["cooldown_s"]:
            new = "half_open"
            reason = (f"cooldown {inputs['cooldown_s']}s elapsed: "
                      "probing")
    elif cur == "half_open":
        if inputs["probe_ok"] is True:
            new = "closed"
            reason = "probe succeeded: closing"
        elif inputs["probe_ok"] is False:
            new = "open"
            reason = "probe failed: re-opening"
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return dict(state=new, changed=new != cur, reason=reason,
                inputs=inputs, input_digest=digest)


class _Breaker:
    """One site's breaker: the impure shell (clock, window pruning,
    thread lock) around :func:`decide_breaker`."""

    def __init__(self, site: str):
        self.site = site
        self.state = "closed"
        self.fail_times: list = []
        self.opened_at: Optional[float] = None
        self.probing = False
        self._lock = threading.Lock()

    def _transition(self, policy: BreakerPolicy, **signals) -> None:
        """Take one pure :func:`decide_breaker` decision from the
        current state + ``signals``, record it, apply it (caller holds
        the lock) — every state change is a ``breaker_state`` event."""
        d = decide_breaker(state=self.state,
                           failures=len(self.fail_times),
                           threshold=policy.threshold,
                           cooldown_s=policy.cooldown_s, **signals)
        if not d["changed"]:
            return
        self.state = d["state"]
        if d["state"] == "open":
            self.opened_at = time.monotonic()
            self.probing = False
            obs.registry().counter("breaker_trips",
                                   site=self.site).inc()
            obs.registry().gauge("breaker_open", site=self.site).set(1)
        elif d["state"] == "closed":
            self.fail_times = []
            self.opened_at = None
            self.probing = False
            obs.registry().gauge("breaker_open", site=self.site).set(0)
        obs.emit("breaker_state", site=self.site, state=d["state"],
                 failures=len(self.fail_times), reason=d["reason"],
                 inputs=d["inputs"], input_digest=d["input_digest"])

    def _prune(self, window_s: float) -> None:
        cut = time.monotonic() - window_s
        while self.fail_times and self.fail_times[0] < cut:
            self.fail_times.pop(0)

    def admit(self, policy: BreakerPolicy) -> str:
        """Gate one dispatch: ``"pass"`` (closed), ``"probe"`` (this
        dispatch is the half-open probe), or ``"open"`` (short-circuit
        to fallback/typed-reject)."""
        with self._lock:
            if self.state == "closed":
                return "pass"
            if self.state == "open":
                elapsed = None if self.opened_at is None else \
                    time.monotonic() - self.opened_at
                self._transition(policy, open_elapsed_s=elapsed)
            if self.state == "half_open":
                if not self.probing:
                    self.probing = True
                    return "probe"
            return "open"

    def record_exhaustion(self, policy: BreakerPolicy) -> None:
        """One transient budget exhaustion at this site: count it and
        maybe trip."""
        with self._lock:
            self.fail_times.append(time.monotonic())
            self._prune(policy.window_s)
            if self.state == "closed":
                self._transition(policy)

    def probe_result(self, ok: bool, policy: BreakerPolicy) -> None:
        with self._lock:
            if self.state != "half_open":
                return
            self._transition(policy, probe_ok=ok)


#: per-site breakers (process-global: the storm is a property of the
#: backend, not of one executor instance)
_BREAKERS: dict = {}
_BREAKERS_LOCK = threading.Lock()


def breaker_for(site: str) -> _Breaker:
    with _BREAKERS_LOCK:
        b = _BREAKERS.get(site)
        if b is None:
            b = _BREAKERS[site] = _Breaker(site)
        return b


def reset_breakers() -> None:
    """Forget all breaker state (tests; a fresh process starts clean
    anyway)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


def breaker_snapshot() -> dict:
    """``{site: state}`` for observability/reporting (never throws)."""
    with _BREAKERS_LOCK:
        return {s: b.state for s, b in _BREAKERS.items()}


# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------

def classify_error(exc: BaseException) -> str:
    """``"oom"`` / ``"transient"`` / ``"fatal"`` for one dispatch error.

    Injected faults classify by their carried code — the same mapping a
    real ``XlaRuntimeError`` gets from its message, so the chaos matrix
    exercises the identical policy path production errors take.
    """
    if isinstance(exc, faults.InjectedFormatError):
        return "fatal"          # bad input is not a device problem
    if isinstance(exc, faults.InjectedFault):
        code = getattr(exc, "code", "")
        if code in ("RESOURCE_EXHAUSTED",):
            return "oom"
        if code in ("DATA_LOSS", "UNAVAILABLE", "PREEMPTED",
                    "DEADLINE_EXCEEDED", "ABORTED", "INTERNAL"):
            return "transient"
        return "fatal"
    name = type(exc).__name__
    module = type(exc).__module__ or ""
    if name == "XlaRuntimeError" or module.startswith(("jaxlib", "jax")):
        msg = str(exc).upper()
        if any(m in msg for m in _OOM_MARKS):
            return "oom"
        if any(m in msg for m in _TRANSIENT_MARKS):
            return "transient"
        return "fatal"
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return "transient"
    return "fatal"


# ---------------------------------------------------------------------------
# the pure decision
# ---------------------------------------------------------------------------

def backoff_delay(key: str, attempt: int, base_s: float, cap_s: float,
                  seed: int = 0) -> float:
    """Exponential backoff with DETERMINISTIC jitter: the jitter fraction
    derives from a digest of (key, attempt, seed), so a replay computes
    the identical delay — seeded chaos stays replayable — while distinct
    sites/attempts still de-synchronize (the thundering-herd fix jitter
    exists for).  Shared with the elastic supervisor's restart backoff."""
    raw = min(cap_s, base_s * (2.0 ** max(attempt - 1, 0)))
    h = hashlib.sha256(f"{key}|{attempt}|{seed}".encode()).digest()
    frac = int.from_bytes(h[:4], "big") / 0xFFFFFFFF
    return round(raw * (1.0 + 0.5 * frac), 6)


def decide_retry(*, site: str, attempt: int, budget: int,
                 error_kind: str, can_split: bool, can_fallback: bool,
                 backoff_s: float = DEFAULT_BACKOFF_S,
                 backoff_cap_s: float = DEFAULT_BACKOFF_CAP_S,
                 seed: int = 0) -> dict:
    """One failed attempt's next action — PURE.

    ``action`` ∈ ``retry`` (sleep ``delay_s``, re-dispatch) / ``split``
    (halve along the ladder rungs, re-dispatch the halves) /
    ``fallback_cpu`` (degraded per-chunk CPU re-run) / ``raise``.  The
    ``retry_attempt`` event records the canonicalized inputs + digest,
    replayed by tools/check_resilience.py.
    """
    inputs = dict(site=site, attempt=int(attempt), budget=int(budget),
                  error_kind=error_kind, can_split=bool(can_split),
                  can_fallback=bool(can_fallback),
                  backoff_s=round(float(backoff_s), 6),
                  backoff_cap_s=round(float(backoff_cap_s), 6),
                  seed=int(seed))
    action, delay, reason = "raise", 0.0, ""
    kind = inputs["error_kind"]
    if kind == "fatal":
        reason = "fatal-error"
    elif kind == "oom" and inputs["can_split"]:
        action, reason = "split", "oom:split-ladder"
    elif inputs["attempt"] < inputs["budget"]:
        action = "retry"
        delay = backoff_delay(site, inputs["attempt"],
                              inputs["backoff_s"],
                              inputs["backoff_cap_s"], inputs["seed"])
        reason = f"{kind}:attempt {inputs['attempt']}/{inputs['budget']}"
    elif inputs["can_fallback"]:
        action, reason = "fallback_cpu", f"{kind}:budget-exhausted"
    else:
        reason = f"{kind}:budget-exhausted:no-fallback"
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    return dict(action=action, delay_s=delay, reason=reason,
                inputs=inputs, input_digest=digest)


# ---------------------------------------------------------------------------
# the dispatch wrapper
# ---------------------------------------------------------------------------

def dispatch_with_retry(fn: Callable[[int], object], *,
                        site: str = "device_dispatch", label: str = "",
                        policy: Optional[RetryPolicy] = None,
                        split: Optional[Callable] = None,
                        fallback: Optional[Callable] = None):
    """Run one dispatch under the policy ladder.

    ``fn(attempt)`` performs the dispatch — the attempt number lets the
    caller re-transfer from host state on retries (a failed donated
    dispatch may have consumed its device buffer) and keep donation to
    the first attempt only.  ``split(exc)`` / ``fallback(exc)`` are the
    caller's halve-and-redispatch and CPU re-run; either may be ``None``
    when the site cannot support it, and the pure decision sees that.

    The fault-injection site fires inside the attempt, so injected
    faults traverse the identical recovery path real errors take.

    The site's circuit breaker gates the whole ladder: while OPEN (a
    transient storm tripped it) the dispatch short-circuits — the
    byte-identical CPU fallback runs with zero device attempts when the
    site has one, a typed :class:`BreakerOpen` raises otherwise.  A
    half-open breaker lets exactly one probe dispatch through; its
    outcome closes or re-opens the breaker.
    """
    if policy is None:
        policy = resolve_retry_policy()
    if site == "device_dispatch":
        # every device dispatch funnels through here — the first one of
        # the process closes the cold-start window (obs.startup)
        obs.startup.mark_at("first_dispatch")
    bpolicy = _breaker_policy_cached()
    breaker = breaker_for(site) if bpolicy.enabled else None
    probe = False
    if breaker is not None:
        gate = breaker.admit(bpolicy)
        probe = gate == "probe"
        if gate == "open":
            exc = BreakerOpen(site, bpolicy.cooldown_s)
            if fallback is not None and policy.cpu_fallback:
                obs.registry().counter("degraded_dispatches",
                                       site=site).inc()
                obs.registry().gauge("degraded").set(1)
                obs.emit("degraded_dispatch", site=site, label=label,
                         attempt=1, error_kind="breaker_open",
                         error=str(exc)[:200])
                return fallback(exc)
            raise exc
    attempt = 0
    while True:
        attempt += 1
        try:
            faults.fire(site)
            result = fn(attempt)
            if probe:
                breaker.probe_result(True, bpolicy)
            return result
        except Exception as e:  # noqa: BLE001 — classified below
            kind = classify_error(e)
            d = decide_retry(
                site=site, attempt=attempt, budget=policy.budget,
                error_kind=kind,
                can_split=split is not None and policy.split,
                can_fallback=fallback is not None and policy.cpu_fallback,
                backoff_s=policy.backoff_s,
                backoff_cap_s=policy.backoff_cap_s, seed=policy.seed)
            obs.registry().counter("retry_attempts", site=site).inc()
            obs.emit("retry_attempt", site=site, label=label,
                     attempt=attempt, error_kind=kind,
                     error=f"{type(e).__name__}: {e}"[:200],
                     action=d["action"], delay_s=d["delay_s"],
                     reason=d["reason"], inputs=d["inputs"],
                     input_digest=d["input_digest"])
            if d["action"] == "retry":
                if d["delay_s"]:
                    time.sleep(d["delay_s"])
                continue
            if breaker is not None:
                # a transient budget exhaustion is the breaker's storm
                # signal (one bad chunk retries; N exhausted chunks in
                # the window trip the site); a half-open probe that
                # ends anywhere but success re-opens
                if kind == "transient" and d["action"] != "retry":
                    breaker.record_exhaustion(bpolicy)
                if probe:
                    breaker.probe_result(False, bpolicy)
            if d["action"] == "split":
                return split(e)
            if d["action"] == "fallback_cpu":
                obs.registry().counter("degraded_dispatches",
                                       site=site).inc()
                obs.registry().gauge("degraded").set(1)
                obs.emit("degraded_dispatch", site=site, label=label,
                         attempt=attempt, error_kind=kind,
                         error=f"{type(e).__name__}: {e}"[:200])
                return fallback(e)
            raise
