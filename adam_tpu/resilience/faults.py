"""Deterministic fault-injection plane.

Every recovery path in the pipeline — retry, split, CPU fallback,
checkpoint resume, elastic restart — exists to survive failures that
real hardware produces rarely and unreproducibly.  This module makes
those failures a *first-class, replayable input*: named injection sites
sit at the existing choke points (the executor's dispatch/put, the
ingest feeders, the spill/checkpoint writers, the elastic workers, the
BAM record decoder), and a seeded fault plan says which site fires on
which occurrence with which fault.

Determinism contract (the executor's ``decide_plan`` convention):
:func:`decide_fault` is a PURE function of ``(site, occurrence,
incarnation, rules)``; every firing emits a ``fault_injected`` event
carrying those inputs verbatim plus their digest, so
tools/check_resilience.py can replay a recorded run's firings offline
and fail on any non-determinism.

Zero-overhead contract: with no plan installed, :func:`fire` is one
module-global ``None`` check — no occurrence counting, no events, no
behavior change (pinned by tests/test_resilience.py).

Faults:

* ``error``    — raise a typed error (:class:`InjectedDeviceError` with
  an XLA-style status code, or :class:`InjectedFormatError` for input
  sites) that the retry engine classifies exactly like the real thing;
* ``latency``  — sleep ``latency_s`` (slow-link / straggler rehearsal);
* ``truncate`` — for write sites: truncate the in-flight file to
  ``frac`` of its bytes, then raise :class:`InjectedTornWrite` — a
  power loss mid-write, as observable by the next process;
* ``corrupt``  — for write sites: overwrite a window of the file's
  middle bytes, then raise :class:`InjectedTornWrite`;
* ``kill``     — SIGKILL the current process (``worker_proc``: the
  elastic supervisor's worker-death path, no Python unwinding).
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import signal
import threading
import time
from typing import Optional

from .. import obs
from ..errors import FormatError

#: the named injection sites (docs/RESILIENCE.md documents each one's
#: choke point); fire() rejects anything else so a typo'd plan fails
#: loudly instead of never firing
SITES = ("device_dispatch", "device_put", "spill_write",
         "checkpoint_write", "feeder_load", "worker_proc", "input_record",
         "shard_lease", "ring_write", "net_send", "net_recv", "net_accept")

FAULTS = ("error", "latency", "truncate", "corrupt", "kill")

#: plan path fallback for the CLI flag (how elastic workers and bench
#: subprocesses inherit the plan — env crosses the process boundary)
FAULT_PLAN_ENV = "ADAM_TPU_FAULT_PLAN"
#: stamped by the elastic supervisor on each worker's env; plan rules
#: with an ``incarnation`` field only fire when it matches
INCARNATION_ENV = "ADAM_TPU_INCARNATION"
#: stamped by the shard-fleet supervisor (parallel/shardstream.py) on
#: each worker's env; plan rules with a ``shard`` field only fire when
#: it matches — how the chaos matrix targets one host of a fleet
SHARD_ENV = "ADAM_TPU_SHARD_ID"

#: stamped by the fleet-serve scheduler (serve/scheduler.py) on each
#: always-warm worker's env; plan rules with a ``worker`` field only
#: fire in that worker's process — how the chaos matrix SIGKILLs one
#: host of a serve fleet while its neighbors keep serving
WORKER_ENV = "ADAM_TPU_WORKER_ID"

#: the serve front-end's per-job scope (adam_tpu/serve): the server sets
#: the current tenant around each job's execution, and plan rules with a
#: ``tenant`` field only fire while that tenant's job runs — how the
#: chaos matrix faults tenant A without touching tenant B.  Module
#: state, not env: tenants multiplex inside ONE process.
_TENANT: Optional[str] = None

#: error codes an ``error`` fault may raise (the transient set mirrors
#: retry.classify_error's XLA status matching; FORMAT raises the typed
#: input error the CLI already turns into a clean one-line exit)
ERROR_CODES = ("RESOURCE_EXHAUSTED", "DATA_LOSS", "UNAVAILABLE",
               "PREEMPTED", "DEADLINE_EXCEEDED", "ABORTED", "INTERNAL",
               "FORMAT", "ENOSPC")


class InjectedFault(RuntimeError):
    """Base of every injected failure — typed, so the chaos matrix can
    pin 'fails cleanly' as 'raises an InjectedFault subclass, never a
    bare crash'."""

    code = "INJECTED"


class InjectedDeviceError(InjectedFault):
    """An injected device/runtime error carrying an XLA-style status
    code; retry.classify_error maps it exactly like a real
    XlaRuntimeError with the same code in its message."""

    def __init__(self, code: str, site: str, occurrence: int):
        self.code = code
        super().__init__(
            f"{code}: injected fault at site {site!r} occurrence "
            f"{occurrence}")


class InjectedTornWrite(InjectedFault):
    """The write was torn (truncated/corrupted) and the writer 'died' —
    what a crash mid-write looks like to the next process.  ``fault``
    says which tear ("truncate" or "corrupt"): stream sites (the net
    plane) map truncate to a mid-frame connection drop and corrupt to
    garbage bytes on the wire."""

    code = "DATA_LOSS"
    fault = "truncate"


class InjectedDiskFull(OSError, InjectedFault):
    """An injected ``OSError(ENOSPC)`` — the disk filled mid-write.
    Subclasses OSError so the durable-write paths' cleanup (tmp-file
    removal in checkpoint.atomic_write) sees exactly what a real
    disk-full raises, and InjectedFault so workers die typed."""

    code = "ENOSPC"

    def __init__(self, site: str, occurrence: int):
        super().__init__(
            errno.ENOSPC,
            f"injected disk full at site {site!r} occurrence {occurrence}")


class InjectedFormatError(FormatError, InjectedFault):
    """Injected malformed-input error; subclasses FormatError so the CLI
    prints its one-line message and exits 2 like any bad input."""

    code = "FORMAT"


_LOCK = threading.Lock()
_PLAN: Optional[dict] = None
_COUNTS: dict = {}
#: site -> canonical rules targeting it (install-time index): fire()'s
#: hot path scans only these cheap matchers and defers the full
#: decide_fault (rules copy + JSON + sha256) to actual hits, so a plan
#: targeting one site costs per-record sites nothing but a dict lookup
_BY_SITE: dict = {}


# ---------------------------------------------------------------------------
# plan install / canonicalization
# ---------------------------------------------------------------------------

def _canon_rule(i: int, rule: dict) -> dict:
    """Validate + canonicalize one plan rule (the exact dict the
    ``fault_injected`` event records, so replay sees what fired)."""
    site = rule.get("site")
    if site not in SITES:
        raise ValueError(f"fault plan rule {i}: unknown site {site!r} "
                         f"(want one of {', '.join(SITES)})")
    fault = rule.get("fault")
    if fault not in FAULTS:
        raise ValueError(f"fault plan rule {i}: unknown fault {fault!r} "
                         f"(want one of {', '.join(FAULTS)})")
    occ = rule.get("occurrence", "1+")
    if isinstance(occ, bool) or not (
            isinstance(occ, int)
            or (isinstance(occ, list) and occ
                and all(isinstance(o, int) and not isinstance(o, bool)
                        for o in occ))
            or (isinstance(occ, str) and occ.endswith("+")
                and occ[:-1].isdigit())):
        raise ValueError(
            f"fault plan rule {i}: occurrence must be an int, a list of "
            f"ints, or 'N+' (every occurrence >= N), got {occ!r}")
    out = dict(site=site, fault=fault, occurrence=occ)
    if fault == "error":
        code = rule.get("error", "UNAVAILABLE")
        if code not in ERROR_CODES:
            raise ValueError(f"fault plan rule {i}: unknown error code "
                             f"{code!r} (want one of {', '.join(ERROR_CODES)})")
        out["error"] = code
    if fault == "latency":
        out["latency_s"] = round(float(rule.get("latency_s", 0.01)), 6)
    if fault in ("truncate", "corrupt"):
        frac = float(rule.get("frac", 0.5))
        if not 0.0 <= frac <= 1.0:
            raise ValueError(f"fault plan rule {i}: frac must be in "
                             f"[0, 1], got {frac}")
        out["frac"] = round(frac, 6)
    if "incarnation" in rule:
        out["incarnation"] = int(rule["incarnation"])
    if "shard" in rule:
        out["shard"] = int(rule["shard"])
    if "worker" in rule:
        out["worker"] = int(rule["worker"])
    if "tenant" in rule:
        out["tenant"] = str(rule["tenant"])
    return out


def canonicalize_plan(plan: dict) -> dict:
    """Validate a raw plan document into its canonical form (what the
    plane decides from and what events record)."""
    if not isinstance(plan, dict) or not isinstance(
            plan.get("rules"), list):
        raise ValueError("fault plan must be an object with a 'rules' list")
    return {"seed": int(plan.get("seed", 0)),
            "rules": [_canon_rule(i, r)
                      for i, r in enumerate(plan["rules"])]}


def install_plan(plan) -> dict:
    """Install a fault plan process-wide: a dict, or a path to a JSON
    file.  Occurrence counters reset — a plan install starts a fresh,
    replayable firing sequence."""
    global _PLAN
    if isinstance(plan, str):
        with open(plan) as f:
            plan = json.load(f)
    canon = canonicalize_plan(plan)
    by_site: dict = {}
    for rule in canon["rules"]:
        by_site.setdefault(rule["site"], []).append(rule)
    with _LOCK:
        _PLAN = canon
        _COUNTS.clear()
        _BY_SITE.clear()
        _BY_SITE.update(by_site)
    return canon


def install_from_env(flag_value: Optional[str] = None) -> Optional[dict]:
    """The CLI entry: the ``-fault_plan`` flag wins, ``ADAM_TPU_FAULT_PLAN``
    is the fallback (how spawned workers inherit the plan); neither set
    leaves the plane inert."""
    path = flag_value or os.environ.get(FAULT_PLAN_ENV) or None
    return install_plan(path) if path else None


def clear_plan() -> None:
    """Remove the installed plan and zero the counters (test isolation).
    The serve tenant scope clears too — a leaked tenant would silently
    re-scope the next test's plan."""
    global _PLAN, _TENANT
    with _LOCK:
        _PLAN = None
        _TENANT = None
        _COUNTS.clear()
        _BY_SITE.clear()


def reset_counters() -> None:
    """Zero the occurrence counters, keeping the plan (a fresh run)."""
    with _LOCK:
        _COUNTS.clear()


def active() -> bool:
    return _PLAN is not None


# ---------------------------------------------------------------------------
# the pure decision + the firing hook
# ---------------------------------------------------------------------------

def _occ_matches(spec, occurrence: int) -> bool:
    if isinstance(spec, int):
        return occurrence == spec
    if isinstance(spec, list):
        return occurrence in spec
    return occurrence >= int(spec[:-1])     # "N+" — persistent fault


def decide_fault(*, site: str, occurrence: int,
                 incarnation: Optional[int] = None,
                 shard: Optional[int] = None,
                 worker: Optional[int] = None,
                 tenant: Optional[str] = None,
                 rules: list) -> dict:
    """Whether (and how) this site occurrence fires — PURE.

    First matching rule wins (a plan is read top to bottom, like the
    executor ladder's first-fit).  The returned decision carries the
    canonicalized ``inputs`` and their ``input_digest``, the replayable
    contract tools/check_resilience.py verifies.  ``shard`` (the fleet
    worker's id, from ``ADAM_TPU_SHARD_ID``), ``worker`` (the
    fleet-serve host's id, from ``ADAM_TPU_WORKER_ID``) and ``tenant``
    (the serve front-end's current job scope) join the inputs ONLY when
    set, so pre-fleet/pre-serve sidecars replay digest-identical.
    """
    inputs = dict(site=site, occurrence=int(occurrence),
                  incarnation=None if incarnation is None
                  else int(incarnation),
                  rules=[dict(r) for r in rules])
    if shard is not None:
        inputs["shard"] = int(shard)
    if worker is not None:
        inputs["worker"] = int(worker)
    if tenant is not None:
        inputs["tenant"] = str(tenant)
    hit = None
    idx = None
    for i, rule in enumerate(inputs["rules"]):
        if rule["site"] != site:
            continue
        if not _occ_matches(rule["occurrence"], inputs["occurrence"]):
            continue
        if "incarnation" in rule and \
                rule["incarnation"] != inputs["incarnation"]:
            continue
        if "shard" in rule and rule["shard"] != inputs.get("shard"):
            continue
        if "worker" in rule and rule["worker"] != inputs.get("worker"):
            continue
        if "tenant" in rule and rule["tenant"] != inputs.get("tenant"):
            continue
        hit, idx = rule, i
        break
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()).hexdigest()[:16]
    out = dict(fire=hit is not None, rule=idx,
               fault=None if hit is None else hit["fault"],
               inputs=inputs, input_digest=digest)
    if hit is not None:
        for k in ("error", "latency_s", "frac"):
            if k in hit:
                out[k] = hit[k]
    return out


def _incarnation() -> Optional[int]:
    v = os.environ.get(INCARNATION_ENV)
    try:
        return int(v) if v else None
    except ValueError:
        return None


def _shard() -> Optional[int]:
    v = os.environ.get(SHARD_ENV)
    try:
        return int(v) if v else None
    except ValueError:
        return None


def _worker() -> Optional[int]:
    v = os.environ.get(WORKER_ENV)
    try:
        return int(v) if v else None
    except ValueError:
        return None


def set_tenant(tenant: Optional[str]) -> None:
    """Scope subsequent firings to one serve tenant (None clears).  The
    serve front-end brackets each job's execution with this, so a plan
    rule carrying ``tenant`` targets exactly one job's dispatches."""
    global _TENANT
    _TENANT = None if tenant is None else str(tenant)


def current_tenant() -> Optional[str]:
    return _TENANT


def fire(site: str, path: Optional[str] = None) -> None:
    """The injection hook every choke point calls.

    No plan → return immediately (the zero-overhead contract: no
    counting, no events).  With a plan: count the occurrence, take the
    pure decision, record it, apply the fault (which may raise, sleep,
    tear ``path``, or SIGKILL the process).
    """
    plan = _PLAN
    if plan is None:
        return
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}")
    # untargeted site: no counting, no lock — its occurrence numbers
    # are unobservable (no rule can ever fire there), and per-record
    # sites must not contend on the global lock just because a plan
    # targets some OTHER site
    candidates = _BY_SITE.get(site)
    if not candidates:
        return
    with _LOCK:
        _COUNTS[site] = occ = _COUNTS.get(site, 0) + 1
    # cheap pre-match before the full pure decision: the hot path
    # (per-record input_record, per-chunk feeder/put sites) must not pay
    # the rules copy + JSON + sha256 of decide_fault on every miss —
    # decide_fault re-derives the SAME first-match on a hit, so the
    # recorded decision stays bit-for-bit replayable
    inc = _incarnation()
    shard = _shard()
    worker = _worker()
    tenant = _TENANT
    if not any(_occ_matches(r["occurrence"], occ)
               and ("incarnation" not in r or r["incarnation"] == inc)
               and ("shard" not in r or r["shard"] == shard)
               and ("worker" not in r or r["worker"] == worker)
               and ("tenant" not in r or r["tenant"] == tenant)
               for r in candidates):
        return
    d = decide_fault(site=site, occurrence=occ,
                     incarnation=inc, shard=shard, worker=worker,
                     tenant=tenant, rules=plan["rules"])
    if not d["fire"]:
        return
    obs.registry().counter("faults_injected", site=site).inc()
    obs.emit("fault_injected", site=site, occurrence=occ,
             fault=d["fault"], rule=d["rule"],
             path=path, inputs=d["inputs"],
             input_digest=d["input_digest"])
    _apply(d, site, occ, path)


def _apply(d: dict, site: str, occ: int, path: Optional[str]) -> None:
    fault = d["fault"]
    if fault == "latency":
        time.sleep(d.get("latency_s", 0.01))
        return
    if fault == "error":
        code = d.get("error", "UNAVAILABLE")
        if code == "FORMAT":
            raise InjectedFormatError(
                f"injected malformed input at site {site!r} "
                f"occurrence {occ}")
        if code == "ENOSPC":
            raise InjectedDiskFull(site, occ)
        raise InjectedDeviceError(code, site, occ)
    if fault == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        return                                      # pragma: no cover
    # truncate / corrupt: tear the in-flight file, then 'die'
    if path is not None:
        try:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                if fault == "truncate":
                    f.truncate(int(size * d.get("frac", 0.5)))
                else:
                    lo = int(size * d.get("frac", 0.5) / 2)
                    n = max(1, min(64, size - lo))
                    f.seek(lo)
                    f.write(b"\xff" * n)
        except OSError:
            pass        # a missing/unwritable target still 'crashes'
    err = InjectedTornWrite(
        f"DATA_LOSS: injected {fault} at site {site!r} occurrence {occ}"
        + (f" ({path})" if path else ""))
    err.fault = fault
    raise err
