"""``adam_tpu.resilience`` — deterministic fault injection + scoped
retry/degradation for every dispatch site.

The reference inherits all failure recovery from Spark lineage
re-execution (SURVEY §5); the TPU rebuild replaced lineage with
job-level elastic restart (parallel/elastic.py) and pass-level
checkpoints (checkpoint.py).  Between those coarse mechanisms this
package adds the per-chunk layer:

* :mod:`.faults` — a deterministic fault-injection plane: named sites
  (``device_dispatch``, ``device_put``, ``spill_write``,
  ``checkpoint_write``, ``feeder_load``, ``worker_proc``,
  ``input_record``) registered at the existing choke points, driven by a
  seeded, replayable fault plan (``-fault_plan PATH`` /
  ``ADAM_TPU_FAULT_PLAN``).  With no plan installed the plane is
  zero-overhead: no counting, no events, no behavior change.
* :mod:`.retry` — the scoped retry/degradation policy engine wrapping
  per-chunk and per-bin device dispatch: bounded retries with
  exponential backoff + deterministic jitter for transient device
  errors, ``RESOURCE_EXHAUSTED`` → split along the existing ladder
  rungs, persistent device loss → per-chunk graceful CPU fallback
  (flagged ``degraded``), all decided by a PURE function whose inputs
  every event records (the ``decide_plan`` convention —
  tools/check_resilience.py replays them offline).

docs/RESILIENCE.md documents the plan format, the policy, and the
pinned chaos matrix (tests/test_resilience.py).
"""

from __future__ import annotations

from .faults import (FAULT_PLAN_ENV, INCARNATION_ENV, SHARD_ENV,  # noqa: F401
                     SITES, InjectedDeviceError, InjectedFault,
                     InjectedFormatError, InjectedTornWrite, active,
                     clear_plan, decide_fault, fire, install_from_env,
                     install_plan, reset_counters)
from .retry import (RETRY_BACKOFF_ENV, RETRY_BUDGET_ENV,  # noqa: F401
                    RETRY_FALLBACK_ENV, RETRY_SEED_ENV, RETRY_SPLIT_ENV,
                    FleetPolicy, RetryPolicy, backoff_delay,
                    classify_error, decide_retry, dispatch_with_retry,
                    resolve_fleet_policy, resolve_retry_policy)
