"""Sequence and record-group dictionaries.

Re-designs ``models/SequenceDictionary.scala:31-490`` and
``models/RecordGroupDictionary.scala:23-44`` from the reference: a bijective
id <-> contig-name map with compatibility checking and id-reconciliation
(``mapTo``/``remap`` with ``nonoverlappingHash``) used when unioning files
whose headers assign different ids to the same contig.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional


@dataclass(frozen=True)
class SequenceRecord:
    """One contig: mirrors SequenceRecord (SequenceDictionary.scala:380-430)."""
    id: int
    name: str
    length: int
    url: Optional[str] = None

    def compatible(self, other: "SequenceRecord") -> bool:
        # same name+length ⇒ same contig, even if ids differ
        return self.name == other.name and self.length == other.length


class SequenceDictionary:
    """Bijective id<->name contig map (SequenceDictionary.scala:31-275)."""

    def __init__(self, records: Iterable[SequenceRecord] = ()):
        self._by_id: Dict[int, SequenceRecord] = {}
        self._by_name: Dict[str, SequenceRecord] = {}
        for rec in records:
            self.add(rec)

    def add(self, rec: SequenceRecord) -> None:
        existing = self._by_id.get(rec.id)
        if existing is not None and not existing.compatible(rec):
            raise ValueError(
                f"incompatible records share id {rec.id}: {existing} vs {rec}")
        existing_name = self._by_name.get(rec.name)
        if existing_name is not None and existing_name.id != rec.id:
            raise ValueError(
                f"contig {rec.name!r} appears with ids "
                f"{existing_name.id} and {rec.id}")
        self._by_id[rec.id] = rec
        self._by_name[rec.name] = rec

    # -- lookups ---------------------------------------------------------
    def __contains__(self, key) -> bool:
        return key in self._by_id or key in self._by_name

    def __getitem__(self, key) -> SequenceRecord:
        if isinstance(key, str):
            return self._by_name[key]
        return self._by_id[key]

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __len__(self) -> int:
        return len(self._by_id)

    def __iter__(self):
        return iter(sorted(self._by_id.values(), key=lambda r: r.id))

    def records(self):
        return list(self)

    def __eq__(self, other) -> bool:
        return isinstance(other, SequenceDictionary) and \
            self._by_id == other._by_id

    def __repr__(self) -> str:
        return f"SequenceDictionary({self.records()})"

    # -- set algebra (SequenceDictionary.scala:120-220) ------------------
    def is_compatible_with(self, other: "SequenceDictionary") -> bool:
        """True when no contig name maps to conflicting (length) records."""
        for name, rec in self._by_name.items():
            o = other._by_name.get(name)
            if o is not None and not rec.compatible(o):
                return False
        return True

    def __add__(self, other: "SequenceDictionary") -> "SequenceDictionary":
        merged = SequenceDictionary(self.records())
        for rec in other:
            if rec.name in merged._by_name:
                if not merged._by_name[rec.name].compatible(rec):
                    raise ValueError(f"incompatible contig {rec.name}")
            else:
                merged.add(rec)
        return merged

    def nonoverlapping_hash(self, name: str) -> int:
        """Deterministic fresh id for ``name`` probing past ids in use here
        (SequenceDictionary.nonoverlappingHash :246-247 — crc32 instead of
        Java hashCode: deterministic across processes, unlike Python's
        salted hash; the probe-increment semantics match)."""
        import zlib
        h = zlib.crc32(name.encode()) % (1 << 30)
        while h in self._by_id:
            h += 1
        return h

    def map_to(self, target: "SequenceDictionary") -> Dict[int, int]:
        """id-remap table taking this dictionary's ids onto ``target``'s.

        Mirrors SequenceDictionary.mapTo (SequenceDictionary.scala:122-160),
        all five cases of its test suite ("all five cases for toMap"):
        contigs present in ``target`` by name take target's id; contigs
        absent keep their own id when it is free in the accumulated
        assignment, else take ``target.nonoverlapping_hash`` (probed further
        past ids this map has already handed out).
        """
        assigned = set(target._by_id)
        remap: Dict[int, int] = {}
        for rec in self:
            t = target._by_name.get(rec.name)
            if t is not None:
                remap[rec.id] = t.id
            elif rec.id not in assigned:
                remap[rec.id] = rec.id
                assigned.add(rec.id)
            else:
                h = target.nonoverlapping_hash(rec.name)
                while h in assigned:
                    h += 1
                remap[rec.id] = h
                assigned.add(h)
        return remap

    def remap(self, id_map: Dict[int, int]) -> "SequenceDictionary":
        return SequenceDictionary(
            SequenceRecord(id_map.get(r.id, r.id), r.name, r.length, r.url)
            for r in self)

    # -- SAM header conversion ------------------------------------------
    @classmethod
    def from_sam_header_lines(cls, lines: Iterable[str]) -> "SequenceDictionary":
        """Build from @SQ header lines (SequenceDictionary.scala:232-275)."""
        recs = []
        idx = 0
        for line in lines:
            if not line.startswith("@SQ"):
                continue
            fields = dict(f.split(":", 1) for f in line.rstrip("\n").split("\t")[1:]
                          if ":" in f)
            recs.append(SequenceRecord(idx, fields["SN"], int(fields.get("LN", 0)),
                                       fields.get("UR")))
            idx += 1
        return cls(recs)

    def to_sam_header_lines(self):
        out = []
        for rec in self:
            line = f"@SQ\tSN:{rec.name}\tLN:{rec.length}"
            if rec.url:
                line += f"\tUR:{rec.url}"
            out.append(line)
        return out


@dataclass
class RecordGroup:
    """One @RG header line's metadata (denormalized into reads on convert)."""
    id: str
    index: int
    sequencing_center: Optional[str] = None
    description: Optional[str] = None
    run_date_epoch: Optional[int] = None
    flow_order: Optional[str] = None
    key_sequence: Optional[str] = None
    library: Optional[str] = None
    predicted_median_insert_size: Optional[int] = None
    platform: Optional[str] = None
    platform_unit: Optional[str] = None
    sample: Optional[str] = None


class RecordGroupDictionary:
    """name -> dense index map (RecordGroupDictionary.scala:23-44)."""

    def __init__(self, groups: Iterable[RecordGroup] = ()):
        self._by_name: Dict[str, RecordGroup] = {}
        for g in groups:
            self.add(g)

    def add(self, group: RecordGroup) -> None:
        self._by_name[group.id] = group

    @classmethod
    def from_sam_header_lines(cls, lines: Iterable[str]) -> "RecordGroupDictionary":
        groups = []
        for line in lines:
            if not line.startswith("@RG"):
                continue
            fields = dict(f.split(":", 1) for f in line.rstrip("\n").split("\t")[1:]
                          if ":" in f)
            g = RecordGroup(
                id=fields.get("ID", str(len(groups))), index=len(groups),
                sequencing_center=fields.get("CN"), description=fields.get("DS"),
                flow_order=fields.get("FO"), key_sequence=fields.get("KS"),
                library=fields.get("LB"), platform=fields.get("PL"),
                platform_unit=fields.get("PU"), sample=fields.get("SM"),
                predicted_median_insert_size=(int(fields["PI"]) if "PI" in fields else None),
            )
            groups.append(g)
        return cls(groups)

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __getitem__(self, name: str) -> RecordGroup:
        return self._by_name[name]

    def get(self, name: str, default=None):
        return self._by_name.get(name, default)

    def __len__(self) -> int:
        return len(self._by_name)

    def __iter__(self):
        return iter(sorted(self._by_name.values(), key=lambda g: g.index))
