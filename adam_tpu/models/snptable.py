"""dbSNP site mask table (models/SnpTable.scala:12-63).

The reference keeps contig -> Set[position] hash sets, broadcast to executors,
probed per base.  Here each contig's positions are a sorted int64 array and
masking a whole [N, L] tile of base positions is one vectorized searchsorted —
the form a TPU/host split wants (the table stays host-side; the resulting
mask ships to the device with the batch).
"""

from __future__ import annotations

from typing import Dict, Iterable

import numpy as np


class SnpTable:
    def __init__(self, table: Dict[str, np.ndarray] | None = None):
        self._by_contig: Dict[str, np.ndarray] = {
            k: np.unique(np.asarray(v, np.int64))
            for k, v in (table or {}).items()}

    @classmethod
    def from_vcf_lines(cls, lines: Iterable[str]) -> "SnpTable":
        """Parse a sites-only VCF: (contig, 1-based pos) per line
        (SnpTable.scala:31-46). Positions are stored 0-based like every other
        coordinate in this framework; the reference keeps the VCF's 1-based
        values and compares them against 0-based read walk positions — an
        off-by-one we do not reproduce."""
        table: Dict[str, list] = {}
        for line in lines:
            if line.startswith("#") or not line.strip():
                continue
            split = line.split("\t")
            table.setdefault(split[0], []).append(int(split[1]) - 1)
        return cls({k: np.asarray(v, np.int64) for k, v in table.items()})

    @classmethod
    def from_vcf(cls, path: str) -> "SnpTable":
        """Sites file -> table.  dbSNP-scale inputs (tens of millions of
        lines) go through pyarrow's native CSV reader — decompression and
        parsing stream, only the ## header block is scanned in Python, and
        only the CHROM/POS columns materialize.  Falls back to the line
        parser on malformed layouts (ragged rows etc.), loudly."""
        import pyarrow as pa
        try:
            return cls._from_vcf_arrow(path)
        except (pa.ArrowInvalid, ValueError) as e:
            import warnings
            warnings.warn(
                f"SnpTable fast path failed for {path!r} ({e}); falling "
                "back to the per-line parser", stacklevel=2)
            with cls._open_text_stream(path) as f:
                return cls.from_vcf_lines(f)

    _HEADER_PROBE_BYTES = 1 << 24

    @staticmethod
    def _open_text_stream(path: str):
        with open(path, "rb") as probe:
            magic = probe.read(2)
        if magic == b"\x1f\x8b":
            import gzip
            return gzip.open(path, "rt")
        return open(path, "rt")

    @staticmethod
    def _open_byte_stream(path: str):
        with open(path, "rb") as probe:
            magic = probe.read(2)
        if magic == b"\x1f\x8b":
            import gzip  # handles multi-member streams, i.e. BGZF too
            return gzip.open(path, "rb")
        return open(path, "rb")

    @classmethod
    def _from_vcf_arrow(cls, path: str) -> "SnpTable":
        import numpy as np
        import pyarrow as pa
        import pyarrow.csv as pacsv

        # count leading '#' header lines from a bounded probe of the head —
        # the body itself is never materialized as Python bytes
        with cls._open_byte_stream(path) as f:
            head = f.read(cls._HEADER_PROBE_BYTES)
        n_header, off = 0, 0
        while off < len(head) and head[off:off + 1] == b"#":
            nl = head.find(b"\n", off)
            if nl < 0:
                if len(head) == cls._HEADER_PROBE_BYTES:
                    raise ValueError("header larger than the probe window")
                return cls({})
            n_header += 1
            off = nl + 1
        if off >= len(head) and len(head) < cls._HEADER_PROBE_BYTES:
            return cls({})

        # incremental reader: record batches stream through a persistent
        # contig mapping, so the transient footprint is one batch plus the
        # final int64 columns — read_csv held the whole string column
        # (measured ~960 MB peak on a 10M-line file; this path ~halves it,
        # and dbSNP is 15x that size)
        mapping: dict = {}
        code_parts: list = []
        pos_parts: list = []
        with cls._open_byte_stream(path) as f:
            reader = pacsv.open_csv(
                f,
                read_options=pacsv.ReadOptions(
                    skip_rows=n_header, autogenerate_column_names=True),
                # VCF is not quoted CSV: a field starting with '"' must not
                # swallow following lines (silent site loss, not an error)
                parse_options=pacsv.ParseOptions(delimiter="\t",
                                                 quote_char=False),
                convert_options=pacsv.ConvertOptions(
                    include_columns=["f0", "f1"],
                    column_types={"f0": pa.string(), "f1": pa.int64()}))
            for batch in reader:
                chrom = batch.column(0).dictionary_encode()
                vals = chrom.dictionary.to_pylist()
                remap = np.array(
                    [-1 if v is None else mapping.setdefault(v,
                                                             len(mapping))
                     for v in vals] or [0], np.int64)
                bidx = chrom.indices.to_numpy(zero_copy_only=False)
                pos = batch.column(1).to_numpy(zero_copy_only=False)
                # drop rows with null CHROM *or* null POS — a null POS
                # surfaces as NaN and would otherwise cast to a garbage
                # int64 sentinel site
                keep = None
                if chrom.indices.null_count:
                    keep = ~np.isnan(bidx)
                if batch.column(1).null_count:
                    pos_ok = ~np.isnan(pos)
                    keep = pos_ok if keep is None else keep & pos_ok
                if keep is not None:
                    bidx, pos = bidx[keep], pos[keep]
                code_parts.append(
                    remap[np.maximum(bidx.astype(np.int64), 0)])
                pos_parts.append(pos.astype(np.int64) - 1)
        if not code_parts:
            return cls({})
        codes = np.concatenate(code_parts)
        pos = np.concatenate(pos_parts)
        contigs = list(mapping)
        # one stable argsort + boundary split: a per-contig boolean scan is
        # O(contigs x sites) and dbSNP carries thousands of accessions
        order = np.argsort(codes, kind="stable")
        sp = pos[order]
        bounds = np.searchsorted(codes[order], np.arange(len(contigs) + 1))
        return cls({contig: sp[bounds[ci]:bounds[ci + 1]]
                    for ci, contig in enumerate(contigs)})

    def __len__(self) -> int:
        return sum(len(v) for v in self._by_contig.values())

    def contigs(self):
        return list(self._by_contig)

    def sites(self, contig: str) -> np.ndarray | None:
        """Sorted 0-based site positions for ``contig`` (None if absent)."""
        return self._by_contig.get(contig)

    def mask(self, contig: str, positions: np.ndarray) -> np.ndarray:
        """bool mask of positions present in the table for ``contig``."""
        sites = self._by_contig.get(contig)
        if sites is None or len(sites) == 0:
            return np.zeros(positions.shape, bool)
        idx = np.searchsorted(sites, positions)
        idx = np.minimum(idx, len(sites) - 1)
        return sites[idx] == positions
