"""dbSNP site mask table (models/SnpTable.scala:12-63).

The reference keeps contig -> Set[position] hash sets, broadcast to executors,
probed per base.  Here each contig's positions are a sorted int64 array and
masking a whole [N, L] tile of base positions is one vectorized searchsorted —
the form a TPU/host split wants (the table stays host-side; the resulting
mask ships to the device with the batch).
"""

from __future__ import annotations

from typing import Dict, Iterable

import numpy as np


class SnpTable:
    def __init__(self, table: Dict[str, np.ndarray] | None = None):
        self._by_contig: Dict[str, np.ndarray] = {
            k: np.unique(np.asarray(v, np.int64))
            for k, v in (table or {}).items()}

    @classmethod
    def from_vcf_lines(cls, lines: Iterable[str]) -> "SnpTable":
        """Parse a sites-only VCF: (contig, 1-based pos) per line
        (SnpTable.scala:31-46). Positions are stored 0-based like every other
        coordinate in this framework; the reference keeps the VCF's 1-based
        values and compares them against 0-based read walk positions — an
        off-by-one we do not reproduce."""
        table: Dict[str, list] = {}
        for line in lines:
            if line.startswith("#") or not line.strip():
                continue
            split = line.split("\t")
            table.setdefault(split[0], []).append(int(split[1]) - 1)
        return cls({k: np.asarray(v, np.int64) for k, v in table.items()})

    @classmethod
    def from_vcf(cls, path: str) -> "SnpTable":
        with open(path, "rt") as f:
            return cls.from_vcf_lines(f)

    def __len__(self) -> int:
        return sum(len(v) for v in self._by_contig.values())

    def contigs(self):
        return list(self._by_contig)

    def mask(self, contig: str, positions: np.ndarray) -> np.ndarray:
        """bool mask of positions present in the table for ``contig``."""
        sites = self._by_contig.get(contig)
        if sites is None or len(sites) == 0:
            return np.zeros(positions.shape, bool)
        idx = np.searchsorted(sites, positions)
        idx = np.minimum(idx, len(sites) - 1)
        return sites[idx] == positions
