"""dbSNP site mask table (models/SnpTable.scala:12-63).

The reference keeps contig -> Set[position] hash sets, broadcast to executors,
probed per base.  Here each contig's positions are a sorted int64 array and
masking a whole [N, L] tile of base positions is one vectorized searchsorted —
the form a TPU/host split wants (the table stays host-side; the resulting
mask ships to the device with the batch).
"""

from __future__ import annotations

from typing import Dict, Iterable

import numpy as np


class SnpTable:
    def __init__(self, table: Dict[str, np.ndarray] | None = None):
        self._by_contig: Dict[str, np.ndarray] = {
            k: np.unique(np.asarray(v, np.int64))
            for k, v in (table or {}).items()}

    @classmethod
    def from_vcf_lines(cls, lines: Iterable[str]) -> "SnpTable":
        """Parse a sites-only VCF: (contig, 1-based pos) per line
        (SnpTable.scala:31-46). Positions are stored 0-based like every other
        coordinate in this framework; the reference keeps the VCF's 1-based
        values and compares them against 0-based read walk positions — an
        off-by-one we do not reproduce."""
        table: Dict[str, list] = {}
        for line in lines:
            if line.startswith("#") or not line.strip():
                continue
            split = line.split("\t")
            table.setdefault(split[0], []).append(int(split[1]) - 1)
        return cls({k: np.asarray(v, np.int64) for k, v in table.items()})

    @classmethod
    def from_vcf(cls, path: str) -> "SnpTable":
        """Sites file -> table.  dbSNP-scale inputs (tens of millions of
        lines) go through pyarrow's native CSV reader — only the ## header
        block is scanned in Python; gzip/BGZF transparently decompress.
        Falls back to the line parser on any malformed/unusual layout."""
        with open(path, "rb") as f:
            data = f.read()
        if data[:2] == b"\x1f\x8b":
            import gzip
            data = gzip.decompress(data)
        try:
            return cls._from_vcf_bytes(data)
        except Exception:
            return cls.from_vcf_lines(data.decode().splitlines())

    @classmethod
    def _from_vcf_bytes(cls, data: bytes) -> "SnpTable":
        import pyarrow as pa
        import pyarrow.csv as pacsv

        off = 0
        while off < len(data) and data[off:off + 1] == b"#":
            nl = data.find(b"\n", off)
            if nl < 0:
                return cls({})
            off = nl + 1
        if off >= len(data):
            return cls({})
        tbl = pacsv.read_csv(
            # py_buffer slice: zero-copy view past the header (a bytes
            # slice would duplicate a dbSNP-scale body)
            pa.BufferReader(pa.py_buffer(data).slice(off)),
            read_options=pacsv.ReadOptions(autogenerate_column_names=True),
            # VCF is not quoted CSV: a field starting with '"' must not
            # swallow following lines (silent site loss, not an error)
            parse_options=pacsv.ParseOptions(delimiter="\t",
                                             quote_char=False),
            convert_options=pacsv.ConvertOptions(
                include_columns=["f0", "f1"],
                column_types={"f0": pa.string(), "f1": pa.int64()}))
        chrom = tbl.column("f0").combine_chunks().dictionary_encode()
        codes = chrom.indices.to_numpy(zero_copy_only=False)
        pos = tbl.column("f1").to_numpy(zero_copy_only=False) - 1
        contigs = chrom.dictionary.to_pylist()
        return cls({contig: pos[codes == ci]
                    for ci, contig in enumerate(contigs)})

    def __len__(self) -> int:
        return sum(len(v) for v in self._by_contig.values())

    def contigs(self):
        return list(self._by_contig)

    def mask(self, contig: str, positions: np.ndarray) -> np.ndarray:
        """bool mask of positions present in the table for ``contig``."""
        sites = self._by_contig.get(contig)
        if sites is None or len(sites) == 0:
            return np.zeros(positions.shape, bool)
        idx = np.searchsorted(sites, positions)
        idx = np.minimum(idx, len(sites) - 1)
        return sites[idx] == positions
