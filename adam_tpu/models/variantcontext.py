"""Variant contexts: site-keyed merge of variants, genotypes and domains.

Re-designs ``models/ADAMVariantContext.scala:24-138``: the reference builds
per-site contexts with three shuffles (keyBy position -> groupByKey x2 ->
join).  Here the columnar path keeps the three tables AS tables (joins and
filters stay in Arrow); this module is the host-side per-site object view —
one dict-keyed pass, same row-at-a-time granularity as the reference's
context objects — plus the ``.v/.g/.vd`` dataset triple loader pairing with
the save convention (AdamRDDFunctions.scala:330-363, cli commands
vcf2adam/compute_variants).  Use it for site-wise consumers (VCF emission,
inspection), not for bulk columnar transforms.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import pyarrow as pa


@dataclass
class VariantContext:
    """All evidence at one site (ADAMVariantContext.scala:24-35): the
    position key, the variants called there (one per alt allele), the
    per-sample genotypes, and optional domain memberships."""

    ref_id: int
    position: int
    variants: List[dict] = field(default_factory=list)
    genotypes: List[dict] = field(default_factory=list)
    domains: List[dict] = field(default_factory=list)


def _key(row: dict) -> Tuple[int, int]:
    rid = row.get("referenceId")
    return (-1 if rid is None else rid, row["position"])


def merge_variants_and_genotypes(
        variants: pa.Table, genotypes: pa.Table,
        domains: Optional[pa.Table] = None) -> List[VariantContext]:
    """Site-keyed merge (mergeVariantsAndGenotypes,
    ADAMVariantContext.scala:36-84).  Genotypes at positions with no variant
    row are kept as genotype-only contexts (the reference's
    ``buildFromGenotypes`` path :86-110); domains attach where present.
    Contexts come back position-sorted.

    Deliberate superset of the reference: variant-only sites (no genotypes)
    are ALSO kept, which neither reference path produces — the reference's
    inner join drops sites a sites-only VCF legitimately carries, and
    downstream consumers (adam2vcf) need them.  Filter on
    ``ctx.genotypes`` to recover the reference's exact join.
    """
    by_site: Dict[Tuple[int, int], VariantContext] = {}

    def ctx(row: dict) -> VariantContext:
        k = _key(row)
        if k not in by_site:
            by_site[k] = VariantContext(k[0], k[1])
        return by_site[k]

    for row in variants.to_pylist():
        ctx(row).variants.append(row)
    for row in genotypes.to_pylist():
        ctx(row).genotypes.append(row)
    if domains is not None:
        for row in domains.to_pylist():
            k = _key(row)
            if k in by_site:           # domains only annotate known sites
                by_site[k].domains.append(row)
    return [by_site[k] for k in sorted(by_site)]


def load_variant_contexts(basename: str) -> List[VariantContext]:
    """Load the ``.v/.g/.vd`` dataset triple written by vcf2adam /
    compute_variants and merge into contexts; a missing ``.vd`` (older
    outputs) degrades to no domain annotations."""
    import os

    from ..io.parquet import load_table

    variants = load_table(basename + ".v")
    genotypes = load_table(basename + ".g")
    domains = None
    vd = basename + ".vd"
    if os.path.exists(vd):
        domains = load_table(vd)
    return merge_variants_and_genotypes(variants, genotypes, domains)
