"""Reference-coordinate primitives: positions, oriented positions, regions.

Mirrors the semantics of models/ReferencePosition.scala:25-207 and
models/ReferenceRegion.scala:25-177 — 0-based coordinates, [start, end)
half-open regions, UNMAPPED sentinel, and the interval algebra (overlap,
containment, distance, adjacency, hull, merge).  Alongside the scalar API is
a vectorized form (`merge_intervals`) used wherever the reference fell back
to driver-side tail recursion over sorted targets
(RealignmentTargetFinder.scala:54-71).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

UNMAPPED_REFID = -1


@dataclass(frozen=True, order=True)
class ReferencePosition:
    """A point on the reference.  Ordering is (refId, pos)."""
    ref_id: int
    pos: int

    @classmethod
    def unmapped(cls) -> "ReferencePosition":
        return cls(UNMAPPED_REFID, -1)

    @property
    def is_mapped(self) -> bool:
        return self.ref_id != UNMAPPED_REFID


@dataclass(frozen=True, order=True)
class OrientedPosition:
    """Position + strand; orders by position then strand
    (ReferencePositionWithOrientation ReferencePosition.scala:25-56)."""
    position: ReferencePosition
    negative_strand: bool


@dataclass(frozen=True, order=True)
class ReferenceRegion:
    """[start, end) half-open region; ordering is (refId, start, end)."""
    ref_id: int
    start: int
    end: int

    def __post_init__(self):
        if self.start < 0 or self.end < self.start:
            raise ValueError(f"bad region [{self.start}, {self.end})")

    @property
    def width(self) -> int:
        return self.end - self.start

    def overlaps(self, other: "ReferenceRegion") -> bool:
        return (self.ref_id == other.ref_id and self.end > other.start
                and self.start < other.end)

    def contains_point(self, p: ReferencePosition) -> bool:
        return (self.ref_id == p.ref_id and self.start <= p.pos
                and self.end > p.pos)

    def contains(self, other: "ReferenceRegion") -> bool:
        return (self.ref_id == other.ref_id and self.start <= other.start
                and self.end >= other.end)

    def distance_to_point(self, p: ReferencePosition) -> Optional[int]:
        """0 if inside; >=1 outside; None across references."""
        if self.ref_id != p.ref_id:
            return None
        if p.pos < self.start:
            return self.start - p.pos
        if p.pos >= self.end:
            return p.pos - self.end + 1
        return 0

    def distance(self, other: "ReferenceRegion") -> Optional[int]:
        """0 when overlapping, 1 when abutting, else gap+1; None across refs."""
        if self.ref_id != other.ref_id:
            return None
        if self.overlaps(other):
            return 0
        if other.start >= self.end:
            return other.start - self.end + 1
        return self.start - other.end + 1

    def is_adjacent(self, other: "ReferenceRegion") -> bool:
        return self.distance(other) == 1

    def hull(self, other: "ReferenceRegion") -> "ReferenceRegion":
        if self.ref_id != other.ref_id:
            raise ValueError("hull across references")
        return ReferenceRegion(self.ref_id, min(self.start, other.start),
                               max(self.end, other.end))

    def merge(self, other: "ReferenceRegion") -> "ReferenceRegion":
        if not (self.overlaps(other) or self.is_adjacent(other)):
            raise ValueError("merge requires overlap or adjacency")
        return self.hull(other)


def region_of_read(ref_id: int, start: int, end: int,
                   mapped: bool) -> Optional[ReferenceRegion]:
    """Read alignment span as a region; the reference builds the *inclusive*
    end then +1 into half-open (ReferenceRegion.scala:34-40), so `end` here
    is the usual exclusive alignment end."""
    if not mapped:
        return None
    return ReferenceRegion(ref_id, start, end)


def merge_intervals(ref_ids: np.ndarray, starts: np.ndarray,
                    ends: np.ndarray, *, adjacency: bool = False
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Merge overlapping (optionally also abutting) intervals, vectorized.

    Replaces the reference's collect-to-driver + tail-recursive joinTargets
    fold: sort by (ref, start), then a cummax-based run segmentation — a new
    run starts wherever an interval's start exceeds the running max end of
    everything before it.  O(n log n) in numpy, no Python loop.
    Returns merged (ref_ids, starts, ends) in sorted order.
    """
    n = len(starts)
    if n == 0:
        return (np.empty(0, ref_ids.dtype), np.empty(0, starts.dtype),
                np.empty(0, ends.dtype))
    order = np.lexsort((starts, ref_ids))
    r, s, e = ref_ids[order], starts[order], ends[order]
    # lift each contig into its own disjoint coordinate band so one running
    # cummax works across the whole sorted array
    band = int(ends.max()) + 2
    off = r.astype(np.int64) * band
    s64, e64 = s.astype(np.int64) + off, e.astype(np.int64) + off
    run_max = np.maximum.accumulate(e64)
    thresh = s64 if adjacency else s64 + 1  # adjacency: end==start still merges
    new_run = np.ones(n, bool)
    new_run[1:] = thresh[1:] > run_max[:-1]
    seg = np.cumsum(new_run) - 1
    starts_out = s[new_run]
    refs_out = r[new_run]
    ends_out = np.maximum.reduceat(e, np.flatnonzero(new_run))
    return refs_out, starts_out, ends_out
