"""Pipeline-concordance comparison engine.

Re-designs ``rdd/comparisons/ComparisonTraversalEngine.scala:40-90``, the
``metrics/`` package (BucketComparisons + the five default comparisons,
AvailableComparisons.scala:25-177; Histogram aggregator,
util/Histogram.scala:22-98) and the findreads filter grammar
(cli/FindReads.scala:59-96).

Two read datasets bucket by readName into 7-way ReadBuckets
(models/ReadBucket.scala:31-111), join on name, and each comparison emits
values per joined pair which aggregate into histograms.  The reference runs
two shuffles and an RDD join; here bucketing is a vectorized arrow/numpy
group-by and the join is a dict merge.
"""

from __future__ import annotations

import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from .. import schema as S
from ..packing import column_int64


@dataclass
class ReadBucket:
    """7-way split of one read name's records (ReadBucket.scala:31-47)."""
    unpaired_primary: List[dict] = field(default_factory=list)
    paired_first_primary: List[dict] = field(default_factory=list)
    paired_second_primary: List[dict] = field(default_factory=list)
    unpaired_secondary: List[dict] = field(default_factory=list)
    paired_first_secondary: List[dict] = field(default_factory=list)
    paired_second_secondary: List[dict] = field(default_factory=list)
    unmapped: List[dict] = field(default_factory=list)

    #: the five slots every comparison walks (AvailableComparisons :52-56)
    COMPARED_SLOTS = ("unpaired_primary", "paired_first_primary",
                      "paired_second_primary", "paired_first_secondary",
                      "paired_second_secondary")


def bucket_reads(table: pa.Table) -> Dict[str, ReadBucket]:
    """Group reads by name into ReadBuckets (ReadBucket.scala:83-104)."""
    out: Dict[str, ReadBucket] = {}
    flags = column_int64(table, "flags", 0)
    rows = table.to_pylist()
    for row, f in zip(rows, flags):
        name = row["readName"]
        b = out.setdefault(name, ReadBucket())
        mapped = (f & S.FLAG_UNMAPPED) == 0
        primary = (f & S.FLAG_SECONDARY) == 0
        paired = (f & S.FLAG_PAIRED) != 0
        first = (f & S.FLAG_FIRST_OF_PAIR) != 0
        if not mapped:
            b.unmapped.append(row)
        elif primary:
            if not paired:
                b.unpaired_primary.append(row)
            elif first:
                b.paired_first_primary.append(row)
            else:
                b.paired_second_primary.append(row)
        else:
            if not paired:
                b.unpaired_secondary.append(row)
            elif first:
                b.paired_first_secondary.append(row)
            else:
                b.paired_second_secondary.append(row)
    return out


# ----------------------------------------------------------------------
# comparisons (AvailableComparisons.scala:25-177)
# ----------------------------------------------------------------------

class Comparison:
    name = ""
    description = ""

    def matched_by_name(self, b1: ReadBucket, b2: ReadBucket) -> list:
        raise NotImplementedError

    def _slot_pairs(self, b1, b2):
        for slot in ReadBucket.COMPARED_SLOTS:
            yield getattr(b1, slot), getattr(b2, slot)


class OverMatched(Comparison):
    name = "overmatched"
    description = "Checks that all buckets have exactly 0 or 1 records"

    def matched_by_name(self, b1, b2):
        ok = all(len(r1) == len(r2) and len(r1) <= 1
                 for r1, r2 in self._slot_pairs(b1, b2))
        return [ok]


class DupeMismatch(Comparison):
    name = "dupemismatch"
    description = "Counts the number of common reads marked as duplicates"

    def matched_by_name(self, b1, b2):
        out = []
        for r1, r2 in self._slot_pairs(b1, b2):
            if len(r1) == len(r2) == 1:
                out.append((
                    1 if (r1[0]["flags"] & S.FLAG_DUPLICATE) else 0,
                    1 if (r2[0]["flags"] & S.FLAG_DUPLICATE) else 0))
        return out


class MappedPosition(Comparison):
    name = "positions"
    description = "Counts how many reads align to the same genomic location"

    def _distance(self, r1, r2):
        if len(r1) != len(r2) or len(r1) > 1:
            return -1
        if len(r1) == 0:
            return 0
        a, b = r1[0], r2[0]
        if a["referenceId"] != b["referenceId"]:
            return -1
        return abs((a["start"] or 0) - (b["start"] or 0))

    def matched_by_name(self, b1, b2):
        return [sum(self._distance(r1, r2)
                    for r1, r2 in self._slot_pairs(b1, b2))]


class MapQualityScores(Comparison):
    name = "mapqs"
    description = "Creates scatter plot of mapping quality scores across identical reads"

    def matched_by_name(self, b1, b2):
        out = []
        for r1, r2 in self._slot_pairs(b1, b2):
            if len(r1) == len(r2) == 1:
                out.append((r1[0]["mapq"], r2[0]["mapq"]))
        return out


class BaseQualityScores(Comparison):
    name = "baseqs"
    description = "Creates scatter plots of base quality scores across identical positions in the same reads"

    def matched_by_name(self, b1, b2):
        out = []
        for r1, r2 in self._slot_pairs(b1, b2):
            if len(r1) == len(r2) == 1 and r1[0]["qual"] and r2[0]["qual"]:
                out.extend((ord(a) - 33, ord(b) - 33)
                           for a, b in zip(r1[0]["qual"], r2[0]["qual"]))
        return out


DEFAULT_COMPARISONS: Dict[str, Comparison] = {
    c.name: c for c in (OverMatched(), DupeMismatch(), MappedPosition(),
                        MapQualityScores(), BaseQualityScores())}


def find_comparison(name: str) -> Comparison:
    if name not in DEFAULT_COMPARISONS:
        raise KeyError(f"Could not find comparison {name}")
    return DEFAULT_COMPARISONS[name]


# ----------------------------------------------------------------------
# histogram aggregation (util/Histogram.scala:22-98)
# ----------------------------------------------------------------------

class Histogram:
    def __init__(self, values=()):
        self.value_to_count = Counter(values)

    def count(self) -> int:
        return sum(self.value_to_count.values())

    def count_identical(self) -> int:
        def identical(k):
            if isinstance(k, tuple):
                return k[0] == k[1]
            if isinstance(k, bool):
                return k
            if isinstance(k, int):
                return k == 0
            return False
        return sum(v for k, v in self.value_to_count.items() if identical(k))

    def __add__(self, other: "Histogram") -> "Histogram":
        h = Histogram()
        h.value_to_count = self.value_to_count + other.value_to_count
        return h

    def write(self, stream) -> None:
        stream.write("value\tcount\n")
        for value, count in self.value_to_count.items():
            stream.write(f"{value}\t{count}\n")


# ----------------------------------------------------------------------
# engine (ComparisonTraversalEngine.scala:40-90)
# ----------------------------------------------------------------------

class ComparisonTraversalEngine:
    def __init__(self, table1: pa.Table, table2: pa.Table,
                 seq_dict1=None, seq_dict2=None):
        # reconcile contig ids across inputs before joining, like the
        # reference's loadAdamFromPaths (AdamContext.scala:364-383)
        if seq_dict1 is not None and seq_dict2 is not None:
            from ..io.dispatch import remap_reference_ids
            table2 = remap_reference_ids(table2, seq_dict2.map_to(seq_dict1))
        self.named1 = bucket_reads(table1)
        self.named2 = bucket_reads(table2)
        names = set(self.named1) & set(self.named2)
        self.joined = {n: (self.named1[n], self.named2[n]) for n in names}

    def unique_to_1(self) -> int:
        return len(set(self.named1) - set(self.named2))

    def unique_to_2(self) -> int:
        return len(set(self.named2) - set(self.named1))

    def generate(self, comparison: Comparison) -> Dict[str, list]:
        return {name: comparison.matched_by_name(b1, b2)
                for name, (b1, b2) in self.joined.items()}

    def aggregate(self, comparison: Comparison) -> Histogram:
        h = Histogram()
        for values in self.generate(comparison).values():
            for v in values:
                h.value_to_count[v] += 1
        return h

    def find(self, filters: Sequence["GeneratorFilter"]) -> List[str]:
        out = []
        for name, (b1, b2) in self.joined.items():
            if all(any(f.passes(v)
                       for v in f.comparison.matched_by_name(b1, b2))
                   for f in filters):
                out.append(name)
        return sorted(out)


# ----------------------------------------------------------------------
# findreads filter grammar (cli/FindReads.scala:59-96)
# ----------------------------------------------------------------------

_FILTER_RE = re.compile(r"([^!=<>]+)(!=|=|<|>)(.*)")


@dataclass
class GeneratorFilter:
    comparison: Comparison
    op: str
    value: object

    def passes(self, v) -> bool:
        target = self.value
        if self.op == "=":
            return v == target
        if self.op == "!=":
            return v != target
        if self.op == "<":
            return v < target
        if self.op == ">":
            return v > target
        raise ValueError(self.op)


def parse_filter(filter_string: str) -> GeneratorFilter:
    m = _FILTER_RE.fullmatch(filter_string)
    if not m:
        raise ValueError(filter_string)
    comparison = find_comparison(m.group(1))
    raw = m.group(3)
    if raw.startswith("("):
        parts = raw.strip("()").split(",")
        value: object = tuple(int(p) for p in parts)
    elif raw in ("true", "false"):
        value = raw == "true"
    elif "." in raw:
        value = float(raw)
    else:
        value = int(raw)
    return GeneratorFilter(comparison, m.group(2), value)


def parse_filters(filters: str) -> List[GeneratorFilter]:
    return [parse_filter(f) for f in filters.split(";")]
