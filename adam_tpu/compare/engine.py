"""Pipeline-concordance comparison engine.

Re-designs ``rdd/comparisons/ComparisonTraversalEngine.scala:40-90``, the
``metrics/`` package (BucketComparisons + the five default comparisons,
AvailableComparisons.scala:25-177; CombinedComparisons/Collection forms,
Comparisons.scala:112-152; Histogram + Combined aggregators,
aggregators/Aggregator.scala:22-145) and the findreads filter grammar
(cli/FindReads.scala:59-96).

Two read datasets bucket by readName into 7-way ReadBuckets
(models/ReadBucket.scala:31-111), join on name, and each comparison emits
values per joined pair which aggregate into histograms.  The reference runs
two shuffles and an RDD join; here the whole traversal is columnar: one
dictionary-encode over both name columns (the hash join), per-(name, slot)
count/row-index matrices built with scatter-adds, and every metric a
batched numpy kernel over the joined ids — no per-read-pair Python.  The
original per-bucket ``matched_by_name`` path is kept as the differential
oracle (tests) and for ad-hoc single-name queries.
"""

from __future__ import annotations

import os
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from .. import schema as S
from ..packing import column_int64


@dataclass
class ReadBucket:
    """7-way split of one read name's records (ReadBucket.scala:31-47)."""
    unpaired_primary: List[dict] = field(default_factory=list)
    paired_first_primary: List[dict] = field(default_factory=list)
    paired_second_primary: List[dict] = field(default_factory=list)
    unpaired_secondary: List[dict] = field(default_factory=list)
    paired_first_secondary: List[dict] = field(default_factory=list)
    paired_second_secondary: List[dict] = field(default_factory=list)
    unmapped: List[dict] = field(default_factory=list)

    #: the five slots every comparison walks (AvailableComparisons :52-56)
    COMPARED_SLOTS = ("unpaired_primary", "paired_first_primary",
                      "paired_second_primary", "paired_first_secondary",
                      "paired_second_secondary")


def bucket_reads(table: pa.Table) -> Dict[str, ReadBucket]:
    """Group reads by name into ReadBuckets (ReadBucket.scala:83-104)."""
    out: Dict[str, ReadBucket] = {}
    flags = column_int64(table, "flags", 0)
    rows = table.to_pylist()
    for row, f in zip(rows, flags):
        name = row["readName"]
        b = out.setdefault(name, ReadBucket())
        mapped = (f & S.FLAG_UNMAPPED) == 0
        primary = (f & S.FLAG_SECONDARY) == 0
        paired = (f & S.FLAG_PAIRED) != 0
        first = (f & S.FLAG_FIRST_OF_PAIR) != 0
        if not mapped:
            b.unmapped.append(row)
        elif primary:
            if not paired:
                b.unpaired_primary.append(row)
            elif first:
                b.paired_first_primary.append(row)
            else:
                b.paired_second_primary.append(row)
        else:
            if not paired:
                b.unpaired_secondary.append(row)
            elif first:
                b.paired_first_secondary.append(row)
            else:
                b.paired_second_secondary.append(row)
    return out


# ----------------------------------------------------------------------
# comparisons (AvailableComparisons.scala:25-177)
# ----------------------------------------------------------------------

class Comparison:
    name = ""
    description = ""

    def matched_by_name(self, b1: ReadBucket, b2: ReadBucket) -> list:
        raise NotImplementedError

    def _slot_pairs(self, b1, b2):
        for slot in ReadBucket.COMPARED_SLOTS:
            yield getattr(b1, slot), getattr(b2, slot)


class OverMatched(Comparison):
    name = "overmatched"
    description = "Checks that all buckets have exactly 0 or 1 records"

    def matched_by_name(self, b1, b2):
        ok = all(len(r1) == len(r2) and len(r1) <= 1
                 for r1, r2 in self._slot_pairs(b1, b2))
        return [ok]


class DupeMismatch(Comparison):
    name = "dupemismatch"
    description = "Counts the number of common reads marked as duplicates"

    def matched_by_name(self, b1, b2):
        out = []
        for r1, r2 in self._slot_pairs(b1, b2):
            if len(r1) == len(r2) == 1:
                out.append((
                    1 if (r1[0]["flags"] & S.FLAG_DUPLICATE) else 0,
                    1 if (r2[0]["flags"] & S.FLAG_DUPLICATE) else 0))
        return out


class MappedPosition(Comparison):
    name = "positions"
    description = "Counts how many reads align to the same genomic location"

    def _distance(self, r1, r2):
        if len(r1) != len(r2) or len(r1) > 1:
            return -1
        if len(r1) == 0:
            return 0
        a, b = r1[0], r2[0]
        if a["referenceId"] != b["referenceId"]:
            return -1
        return abs((a["start"] or 0) - (b["start"] or 0))

    def matched_by_name(self, b1, b2):
        return [sum(self._distance(r1, r2)
                    for r1, r2 in self._slot_pairs(b1, b2))]


class MapQualityScores(Comparison):
    name = "mapqs"
    description = "Creates scatter plot of mapping quality scores across identical reads"

    def matched_by_name(self, b1, b2):
        out = []
        for r1, r2 in self._slot_pairs(b1, b2):
            if len(r1) == len(r2) == 1:
                out.append((r1[0]["mapq"], r2[0]["mapq"]))
        return out


class BaseQualityScores(Comparison):
    name = "baseqs"
    description = "Creates scatter plots of base quality scores across identical positions in the same reads"

    def matched_by_name(self, b1, b2):
        out = []
        for r1, r2 in self._slot_pairs(b1, b2):
            if len(r1) == len(r2) == 1 and r1[0]["qual"] and r2[0]["qual"]:
                out.extend((ord(a) - 33, ord(b) - 33)
                           for a, b in zip(r1[0]["qual"], r2[0]["qual"]))
        return out


DEFAULT_COMPARISONS: Dict[str, Comparison] = {
    c.name: c for c in (OverMatched(), DupeMismatch(), MappedPosition(),
                        MapQualityScores(), BaseQualityScores())}


# ----------------------------------------------------------------------
# columnar traversal (the CombinedComparisons/CombinedAggregator form,
# Comparisons.scala:112-152 + aggregators/Aggregator.scala:122-145)
# ----------------------------------------------------------------------

#: compared slot codes 0..4 == ReadBucket.COMPARED_SLOTS order;
#: 5 = unpaired_secondary (never compared), 6 = unmapped
_N_SLOTS = 7


@dataclass
class _MetricValues:
    """Columnar result of one comparison over the join: ``values[i]``
    belongs to joined name ``name_idx[i]``.  ``values`` is [V] for scalar
    metrics (kind 'int'/'bool') or [V, 2] for pair metrics (kind 'pair').
    ``null_as_none``: -1 entries decode as None (null mapq parity with the
    per-bucket oracle, which emits the raw dict value)."""
    name_idx: np.ndarray
    values: np.ndarray
    kind: str  # 'bool' | 'int' | 'pair'
    null_as_none: bool = False

    def _decode(self, v: int):
        return None if self.null_as_none and v == -1 else v

    def histogram(self) -> Histogram:
        h = Histogram()
        if len(self.values) == 0:
            return h
        if self.kind == "pair":
            uniq, cnt = np.unique(self.values, axis=0, return_counts=True)
            for (a, b), c in zip(uniq.tolist(), cnt.tolist()):
                h.value_to_count[(self._decode(a), self._decode(b))] = c
        else:
            uniq, cnt = np.unique(self.values, return_counts=True)
            cast = bool if self.kind == "bool" else int
            for u, c in zip(uniq.tolist(), cnt.tolist()):
                h.value_to_count[cast(u)] = c
        return h

    def to_python(self):
        if self.kind == "pair":
            return [(self._decode(a), self._decode(b))
                    for a, b in self.values.tolist()]
        if self.kind == "bool":
            return [bool(v) for v in self.values.tolist()]
        return [int(v) for v in self.values.tolist()]


class _Side:
    """Per-input columnar bucket structure: counts and single-row indices
    per (readName, slot) — the vectorized ReadBucket."""

    def __init__(self, table: pa.Table, codes: np.ndarray, n_names: int):
        n = table.num_rows
        flags = column_int64(table, "flags", 0)
        mapped = (flags & S.FLAG_UNMAPPED) == 0
        primary = (flags & S.FLAG_SECONDARY) == 0
        paired = (flags & S.FLAG_PAIRED) != 0
        first = (flags & S.FLAG_FIRST_OF_PAIR) != 0
        slot = np.full(n, 6, np.int8)                       # unmapped
        slot[mapped & primary & ~paired] = 0                # unpaired_primary
        slot[mapped & primary & paired & first] = 1
        slot[mapped & primary & paired & ~first] = 2
        slot[mapped & ~primary & ~paired] = 5               # not compared
        slot[mapped & ~primary & paired & first] = 3
        slot[mapped & ~primary & paired & ~first] = 4

        self.counts = np.zeros((n_names, _N_SLOTS), np.int32)
        np.add.at(self.counts, (codes, slot), 1)
        self.rowof = np.zeros((n_names, 5), np.int64)
        cmp_sel = slot < 5
        self.rowof[codes[cmp_sel], slot[cmp_sel]] = \
            np.flatnonzero(cmp_sel)
        self.present = self.counts.sum(axis=1) > 0

        self.flags = flags
        self.start = column_int64(table, "start", 0)
        self.refid = column_int64(table, "referenceId", -1)
        self.mapq = column_int64(table, "mapq", -1)   # -1 == null
        qual = table.column("qual").combine_chunks()
        self.qual_valid = np.asarray(qual.is_valid()) if len(qual) \
            else np.zeros(0, bool)
        bufs = qual.buffers()
        self.qual_offsets = np.frombuffer(
            bufs[1], np.int32, count=n + 1, offset=qual.offset * 4) \
            if n else np.zeros(1, np.int32)
        self.qual_data = np.frombuffer(bufs[2], np.uint8) \
            if len(bufs) > 2 and bufs[2] is not None else np.zeros(0, np.uint8)


@dataclass
class _JoinContext:
    """Shared state of one columnar traversal: both sides + joined ids."""
    s1: _Side
    s2: _Side
    joined: np.ndarray          # [m] name ids present on both sides
    names: pa.Array             # dictionary: name id -> readName
    n_names: int

    def singles(self):
        """[m, 5] mask of slots where both sides hold exactly one record,
        plus the row indices into each table."""
        c1 = self.s1.counts[self.joined][:, :5]
        c2 = self.s2.counts[self.joined][:, :5]
        single = (c1 == 1) & (c2 == 1)
        return c1, c2, single


def _columnar_overmatched(ctx: _JoinContext) -> _MetricValues:
    c1, c2, _ = ctx.singles()
    ok = ((c1 == c2) & (c1 <= 1)).all(axis=1)
    return _MetricValues(ctx.joined, ok, "bool")


def _columnar_dupemismatch(ctx: _JoinContext) -> _MetricValues:
    _, _, single = ctx.singles()
    mi, si = np.nonzero(single)
    r1 = ctx.s1.rowof[ctx.joined[mi], si]
    r2 = ctx.s2.rowof[ctx.joined[mi], si]
    pairs = np.stack([
        (ctx.s1.flags[r1] & S.FLAG_DUPLICATE) != 0,
        (ctx.s2.flags[r2] & S.FLAG_DUPLICATE) != 0], axis=1).astype(np.int64)
    return _MetricValues(ctx.joined[mi], pairs, "pair")


def _columnar_positions(ctx: _JoinContext) -> _MetricValues:
    c1, c2, single = ctx.singles()
    dist = np.full(single.shape, -1, np.int64)
    dist[(c1 == 0) & (c2 == 0)] = 0
    mi, si = np.nonzero(single)
    r1 = ctx.s1.rowof[ctx.joined[mi], si]
    r2 = ctx.s2.rowof[ctx.joined[mi], si]
    d = np.where(ctx.s1.refid[r1] != ctx.s2.refid[r2], -1,
                 np.abs(ctx.s1.start[r1] - ctx.s2.start[r2]))
    dist[mi, si] = d
    return _MetricValues(ctx.joined, dist.sum(axis=1), "int")


def _columnar_mapqs(ctx: _JoinContext) -> _MetricValues:
    _, _, single = ctx.singles()
    mi, si = np.nonzero(single)
    r1 = ctx.s1.rowof[ctx.joined[mi], si]
    r2 = ctx.s2.rowof[ctx.joined[mi], si]
    pairs = np.stack([ctx.s1.mapq[r1], ctx.s2.mapq[r2]], axis=1)
    return _MetricValues(ctx.joined[mi], pairs, "pair", null_as_none=True)


def _columnar_baseqs(ctx: _JoinContext) -> _MetricValues:
    _, _, single = ctx.singles()
    mi, si = np.nonzero(single)
    r1 = ctx.s1.rowof[ctx.joined[mi], si]
    r2 = ctx.s2.rowof[ctx.joined[mi], si]
    o1, o2 = ctx.s1.qual_offsets, ctx.s2.qual_offsets
    l1 = o1[r1 + 1] - o1[r1]
    l2 = o2[r2 + 1] - o2[r2]
    keep = ctx.s1.qual_valid[r1] & ctx.s2.qual_valid[r2] & \
        (l1 > 0) & (l2 > 0)
    mi, r1, r2 = mi[keep], r1[keep], r2[keep]
    lens = np.minimum(l1, l2)[keep].astype(np.int64)
    tot = int(lens.sum())
    if tot == 0:
        return _MetricValues(np.zeros(0, np.int64),
                             np.zeros((0, 2), np.int64), "pair")
    first = np.cumsum(lens) - lens
    within = np.arange(tot) - np.repeat(first, lens)
    i1 = np.repeat(o1[r1].astype(np.int64), lens) + within
    i2 = np.repeat(o2[r2].astype(np.int64), lens) + within
    pairs = np.stack([ctx.s1.qual_data[i1].astype(np.int64) - 33,
                      ctx.s2.qual_data[i2].astype(np.int64) - 33], axis=1)
    return _MetricValues(np.repeat(ctx.joined[mi], lens), pairs, "pair")


_COLUMNAR_KERNELS: Dict[str, Callable[[_JoinContext], _MetricValues]] = {
    "overmatched": _columnar_overmatched,
    "dupemismatch": _columnar_dupemismatch,
    "positions": _columnar_positions,
    "mapqs": _columnar_mapqs,
    "baseqs": _columnar_baseqs,
}


def find_comparison(name: str) -> Comparison:
    if name not in DEFAULT_COMPARISONS:
        raise KeyError(f"Could not find comparison {name}")
    return DEFAULT_COMPARISONS[name]


# ----------------------------------------------------------------------
# histogram aggregation (util/Histogram.scala:22-98)
# ----------------------------------------------------------------------

class Histogram:
    def __init__(self, values=()):
        self.value_to_count = Counter(values)

    def count(self) -> int:
        return sum(self.value_to_count.values())

    def count_subset(self, predicate: Callable[[object], bool]) -> int:
        """Total count of entries whose *value* satisfies ``predicate``
        (util/Histogram.scala:37 countSubset)."""
        return sum(v for k, v in self.value_to_count.items() if predicate(k))

    def count_identical(self) -> int:
        def identical(k):
            if isinstance(k, tuple):
                return k[0] == k[1]
            if isinstance(k, bool):
                return k
            if isinstance(k, int):
                return k == 0
            return False
        return self.count_subset(identical)

    def __add__(self, other: "Histogram") -> "Histogram":
        h = Histogram()
        h.value_to_count = self.value_to_count + other.value_to_count
        return h

    def write(self, stream) -> None:
        stream.write("value\tcount\n")
        for value, count in self.value_to_count.items():
            stream.write(f"{value}\t{count}\n")


# ----------------------------------------------------------------------
# engine (ComparisonTraversalEngine.scala:40-90)
# ----------------------------------------------------------------------

class ComparisonTraversalEngine:
    def __init__(self, table1: pa.Table, table2: pa.Table,
                 seq_dict1=None, seq_dict2=None):
        # reconcile contig ids across inputs before joining, like the
        # reference's loadAdamFromPaths (AdamContext.scala:364-383)
        if seq_dict1 is not None and seq_dict2 is not None:
            from ..io.dispatch import remap_reference_ids
            table2 = remap_reference_ids(table2, seq_dict2.map_to(seq_dict1))
        self._tables = (table1, table2)
        self._named: Optional[tuple] = None      # lazy oracle buckets
        n1 = table1.num_rows
        names = pa.concat_arrays([
            table1.column("readName").combine_chunks(),
            table2.column("readName").combine_chunks()]).dictionary_encode()
        codes = names.indices.to_numpy(zero_copy_only=False)
        n_names = len(names.dictionary)
        self._null_id = -1
        if names.indices.null_count:
            # null readNames bucket together (bucket_reads keyed them None)
            self._null_id = n_names
            codes = np.where(np.isnan(codes), n_names, codes)
            n_names += 1
        codes = codes.astype(np.int64)
        s1 = _Side(table1, codes[:n1], n_names)
        s2 = _Side(table2, codes[n1:], n_names)
        self._ctx = _JoinContext(
            s1, s2, np.flatnonzero(s1.present & s2.present),
            names.dictionary, n_names)

    def _name_of(self, ids: np.ndarray) -> list:
        """Name ids -> readName strings (None for the null bucket)."""
        out = []
        d = self._ctx.names
        for i in np.asarray(ids).tolist():
            out.append(None if i == self._null_id else d[i].as_py())
        return out

    @property
    def n_joined(self) -> int:
        return len(self._ctx.joined)

    @property
    def n_names_1(self) -> int:
        return int(self._ctx.s1.present.sum())

    @property
    def n_names_2(self) -> int:
        return int(self._ctx.s2.present.sum())

    def unique_to_1(self) -> int:
        return int((self._ctx.s1.present & ~self._ctx.s2.present).sum())

    def unique_to_2(self) -> int:
        return int((self._ctx.s2.present & ~self._ctx.s1.present).sum())

    def _values(self, comparison: Comparison) -> _MetricValues:
        return _COLUMNAR_KERNELS[comparison.name](self._ctx)

    def _oracle_buckets(self):
        """Lazy per-bucket structures for comparisons without a columnar
        kernel (user-defined BucketComparisons subclasses)."""
        if self._named is None:
            self._named = (bucket_reads(self._tables[0]),
                           bucket_reads(self._tables[1]))
        return self._named

    def generate(self, comparison: Comparison) -> Dict[str, list]:
        """Per-name value lists (ComparisonTraversalEngine.this.generate
        :61-65) — a view over the columnar values for API parity."""
        if comparison.name not in _COLUMNAR_KERNELS:
            named1, named2 = self._oracle_buckets()
            return {n: comparison.matched_by_name(named1[n], named2[n])
                    for n in set(named1) & set(named2)}
        mv = self._values(comparison)
        order = np.argsort(mv.name_idx, kind="stable")
        vals = _MetricValues(mv.name_idx[order], mv.values[order], mv.kind,
                             mv.null_as_none)
        ids, starts = np.unique(vals.name_idx, return_index=True)
        py = vals.to_python()
        bounds = list(starts[1:]) + [len(py)]
        name_strs = self._name_of(ids)
        out = {name: [] for name in self._name_of(self._ctx.joined)}
        for name, lo, hi in zip(name_strs, starts, bounds):
            out[name] = py[lo:hi]
        return out

    def aggregate(self, comparison: Comparison) -> Histogram:
        if comparison.name not in _COLUMNAR_KERNELS:
            h = Histogram()
            for values in self.generate(comparison).values():
                for v in values:
                    h.value_to_count[v] += 1
            return h
        return self._values(comparison).histogram()

    def aggregate_all(self, comparisons: Sequence[Comparison]
                      ) -> Dict[str, Histogram]:
        """One traversal computing every comparison's histogram — the
        CombinedComparisons + CombinedAggregator collection forms
        (Comparisons.scala:112-152, aggregators/Aggregator.scala:122-145).
        The join context is built once and shared; each metric is one
        batched kernel over it."""
        return {c.name: self.aggregate(c) for c in comparisons}

    def find(self, filters: Sequence["GeneratorFilter"]) -> List[str]:
        """Names for which every filter passes on at least one value
        (cli/FindReads.scala:59-96) — vectorized per-name any/all."""
        ctx = self._ctx
        ok_all = np.ones(ctx.n_names, bool)
        joined_mask = np.zeros(ctx.n_names, bool)
        joined_mask[ctx.joined] = True
        for f in filters:
            if f.comparison.name not in _COLUMNAR_KERNELS:
                gen = self.generate(f.comparison)
                passing = {n for n, vs in gen.items()
                           if any(f.passes(v) for v in vs)}
                for i in np.flatnonzero(ok_all & joined_mask):
                    if self._name_of([i])[0] not in passing:
                        ok_all[i] = False
                continue
            mv = self._values(f.comparison)
            passes = f.passes_array(mv.values, mv.kind)
            any_pass = np.zeros(ctx.n_names, bool)
            np.logical_or.at(any_pass, mv.name_idx, passes)
            ok_all &= any_pass                 # empty value list => fails
        ids = np.flatnonzero(ok_all & joined_mask)
        names = self._name_of(ids)
        # a null-name bucket sorts first (Python can't order None vs str)
        return sorted(names, key=lambda x: (x is not None, x))


# ----------------------------------------------------------------------
# findreads filter grammar (cli/FindReads.scala:59-96)
# ----------------------------------------------------------------------

_FILTER_RE = re.compile(r"([^!=<>]+)(!=|=|<|>)(.*)")


@dataclass
class GeneratorFilter:
    comparison: Comparison
    op: str
    value: object

    def passes(self, v) -> bool:
        target = self.value
        if self.op == "=":
            return v == target
        if self.op == "!=":
            return v != target
        if self.op == "<":
            return v < target
        if self.op == ">":
            return v > target
        raise ValueError(self.op)

    def passes_array(self, values: np.ndarray, kind: str) -> np.ndarray:
        """Vectorized ``passes`` over a metric's columnar values."""
        if kind == "pair":
            t = np.asarray(self.value, np.int64)
            if t.shape != (2,):
                raise ValueError(
                    f"filter value {self.value!r} vs pair-valued comparison")
            if self.op == "=":
                return (values == t).all(axis=1)
            if self.op == "!=":
                return (values != t).any(axis=1)
            lex_lt = (values[:, 0] < t[0]) | \
                ((values[:, 0] == t[0]) & (values[:, 1] < t[1]))
            if self.op == "<":
                return lex_lt
            if self.op == ">":
                return ~lex_lt & ~(values == t).all(axis=1)
            raise ValueError(self.op)
        target = self.value
        if self.op == "=":
            return values == target
        if self.op == "!=":
            return values != target
        if self.op == "<":
            return values < target
        if self.op == ">":
            return values > target
        raise ValueError(self.op)


def parse_filter(filter_string: str) -> GeneratorFilter:
    m = _FILTER_RE.fullmatch(filter_string)
    if not m:
        raise ValueError(filter_string)
    comparison = find_comparison(m.group(1))
    raw = m.group(3)
    if raw.startswith("("):
        parts = raw.strip("()").split(",")
        value: object = tuple(int(p) for p in parts)
    elif raw in ("true", "false"):
        value = raw == "true"
    elif "." in raw:
        value = float(raw)
    else:
        value = int(raw)
    return GeneratorFilter(comparison, m.group(2), value)


def parse_filters(filters: str) -> List[GeneratorFilter]:
    return [parse_filter(f) for f in filters.split(";")]


# ----------------------------------------------------------------------
# streaming compare (bounded memory over name-hash buckets)
# ----------------------------------------------------------------------

#: the projection one compare traversal actually consumes — the reference
#: projects 6 id fields + generator schemas (CompareAdam.scala:70-86); the
#: reference* columns ride along to rebuild the dictionaries for id
#: reconciliation on Parquet inputs
COMPARE_COLUMNS = ("readName", "flags", "start", "referenceId", "mapq",
                   "qual", "referenceName", "referenceLength",
                   "referenceUrl")


def streaming_compare(paths1, paths2, comparisons, *, n_buckets: int = 32,
                      chunk_rows: int = 1 << 20,
                      workdir: Optional[str] = None,
                      find_filters: Optional[Sequence] = None) -> dict:
    """Bounded-memory compare: both inputs spill into name-hash buckets,
    then each bucket runs the columnar traversal independently and the
    histograms/counters merge (they are monoids, like everything the
    reference aggregates).

    A read name lands in exactly one bucket on both sides, so per-bucket
    joins/uniques/histograms sum to exactly the whole-input result — the
    same invariant behind the reference's hash-partitioned join
    (ComparisonTraversalEngine.scala:40-45).  Host memory is bounded by
    the largest bucket (~input/n_buckets), not the inputs.

    Contig ids reconcile exactly like load_reads_union
    (AdamContext.loadAdamFromPaths :364-383): each file's dictionary maps
    onto its side's accumulated one and chunks are remapped as they
    spill; side 2 then maps onto side 1 at bucket-compare time.
    """
    import glob as _glob
    import shutil
    import tempfile

    from ..io.dispatch import remap_reference_ids
    from ..io.parquet import iter_tables, load_table
    from ..io.stream import open_read_stream
    from ..models.dictionary import SequenceDictionary
    from ..packing import hash_strings_128
    from ..parallel.pipeline import (_accumulate_seq_records,
                                     route_slices_to_dirs)

    if n_buckets < 1:
        raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
    own = workdir is None
    if own:
        workdir = tempfile.mkdtemp(prefix="adam_tpu_compare_")
    os.makedirs(workdir, exist_ok=True)
    for stale in _glob.glob(os.path.join(workdir, "s[01]-b*")):
        shutil.rmtree(stale, ignore_errors=True)  # a hard-killed prior
    #                                               run must not double in

    def file_dict(path):
        """The file's sequence dictionary without loading its rows: the
        header for SAM/BAM; a reference-column scan for Parquet."""
        stream = open_read_stream(path, columns=None, chunk_rows=chunk_rows)
        if stream.seq_dict is not None:
            return stream.seq_dict
        seen: dict = {}
        for t in iter_tables(path, chunk_rows=chunk_rows,
                             columns=[c for c in (
                                 "referenceId", "referenceName",
                                 "referenceLength", "referenceUrl")]):
            _accumulate_seq_records(t, seen)
        return SequenceDictionary(seen.values())

    schemas = [None, None]
    dicts = [None, None]
    try:
        for side, paths in ((0, paths1), (1, paths2)):
            acc = None
            chunk_i = 0
            bucket_dirs: dict = {}
            for file_i, path in enumerate(paths):
                # the FIRST file's dictionary accumulates during the spill
                # itself (no remap can apply to it); only later files pay
                # the dictionary pre-scan their remap requires
                id_map = {}
                first_seen: dict = {}
                if file_i > 0:
                    sd = file_dict(path)
                    id_map = sd.map_to(acc)
                    acc = acc + sd.remap(id_map)
                stream = open_read_stream(path, columns=COMPARE_COLUMNS,
                                          chunk_rows=chunk_rows)
                for table in stream:
                    if id_map:
                        table = remap_reference_ids(table, id_map)
                    if schemas[side] is None:
                        schemas[side] = table.schema
                    if file_i == 0 and stream.seq_dict is None:
                        _accumulate_seq_records(table, first_seen)
                    lo, _hi = hash_strings_128(table.column("readName"))
                    bucket = (lo % n_buckets).astype(np.int64)
                    route_slices_to_dirs(
                        table, bucket, workdir, chunk_i, bucket_dirs, {},
                        lambda b, _s=side: f"s{_s}-b{b:04d}")
                    chunk_i += 1
                if file_i == 0:
                    acc = stream.seq_dict if stream.seq_dict is not None \
                        else SequenceDictionary(first_seen.values())
            dicts[side] = acc if acc is not None else SequenceDictionary()

        id_map = dicts[1].map_to(dicts[0]) if len(dicts[0]) and \
            len(dicts[1]) else {}
        # a side that yielded zero chunks still joins: an empty table of
        # the other side's schema keeps the populated side's totals exact
        # (both are the same COMPARE_COLUMNS projection)
        for side in (0, 1):
            if schemas[side] is None:
                schemas[side] = schemas[1 - side]

        totals = dict(n_names_1=0, n_names_2=0, unique_to_1=0,
                      unique_to_2=0, n_joined=0)
        hists = {c.name: Histogram() for c in comparisons}
        matching: list = []
        if schemas[0] is None:                    # both inputs empty
            return {"totals": totals, "histograms": hists,
                    "matching_names": matching}
        for b in range(n_buckets):
            sides = []
            for side in (0, 1):
                d = os.path.join(workdir, f"s{side}-b{b:04d}")
                sides.append(load_table(d) if os.path.isdir(d)
                             else schemas[side].empty_table())
            t1, t2 = sides
            if t1.num_rows == 0 and t2.num_rows == 0:
                continue
            if id_map:
                t2 = remap_reference_ids(t2, id_map)
            engine = ComparisonTraversalEngine(t1, t2)
            totals["n_names_1"] += engine.n_names_1
            totals["n_names_2"] += engine.n_names_2
            totals["unique_to_1"] += engine.unique_to_1()
            totals["unique_to_2"] += engine.unique_to_2()
            totals["n_joined"] += engine.n_joined
            for name, h in engine.aggregate_all(comparisons).items():
                hists[name] = hists[name] + h
            if find_filters is not None:
                # a name lives in exactly one bucket, so per-bucket finds
                # concatenate without dedup (the findreads path)
                matching.extend(engine.find(find_filters))
        return {"totals": totals, "histograms": hists,
                "matching_names": matching}
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            for d in _glob.glob(os.path.join(workdir, "s[01]-b*")):
                shutil.rmtree(d, ignore_errors=True)
