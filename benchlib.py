"""The bench.py stage scheduler, extracted behind injectable dependencies
so it is testable without hardware (VERDICT r4, next-round #6).

`orchestrate` owns the decisions that previously lived inline in
bench.main(): device-attempt retry while budget lasts, skip-after-2
consecutive hangs per stage, concede-after-2 consecutive probe hangs
(dead tunnel), CPU-incidental result salvage, and the final CPU-fallback
pass for stages that never produced a device number.  bench.py supplies
the real `run_worker` (subprocess + per-stage stdout deadlines) and
`remaining` (wall budget); tests supply fakes.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable

#: stages that only make sense on a TPU backend — the CPU fallback pass
#: never runs them
TPU_ONLY_STAGES = ("pallas", "bqsr_race8")


def orchestrate(want: list[str],
                run_worker: Callable[[list[str], dict, float],
                                     tuple[dict, str | None, str | None]],
                remaining: Callable[[], float],
                cpu_reserve_s: float,
                sleep: Callable[[float], None] = time.sleep,
                tpu_only: Iterable[str] = TPU_ONLY_STAGES,
                metrics_path_for: "Callable[[str], str] | None" = None,
                trace_path_for: "Callable[[str], str] | None" = None,
                ledger=None,
                window_id: str = "",
                scale_env: "Callable[[dict], dict] | None" = None,
                cpu_order: "Callable[[list[str]], list[str]] | None" = None,
                ) -> tuple[dict, list[str]]:
    """Collect stage payloads for `want`, retrying the flaky device path
    while budget lasts, then CPU-fallback for whatever never landed.

    run_worker(stages, env_extra, deadline_s) -> (stage->payload, err,
    failed_stage) — bench._run_worker's contract.  Returns (stages,
    errors).

    ``metrics_path_for(tag)`` (tags: ``attempt<N>``, ``cpu``) names a
    per-run telemetry sidecar: the path rides to the worker via
    ``ADAM_TPU_METRICS`` (the worker writes an obs JSONL there) and is
    recorded as ``metrics_path`` in every stage payload collected from
    that run — so a BENCH_*.json entry can cite the sidecar's per-stage
    numbers instead of only end-to-end wall time.  ``trace_path_for``
    does the same for the run TIMELINE (``ADAM_TPU_TRACE`` →
    Chrome-trace JSON, obs.trace): the path is stamped as
    ``trace_path`` in each payload, and since the evidence ledger keeps
    whole payloads, an on-chip capture window leaves a loadable
    timeline behind, not just a headline number.

    ``ledger`` (an evidence.ledger.Ledger, or None) is checkpointed
    after EVERY worker run: each captured stage folds in keep-best and
    the file saves immediately, so a window that slams shut mid-attempt
    has already persisted whatever streamed.  Ledger failures never
    break the bench contract.  ``scale_env(probe_payload) -> env dict``
    (evidence.scheduler.scale_env_from_probe) re-sizes later attempts'
    problem sizes to the link rate the first successful probe measured
    — flap re-entry runs shrunken stages instead of re-stalling on
    full-size wires.  ``cpu_order(missing) -> missing`` reorders the
    final CPU pass (evidence.scheduler.order_cpu_fallback): the
    fallback completes the ARTIFACT headline-first — the window's
    information-first order is meaningless off-chip and would let the
    slow CPU race legs starve the flagstat value.
    """
    errors: list[str] = []
    stages: dict = {}
    attempt = 0
    cpu_incidental: dict = {}
    fails: dict = {}
    skip: set = set()
    link_env: dict = {}

    def tagged(got: dict, tag: str) -> dict:
        stamps = {}
        if metrics_path_for is not None:
            stamps["metrics_path"] = metrics_path_for(tag)
        if trace_path_for is not None:
            stamps["trace_path"] = trace_path_for(tag)
        if not stamps:
            return got
        return {k: ({**v, **stamps} if isinstance(v, dict) else v)
                for k, v in got.items()}

    def worker_env(tag: str) -> dict:
        env = {}
        if metrics_path_for is not None:
            env["ADAM_TPU_METRICS"] = metrics_path_for(tag)
        if trace_path_for is not None:
            env["ADAM_TPU_TRACE"] = trace_path_for(tag)
        return env

    def note_ledger(got: dict) -> None:
        if ledger is None or not got:
            return
        try:
            ledger.record_stages(got, window_id=window_id)
            ledger.save()
        except Exception:  # noqa: BLE001 — evidence write must never
            pass           # kill the one-line bench contract

    # device attempts: keep retrying the flaky tunnel while budget
    # lasts; a stage that hangs twice is skipped (not retried forever)
    # so later stages still get their shot at the device
    while remaining() > cpu_reserve_s + 60:
        attempt += 1
        missing = [s for s in want if s not in stages and s not in skip]
        if not missing:
            break
        got, err, failed = run_worker(
            missing, link_env | worker_env(f"attempt{attempt}"),
            remaining() - cpu_reserve_s)
        got = tagged(got, f"attempt{attempt}")
        if scale_env is not None and \
                got.get("probe", {}).get("platform") == "tpu":
            # only a genuine tunnel probe's link rate may (re)size the
            # wires: a silent in-worker CPU fallback measures its local
            # loopback and would wipe the slow-tunnel shrink overrides
            try:
                link_env = dict(scale_env(got["probe"]) or {})
            except Exception:  # noqa: BLE001 — sizing is best-effort
                link_env = {}
            if link_env:
                # stage reporting: the artifact (and the ledger) should
                # show HOW this window's wires were shrunk, not leave
                # readers to re-derive it from the link rate
                got["probe"] = {**got["probe"],
                                "scaled_env": dict(link_env)}
        if got.get("probe", {}).get("platform") not in (None, "tpu"):
            # a fast tunnel failure silently falls back to the CPU
            # backend INSIDE the worker; those numbers are fallback
            # material, not device results — keep retrying the tunnel
            cpu_incidental |= {k: v for k, v in got.items()
                               if k not in cpu_incidental}
            note_ledger(got)
            errors.append(
                f"attempt {attempt}: backend fell back to "
                f"{got['probe'].get('platform')}")
            sleep(min(10.0, max(0.0, remaining() - cpu_reserve_s)))
            continue
        note_ledger(got)
        stages |= {k: v for k, v in got.items() if k not in stages}
        if "probe" in got:
            # the tunnel answered: probe hangs so far were flaps,
            # not death — only CONSECUTIVE probe hangs may concede
            fails.pop("probe", None)
        if err:
            errors.append(f"attempt {attempt}: {err}")
            if failed:
                fails[failed] = fails.get(failed, 0) + 1
                if fails[failed] >= 2:
                    skip.add(failed)
            if fails.get("probe", 0) >= 2:
                # the tunnel is dead, not flaky: every further
                # attempt would burn another probe deadline the CPU
                # fallback needs (observed: the fallback's race
                # stage starved after two 150 s probe hangs)
                break
            sleep(min(10.0, max(0.0, remaining() - cpu_reserve_s)))
        else:
            break
    # CPU fallback for whatever never landed (TPU-only stages excluded);
    # incidental CPU results from failed device attempts count first
    for k, v in cpu_incidental.items():
        stages.setdefault(k, v)
    missing = [s for s in want
               if s not in tpu_only and s not in stages]
    if missing:
        if cpu_order is not None:
            missing = list(cpu_order(missing))
        # note: link_env deliberately NOT applied — sizes scaled to the
        # tunnel link rate are meaningless for an in-process CPU pass
        got, err, _failed = run_worker(
            ["probe"] + [m for m in missing if m != "probe"],
            {"JAX_PLATFORMS": "cpu"} | worker_env("cpu"),
            max(remaining() - 10, 30))
        got = tagged(got, "cpu")
        note_ledger(got)
        for k, v in got.items():
            stages.setdefault(k, v)
        if err:
            errors.append(f"cpu fallback: {err}")
    return stages, errors
