"""Concordance at scale (VERDICT r3 #5): streaming compare + findreads
over a >= 10 M-read synthetic pair, recording reads/s and peak host RSS.

The workload the reference built its ComparisonTraversalEngine for
(ComparisonTraversalEngine.scala:40-88: hash-partitioned name join over
two pipeline runs) — here the name-hash bucket spill + columnar bucket
joins of ``compare.engine.streaming_compare``.

Both sides synthesize directly as chunked Parquet datasets (bounded
memory; no BAM detour).  Side 2 perturbs ~1% of positions, ~2% of mapqs
and drops ~0.5% of reads, so every comparison has real work and
findreads returns a non-trivial set.

Usage::

    python bench_compare.py [--reads 10000000] [--out COMPARE_BENCH.json]
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import shutil
import tempfile
import time


def synth_pair(base: str, n_reads: int, chunk: int = 1 << 20,
               seed: int = 0) -> dict:
    import numpy as np
    import pyarrow as pa

    from adam_tpu import schema as S
    from adam_tpu.io.parquet import DatasetWriter

    rng = np.random.RandomState(seed)
    L = 36
    n_contigs = 24
    t0 = time.perf_counter()
    paths = [os.path.join(base, "side1"), os.path.join(base, "side2")]
    writers = [DatasetWriter(p, part_rows=chunk, compression="zstd")
               for p in paths]
    bases = np.frombuffer(b"ACGT", np.uint8)
    done = 0
    while done < n_reads:
        n = min(chunk, n_reads - done)
        names = np.char.add("r", np.arange(done, done + n).astype(str))
        refid = rng.randint(0, n_contigs, n).astype(np.int32)
        start = rng.randint(0, 10_000_000, n).astype(np.int64)
        mapq = rng.randint(0, 61, n).astype(np.int32)
        flags = np.where(rng.rand(n) < 0.5, 16, 0).astype(np.int64)
        qual_mat = (rng.randint(25, 41, (n, L)) + 33).astype(np.uint8)
        quals = qual_mat.view(f"S{L}").ravel().astype(str)

        def col_table(refid, start, mapq, keep):
            m = {
                "readName": pa.array(names[keep]),
                "referenceId": pa.array(refid[keep], pa.int32()),
                "referenceName": pa.array(
                    [f"chr{r + 1}" for r in refid[keep]]),
                "start": pa.array(start[keep], pa.int64()),
                "mapq": pa.array(mapq[keep], pa.int32()),
                "flags": pa.array(flags[keep], pa.int64()),
                "qual": pa.array(quals[keep]),
            }
            nn = int(keep.sum())
            return pa.Table.from_pydict(
                {f: m.get(f, pa.nulls(nn, S.READ_SCHEMA.field(f).type))
                 for f in S.READ_SCHEMA.names}, schema=S.READ_SCHEMA)

        all_rows = np.ones(n, bool)
        writers[0].write(col_table(refid, start, mapq, all_rows))
        start2 = np.where(rng.rand(n) < 0.01,
                          rng.randint(0, 10_000_000, n), start)
        mapq2 = np.where(rng.rand(n) < 0.02,
                         rng.randint(0, 61, n), mapq).astype(np.int32)
        keep2 = rng.rand(n) >= 0.005
        writers[1].write(col_table(refid, start2.astype(np.int64), mapq2,
                                   keep2))
        done += n
    for w in writers:
        w.close()
    return {"paths": paths, "synth_s": round(time.perf_counter() - t0, 1),
            "bytes": sum(
                os.path.getsize(os.path.join(p, f))
                for p in paths for f in os.listdir(p))}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reads", type=int, default=10_000_000)
    ap.add_argument("--buckets", type=int, default=64)
    ap.add_argument("--chunk_rows", type=int, default=1 << 20)
    ap.add_argument("--out", default="COMPARE_BENCH.json")
    args = ap.parse_args()

    from adam_tpu.platform import honor_platform_env
    honor_platform_env()

    from adam_tpu.compare.engine import (find_comparison, parse_filters,
                                         streaming_compare)

    base = tempfile.mkdtemp(prefix="adam_compare_bench_")
    doc = {"n_reads_per_side": args.reads, "n_buckets": args.buckets,
           "chunk_rows": args.chunk_rows}
    try:
        st = synth_pair(base, args.reads, chunk=args.chunk_rows)
        doc["synth_s"] = st["synth_s"]
        doc["input_bytes"] = st["bytes"]
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

        comps = [find_comparison(n)
                 for n in ("positions", "mapqs", "dupemismatch")]
        t0 = time.perf_counter()
        r = streaming_compare(
            [st["paths"][0]], [st["paths"][1]], comps,
            n_buckets=args.buckets, chunk_rows=args.chunk_rows)
        doc["compare_wall_s"] = round(time.perf_counter() - t0, 1)
        doc.update({k: int(v) for k, v in r["totals"].items()})
        doc["positions_nonzero"] = int(
            r["histograms"]["positions"].count_subset(lambda v: v != 0))
        doc["compare_reads_per_sec"] = round(
            2 * args.reads / max(doc["compare_wall_s"], 1e-9))

        t0 = time.perf_counter()
        f = streaming_compare(
            [st["paths"][0]], [st["paths"][1]], [],
            n_buckets=args.buckets, chunk_rows=args.chunk_rows,
            find_filters=parse_filters("positions!=0"))
        doc["findreads_wall_s"] = round(time.perf_counter() - t0, 1)
        doc["findreads_hits"] = len(f["matching_names"])
        doc["findreads_reads_per_sec"] = round(
            2 * args.reads / max(doc["findreads_wall_s"], 1e-9))

        doc["peak_rss_gb"] = round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2)
        doc["rss_before_gb"] = round(rss0 / 1e6, 2)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    with open(args.out, "w") as fh:
        json.dump(doc, fh, indent=1)
    print(json.dumps(doc))


if __name__ == "__main__":
    raise SystemExit(main())
