"""Realignment throughput on a synthetic many-target chromosome.

Two measurements:

1. The single-shot batched sweep (realigner._sweep_groups) against the
   markdup stage over the same reads — VERDICT r1 #7's done-gate (realign
   within 2x of markdup on 1000 synthetic targets).
2. The pass-4 pipeline (parallel/realign_exec.py): the full multi-bin
   streamed transform with realignment run twice — serial
   (``realign_opts={'pipeline': False}``) and pipelined — with the
   pipelined run's per-unit stage breakdown (load / prep / sweep /
   finish / emit wall) pulled from the ``realign_stage_seconds``
   histograms (the serial walk is monolithic per bin — it reports its
   p4 wall only) and the frozen realign plan stamped into the artifact
   the way bench.py stamps executor plans.  The pipelined p4 wall must
   beat serial by >= 1.3x on the CPU backend from I/O+prep overlap
   alone (the PR 4 acceptance gate).

Prints one JSON line per stage.  Not run by the driver (bench.py stays the
single-line contract); run manually: ``python bench_realign.py [n_targets]``.
"""

from __future__ import annotations

import io
import json
import shutil
import sys
import tempfile
import time


def _stage_breakdown() -> dict:
    """Sum of each realign pipeline stage's wall from the obs registry."""
    from adam_tpu import obs

    snap = obs.registry().snapshot()
    out = {}
    for key, h in snap.get("histograms", {}).items():
        if key.startswith("realign_stage_seconds{stage="):
            stage = key[len("realign_stage_seconds{stage="):-1]
            out[stage] = round(h["sum"], 3)
    return out


def _p4_wall() -> float:
    from adam_tpu import obs

    snap = obs.registry().snapshot()
    h = snap.get("histograms", {}).get("stage_seconds{stage=p4-bins}")
    return round(h["sum"], 3) if h else 0.0


def bench_single_shot(n_targets: int) -> None:
    from adam_tpu.io.sam import read_sam
    from adam_tpu.ops.markdup import mark_duplicates
    from adam_tpu.packing import pack_reads
    from adam_tpu.realign.realigner import realign_indels
    from tests._synth_realign import synth_sam

    text = synth_sam(n_targets, reads_per_target=20, seed=0)
    table, _, _ = read_sam(io.StringIO(text))
    n = table.num_rows
    batch = pack_reads(table)

    t0 = time.perf_counter()
    mark_duplicates(table, batch)
    t_markdup = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = realign_indels(table, batch)
    t_realign = time.perf_counter() - t0

    changed = sum(1 for a, b in zip(table.column("cigar").to_pylist(),
                                    out.column("cigar").to_pylist())
                  if a != b)
    for name, dt in (("markdup", t_markdup), ("realign", t_realign)):
        print(json.dumps({"metric": f"{name}_wall_s", "value": round(dt, 2),
                          "unit": "s", "n_reads": n,
                          "n_targets": n_targets}))
    print(json.dumps({"metric": "realign_vs_markdup", "unit": "ratio",
                      "value": round(t_realign / t_markdup, 2),
                      "reads_realigned": changed}))


def bench_pipeline(n_targets: int, n_bins: int = 8) -> None:
    from adam_tpu import obs
    from adam_tpu.instrument import report
    from adam_tpu.parallel.mesh import make_mesh
    from adam_tpu.parallel.pipeline import streaming_transform
    from adam_tpu.parallel.realign_exec import (decide_realign_plan,
                                                resolve_realign_opts)
    from adam_tpu.platform import is_tpu_backend
    from tests._synth_realign import synth_sam

    workroot = tempfile.mkdtemp(prefix="bench_realign_")
    try:
        src = f"{workroot}/synth.sam"
        with open(src, "w") as f:
            f.write(synth_sam(n_targets, reads_per_target=12, seed=0,
                              tail_reads=4))

        # warm the XLA compile caches (the sweep shapes are canonical
        # rungs, so a small run compiles what the timed runs will use) —
        # otherwise whichever mode runs first eats the compiles and the
        # comparison measures compilation, not scheduling
        warm_src = f"{workroot}/warm.sam"
        with open(warm_src, "w") as f:
            f.write(synth_sam(max(n_targets // 8, 8), reads_per_target=12,
                              seed=0, tail_reads=4))
        streaming_transform(
            warm_src, f"{workroot}/out_warm", realign=True, sort=True,
            workdir=f"{workroot}/wk_warm", mesh=make_mesh(),
            chunk_rows=1 << 16, n_bins=n_bins)

        walls: dict = {}
        for mode, opts in (("serial", {"pipeline": False}),
                           ("pipelined", {})):
            obs.reset_all()
            report().reset()
            t0 = time.perf_counter()
            streaming_transform(
                src, f"{workroot}/out_{mode}", realign=True, sort=True,
                workdir=f"{workroot}/wk_{mode}", mesh=make_mesh(),
                chunk_rows=1 << 16, n_bins=n_bins, realign_opts=opts)
            wall = time.perf_counter() - t0
            p4 = _p4_wall() or wall
            walls[mode] = p4
            line = {"metric": "realign_p4_wall_s", "mode": mode,
                    "value": round(p4, 3), "total_wall_s": round(wall, 3),
                    "n_targets": n_targets, "n_bins": n_bins}
            stages = _stage_breakdown()
            if stages:      # engine-only histograms; serial is monolithic
                line["stages"] = stages
            print(json.dumps(line))

        # the frozen plan the product runs with, stamped like bench.py's
        # executor plans (decide_realign_plan is pure + replayable)
        plan = decide_realign_plan(
            n_bins=n_bins + 1, on_tpu=is_tpu_backend(),
            **resolve_realign_opts(None))
        print(json.dumps({
            "metric": "realign_pipeline_speedup", "unit": "ratio",
            "value": round(walls["serial"] / max(walls["pipelined"], 1e-9),
                           3),
            "target": 1.3,
            "realign_plan": {
                "pipeline_depth": plan["pipeline_depth"],
                "donate": plan["donate"], "reason": plan["reason"],
                "input_digest": plan["input_digest"]}}))
    finally:
        shutil.rmtree(workroot, ignore_errors=True)


def main() -> None:
    from adam_tpu.platform import honor_platform_env
    honor_platform_env()  # JAX_PLATFORMS=cpu must beat the axon plugin

    n_targets = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    bench_single_shot(n_targets)
    bench_pipeline(max(n_targets // 2, 64))


if __name__ == "__main__":
    main()
