"""Realignment throughput on a synthetic many-target chromosome.

Evidence for VERDICT r1 #7's done-gate: realign wall time on a synthetic
1000-target chromosome within 2x of the markdup stage over the same reads.
The batched sweep (realigner._sweep_groups) buckets every
(target, consensus) job by padded shape and sweeps many targets per
vmapped MXU dispatch, so the compile count stays O(#shapes), not O(#targets).

Prints one JSON line per stage.  Not run by the driver (bench.py stays the
single-line contract); run manually: ``python bench_realign.py [n_targets]``.
"""

from __future__ import annotations

import io
import json
import sys
import time


def main() -> None:
    from adam_tpu.platform import honor_platform_env
    honor_platform_env()  # JAX_PLATFORMS=cpu must beat the axon plugin

    n_targets = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    sys.path.insert(0, "tests")
    from _synth_realign import synth_sam

    from adam_tpu.io.sam import read_sam
    from adam_tpu.ops.markdup import mark_duplicates
    from adam_tpu.packing import pack_reads
    from adam_tpu.realign.realigner import realign_indels

    text = synth_sam(n_targets, reads_per_target=20, seed=0)
    table, _, _ = read_sam(io.StringIO(text))
    n = table.num_rows
    batch = pack_reads(table)

    t0 = time.perf_counter()
    mark_duplicates(table, batch)
    t_markdup = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = realign_indels(table, batch)
    t_realign = time.perf_counter() - t0

    changed = sum(1 for a, b in zip(table.column("cigar").to_pylist(),
                                    out.column("cigar").to_pylist())
                  if a != b)
    for name, dt in (("markdup", t_markdup), ("realign", t_realign)):
        print(json.dumps({"metric": f"{name}_wall_s", "value": round(dt, 2),
                          "unit": "s", "n_reads": n,
                          "n_targets": n_targets}))
    print(json.dumps({"metric": "realign_vs_markdup", "unit": "ratio",
                      "value": round(t_realign / t_markdup, 2),
                      "reads_realigned": changed}))


if __name__ == "__main__":
    main()
