"""One-command static + artifact validation for the repo.

Runs, in order:

1. **graftlint** — the AST invariant linter over ``adam_tpu/`` +
   ``tools/`` with the checked-in baseline (docs/STATIC_ANALYSIS.md);
2. **bench_gate** — the committed BENCH artifacts through their
   regression gates;
3. **check_evidence** — the committed evidence ledger
   (``EVIDENCE_LEDGER.json``), when one exists;
4. any **sidecar paths passed as arguments**, routed by shape:
   ``*.trace.json`` -> check_trace, other ``*.json`` -> check_evidence,
   ``*series.jsonl`` -> check_series, other ``*.jsonl`` ->
   check_metrics + check_executor + check_resilience.

This is the verify-flow entry: where ``python -m pytest tests/`` checks
behavior, ``python -m tools.lint_all`` checks the conventions and the
committed artifacts in one shot — run both before shipping.  Each step
runs in a subprocess so one validator's crash cannot mask another's
verdict; exit status is nonzero iff any step failed.

    python -m tools.lint_all [--fast] [SIDECAR ...]

``--fast`` skips bench_gate (it re-derives every gate from the
committed artifacts, ~10 s of numpy churn) — graftlint + evidence +
sidecars only.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import List, Sequence, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER = os.path.join(ROOT, "EVIDENCE_LEDGER.json")


def _has_fault_events(path: str) -> bool:
    """True when the sidecar records any fault/retry decision —
    check_resilience treats their absence as a failure, so it only
    runs on sidecars that have something to replay."""
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for ln in f:
                if "fault_injected" not in ln and "retry_attempt" not in ln:
                    continue
                try:
                    doc = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(doc, dict) and doc.get("event") in (
                        "fault_injected", "retry_attempt"):
                    return True
        return False
    except OSError:
        return False


def _steps(argv: Sequence[str]) -> List[Tuple[str, List[str]]]:
    """(label, argv) per step; sidecars are routed by filename shape."""
    fast = "--fast" in argv
    # absolute early: the validator subprocesses run with cwd=ROOT, so
    # a path relative to the INVOKING cwd would resolve differently
    # here and there
    paths = [os.path.abspath(a) for a in argv if a != "--fast"]
    py = sys.executable
    steps: List[Tuple[str, List[str]]] = [
        ("graftlint", [py, "-m", "tools.graftlint"]),
    ]
    if not fast:
        steps.append(
            ("bench_gate", [py, os.path.join(ROOT, "tools",
                                             "bench_gate.py")]))
    if os.path.exists(LEDGER):
        steps.append(
            ("check_evidence", [py, os.path.join(ROOT, "tools",
                                                 "check_evidence.py"),
                                LEDGER]))
    for p in paths:
        tool_dir = os.path.join(ROOT, "tools")
        if p.endswith(".trace.json"):
            steps.append((f"check_trace {p}",
                          [py, os.path.join(tool_dir, "check_trace.py"),
                           p]))
        elif p.endswith(".json"):
            steps.append((f"check_evidence {p}",
                          [py, os.path.join(tool_dir,
                                            "check_evidence.py"), p]))
        elif p.endswith("series.jsonl"):
            # the time-series plane has its own schema + monoid laws
            steps.append((f"check_series {p}",
                          [py, os.path.join(tool_dir,
                                            "check_series.py"), p]))
        else:
            steps.append((f"check_metrics {p}",
                          [py, os.path.join(tool_dir,
                                            "check_metrics.py"), p]))
            steps.append((f"check_executor {p}",
                          [py, os.path.join(tool_dir,
                                            "check_executor.py"), p]))
            # check_resilience requires fault events; only a faulted
            # run's sidecar can satisfy it
            if _has_fault_events(p):
                steps.append((f"check_resilience {p}",
                              [py, os.path.join(tool_dir,
                                                "check_resilience.py"),
                               p]))
    return steps


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    failures: List[str] = []
    for label, cmd in _steps(argv):
        print(f"== lint_all: {label}", flush=True)
        rc = subprocess.call(cmd, cwd=ROOT)
        if rc != 0:
            failures.append(f"{label} (exit {rc})")
    if failures:
        print(f"lint_all: FAILED — {', '.join(failures)}",
              file=sys.stderr)
        return 1
    print("lint_all: all checks hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
