#!/usr/bin/env python3
"""Diff two BENCH artifacts and GATE on regression.

Five rounds of BENCH_r0N.json accumulated a trajectory nobody machine-
checked: a PR that halved the headline would only be caught by a human
reading two JSON blobs.  This tool makes the bench trajectory gate —
compare an OLD artifact against a NEW one and exit nonzero when any
tracked metric regressed past the threshold:

* throughput metrics (``value``, ``*_reads_per_sec``,
  ``transform_vs_target``, ``vs_baseline``, ``paged_h2d_reduction`` —
  the resident-paging transfer headline, BENCH_PAGED.json) — HIGHER is
  better;
* cost metrics (``*_stage_wall_s``, ``*_wall_s``, ``first_matmul_s``,
  ``*pad_waste*``, ``*spill_amplification*``) — LOWER is better (the
  last two are the executor's pad-tax and the I/O ledger's spill ratio,
  docs/OBSERVABILITY.md).

Accepts both artifact shapes: the bench one-line doc itself
(BENCH_TPU_EVIDENCE.json) and the driver wrapper holding it under
``parsed`` (BENCH_r0N.json).  Artifacts from different platforms
(cpu vs tpu) are incomparable — flagged and exited 2 unless
``--allow-cross-platform`` (numbers still print).

Usage::

    python tools/compare_bench.py OLD.json NEW.json [--threshold 10]
           [--keys value,transform_fused_reads_per_sec] [--allow-cross-platform]

Exit codes: 0 no regression, 1 regression past threshold, 2 usage /
unreadable / cross-platform.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

#: substrings/suffixes that mark a LOWER-is-better metric
_LOWER_BETTER = ("pad_waste", "spill_amplification", "_wall_s",
                 "first_matmul_s", "rtt_ms")
#: markers of HIGHER-is-better metrics
_HIGHER_BETTER_SUFFIX = ("_reads_per_sec", "_tflops",
                         "_gbytes_per_sec")
_HIGHER_BETTER_EXACT = ("value", "vs_baseline", "transform_vs_target",
                        "mfu", "mfu_pct", "paged_h2d_reduction")


def load_doc(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]         # the BENCH_r0N.json driver wrapper
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench artifact object")
    return doc


def direction(key: str) -> Optional[str]:
    """'up' (higher better), 'down' (lower better), None (untracked)."""
    if key in _HIGHER_BETTER_EXACT or \
            key.endswith(_HIGHER_BETTER_SUFFIX):
        return "up"
    if any(m in key for m in _LOWER_BETTER):
        return "down"
    return None


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def compare(old: dict, new: dict, threshold_pct: float,
            keys: Optional[List[str]] = None
            ) -> Tuple[List[str], List[str], Dict[str, tuple]]:
    """Returns (regressions, notes, rows) where rows maps key ->
    (old, new, delta_pct, direction)."""
    regressions: List[str] = []
    notes: List[str] = []
    rows: Dict[str, tuple] = {}
    if keys:
        tracked = []
        for k in keys:
            d = direction(k)
            if d is None:
                # an explicit key with no recognized direction marker:
                # say the assumption out loud — silently guessing "up"
                # would invert the gate for a cost metric
                notes.append(f"{k}: direction unrecognized — assuming "
                             "higher-is-better (name it *_wall_s / "
                             "*pad_waste* / *spill_amplification* "
                             "for lower-is-better)")
                d = "up"
            tracked.append((k, d))
    else:
        tracked = [(k, d) for k in sorted(set(old) | set(new))
                   if (d := direction(k)) is not None]
    for key, d in tracked:
        ov, nv = old.get(key), new.get(key)
        if not _is_num(ov) or not _is_num(nv):
            if _is_num(ov) and nv is None:
                notes.append(f"{key}: present in OLD, missing in NEW")
            continue
        if ov == 0:
            if nv != 0:
                # relative change against a zero baseline is undefined
                # (0 pad waste -> 0.0001 is not an infinite regression);
                # surface it, never gate on it
                notes.append(f"{key}: zero baseline ({ov!r} -> {nv!r})"
                             " — relative change undefined, not gated")
                continue
            delta = 0.0
        else:
            delta = 100.0 * (nv - ov) / abs(ov)
        rows[key] = (ov, nv, delta, d)
        regressed = (d == "up" and delta < -threshold_pct) or \
                    (d == "down" and delta > threshold_pct)
        if regressed:
            arrow = "fell" if d == "up" else "rose"
            regressions.append(
                f"{key}: {arrow} {abs(delta):.1f}% "
                f"({ov!r} -> {nv!r}; threshold {threshold_pct}%)")
    return regressions, notes, rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two BENCH_*.json artifacts; exit 1 on "
                    "regression past --threshold")
    ap.add_argument("old", help="baseline artifact")
    ap.add_argument("new", help="candidate artifact")
    ap.add_argument("--threshold", type=float, default=10.0,
                    metavar="PCT",
                    help="allowed change in the bad direction (%%; "
                         "default 10)")
    ap.add_argument("--keys", default=None,
                    help="comma-separated metric keys (default: every "
                         "tracked throughput/cost key present)")
    ap.add_argument("--allow-cross-platform", action="store_true",
                    help="compare artifacts from different backends "
                         "anyway (numbers are NOT comparable across "
                         "cpu/tpu; off by default)")
    args = ap.parse_args(argv)

    try:
        old, new = load_doc(args.old), load_doc(args.new)
    except (OSError, ValueError) as e:
        print(f"compare_bench: {e}", file=sys.stderr)
        return 2

    po, pn = old.get("platform"), new.get("platform")
    if po != pn and not args.allow_cross_platform:
        print(f"compare_bench: platform mismatch ({po!r} vs {pn!r}) — "
              "cross-backend numbers do not gate "
              "(--allow-cross-platform overrides)", file=sys.stderr)
        return 2

    keys = [k.strip() for k in args.keys.split(",")] if args.keys else None
    regressions, notes, rows = compare(old, new, args.threshold, keys)
    if not rows and not notes:
        print("compare_bench: no tracked numeric keys in common",
              file=sys.stderr)
        return 2

    width = max((len(k) for k in rows), default=10)
    print(f"{'metric':<{width}}  {'old':>14}  {'new':>14}  {'Δ%':>8}")
    for key, (ov, nv, delta, d) in rows.items():
        mark = ""
        if (d == "up" and delta < -args.threshold) or \
                (d == "down" and delta > args.threshold):
            mark = "  REGRESSION"
        elif (d == "up" and delta > args.threshold) or \
                (d == "down" and delta < -args.threshold):
            mark = "  improved"
        print(f"{key:<{width}}  {ov:>14.4g}  {nv:>14.4g}  "
              f"{delta:>+7.1f}%{mark}")
    for n in notes:
        print(f"note: {n}")
    if regressions:
        print(f"\n{len(regressions)} regression(s) past "
              f"{args.threshold}%:", file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    print(f"\nok: no regression past {args.threshold}% "
          f"({len(rows)} metrics compared)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
