#!/usr/bin/env python3
"""Replay a telemetry sidecar's executor decisions and assert they are
deterministic.

The streaming executor's autotuner (adam_tpu/parallel/executor.py,
``decide_plan``) is a PURE function of its inputs, and every
``executor_bucket_selected`` event records those inputs verbatim plus a
digest of them.  This checker re-derives each recorded decision offline
and fails when:

* replaying ``decide_plan(**inputs)`` yields a different chunk_rows /
  ladder / ladder_base / prefetch_depth / donate than the event
  recorded (the autotuner drifted from purity — e.g. someone added a
  clock or env read inside the decision); the same replay runs for the
  fleet's ``shard_plan_selected`` (decide_shard_plan) and
  ``shard_reassigned`` (decide_shard_reassignment /
  decide_shard_speculation, selected by the recorded ``cause``), the
  serve front-end's ``admission_selected`` (decide_admission), the
  fleet-serve scheduler's ``placement_selected``
  (decide_placement) and ``job_requeued`` (decide_requeue /
  decide_steal, selected by the recorded ``cause``), the overload
  plane's ``overload_state`` (serve/overload.decide_overload), the
  backend circuit breaker's ``breaker_state``
  (resilience/retry.decide_breaker), the variant-calling plane's
  ``call_plan_selected`` (call/plan.decide_call_plan) and the fleet
  data plane's ``transport_selected`` / ``shard_entry_selected``
  (parallel/ringplane.decide_transport / decide_shard_entry);
* the recorded ``input_digest`` does not match the digest of the
  recorded inputs (the event lied about what it decided from);
* two events — within one file or across files — share an
  ``input_digest`` but disagree on the decision (same inputs must mean
  the same plan, the fixed-input-digest determinism contract the smoke
  test pins).

Usage::

    python tools/check_executor.py RUN.metrics.jsonl [...]

Exit 0 when every recorded decision replays identically; 1 otherwise
with one line per violation.  Companion to tools/check_metrics.py
(which validates the event SCHEMA; this validates the event's
semantics).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Tuple

# runnable as a script from anywhere (same repo-root shim as aot_check)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the plan fields a replay must reproduce exactly (``layout`` — the
#: ragged-vs-padded dimension — and ``fused_device`` — the mega-pass
#: dimension — are compared only when the event carries them, so
#: pre-layout/pre-mega sidecars still replay)
PLAN_FIELDS = ("chunk_rows", "ladder", "ladder_base", "prefetch_depth",
               "donate", "layout", "page_rows", "pool_pages",
               "fused_device")

#: the fused-transform plan fields a replay must reproduce exactly
#: (pipeline.decide_fusion_plan; same purity contract)
FUSION_FIELDS = ("mode", "streams", "route_in_s1", "carry_ridx",
                 "count_pass", "apply_at", "wire_spill", "direct_emit")

#: the pass-4 plan fields a replay must reproduce exactly
#: (realign_exec.decide_realign_plan — the layout decision included)
REALIGN_FIELDS = ("pipeline_depth", "donate", "layout")

#: the fleet plan/reassignment fields a replay must reproduce exactly
#: (shardstream.decide_shard_plan / decide_shard_reassignment /
#: decide_shard_speculation — shard_reassigned picks its decider by
#: the recorded ``cause``)
SHARD_PLAN_FIELDS = ("assignments", "reason")
SHARD_DEATH_FIELDS = ("action", "new_incarnation", "splits", "reason")
SHARD_SPEC_FIELDS = ("action", "victim", "target", "tail_runs",
                     "reason")

#: the page-allocator fields a replay must reproduce exactly
#: (parallel/pagedbuf.decide_pages — the resident paged-buffer plane;
#: same purity contract)
PAGES_FIELDS = ("pages", "action", "reason")

#: the serve admission fields a replay must reproduce exactly
#: (serve/admission.decide_admission — which jobs run, which share
#: dispatches, and which are shed/cancelled; ``reject``/``cancel``
#: joined in the overload era and are compared only when recorded)
ADMISSION_FIELDS = ("admit", "pack_groups", "reason", "reject",
                    "cancel")

#: the brownout-ladder fields a replay must reproduce exactly
#: (serve/overload.decide_overload — the overload state machine;
#: same purity contract)
OVERLOAD_FIELDS = ("level", "state", "actions", "calm_rounds",
                   "reason")

#: the circuit-breaker fields a replay must reproduce exactly
#: (resilience/retry.decide_breaker; ``failures`` in the event is the
#: host-side window count, not a decision output)
BREAKER_FIELDS = ("state", "reason")

#: the fleet-serve scheduler fields a replay must reproduce exactly
#: (serve/scheduler.decide_placement / decide_requeue / decide_steal —
#: ``job_requeued`` picks its decider by the recorded ``cause``, the
#: shard_reassigned discipline)
PLACEMENT_FIELDS = ("place", "reason")
REQUEUE_FIELDS = ("action", "reason")
STEAL_FIELDS = ("action", "moves", "reason")

#: the variant-calling plan fields a replay must reproduce exactly
#: (call/plan.decide_call_plan; same purity contract)
CALL_FIELDS = ("stripe_span", "min_depth", "min_alt", "reason")

#: the fleet data-plane fields a replay must reproduce exactly
#: (parallel/ringplane.decide_transport / decide_shard_entry — how
#: unit results travel and where SAM/BAM shards enter the input)
TRANSPORT_FIELDS = ("transport", "spool_sync", "reason")
ENTRY_FIELDS = ("entry", "reason")

#: the spool-retention fields a replay must reproduce exactly
#: (serve/retention.decide_retention — what a GC sweep may unlink;
#: the event records collect/kept as COUNTS, so the replay adapter
#: below compares the recomputed list lengths plus the reason)
RETENTION_FIELDS = ("collect", "kept", "reason")

#: fields absent from older sidecars: compared only when recorded
_OPTIONAL_FIELDS = ("layout", "page_rows", "pool_pages", "reject",
                    "cancel", "fused_device")

#: event kinds whose canonicalized inputs grew layout keys in PR 8 —
#: a pre-layout event's recorded inputs digest differently under the
#: current decider (the new dict carries more keys), so the digest
#: replay is skipped for them; the decision FIELDS still replay
_LAYOUT_KINDS = ("executor_bucket_selected", "realign_plan_selected")

_REPLAYED = ("executor_bucket_selected", "fusion_plan_selected",
             "realign_plan_selected", "shard_plan_selected",
             "shard_reassigned", "admission_selected",
             "placement_selected", "job_requeued", "pages_selected",
             "overload_state", "breaker_state", "call_plan_selected",
             "transport_selected", "shard_entry_selected", "spool_gc")


def _events(path: str, kinds=_REPLAYED) -> List[Tuple[int, dict]]:
    out = []
    with open(path) as f:
        for i, ln in enumerate(f, 1):
            if not ln.strip():
                continue
            try:
                doc = json.loads(ln)
            except ValueError:
                continue        # schema problems are check_metrics' job
            if isinstance(doc, dict) and doc.get("event") in kinds:
                out.append((i, doc))
    return out


def check(paths: List[str]) -> List[str]:
    """Replay every recorded decision; return human-readable violations
    (empty = deterministic)."""
    from adam_tpu.parallel.executor import decide_plan
    from adam_tpu.parallel.pipeline import decide_fusion_plan
    from adam_tpu.parallel.realign_exec import decide_realign_plan
    from adam_tpu.parallel.shardstream import (decide_shard_plan,
                                               decide_shard_reassignment,
                                               decide_shard_speculation)
    from adam_tpu.call.plan import decide_call_plan
    from adam_tpu.parallel.pagedbuf import decide_pages
    from adam_tpu.parallel.ringplane import (decide_shard_entry,
                                             decide_transport)
    from adam_tpu.resilience.retry import decide_breaker
    from adam_tpu.serve.admission import decide_admission
    from adam_tpu.serve.overload import decide_overload
    from adam_tpu.serve.retention import decide_retention
    from adam_tpu.serve.scheduler import (decide_placement,
                                          decide_requeue, decide_steal)

    def replay_retention(**inputs):
        # the spool_gc event records collect/kept as counts (the
        # collected names are in the inputs already); reshape the
        # replayed decision to the recorded shape
        d = decide_retention(**inputs)
        return dict(d, collect=len(d["collect"]), kept=len(d["kept"]))

    deciders = {"executor_bucket_selected": (decide_plan, PLAN_FIELDS),
                "fusion_plan_selected": (decide_fusion_plan,
                                         FUSION_FIELDS),
                "realign_plan_selected": (decide_realign_plan,
                                          REALIGN_FIELDS),
                "shard_plan_selected": (decide_shard_plan,
                                        SHARD_PLAN_FIELDS),
                "admission_selected": (decide_admission,
                                       ADMISSION_FIELDS),
                "placement_selected": (decide_placement,
                                       PLACEMENT_FIELDS),
                "pages_selected": (decide_pages, PAGES_FIELDS),
                "overload_state": (decide_overload, OVERLOAD_FIELDS),
                "breaker_state": (decide_breaker, BREAKER_FIELDS),
                "call_plan_selected": (decide_call_plan, CALL_FIELDS),
                "transport_selected": (decide_transport,
                                       TRANSPORT_FIELDS),
                "shard_entry_selected": (decide_shard_entry,
                                         ENTRY_FIELDS),
                "spool_gc": (replay_retention, RETENTION_FIELDS)}
    errs: List[str] = []
    # digests are namespaced per event kind: the two deciders hash
    # different input tuples and must never cross-validate
    by_digest: Dict[Tuple[str, str], Tuple[str, int, dict]] = {}
    n_checked = 0
    for path in paths:
        events = _events(path)
        if not events:
            errs.append(f"{path}: no replayable plan events "
                        "(not an executor run, or events were lost)")
            continue
        for i, ev in events:
            kind = ev.get("event")
            if kind == "shard_reassigned":
                # one event name, two pure deciders — the recorded
                # cause says which one produced it
                if ev.get("cause") == "speculation":
                    decider, fields = (decide_shard_speculation,
                                       SHARD_SPEC_FIELDS)
                else:
                    decider, fields = (decide_shard_reassignment,
                                       SHARD_DEATH_FIELDS)
            elif kind == "job_requeued":
                # same discipline: steal events came from decide_steal,
                # every other cause from decide_requeue
                if ev.get("cause") == "steal":
                    decider, fields = (decide_steal, STEAL_FIELDS)
                else:
                    decider, fields = (decide_requeue, REQUEUE_FIELDS)
            else:
                decider, fields = deciders[kind]
            inputs = ev.get("inputs")
            if not isinstance(inputs, dict):
                errs.append(f"{path}:{i}: {kind} carries no inputs — "
                            "decision cannot be replayed")
                continue
            try:
                plan = decider(**inputs)
            except TypeError as e:
                errs.append(f"{path}:{i}: inputs do not replay through "
                            f"{decider.__name__}: {e}")
                continue
            n_checked += 1
            for field in fields:
                if field in _OPTIONAL_FIELDS and field not in ev:
                    continue        # pre-layout sidecar: nothing recorded
                if ev.get(field) != plan.get(field):
                    errs.append(
                        f"{path}:{i}: non-deterministic {kind} — "
                        f"recorded {field}={ev.get(field)!r}, replay "
                        f"yields {plan.get(field)!r}")
            pre_layout = kind in _LAYOUT_KINDS and "layout" not in inputs
            if not pre_layout and \
                    ev.get("input_digest") != plan["input_digest"]:
                errs.append(
                    f"{path}:{i}: input_digest mismatch (recorded "
                    f"{ev.get('input_digest')!r}, inputs digest to "
                    f"{plan['input_digest']!r})")
            # cross-event/cross-file: one digest, one decision
            decision = {f: ev.get(f) for f in fields}
            dig = ev.get("input_digest")
            if isinstance(dig, str):
                seen = by_digest.get((kind, dig))
                if seen is None:
                    by_digest[(kind, dig)] = (path, i, decision)
                elif seen[2] != decision:
                    errs.append(
                        f"{path}:{i}: digest {dig} decided differently "
                        f"than {seen[0]}:{seen[1]} — same inputs must "
                        "yield the same plan")
    if not errs and not n_checked:
        errs.append("no replayable executor decisions found")
    return errs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: check_executor.py RUN.metrics.jsonl [...]",
              file=sys.stderr)
        return 2
    errors = check(argv)
    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        return 1
    n = sum(len(_events(p)) for p in argv)
    print(f"ok: {n} executor decision(s) replayed deterministically "
          f"across {len(argv)} file(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
