"""Tunnel-independent TPU evidence: AOT-lower every product Pallas/XLA
kernel for the TPU target and record per-kernel status.

``jax.export.export(jit_fn, platforms=["tpu"])`` runs trace + StableHLO
lowering — and, for Pallas kernels, the Mosaic dialect conversion and
serialization — without a live device.  A kernel that Mosaic would reject
(unsupported op, bad layout, rank/tiling constraint) fails HERE, so this
check retires the "Mosaic might reject the int8 legs" class of risk even
when the tunnel is down (VERDICT r4, next-round #2).

    JAX_PLATFORMS=cpu python tools/aot_check.py [--out AOT_CHECK.json]

Each kernel gets: ok, lowering wall seconds, serialized-module size (a
proxy for "the Mosaic payload is really in there"), or the exception.
The watcher's no-tunnel branch runs this once per round.
"""
# graftlint-file: disable=GL002 — one-shot AOT-lowering harness: each
# kernel is deliberately wrapped in a fresh jit once per process run to
# measure its lowering; there is no warm path to leak recompiles into.

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# tunnel-independence is the point: force the CPU client so the check
# never blocks on (or is invalidated by) tunnel state.  The bare env var
# is NOT enough — the axon plugin initializes (and touches the tunnel)
# regardless; platform.force_cpu flips the jax config too.  8 virtual
# devices back the sharded-section mesh (the lowering still targets
# TPU — jax.export records nr_devices=8 and the module carries the
# partitioned collectives).
from adam_tpu.platform import force_cpu  # noqa: E402

force_cpu(n_devices=8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import export  # noqa: E402


def S(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _read_args(n=64, L=128):
    """Abstract ReadBatch tensors in the product packer's dtypes and the
    count kernels' positional order: (bases, quals, read_len, flags,
    read_group, state, usable)."""
    return (S((n, L), jnp.int8), S((n, L), jnp.int8),
            S((n,), jnp.int32), S((n,), jnp.int32), S((n,), jnp.int32),
            S((n, L), jnp.int8), S((n,), jnp.bool_))


def kernel_cases():
    """(name, jit_fn, abstract_args) for every product TPU kernel."""
    from adam_tpu.align.sw_pallas import sw_score_batch_pallas
    from adam_tpu.bqsr.count_pallas import (count_kernel_pallas,
                                            count_kernel_pallas_rows)
    from adam_tpu.ops import flagstat_pallas as fp
    from adam_tpu.realign.sweep_pallas import sweep_pallas

    cases = []

    # flagstat v1/v2: the public wrappers split wire into blocked + tail
    # with host-side (concrete) shape logic, so the jittable surface — and
    # the thing worth lowering — is the inner blocked kernel + tail path
    tail = S((100,), jnp.uint32)
    cases.append(("flagstat_v1",
                  jax.jit(lambda w3, t: fp._flagstat_blocked(w3, t)),
                  (S((2, fp.BLOCK_ROWS, fp.LANES), jnp.uint32), tail)))
    cases.append(("flagstat_v2",
                  jax.jit(lambda w3, t: fp._flagstat_blocked_v2(w3, t)),
                  (S((2, fp.V2_ROWS, fp.LANES), jnp.uint32), tail)))

    # BQSR count kernels: product geometry for one read group of 128 bp
    # reads (n_qual_rg = 60*RG+94, n_cycle = 2L+1 — table.py)
    args = _read_args(n=64, L=128)
    n_qual_rg, n_cycle = 60 + 94, 2 * 128 + 1
    for name, fn in (("count_flat", count_kernel_pallas),
                     ("count_rows", count_kernel_pallas_rows)):
        for tag, int8_mxu in (("bf16", False), ("int8", True)):
            cases.append((
                f"{name}_{tag}",
                jax.jit(lambda *a, _fn=fn, _i8=int8_mxu: _fn(
                    *a, n_qual_rg=n_qual_rg, n_cycle=n_cycle,
                    int8_mxu=_i8)),
                args))

    # realign consensus sweep
    R, L, CL = 16, 128, 256
    cases.append(("sweep",
                  jax.jit(lambda r, q, rl, c, cl: sweep_pallas(
                      r, q, rl, c, cl)),
                  (S((R, L), jnp.uint8), S((R, L), jnp.int8),
                   S((R,), jnp.int32), S((CL,), jnp.uint8),
                   S((), jnp.int32))))

    # Smith-Waterman scoring
    N, Lx, Ly = 16, 128, 128
    cases.append(("sw_score",
                  jax.jit(lambda xs, xl, ys, yl: sw_score_batch_pallas(
                      xs, xl, ys, yl)),
                  (S((N, Lx), jnp.uint8), S((N,), jnp.int32),
                   S((N, Ly), jnp.uint8), S((N,), jnp.int32))))
    return cases


def sharded_cases():
    """(name, jit_fn, abstract_args) for the MULTI-CHIP product paths:
    shard_map'd Pallas kernels + psum over the reads axis, lowered for
    TPU with nr_devices=8.  This is the dryrun's coverage at the Mosaic
    layer: the dryrun executes these graphs on the CPU mesh in interpret
    mode; here the same graphs lower through real Mosaic + partitioned
    collectives without a device."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from adam_tpu.bqsr.count_pallas import sharded_count_pallas
    from adam_tpu.bqsr.recalibrate import _sharded_apply_fn
    from adam_tpu.bqsr.table import RecalTable
    from adam_tpu.ops import flagstat_pallas as fp
    from adam_tpu.parallel.mesh import READS_AXIS, make_mesh

    mesh = make_mesh(n_devices=8)
    rows = NamedSharding(mesh, P(READS_AXIS))
    repl = NamedSharding(mesh, P())
    cases = []

    n_wire = 8 * fp.V2_ROWS * fp.LANES
    cases.append((
        "sharded_flagstat_pallas",
        jax.jit(fp.flagstat_wire32_sharded_pallas(mesh, interpret=False),
                in_shardings=rows),
        (S((n_wire,), jnp.uint32),)))

    n, L, n_rg = 64, 128, 1
    rt = RecalTable(n_read_groups=n_rg, max_read_len=L)
    args = _read_args(n=n, L=L)
    for variant in ("flat", "rows"):
        cases.append((
            f"sharded_count_pallas_{variant}",
            jax.jit(sharded_count_pallas(mesh, rt.n_qual_rg, rt.n_cycle,
                                         variant, interpret=False),
                    in_shardings=(rows,) * 7),
            args))

    from adam_tpu.bqsr.covariates import N_CONTEXT
    lut_len = 128 * n_rg * rt.n_cycle * N_CONTEXT
    cases.append((
        "sharded_apply_lut",
        jax.jit(_sharded_apply_fn(mesh, n_rg),
                in_shardings=(rows,) * 6 + (repl,)),
        args[:5] + (S((n,), jnp.bool_), S((lut_len,), jnp.int8))))
    return cases


def check_one(name, fn, args):
    t0 = time.perf_counter()
    try:
        exp = export.export(fn, platforms=["tpu"])(*args)
        blob = exp.serialize()
        return {"kernel": name, "ok": True,
                "lower_s": round(time.perf_counter() - t0, 2),
                "serialized_bytes": len(blob),
                "nr_devices": exp.nr_devices,
                "has_tpu_custom_call":
                    b"tpu_custom_call" in exp.mlir_module_serialized}
    except Exception as e:  # noqa: BLE001 — per-kernel isolation is the job
        return {"kernel": name, "ok": False,
                "lower_s": round(time.perf_counter() - t0, 2),
                "error": f"{type(e).__name__}: {e}"[:500],
                "trace_tail": traceback.format_exc().splitlines()[-3:]}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="AOT_CHECK.json")
    args = ap.parse_args()
    results = [check_one(*c) for c in kernel_cases()]
    try:
        results += [check_one(*c) for c in sharded_cases()]
    except Exception as e:  # noqa: BLE001 — sharded section is additive
        results.append({"kernel": "sharded_section", "ok": False,
                        "error": f"{type(e).__name__}: {e}"[:500]})
    doc = {
        "what": "AOT TPU lowering status of every product Pallas kernel "
                "(trace + StableHLO + Mosaic serialization, no device)",
        "jax_version": jax.__version__,
        "lowering_platform": "tpu",
        "client_platform": jax.default_backend(),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "kernels": results,
        "all_ok": all(r["ok"] for r in results),
    }
    from adam_tpu.checkpoint import atomic_write

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    atomic_write(os.path.join(repo, args.out), json.dumps(doc, indent=1))
    for r in results:
        print(json.dumps(r))
    print(f"all_ok={doc['all_ok']}")
    return 0 if doc["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
