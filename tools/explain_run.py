#!/usr/bin/env python3
"""Standalone per-job run explainer — ``adam-tpu explain`` without the
package on PYTHONPATH.

Joins a served job's durable artifacts (result doc, event sidecars,
series.jsonl files, trace docs) into one causal timeline: submitted →
queued behind N jobs of which tenants → admission/placement with the
deciders' recorded inputs → retries / degrades / requeues / steals →
rung and breaker context → finish.  Pure reader: never touches the
spool, so it is safe against a live fleet or a spool copied off a
shared filesystem.

    python tools/explain_run.py SPOOL JOB_ID [--json]
        [--events PATH]... [--series PATH]... [--timeline PATH]...

Exit 0: job found; 3: no durable record of the job; 2: bad input.
The join logic lives in adam_tpu/serve/explain.py (the CLI command and
this script are the same engine); docs/OBSERVABILITY.md documents the
attribution rules (exact vs window vs context).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from adam_tpu.serve.explain import (explain_job,  # noqa: E402
                                    render_timeline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="reconstruct one served job's causal timeline "
                    "from durable artifacts alone")
    ap.add_argument("spool", help="the server's spool directory")
    ap.add_argument("job", help="job id (the result doc's stem)")
    ap.add_argument("--events", action="append", default=[],
                    metavar="PATH", help="extra event sidecar(s)")
    ap.add_argument("--series", action="append", default=[],
                    metavar="PATH", help="extra series.jsonl file(s)")
    ap.add_argument("--timeline", action="append", default=[],
                    metavar="PATH", help="extra .trace.json file(s)")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="print the full timeline doc as JSON")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.spool):
        print(f"explain_run: no such spool: {args.spool}",
              file=sys.stderr)
        return 2
    doc = explain_job(args.spool, args.job, events=args.events,
                      series=args.series, timelines=args.timeline)
    if args.as_json:
        print(json.dumps(doc, sort_keys=True, default=str))
    else:
        print(render_timeline(doc))
    return 0 if doc["found"] else 3


if __name__ == "__main__":
    sys.exit(main())
