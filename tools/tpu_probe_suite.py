"""One-shot TPU probe suite: every open hardware question, answered in one
tunnel window.

Run when the tunnel is up (tools/tpu_watch.py tells you).  Prints one JSON
line per probe so a mid-run tunnel death keeps earlier answers:

  1. scan-compile knee: lax.scan compile seconds vs trip count for the BQSR
     count-matmul body (the flagstat einsum showed ~2 s/iteration compile,
     i.e. the remote AOT compiler unrolls; is the count scan usable at
     product chunk sizes?)
  2. BQSR count backends on chip: scatter vs matmul wall rate at a product
     chunk shape
  3. apply-pass rate
  4. realign sweep + Smith-Waterman Pallas kernels: compile?, match?, ms
  5. pallas flagstat block-size sweep (is 2^18 inside scoped VMEM, and
     faster than the shipping 2^17?)

Each probe runs in this process; order is least-risky first so a hang
costs the fewest answers.  Use `--only 1,3` to cherry-pick.
"""
# graftlint-file: disable=GL002 — one-shot hardware probe harness: each
# probe builds a fresh jit on purpose (compile time IS the measurement);
# nothing here is a warm path.

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# spawned as `python tools/tpu_probe_suite.py`, sys.path[0] is tools/ —
# the repo root must be added or `import adam_tpu` dies before the first
# probe line (exactly how round-4's probe captures came back empty)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def emit(name, **kw):
    print(json.dumps({"probe": name} | kw), flush=True)


def t():
    return time.perf_counter()


def probe_scan_knee():
    import jax
    import jax.numpy as jnp

    from adam_tpu.bqsr.recalibrate import _count_kernel_matmul
    from adam_tpu.bqsr.table import RecalTable

    L, n_rg = 100, 4
    rt = RecalTable(n_read_groups=n_rg, max_read_len=L)
    for n_blocks in (16, 64, 256):
        n = 512 * n_blocks
        args = _count_args(n, L, n_rg)
        t0 = t()
        out = _count_kernel_matmul(*args, n_qual_rg=rt.n_qual_rg,
                                   n_cycle=rt.n_cycle)
        jax.device_get(out[0])
        compile_s = t() - t0
        t0 = t()
        for _ in range(4):
            out = _count_kernel_matmul(*args, n_qual_rg=rt.n_qual_rg,
                                       n_cycle=rt.n_cycle)
        jax.device_get(out[0])
        run_s = (t() - t0) / 4
        emit("scan_knee", n_blocks=n_blocks, n_reads=n,
             compile_s=round(compile_s, 1), run_s=round(run_s, 3),
             reads_per_sec=round(n / run_s))


def _count_args(n, L, n_rg):
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    return (jnp.asarray(rng.randint(0, 4, (n, L)).astype(np.int8)),
            jnp.asarray(rng.randint(2, 41, (n, L)).astype(np.int8)),
            jnp.full((n,), L, jnp.int32),
            jnp.zeros((n,), jnp.int32),
            jnp.asarray(rng.randint(0, n_rg, n).astype(np.int32)),
            jnp.asarray(rng.randint(0, 3, (n, L)).astype(np.int8)),
            jnp.ones((n,), bool))


def probe_backends():
    import jax

    from adam_tpu.bqsr.recalibrate import (_count_kernel,
                                           _count_kernel_matmul)
    from adam_tpu.bqsr.table import RecalTable

    L, n_rg, n = 100, 4, 131072
    rt = RecalTable(n_read_groups=n_rg, max_read_len=L)
    args = _count_args(n, L, n_rg)
    for name, kern in (("scatter", _count_kernel),
                       ("matmul", _count_kernel_matmul)):
        try:
            t0 = t()
            out = kern(*args, n_qual_rg=rt.n_qual_rg, n_cycle=rt.n_cycle)
            jax.device_get(out[0])
            compile_s = t() - t0
            t0 = t()
            for _ in range(8):
                out = kern(*args, n_qual_rg=rt.n_qual_rg,
                           n_cycle=rt.n_cycle)
            jax.device_get(out[0])
            run_s = (t() - t0) / 8
            emit("count_backend", impl=name, n_reads=n,
                 compile_s=round(compile_s, 1),
                 reads_per_sec=round(n / run_s))
        except Exception as e:  # noqa: BLE001
            emit("count_backend", impl=name, error=str(e)[:200])


def probe_apply():
    """Old per-base delta-gather apply vs the r5 LUT apply, on chip —
    the LUT won 1.65x on CPU; this says whether the chip agrees (fewer
    big gathers should matter MORE on TPU)."""
    import jax
    import jax.numpy as jnp

    from adam_tpu.bqsr.recalibrate import (_apply_kernel,
                                           _apply_kernel_lut,
                                           _build_apply_lut)
    from adam_tpu.bqsr.table import RecalTable

    L, n_rg, n = 100, 4, 262144
    rt = RecalTable(n_read_groups=n_rg, max_read_len=L)
    fin = rt.finalize()
    fin_dev = tuple(jnp.asarray(a) for a in (
        fin.rg_delta, fin.qual_delta, fin.cycle_delta, fin.ctx_delta,
        fin.rg_of_qualrg))
    a = _count_args(n, L, n_rg)
    mask = jnp.ones((n,), bool)

    def run(label, fn):
        t0 = t()
        out = fn()
        jax.device_get(out[:1, :1])
        compile_s = t() - t0
        t0 = t()
        for _ in range(8):
            out = fn()
        jax.device_get(out[:1, :1])
        run_s = (t() - t0) / 8
        emit("apply", variant=label, n_reads=n,
             compile_s=round(compile_s, 1),
             reads_per_sec=round(n / run_s))

    run("gather", lambda: _apply_kernel(a[0], a[1], a[2], a[3], a[4],
                                        mask, *fin_dev))
    lut = _build_apply_lut(n_rg, *fin_dev)
    run("lut", lambda: _apply_kernel_lut(a[0], a[1], a[2], a[3], a[4],
                                         mask, lut, n_rg=n_rg))


def probe_pallas_kernels():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    R, L, CL = 64, 100, 512
    bases = np.frombuffer(b"ACGT", np.uint8)
    reads = jnp.asarray(bases[rng.randint(0, 4, (R, L))])
    quals = jnp.asarray(rng.randint(2, 41, (R, L)).astype(np.int32))
    lens = jnp.full((R,), L, jnp.int32)
    cons = jnp.asarray(bases[rng.randint(0, 4, (CL,))])
    from adam_tpu.realign.realigner import _sweep_conv
    try:
        from adam_tpu.realign.sweep_pallas import sweep_pallas
        t0 = t()
        q, o = sweep_pallas(reads, quals, lens, cons, CL, interpret=False)
        jax.device_get(q)
        compile_s = t() - t0
        qc, oc = _sweep_conv(reads, quals, lens, cons, CL)
        ok = bool(np.array_equal(np.asarray(q), np.asarray(qc)) and
                  np.array_equal(np.asarray(o), np.asarray(oc)))
        emit("sweep_pallas", compiles=True, matches=ok,
             compile_s=round(compile_s, 1))
    except Exception as e:  # noqa: BLE001
        emit("sweep_pallas", compiles=False, error=str(e)[:300])
    try:
        from adam_tpu.align.smithwaterman import sw_score_batch
        from adam_tpu.align.sw_pallas import sw_score_batch_pallas
        B, SL = 32, 128
        a = jnp.asarray(rng.randint(0, 4, (B, SL)).astype(np.uint8))
        b = jnp.asarray(rng.randint(0, 4, (B, SL)).astype(np.uint8))
        al = jnp.full((B,), SL, jnp.int32)
        bl = jnp.full((B,), SL, jnp.int32)
        t0 = t()
        got = sw_score_batch_pallas(a, al, b, bl, interpret=False)
        jax.device_get(got)
        compile_s = t() - t0
        ref = sw_score_batch(a, al, b, bl)[0]
        emit("sw_pallas", compiles=True,
             matches=bool(np.array_equal(np.asarray(got),
                                         np.asarray(ref))),
             compile_s=round(compile_s, 1))
    except Exception as e:  # noqa: BLE001
        emit("sw_pallas", compiles=False, error=str(e)[:300])


def probe_flagstat_blocks():
    """Pallas flagstat wire sweep at candidate VMEM block sizes (2^19
    exceeded scoped VMEM; is 2^18 inside it, and is it faster than the
    shipping 2^17?)."""
    import jax

    from adam_tpu.ops.flagstat import pack_flagstat_wire32
    from adam_tpu.ops.flagstat_pallas import _blocked_call

    rng = np.random.RandomState(0)
    n = 1 << 24                       # 16M reads resident
    wire = pack_flagstat_wire32(
        rng.randint(0, 1 << 12, size=n).astype(np.uint16),
        rng.randint(0, 61, size=n).astype(np.uint8),
        rng.randint(0, 24, size=n).astype(np.int16),
        rng.randint(0, 24, size=n).astype(np.int16),
        np.ones(n, bool))
    for rows in (128, 256, 512):      # x1024 lanes = 2^17..2^19 words
        B = rows * 1024
        w3 = jax.device_put(wire[:(n // B) * B].reshape(-1, rows, 1024))
        try:
            f = jax.jit(lambda a, _r=rows: _blocked_call(a,
                                                         interpret=False))
            t0 = t()
            jax.device_get(f(w3))
            compile_s = t() - t0
            k = 32
            t0 = t()
            for _ in range(k):
                out = f(w3)
            jax.device_get(out)
            per = (t() - t0) / k
            emit("flagstat_block", rows=rows,
                 compile_s=round(compile_s, 1),
                 greads_per_sec=round((n // B) * B / per / 1e9, 2))
        except Exception as e:  # noqa: BLE001
            emit("flagstat_block", rows=rows, error=str(e)[:200])




def probe_count_pallas():
    """Round-4: the packed-word Pallas count kernel on chip — bf16 vs
    int8 one-hots, and the BLOCK_ELEMS sweep (DMA/grid amortization vs
    VMEM pressure).  This is the kernel the bqsr_race stage times at one
    shape; here we learn which shape to ship."""
    import jax

    from adam_tpu.bqsr import count_pallas as CP
    from adam_tpu.bqsr.table import RecalTable

    L, n_rg = 100, 4
    n = 500_000
    rt = RecalTable(n_read_groups=n_rg, max_read_len=L)
    args = _count_args(n, L, n_rg)
    word3, wbits3 = CP._pack_words(*args, n_qual_rg=rt.n_qual_rg,
                                   n_cycle=rt.n_cycle)
    q_rows = CP._round_up(rt.n_qual_rg, 8)
    cyc_bins = CP._round_up(rt.n_cycle, 128)
    n_elems = word3.size
    flat_w = word3.reshape(-1)
    flat_b = wbits3.reshape(-1)
    for block in (1024, 2048, 4096, 8192):
        nb = n_elems // block
        w3 = jax.device_put(flat_w[:nb * block].reshape(nb, 1, block))
        b3 = jax.device_put(flat_b[:nb * block].reshape(nb, 1, block))
        for int8 in (False, True):
            try:
                saved = CP.BLOCK_ELEMS
                CP.BLOCK_ELEMS = block
                t0 = t()
                out = CP._count_call(w3, b3, q_rows=q_rows,
                                     cyc_bins=cyc_bins, interpret=False,
                                     int8_mxu=int8)
                jax.device_get(out[0])
                compile_s = t() - t0
                k = 16
                t0 = t()
                for _ in range(k):
                    out = CP._count_call(w3, b3, q_rows=q_rows,
                                         cyc_bins=cyc_bins,
                                         interpret=False, int8_mxu=int8)
                jax.device_get(out[0][0, 0])
                per = (t() - t0) / k
                emit("count_pallas", block=block, int8=int8,
                     compile_s=round(compile_s, 1),
                     reads_per_sec=round(nb * block / L / per),
                     gelems_per_sec=round(nb * block / per / 1e9, 3))
            except Exception as e:  # noqa: BLE001
                emit("count_pallas", block=block, int8=int8,
                     error=str(e)[:200])
            finally:
                CP.BLOCK_ELEMS = saved


def probe_flagstat_v2():
    """Round-4: v1 vs v2 flagstat kernel, plus an attribution pair — a
    mask-only v2 (sums skipped) and a sum-only v2 (masks constant) — so
    the measurement says WHAT binds the sweep (VERDICT r3 #3: ">=25% of
    peak HBM or prove what binds")."""
    import jax

    from adam_tpu.ops.flagstat import pack_flagstat_wire32
    from adam_tpu.ops import flagstat_pallas as FP

    rng = np.random.RandomState(0)
    n = 1 << 24
    wire = pack_flagstat_wire32(
        rng.randint(0, 1 << 12, size=n).astype(np.uint16),
        rng.randint(0, 61, size=n).astype(np.uint8),
        rng.randint(0, 24, size=n).astype(np.int16),
        rng.randint(0, 24, size=n).astype(np.int16),
        np.ones(n, bool))

    def run(label, call, rows):
        B = rows * FP.LANES
        w3 = jax.device_put(wire[:(n // B) * B].reshape(-1, rows,
                                                        FP.LANES))
        try:
            f = jax.jit(lambda a: call(a, interpret=False))
            t0 = t()
            jax.device_get(f(w3))
            compile_s = t() - t0
            k = 32
            t0 = t()
            for _ in range(k):
                out = f(w3)
            jax.device_get(out)
            per = (t() - t0) / k
            emit("flagstat_v2", variant=label,
                 compile_s=round(compile_s, 1),
                 greads_per_sec=round((n // B) * B / per / 1e9, 2),
                 gbytes_per_sec=round((n // B) * B * 4 / per / 1e9, 1))
        except Exception as e:  # noqa: BLE001
            emit("flagstat_v2", variant=label, error=str(e)[:200])

    run("v1", FP._blocked_call, FP.BLOCK_ROWS)
    run("v2", FP._blocked_call_v2, FP.V2_ROWS)

    # attribution variants: same grid/DMA, reduced in-kernel work
    from jax.experimental import pallas as pl
    import jax.numpy as jnp

    def make_stub(body):
        def kern(wire_ref, acc_ref):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _init():
                acc_ref[...] = jnp.zeros_like(acc_ref)
            body(wire_ref, acc_ref)

        def call(wire3d, *, interpret):
            from adam_tpu.platform import pallas_tpu_compiler_params
            n_blk, rows, lanes = wire3d.shape
            return pl.pallas_call(
                kern, grid=(n_blk,),
                in_specs=[pl.BlockSpec((None, rows, lanes),
                                       lambda i: (i, 0, 0))],
                out_specs=pl.BlockSpec((36, FP.LANES), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((36, FP.LANES), jnp.int32),
                compiler_params=pallas_tpu_compiler_params(
                    dimension_semantics=("arbitrary",)),
                interpret=interpret)(wire3d)
        return call

    def dma_only(wire_ref, acc_ref):
        # touch the block once: one select+sum, no indicator masks
        acc_ref[0, :] += jnp.sum(wire_ref[...].astype(jnp.int32) & 1,
                                 axis=0)

    def masks_only(wire_ref, acc_ref):
        # all 18 indicators + pf pack, but a single lane-sum at the end
        inds, passed, failed = FP._wire_masks(wire_ref[...])
        pf = passed.astype(jnp.int32) + (failed.astype(jnp.int32) << 16)
        total = pf
        for ind in inds:
            total = total ^ jnp.where(ind, pf, 0)   # mask cost, no sums
        acc_ref[0, :] += jnp.sum(total, axis=0)

    run("dma_only", make_stub(dma_only), FP.V2_ROWS)
    run("masks_only", make_stub(masks_only), FP.V2_ROWS)


PROBES = {
    "1": ("scan_knee", probe_scan_knee),
    "2": ("count_backends", probe_backends),
    "3": ("apply", probe_apply),
    "4": ("pallas", probe_pallas_kernels),
    "5": ("flagstat_blocks", probe_flagstat_blocks),
    "6": ("count_pallas", probe_count_pallas),
    "7": ("flagstat_v2", probe_flagstat_v2),
}


def main():
    ap = argparse.ArgumentParser()
    # priority order for a window that may die mid-suite: the flagstat
    # v2 roofline (VERDICT r4 #3) first, the r5 LUT-apply race second,
    # then the count kernels and the exploratory sweeps
    ap.add_argument("--only", default="7,3,6,4,5,2,1",
                    help="comma-separated probe ids, run order")
    args = ap.parse_args()
    from adam_tpu.platform import honor_platform_env
    honor_platform_env()      # the axon plugin ignores bare JAX_PLATFORMS;
    #                           without this a CPU debug run hangs on the
    #                           (possibly dead) tunnel instead
    import jax
    d = jax.devices()[0]
    emit("env", device_kind=getattr(d, "device_kind", "?"),
         platform=d.platform)
    for pid in args.only.split(","):
        name, fn = PROBES[pid.strip()]
        t0 = t()
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            emit(name, fatal=str(e)[:300])
        emit(name + "_done", wall_s=round(t() - t0, 1))


if __name__ == "__main__":
    main()
