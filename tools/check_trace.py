#!/usr/bin/env python3
"""Validate an adam-tpu Chrome-trace timeline (the ``-trace`` output).

The replay-validator convention of tools/check_executor.py and
tools/check_resilience.py, applied to the tracing plane
(adam_tpu/obs/trace.py, docs/OBSERVABILITY.md): the file a run wrote
must be loadable by Perfetto AND internally consistent — timeline bugs
(negative durations, cross-thread stack corruption, unsorted lanes)
show up here before anyone burns time staring at a garbled UI.

Contract checked:

* the file is a JSON object with a ``traceEvents`` list (the Chrome
  Trace Event Format container adam-tpu writes; ``displayTimeUnit``
  optional);
* every event is an object with a string ``name`` and a ``ph`` in
  {X, i, C, M}; non-metadata events carry numeric ``ts`` and int
  ``pid``/``tid``;
* ``X`` (complete-span) events carry ``dur >= 0``;
* per (pid, tid) lane, ``X`` events appear in non-decreasing ``ts``
  order (the writer sorts; an unsorted lane means a merge bug);
* per lane, spans NEST or are DISJOINT — a span that partially overlaps
  another on the same lane is exactly the corruption the old shared
  stage stack produced, and the thing the thread-aware stack exists to
  prevent ("balanced begin/end" in complete-event form);
* at least one ``X`` event exists (an empty timeline is a wiring bug,
  not a valid artifact).

Usage::

    python tools/check_trace.py RUN.trace.json [...]

Exit 0 when every file validates; 1 otherwise, one error line per
violation.  Used by tests/test_trace.py so the documented format and
the produced format cannot drift.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Tuple

_PHASES = ("X", "i", "C", "M")
_NUM = (int, float)


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def _is_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def validate(path: str) -> List[str]:
    """Return human-readable violations (empty = valid timeline)."""
    errs: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    except ValueError as e:
        return [f"{path}: invalid JSON (torn write?): {e}"]
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("traceEvents"), list):
        return [f"{path}: not a Chrome-trace document "
                "(object with a 'traceEvents' list)"]

    lanes: Dict[Tuple, List[Tuple[float, float, str]]] = {}
    n_spans = 0
    for i, ev in enumerate(doc["traceEvents"]):
        where = f"{path}: traceEvents[{i}]"
        if not isinstance(ev, dict):
            errs.append(f"{where}: event is not an object")
            continue
        ph = ev.get("ph")
        if ph not in _PHASES:
            errs.append(f"{where}: unknown phase {ph!r}")
            continue
        if not isinstance(ev.get("name"), str):
            errs.append(f"{where}: missing string 'name'")
        if ph == "M":
            continue            # metadata carries no clock
        if not _is_num(ev.get("ts")):
            errs.append(f"{where}: missing numeric 'ts'")
            continue
        if not (_is_int(ev.get("pid")) and _is_int(ev.get("tid"))):
            errs.append(f"{where}: missing int 'pid'/'tid'")
            continue
        if ph != "X":
            continue
        n_spans += 1
        dur = ev.get("dur")
        if not (_is_num(dur) and dur >= 0):
            errs.append(f"{where}: X event missing non-negative 'dur'")
            continue
        lane = (ev["pid"], ev["tid"])
        seq = lanes.setdefault(lane, [])
        if seq and ev["ts"] < seq[-1][0]:
            errs.append(f"{where}: lane {lane} timestamps regress "
                        f"({ev['ts']} after {seq[-1][0]} — unsorted "
                        "lane, merge bug)")
        seq.append((float(ev["ts"]), float(ev["ts"]) + float(dur),
                    ev.get("name", "?")))

    # span nesting per lane: walking starts in ts order, an open-span
    # stack catches partial overlap — the complete-event form of
    # "balanced begin/end"
    for lane, seq in lanes.items():
        stack: List[Tuple[float, float, str]] = []
        # equal-start ties order the LONGER span first (the parent): a
        # child sharing its parent's start must stack under it
        for ts, te, name in sorted(seq, key=lambda x: (x[0], -x[1])):
            while stack and ts >= stack[-1][1] - 1e-9:
                stack.pop()
            if stack and te > stack[-1][1] + 1e-6:
                errs.append(
                    f"{path}: lane {lane}: span {name!r} "
                    f"[{ts:.1f}, {te:.1f}] partially overlaps enclosing "
                    f"{stack[-1][2]!r} [.., {stack[-1][1]:.1f}] — "
                    "mis-nested spans (the shared-stage-stack bug)")
            stack.append((ts, te, name))

    if not errs and n_spans == 0:
        errs.append(f"{path}: no spans (X events) — an empty timeline "
                    "is a wiring bug, not evidence")
    return errs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: check_trace.py RUN.trace.json [...]",
              file=sys.stderr)
        return 2
    bad = 0
    for path in argv:
        errors = validate(path)
        if errors:
            bad += 1
            for e in errors:
                print(e, file=sys.stderr)
        else:
            with open(path) as f:
                doc = json.load(f)
            evs = doc["traceEvents"]
            lanes = {(e.get("pid"), e.get("tid")) for e in evs
                     if e.get("ph") == "X"}
            print(f"{path}: ok ({sum(1 for e in evs if e.get('ph') == 'X')}"
                  f" spans across {len(lanes)} lanes)")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
