#!/usr/bin/env python3
"""Validate an adam-tpu evidence ledger file (schema 1).

The ledger (default ``EVIDENCE_LEDGER.json``) is produced by
``adam_tpu.evidence.ledger`` — bench.py records every captured stage
into it, merged keep-best across tunnel windows; tools/tpu_watch.py
reads it to re-enter windows with only the missing stages.  Format
documented in docs/EVIDENCE.md; this validator is the drift guard
(mirroring tools/check_metrics.py for the telemetry sidecars).

Contract checked here:

* the document is a JSON object with ``schema == 1``, an ``updated_at``
  string, a ``stages`` object, and a ``probes`` list;
* every stage record carries: ``stage`` (str, matching its key),
  ``platform`` (str), ``result_digest`` (hex str, >= 8 chars),
  ``window_id`` (non-empty str), ``captured_at`` (str), ``payload``
  (object), plus ``wire_bytes`` (int >= 0 or null), ``wall_s`` (number
  >= 0 or null) and ``link_bytes_per_sec`` (number > 0 or null);
* a stage whose payload is a skip marker must not have been recorded;
* every probe record carries: ``window_id``/``captured_at`` strings,
  ``rtt_ms`` (number >= 0), ``repeat_matmul_tflops`` (list of >= 1
  numbers), ``matmul_tflops`` (number or null),
  ``chain_linearity_residual`` (number >= 0 or null),
  ``calibration_tflops`` (number), ``calibration_deviation`` (number
  or null) and ``calibration_deviation_flag`` (bool) — the
  self-diagnosing fields a partial window artifact explains itself
  with;
* a ledger with captured stages must hold at least one probe record
  (evidence without window health context is unadjudicatable).

Usage::

    python tools/check_evidence.py EVIDENCE_LEDGER.json [...]

Exit 0 when every file validates; 1 otherwise, one error line per
violation.  Run in CI by tests/test_check_evidence.py against both a
synthesized ledger and a real CPU bench.py invocation.
"""

from __future__ import annotations

import json
import sys
from typing import List

SCHEMA_VERSION = 1

_NUM = (int, float)


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def _is_hex(v) -> bool:
    return (isinstance(v, str) and len(v) >= 8 and
            all(c in "0123456789abcdef" for c in v))


def _check_stage(errs, path, name, rec) -> None:
    def err(msg):
        errs.append(f"{path}: stages[{name!r}]: {msg}")

    if not isinstance(rec, dict):
        err("record is not an object")
        return
    if rec.get("stage") != name:
        err(f"stage field {rec.get('stage')!r} != key")
    if not isinstance(rec.get("platform"), str) or not rec.get("platform"):
        err("missing non-empty string 'platform'")
    if not _is_hex(rec.get("result_digest")):
        err("result_digest is not a hex digest")
    if not isinstance(rec.get("window_id"), str) or not rec.get("window_id"):
        err("missing non-empty string 'window_id'")
    if not isinstance(rec.get("captured_at"), str):
        err("missing string 'captured_at'")
    payload = rec.get("payload")
    if not isinstance(payload, dict):
        err("missing object 'payload'")
    elif any(k == "skipped" or k.endswith("_skipped") for k in payload):
        err("skip-marker payload recorded as evidence")
    wb = rec.get("wire_bytes")
    if wb is not None and not (isinstance(wb, int) and
                               not isinstance(wb, bool) and wb >= 0):
        err("wire_bytes is not a non-negative int or null")
    ws = rec.get("wall_s")
    if ws is not None and not (_is_num(ws) and ws >= 0):
        err("wall_s is not a non-negative number or null")
    lr = rec.get("link_bytes_per_sec")
    if lr is not None and not (_is_num(lr) and lr > 0):
        err("link_bytes_per_sec is not a positive number or null")


def _check_probe(errs, path, i, rec) -> None:
    def err(msg):
        errs.append(f"{path}: probes[{i}]: {msg}")

    if not isinstance(rec, dict):
        err("record is not an object")
        return
    for field in ("window_id", "captured_at"):
        if not isinstance(rec.get(field), str) or not rec.get(field):
            err(f"missing non-empty string {field!r}")
    if not (_is_num(rec.get("rtt_ms")) and rec["rtt_ms"] >= 0):
        err("missing non-negative 'rtt_ms'")
    samples = rec.get("repeat_matmul_tflops")
    if not (isinstance(samples, list) and len(samples) >= 1 and
            all(_is_num(s) for s in samples)):
        err("repeat_matmul_tflops is not a non-empty number list")
    mt = rec.get("matmul_tflops")
    if mt is not None and not _is_num(mt):
        err("matmul_tflops is not a number or null")
    resid = rec.get("chain_linearity_residual")
    if resid is not None and not (_is_num(resid) and resid >= 0):
        err("chain_linearity_residual is not a non-negative number "
            "or null")
    if not _is_num(rec.get("calibration_tflops")):
        err("missing numeric 'calibration_tflops'")
    dev = rec.get("calibration_deviation")
    if dev is not None and not _is_num(dev):
        err("calibration_deviation is not a number or null")
    if not isinstance(rec.get("calibration_deviation_flag"), bool):
        err("missing boolean 'calibration_deviation_flag'")


def validate(path: str) -> List[str]:
    """Return a list of human-readable schema violations (empty = valid)."""
    errs: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    except ValueError as e:
        return [f"{path}: invalid JSON: {e}"]
    if not isinstance(doc, dict):
        return [f"{path}: document is not a JSON object"]
    if doc.get("schema") != SCHEMA_VERSION:
        errs.append(f"{path}: schema {doc.get('schema')!r} != "
                    f"{SCHEMA_VERSION}")
    if not isinstance(doc.get("updated_at"), str):
        errs.append(f"{path}: missing string 'updated_at'")
    stages = doc.get("stages")
    if not isinstance(stages, dict):
        errs.append(f"{path}: missing 'stages' object")
        stages = {}
    probes = doc.get("probes")
    if not isinstance(probes, list):
        errs.append(f"{path}: missing 'probes' list")
        probes = []
    for name, rec in stages.items():
        _check_stage(errs, path, name, rec)
    for i, rec in enumerate(probes):
        _check_probe(errs, path, i, rec)
    if stages and not probes:
        errs.append(f"{path}: captured stages but no probe records — "
                    f"evidence lacks window health context")
    return errs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: check_evidence.py EVIDENCE_LEDGER.json [...]",
              file=sys.stderr)
        return 2
    bad = 0
    for path in argv:
        errors = validate(path)
        if errors:
            bad += 1
            for e in errors:
                print(e, file=sys.stderr)
        else:
            with open(path) as f:
                doc = json.load(f)
            n_tpu = sum(1 for r in doc.get("stages", {}).values()
                        if isinstance(r, dict) and
                        r.get("platform") == "tpu")
            print(f"{path}: ok ({len(doc.get('stages', {}))} stages, "
                  f"{n_tpu} on-chip, {len(doc.get('probes', []))} "
                  f"probes, schema {SCHEMA_VERSION})")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
