#!/usr/bin/env python3
"""Validate an adam-tpu time-series file (``series.jsonl``, schema 1).

The replay-validator convention of tools/check_metrics.py and
tools/check_trace.py, applied to the sampling plane
(adam_tpu/obs/series.py, docs/OBSERVABILITY.md): the rows a serve run
sampled must be loadable AND obey the laws the fleet merge relies on —
each row is a CUMULATIVE registry snapshot (an exact monoid element),
so counters may never decrease, sequence numbers may never repeat, and
folding a row into the empty snapshot must reproduce the row exactly.

Contract checked:

* line 1 is the ``series_manifest``: ``schema == 1``, numeric ``t0``,
  ``interval_s > 0``, ``max_rows >= 1``, ``source`` an object;
* every other line is a ``sample`` row: ``schema == 1``, numeric ``t``,
  int ``seq >= 0``, int ``dropped >= 0``, and a ``metrics`` snapshot
  object with ``counters``/``gauges``/``histograms`` maps;
* per source, ``t`` is non-decreasing, ``seq`` strictly increasing and
  ``dropped`` non-decreasing (rows drop oldest-first, never uncount);
* counters are numeric and >= 0, and NON-DECREASING across a source's
  rows (cumulative snapshots — the monoid law the sidecar merge
  assumes); gauges are numeric;
* histograms are internally consistent (``count`` == sum of bucket
  counts, ``count``/``sum`` non-decreasing per source, ``min <= max``
  when count > 0);
* merging any row into the empty snapshot reproduces the row
  (the monoid identity law, checked with a literal mirror of
  ``obs.series.merge_snapshots`` — this file imports nothing from the
  package, like every validator here);
* a torn FINAL line is tolerated (a SIGKILL'd writer's tail is exactly
  the artifact this plane exists to survive); a torn middle line is a
  corruption error.

Usage::

    python tools/check_series.py SPOOL/series.jsonl [...]

Exit 0 when every file validates; 1 otherwise, one error line per
violation.  Used by tests/test_series.py so the documented schema and
the produced schema cannot drift.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List

_NUM = (int, float)


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def _is_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _merge(a: dict, b: dict) -> dict:
    """Literal mirror of adam_tpu.obs.series.merge_snapshots (counters
    sum, gauges max, histograms fold) — kept import-free like
    check_metrics' _FAULT_SITES mirror."""
    out = json.loads(json.dumps(a))        # deep copy via round-trip
    for name, v in (b.get("counters") or {}).items():
        out.setdefault("counters", {})
        out["counters"][name] = out["counters"].get(name, 0) + v
    for name, v in (b.get("gauges") or {}).items():
        out.setdefault("gauges", {})
        prev = out["gauges"].get(name)
        out["gauges"][name] = v if prev is None else max(prev, v)
    for name, h in (b.get("histograms") or {}).items():
        out.setdefault("histograms", {})
        o = out["histograms"].get(name)
        if o is None:
            out["histograms"][name] = json.loads(json.dumps(h))
            continue
        o["count"] = o.get("count", 0) + h.get("count", 0)
        o["sum"] = o.get("sum", 0) + h.get("sum", 0)
        for k in ("min",):
            if h.get(k) is not None:
                o[k] = h[k] if o.get(k) is None else min(o[k], h[k])
        for k in ("max",):
            if h.get(k) is not None:
                o[k] = h[k] if o.get(k) is None else max(o[k], h[k])
        for bk, bc in (h.get("buckets") or {}).items():
            o.setdefault("buckets", {})
            o["buckets"][bk] = o["buckets"].get(bk, 0) + bc
    return out


def _check_snapshot(where: str, m, errs: List[str]) -> None:
    if not isinstance(m, dict):
        errs.append(f"{where}: 'metrics' is not a snapshot object")
        return
    for sect in ("counters", "gauges", "histograms"):
        if not isinstance(m.get(sect), dict):
            errs.append(f"{where}: snapshot missing {sect!r} map")
            return
    for name, v in m["counters"].items():
        if not (_is_num(v) and v >= 0):
            errs.append(f"{where}: counter {name!r} not a "
                        "non-negative number")
    for name, v in m["gauges"].items():
        if not _is_num(v):
            errs.append(f"{where}: gauge {name!r} not numeric")
    for name, h in m["histograms"].items():
        if not isinstance(h, dict):
            errs.append(f"{where}: histogram {name!r} not an object")
            continue
        count = h.get("count")
        if not (_is_int(count) and count >= 0):
            errs.append(f"{where}: histogram {name!r} missing "
                        "non-negative int 'count'")
            continue
        buckets = h.get("buckets") or {}
        if isinstance(buckets, dict) and \
                sum(buckets.values()) != count:
            errs.append(f"{where}: histogram {name!r} count {count} "
                        f"!= bucket total {sum(buckets.values())}")
        if count > 0 and _is_num(h.get("min")) and \
                _is_num(h.get("max")) and h["min"] > h["max"]:
            errs.append(f"{where}: histogram {name!r} min > max")


def validate(path: str) -> List[str]:
    """Return human-readable violations (empty = valid series)."""
    errs: List[str] = []
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    lines = [ln for ln in lines if ln.strip()]
    if not lines:
        return [f"{path}: empty file — a published series always "
                "holds its manifest row"]

    docs: List[Dict] = []
    for i, ln in enumerate(lines, 1):
        try:
            d = json.loads(ln)
        except ValueError:
            if i == len(lines):
                continue        # torn tail of a killed writer: fine
            errs.append(f"{path}:{i}: invalid JSON mid-file "
                        "(corruption, not a crash tail)")
            continue
        if not isinstance(d, dict):
            errs.append(f"{path}:{i}: line is not a JSON object")
            continue
        docs.append({"i": i, "d": d})

    if not docs:
        return errs or [f"{path}: no parseable rows"]
    first = docs[0]["d"]
    if first.get("kind") != "series_manifest":
        errs.append(f"{path}:1: first row is {first.get('kind')!r}, "
                    "not the 'series_manifest'")
    else:
        if first.get("schema") != 1:
            errs.append(f"{path}:1: manifest schema "
                        f"{first.get('schema')!r} != 1")
        if not _is_num(first.get("t0")):
            errs.append(f"{path}:1: manifest missing numeric 't0'")
        if not (_is_num(first.get("interval_s"))
                and first["interval_s"] > 0):
            errs.append(f"{path}:1: manifest missing positive "
                        "'interval_s'")
        if not (_is_int(first.get("max_rows"))
                and first["max_rows"] >= 1):
            errs.append(f"{path}:1: manifest missing int "
                        "'max_rows' >= 1")
        if not isinstance(first.get("source"), dict):
            errs.append(f"{path}:1: manifest missing 'source' object")
        docs = docs[1:]

    # per-source row laws: time/seq/dropped ordering + cumulative
    # counters (the monoid law the fleet fold assumes)
    last: Dict[str, dict] = {}
    n_samples = 0
    for rec in docs:
        i, d = rec["i"], rec["d"]
        where = f"{path}:{i}"
        if d.get("kind") != "sample":
            errs.append(f"{where}: unknown row kind {d.get('kind')!r}")
            continue
        n_samples += 1
        if d.get("schema") != 1:
            errs.append(f"{where}: sample schema "
                        f"{d.get('schema')!r} != 1")
        if not _is_num(d.get("t")):
            errs.append(f"{where}: sample missing numeric 't'")
            continue
        if not (_is_int(d.get("seq")) and d["seq"] >= 0):
            errs.append(f"{where}: sample missing non-negative int "
                        "'seq'")
            continue
        if not (_is_int(d.get("dropped")) and d["dropped"] >= 0):
            errs.append(f"{where}: sample missing non-negative int "
                        "'dropped'")
            continue
        _check_snapshot(where, d.get("metrics"), errs)
        m = d.get("metrics") if isinstance(d.get("metrics"), dict) \
            else {"counters": {}, "gauges": {}, "histograms": {}}

        src = json.dumps(d.get("source"), sort_keys=True)
        prev = last.get(src)
        if prev is not None:
            if d["t"] < prev["t"]:
                errs.append(f"{where}: time regresses ({d['t']} after "
                            f"{prev['t']} for source {src})")
            if d["seq"] <= prev["seq"]:
                errs.append(f"{where}: seq not strictly increasing "
                            f"({d['seq']} after {prev['seq']})")
            if d["dropped"] < prev["dropped"]:
                errs.append(f"{where}: 'dropped' decreases "
                            f"({d['dropped']} after {prev['dropped']}"
                            ") — drops are cumulative")
            pm = prev["m"]
            for name, v in (pm.get("counters") or {}).items():
                cur = (m.get("counters") or {}).get(name)
                if _is_num(cur) and _is_num(v) and cur < v:
                    errs.append(
                        f"{where}: counter {name!r} decreases "
                        f"({cur} after {v}) — rows must be cumulative "
                        "snapshots (the monoid law)")
            for name, h in (pm.get("histograms") or {}).items():
                cur = (m.get("histograms") or {}).get(name)
                if isinstance(cur, dict) and isinstance(h, dict) and \
                        _is_int(cur.get("count")) and \
                        _is_int(h.get("count")) and \
                        cur["count"] < h["count"]:
                    errs.append(f"{where}: histogram {name!r} count "
                                "decreases — rows must be cumulative")
        last[src] = {"t": d["t"], "seq": d["seq"],
                     "dropped": d["dropped"], "m": m}

        # monoid identity: empty ∪ row == row
        empty = {"counters": {}, "gauges": {}, "histograms": {}}
        if json.dumps(_merge(empty, m), sort_keys=True) != \
                json.dumps(m, sort_keys=True):
            errs.append(f"{where}: merge(empty, row) != row — the "
                        "snapshot violates the merge identity law")

    if not errs and n_samples == 0:
        errs.append(f"{path}: no sample rows — a published series "
                    "holds at least its stop()-time sample")
    return errs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: check_series.py SPOOL/series.jsonl [...]",
              file=sys.stderr)
        return 2
    bad = 0
    for path in argv:
        errors = validate(path)
        if errors:
            bad += 1
            for e in errors:
                print(e, file=sys.stderr)
        else:
            n = 0
            srcs = set()
            with open(path, encoding="utf-8", errors="replace") as f:
                for ln in f:
                    try:
                        d = json.loads(ln)
                    except ValueError:
                        continue
                    if isinstance(d, dict) and d.get("kind") == \
                            "sample":
                        n += 1
                        srcs.add(json.dumps(d.get("source"),
                                            sort_keys=True))
            print(f"{path}: ok ({n} sample(s) from {len(srcs)} "
                  "source(s))")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
