#!/usr/bin/env python3
"""Replay a telemetry sidecar's fault firings and retry decisions and
assert they are deterministic.

The resilience plane's two decision functions
(adam_tpu/resilience/faults.py ``decide_fault``,
adam_tpu/resilience/retry.py ``decide_retry``) are PURE functions of
their inputs, and every ``fault_injected`` / ``retry_attempt`` event
records those inputs verbatim plus a digest of them.  This checker
re-derives each recorded decision offline and fails when:

* replaying ``decide_fault(**inputs)`` does not fire, fires a different
  fault, or picks a different rule than the event recorded (the plane
  drifted from purity — e.g. someone added a clock or random read);
* replaying ``decide_retry(**inputs)`` yields a different action or
  delay than the event recorded (the policy drifted);
* a recorded ``input_digest`` does not match the digest of the recorded
  inputs (the event lied about what it decided from);
* two events — within one file or across files — share an
  ``input_digest`` but disagree on the decision (same inputs must mean
  the same firing/action, the determinism contract the chaos matrix
  pins).

Usage::

    python tools/check_resilience.py RUN.metrics.jsonl [...]

Exit 0 when every recorded decision replays identically; 1 otherwise
with one line per violation.  Companion to tools/check_metrics.py
(which validates the event SCHEMA; this validates the event's
semantics) and tools/check_executor.py (the same convention for the
executor's plans).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Tuple

# runnable as a script from anywhere (same repo-root shim as aot_check)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the decision fields a replay must reproduce exactly, per event kind
FAULT_FIELDS = ("fault", "rule")
RETRY_FIELDS = ("action", "delay_s")


def _events(path: str, kinds: tuple) -> List[Tuple[int, dict]]:
    out = []
    with open(path) as f:
        for i, ln in enumerate(f, 1):
            if not ln.strip():
                continue
            try:
                doc = json.loads(ln)
            except ValueError:
                continue        # schema problems are check_metrics' job
            if isinstance(doc, dict) and doc.get("event") in kinds:
                out.append((i, doc))
    return out


def _check_one(path, i, ev, replay_fn, fields, errs, by_digest, kind):
    inputs = ev.get("inputs")
    if not isinstance(inputs, dict):
        errs.append(f"{path}:{i}: {kind} event carries no inputs — "
                    "decision cannot be replayed")
        return False
    try:
        d = replay_fn(**inputs)
    except TypeError as e:
        errs.append(f"{path}:{i}: inputs do not replay through "
                    f"{kind}: {e}")
        return False
    for field in fields:
        if ev.get(field) != d.get(field):
            errs.append(
                f"{path}:{i}: non-deterministic {kind} decision — "
                f"recorded {field}={ev.get(field)!r}, replay yields "
                f"{d.get(field)!r}")
    if kind == "fault" and not d.get("fire"):
        errs.append(f"{path}:{i}: recorded firing does not fire on "
                    "replay — the plane decided from something beyond "
                    "its recorded inputs")
    if ev.get("input_digest") != d.get("input_digest"):
        errs.append(
            f"{path}:{i}: input_digest mismatch (recorded "
            f"{ev.get('input_digest')!r}, inputs digest to "
            f"{d.get('input_digest')!r})")
    # cross-event/cross-file: one digest, one decision
    decision = {f: ev.get(f) for f in fields}
    dig = ev.get("input_digest")
    if isinstance(dig, str):
        seen = by_digest.get((kind, dig))
        if seen is None:
            by_digest[(kind, dig)] = (path, i, decision)
        elif seen[2] != decision:
            errs.append(
                f"{path}:{i}: digest {dig} decided differently than "
                f"{seen[0]}:{seen[1]} — same inputs must yield the "
                "same decision")
    return True


def check(paths: List[str]) -> List[str]:
    """Replay every recorded firing/policy decision; return
    human-readable violations (empty = deterministic)."""
    from adam_tpu.resilience.faults import decide_fault
    from adam_tpu.resilience.retry import decide_retry

    errs: List[str] = []
    by_digest: Dict[tuple, Tuple[str, int, dict]] = {}
    n_checked = 0
    for path in paths:
        faults = _events(path, ("fault_injected",))
        retries = _events(path, ("retry_attempt",))
        if not faults and not retries:
            errs.append(f"{path}: no fault_injected/retry_attempt "
                        "events (not a faulted run, or events were "
                        "lost)")
            continue
        for i, ev in faults:
            if _check_one(path, i, ev, decide_fault, FAULT_FIELDS,
                          errs, by_digest, "fault"):
                n_checked += 1
        for i, ev in retries:
            if _check_one(path, i, ev, decide_retry, RETRY_FIELDS,
                          errs, by_digest, "retry"):
                n_checked += 1
    if not errs and not n_checked:
        errs.append("no replayable resilience decisions found")
    return errs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: check_resilience.py RUN.metrics.jsonl [...]",
              file=sys.stderr)
        return 2
    errors = check(argv)
    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        return 1
    n = sum(len(_events(p, ("fault_injected", "retry_attempt")))
            for p in argv)
    print(f"ok: {n} resilience decision(s) replayed deterministically "
          f"across {len(argv)} file(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
