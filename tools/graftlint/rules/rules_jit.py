"""GL002 jit-memoization: compile constructors only at module scope or
behind a memoizer.

Originating bug class: the PR 10 warm-path recompile leak —
``flagstat_wire32_sharded`` rebuilt a fresh ``jax.jit`` wrapper per
call, so every serve-mode job recompiled kernels the previous job had
already compiled (jit caches hang off the wrapper OBJECT, not the
traced function).  The fix was ``functools.lru_cache`` per (mesh,
donate); this rule keeps the next per-chunk/per-job constructor from
shipping.

A compile constructor (``jax.jit(...)``, ``pl.pallas_call(...)``) may
appear:

* at module scope — including decorator position
  (``@partial(jax.jit, ...)`` executes at import time);
* inside a function decorated with ``functools.lru_cache`` /
  ``functools.cache`` (the memoization-helper convention:
  ``flagstat_wire32_sharded``, ``_build_resharder``,
  ``_donating_count_fn``...);
* inside a function that is itself jit-compiled at module scope (a
  ``pallas_call`` in a kernel body traces once per shape through the
  module-scope wrapper).

Anywhere else is a per-call wrapper: the jit cache dies with the
wrapper and the warm path recompiles.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..engine import Finding, FuncInfo, Module, Repo

ID = "GL002"
NAME = "jit-memoization"

_CONSTRUCTORS = {
    "jax.jit",
    "jax.experimental.pallas.pallas_call",
    "jax.experimental.pallas.tpu.pallas_call",
}
_MEMOIZERS = {"functools.lru_cache", "functools.cache"}


def _decorated_with(m: Module, fn: FuncInfo, targets: set) -> bool:
    """True when any decorator is one of *targets*, directly or via
    ``partial(<target>, ...)``."""
    for dec in fn.node.decorator_list:
        d = m.resolve(m.dotted(dec))
        if d in targets:
            return True
        if isinstance(dec, ast.Call):
            d = m.resolve(m.dotted(dec.func))
            if d in targets:
                return True
            if d == "functools.partial" and dec.args:
                a0 = m.resolve(m.dotted(dec.args[0]))
                if a0 in targets:
                    return True
    return False


def _deco_allowed(m: Module, fn: Optional[FuncInfo]) -> bool:
    while fn is not None:
        if _decorated_with(m, fn, _MEMOIZERS):
            return True
        if _decorated_with(m, fn, {"jax.jit"}):
            # the kernel body itself; the module-scope jit wrapper owns
            # the cache
            return True
        fn = fn.parent
    return False


class _CallSites:
    """Where is each function called from, across the scan set?
    (modules that file-disable this rule are excluded — their call
    sites are exempt by declaration)."""

    def __init__(self, repo: Repo):
        self.sites: dict = {}   # (mod_dotted, leaf) and ("", leaf) keys
        for m in repo.modules:
            if ID in m.file_disables:
                continue
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = m.dotted(node.func)
                if not d:
                    continue
                leaf = d.split(".")[-1]
                enc = m.enclosing(node)
                if "." not in d:
                    self.sites.setdefault((m.rel, leaf),
                                          []).append((m, enc))
                    # a bare name may be a cross-module import
                    # (`from .helper import _h; _h(c)`): also key it
                    # under the resolved target so of() finds the
                    # caller from the DEFINING module's side
                    r = m.resolve(d)
                    if r and r != d:
                        self.sites.setdefault(("*", r),
                                              []).append((m, enc))
                else:
                    r = m.resolve(d) or d
                    self.sites.setdefault(("*", r), []).append((m, enc))

    def of(self, m: Module, fn: FuncInfo) -> list:
        leaf = fn.qualname.split(".")[-1]
        mod_dotted = m.rel[:-3].replace("/", ".")
        if mod_dotted.endswith(".__init__"):
            # importers say `from pkg import fn`, not pkg.__init__.fn
            mod_dotted = mod_dotted[: -len(".__init__")]
        out = list(self.sites.get((m.rel, leaf), []))
        out += self.sites.get(("*", f"{mod_dotted}.{leaf}"), [])
        return out


def _site_allowed(m: Module, fn: FuncInfo, sites: _CallSites) -> bool:
    """Allowed by decorator on the enclosing chain, or — for a plain
    helper — because EVERY call site in the scan set is inside a
    decorator-allowed function (the ``_blocked_call`` shape: a
    pallas_call helper only ever invoked from module-scope-jitted
    wrappers).  Deliberately ONE hop: a chain of plain callers rooted
    at a module-scope ``main()`` must not bless a per-chunk
    constructor — that is exactly the warm-path leak."""
    if _deco_allowed(m, fn):
        return True
    callers = sites.of(m, fn)
    return bool(callers) and all(
        cfn is not None and _deco_allowed(cm, cfn)
        for cm, cfn in callers)


def check(repo: Repo) -> Iterable[Finding]:
    findings: List[Finding] = []
    sites = _CallSites(repo)
    for m in repo.modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            t = m.resolve(m.dotted(node.func))
            if t not in _CONSTRUCTORS:
                continue
            fn = m.enclosing(node)
            if fn is None or _site_allowed(m, fn, sites):
                continue
            findings.append(Finding(
                rule=ID, name=NAME, path=m.rel, line=node.lineno,
                symbol=fn.qualname,
                message=(f"{t.split('.')[-1]} constructed inside "
                         f"{fn.qualname}, which is neither module-scope "
                         "nor memoized — a fresh wrapper per call "
                         "recompiles on every warm-path invocation "
                         "(the PR 10 serve recompile leak)"),
                hint="decorate the constructor with "
                     "functools.lru_cache keyed on hashable args "
                     "(mesh hashes by devices+axes; see "
                     "ops/flagstat.flagstat_wire32_sharded), or hoist "
                     "the jit to module scope"))
    return findings
