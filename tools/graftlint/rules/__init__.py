"""Rule registry.  Each rule module exposes ``ID`` ("GL00X"), ``NAME``
(kebab-case slug), and ``check(repo) -> Iterable[Finding]``.  The
catalog — with the shipped bug that motivated each rule — lives in
docs/STATIC_ANALYSIS.md; adding a rule = adding a module here plus
fixture twins under tests/resources/graftlint/."""

from . import (rules_decider, rules_durable, rules_events, rules_faults,
               rules_jit, rules_race)

RULES = {mod.ID: mod for mod in (
    rules_decider,   # GL001 decider-purity
    rules_jit,       # GL002 jit-memoization
    rules_durable,   # GL003 durable-write discipline
    rules_events,    # GL004 event-schema drift
    rules_faults,    # GL005 fault-site drift
    rules_race,      # GL006 static stage/race detector
)}

RULES_BY_NAME = {mod.NAME: mod for mod in RULES.values()}
