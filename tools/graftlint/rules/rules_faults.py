"""GL005 fault-site drift: every literal fault-plane site string is in
the registered table, and the validator mirror matches it.

Originating bug class: the PR 9 site-table drift — ``shard_lease`` was
added to ``resilience.faults.SITES`` and the hardcoded mirror in
tools/check_metrics.py silently lagged until a test pinned that one
list.  This rule generalizes the pin from one list to the whole tree:

* every ``faults.fire("<site>")`` literal anywhere in the scan set must
  name a registered site (``faults.fire`` raises on unknown sites at
  runtime, but only when a plan is installed AND the site fires — a
  typo at a rarely-exercised choke point ships silently);
* the ``_FAULT_SITES`` mirror in tools/check_metrics.py must equal
  ``SITES`` exactly (the validator must reject what the plane would
  reject).

Fires through a variable (``faults.fire(site)`` in the retry engine)
are out of static reach and stay runtime-checked.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from ..engine import Finding, Module, Repo

ID = "GL005"
NAME = "fault-site"

FAULTS_MOD = "adam_tpu/resilience/faults.py"
CHECK_METRICS = "tools/check_metrics.py"


def _tuple_of_strs(m: Module, name: str) -> Tuple[Optional[list], int]:
    for stmt in m.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == name and \
                isinstance(stmt.value, (ast.Tuple, ast.List)):
            vals = [e.value for e in stmt.value.elts
                    if isinstance(e, ast.Constant) and
                    isinstance(e.value, str)]
            return vals, stmt.lineno
    return None, 1


def registered_sites(repo: Repo) -> Tuple[Optional[list], int]:
    m = repo.reference(FAULTS_MOD)
    if m is None:
        return None, 1
    return _tuple_of_strs(m, "SITES")


def check(repo: Repo) -> Iterable[Finding]:
    findings: List[Finding] = []
    sites, _ = registered_sites(repo)
    if sites is None:
        return findings

    for m in repo.modules:
        if m.rel == FAULTS_MOD:
            continue
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            d = m.dotted(node.func)
            if not d or d.split(".")[-1] != "fire":
                continue
            r = m.resolve(d) or d
            if not r.endswith("faults.fire"):
                continue
            a0 = node.args[0]
            if not (isinstance(a0, ast.Constant) and
                    isinstance(a0.value, str)):
                continue
            if a0.value not in sites:
                findings.append(Finding(
                    rule=ID, name=NAME, path=m.rel, line=node.lineno,
                    symbol=f"site:{a0.value}",
                    message=(f"fault site {a0.value!r} is not in the "
                             "registered resilience.faults.SITES table "
                             "— a plan targeting it can never fire and "
                             "fire() raises once one does"),
                    hint="register the site in faults.SITES (and the "
                         "check_metrics mirror), or fix the typo "
                         f"(registered: {', '.join(sites)})"))

    cm = repo.reference(CHECK_METRICS)
    if cm is not None:
        mirror, mline = _tuple_of_strs(cm, "_FAULT_SITES")
        if mirror is not None and list(mirror) != list(sites):
            missing = [s for s in sites if s not in mirror]
            extra = [s for s in mirror if s not in sites]
            findings.append(Finding(
                rule=ID, name=NAME, path=CHECK_METRICS, line=mline,
                symbol="_FAULT_SITES",
                message=("check_metrics._FAULT_SITES drifted from "
                         f"faults.SITES (missing: {missing or 'none'}, "
                         f"extra: {extra or 'none'}) — the validator "
                         "no longer rejects what the plane rejects"),
                hint="copy faults.SITES into the _FAULT_SITES literal "
                     "(kept literal so the validator runs without "
                     "importing the package)"))
    return findings
