"""GL003 durable-write discipline: artifact writes go through the
atomic helpers.

Originating bug class: torn artifacts.  The PR 5 hardening wrapped
every manifest/marker/ledger write in ``checkpoint.atomic_write`` (tmp
in the target dir + flush + fsync + rename + parent-dir fsync) after
the chaos matrix showed a mid-write crash leaving a half-written
manifest that a resume then trusted.  A bare ``json.dump(obj,
open(path, "w"))`` or ``np.save(path, ...)`` re-opens exactly that
hole: the next crash between open and close publishes a torn file
under the real name.

Flagged patterns (the shipped bug shapes):

* ``json.dump(obj, f)``
* ``f.write(json.dumps(...))`` — directly or through a local name
  assigned from ``json.dumps``
* ``np.save(...)`` / ``np.savez(...)`` / ``np.savez_compressed(...)``

A site is exempt when the atomic discipline is visible around it:

* the enclosing function also calls ``os.replace`` / ``os.rename`` /
  ``atomic_write`` / ``save_doc`` (write-tmp-then-rename in one place);
* the write is in a method and a sibling method of the same class does
  the rename (the EventLog shape: append to ``.tmp`` in ``emit``,
  publish in ``close``);
* the file object is a caller-supplied parameter (the caller owns
  durability — report writers handed ``sys.stdout``);
* the target is ``sys.stdout`` / ``sys.stderr``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ..engine import Finding, FuncInfo, Module, Repo

ID = "GL003"
NAME = "durable-write"

_NP_SAVERS = {"numpy.save", "numpy.savez", "numpy.savez_compressed"}
#: ``os.link`` publishes atomically too (the spool's no-clobber submit)
_ATOMIC_CALLS = {"replace", "rename", "renames", "link", "atomic_write",
                 "atomic_np_write", "save_doc"}


def _has_atomic_call(m: Module, node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            d = m.dotted(n.func)
            if d and d.split(".")[-1] in _ATOMIC_CALLS:
                return True
    return False


def _class_has_atomic(m: Module, fn: FuncInfo) -> bool:
    if fn.class_name is None:
        return False
    return any(f.class_name == fn.class_name and
               _has_atomic_call(m, f.node)
               for f in m.functions)


def _params_of_chain(fn: Optional[FuncInfo]) -> Set[str]:
    names: Set[str] = set()
    while fn is not None:
        a = fn.node.args
        names |= {arg.arg for arg in
                  (a.args + a.posonlyargs + a.kwonlyargs)}
        fn = fn.parent
    return names


def _dumps_locals(m: Module, scope_node: ast.AST) -> Set[str]:
    """Local names assigned from ``json.dumps(...)`` in this scope."""
    out: Set[str] = set()
    for n in ast.walk(scope_node):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and m.resolve(m.dotted(n.value.func)) == "json.dumps":
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _mentions(node: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))


def check(repo: Repo) -> Iterable[Finding]:
    findings: List[Finding] = []
    for m in repo.modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            t = m.resolve(m.dotted(node.func))
            file_expr = None
            what = None
            fn = m.enclosing(node)
            scope_node = fn.node if fn is not None else m.tree
            if t == "json.dump":
                what = "json.dump"
                file_expr = node.args[1] if len(node.args) > 1 else None
            elif t in _NP_SAVERS:
                what = t.split(".")[-1]
                file_expr = node.args[0] if node.args else None
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "write" and node.args:
                a0 = node.args[0]
                is_dumps = (isinstance(a0, ast.Call) and
                            m.resolve(m.dotted(a0.func)) == "json.dumps")
                if not is_dumps:
                    dl = _dumps_locals(m, scope_node)
                    is_dumps = bool(dl) and _mentions(a0, dl)
                if not is_dumps:
                    continue
                what = "write(json.dumps(...))"
                file_expr = node.func.value
            else:
                continue

            # exemptions, cheapest first
            if file_expr is not None:
                fd = m.resolve(m.dotted(file_expr))
                if fd in ("sys.stdout", "sys.stderr"):
                    continue
                root = file_expr
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if isinstance(root, ast.Name) and \
                        root.id in _params_of_chain(fn):
                    continue
            if _has_atomic_call(m, scope_node):
                continue
            if fn is not None and _class_has_atomic(m, fn):
                continue
            qual = fn.qualname if fn is not None else "<module>"
            findings.append(Finding(
                rule=ID, name=NAME, path=m.rel, line=node.lineno,
                symbol=qual,
                message=(f"bare durable write ({what}) in {qual} — a "
                         "crash mid-write publishes a torn artifact "
                         "under the real name"),
                hint="route through checkpoint.atomic_write / "
                     "ledger.save_doc, or write to '<path>.tmp' and "
                     "os.replace() it into place (fsync for "
                     "crash-durability)"))
    return findings
