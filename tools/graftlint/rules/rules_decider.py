"""GL001 decider-purity: ``decide_*`` planners must be pure and every
product call site must flow through an event-emitting wrapper.

Originating bug class: the whole replay plane (tools/check_executor.py,
tools/check_resilience.py) rests on planners being deterministic
functions of their recorded ``inputs`` — a planner that peeks at
``os.environ``, the clock, randomness, the filesystem, a jax backend
probe, or a mutable module global replays DIFFERENTLY offline and the
sidecar digests stop meaning anything.  Env resolution belongs in the
``resolve_*`` wrappers (executor.resolve_ragged_env,
retry.resolve_retry_policy...), which run once at the impure boundary
and hand the planner plain values.

A planner here is a module-level function named ``decide_*`` whose
arguments are all keyword-only — the signature convention every shipped
planner uses (``decide_plan``, ``decide_fault``, ``decide_admission``,
...).  ``ops/markdup.decide_duplicates`` takes positional arrays and is
a kernel, not a planner; the signature rule keeps it out.

The call-site half: in product code (``adam_tpu/``) a planner may only
be invoked from a function that also emits the decision through
``obs.emit`` — the event IS the replay record.  Validators and tests
call planners bare on purpose (that is the replay); they are out of
scope.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..engine import Finding, FuncInfo, Module, Repo

ID = "GL001"
NAME = "decider-purity"

#: resolved-call prefixes a pure planner may never touch
_FORBIDDEN_PREFIXES = (
    "time.", "random.", "uuid.", "secrets.", "socket.", "subprocess.",
    "tempfile.", "shutil.", "datetime.", "numpy.random.", "jax.",
)
#: bare calls that reach the filesystem / stdin
_FORBIDDEN_BARE = {"open", "input"}
#: the pure string-algebra corner of ``os`` (everything else in os.* is
#: environment or filesystem)
_OS_PURE = {
    "os.path.join", "os.path.basename", "os.path.dirname",
    "os.path.splitext", "os.path.split", "os.path.normpath", "os.sep",
    "os.fspath",
}

_MUTATORS = {"append", "add", "update", "pop", "clear", "setdefault",
             "extend", "insert", "remove", "discard", "popitem"}


def is_planner(fn: FuncInfo) -> bool:
    node = fn.node
    if not fn.qualname.startswith("decide_") or "." in fn.qualname:
        return False
    a = node.args
    return (not a.args and not a.posonlyargs and bool(a.kwonlyargs))


def _mutable_globals(m: Module) -> Set[str]:
    """Module-level names that are demonstrably mutable state: targets
    of a ``global`` rebind anywhere in the module, or module-level
    containers the module itself mutates."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Global):
            out.update(node.names)
    containers: Set[str] = set()
    for stmt in m.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            name, val = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and \
                stmt.value is not None:
            name, val = stmt.target.id, stmt.value
        else:
            continue
        if isinstance(val, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                            ast.DictComp, ast.SetComp)):
            containers.add(name)
        elif isinstance(val, ast.Call):
            t = m.call_target(val) or ""
            if t in ("dict", "list", "set", "collections.defaultdict",
                     "collections.OrderedDict", "collections.deque"):
                containers.add(name)
    for node in ast.walk(m.tree):
        root = None
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    root = _root_name(t)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            root = _root_name(node.func)
        if root in containers:
            out.add(root)
    return out


def _root_name(node: ast.AST):
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _local_bindings(fn_node: ast.AST) -> Set[str]:
    a = fn_node.args
    names = {arg.arg for arg in (a.args + a.posonlyargs + a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.ImportFrom) or \
                isinstance(node, ast.Import):
            for alias in node.names:
                names.add(alias.asname or alias.name.split(".")[0])
    return names


def _emitting_functions(m: Module) -> Set[str]:
    """Qualnames of functions that emit — directly, or transitively
    through a same-module helper called by bare name (the
    ``emit_fusion_plan`` / ``_emit_reassigned`` wrapper shape)."""
    direct: Set[str] = set()
    calls: dict = {}
    for fn in m.functions:
        names: Set[str] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                d = m.dotted(node.func)
                if not d:
                    continue
                if d.split(".")[-1] == "emit":
                    direct.add(fn.qualname)
                elif "." not in d:
                    names.add(d)
        calls[fn.qualname] = names
    by_leaf: dict = {}
    for fn in m.functions:
        by_leaf.setdefault(fn.qualname.split(".")[-1],
                           set()).add(fn.qualname)
    emitting = set(direct)
    changed = True
    while changed:
        changed = False
        for qn, names in calls.items():
            if qn in emitting:
                continue
            for n in names:
                if by_leaf.get(n, set()) & emitting:
                    emitting.add(qn)
                    changed = True
                    break
    return emitting


def check(repo: Repo) -> Iterable[Finding]:
    findings: List[Finding] = []
    planner_names: Set[str] = set()
    for m in repo.modules:
        for fn in m.functions:
            if is_planner(fn):
                planner_names.add(fn.qualname)

    for m in repo.modules:
        for fn in m.functions:
            if not is_planner(fn):
                continue
            locals_ = _local_bindings(fn.node)
            mutable = _mutable_globals(m) - locals_
            for node in ast.walk(fn.node):
                bad = None
                if isinstance(node, ast.Call):
                    t = m.resolve(m.dotted(node.func))
                    if t in _FORBIDDEN_BARE:
                        bad = t
                    elif t and t.startswith("os.") and t not in _OS_PURE:
                        bad = t
                    elif t and any(t == p[:-1] or t.startswith(p)
                                   for p in _FORBIDDEN_PREFIXES):
                        bad = t
                elif isinstance(node, ast.Attribute):
                    if m.resolve(m.dotted(node)) == "os.environ":
                        bad = "os.environ"
                if bad is not None:
                    findings.append(Finding(
                        rule=ID, name=NAME, path=m.rel, line=node.lineno,
                        symbol=f"{fn.qualname}:{bad}",
                        message=(f"planner {fn.qualname} calls impure "
                                 f"API {bad} — decide_* must be a pure "
                                 "function of its recorded inputs"),
                        hint="resolve env/clock/backend state in a "
                             "resolve_* wrapper and pass the value in "
                             "as a keyword input (check_executor "
                             "replays the decision offline)"))
                elif isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in mutable:
                    findings.append(Finding(
                        rule=ID, name=NAME, path=m.rel, line=node.lineno,
                        symbol=f"{fn.qualname}:{node.id}",
                        message=(f"planner {fn.qualname} reads mutable "
                                 f"module global {node.id} — hidden "
                                 "state breaks offline replay"),
                        hint="pass the value in as a keyword input; "
                             "module constants are fine, mutated "
                             "globals are not"))

        # call-site half: product code only
        if not m.rel.startswith("adam_tpu/"):
            continue
        emitting = _emitting_functions(m)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            d = m.dotted(node.func)
            leaf = d.split(".")[-1] if d else None
            if leaf not in planner_names:
                continue
            fn = m.enclosing(node)
            if fn is not None and is_planner(fn):
                continue        # a planner may compose planners
            if fn is None:
                findings.append(Finding(
                    rule=ID, name=NAME, path=m.rel, line=node.lineno,
                    symbol=f"<module>:{leaf}",
                    message=(f"planner {leaf} called at module scope — "
                             "decisions must flow through an "
                             "event-emitting wrapper"),
                    hint="call it from the wrapper that emits the "
                         "*_selected event with inputs + digest"))
            elif fn.qualname not in emitting:
                findings.append(Finding(
                    rule=ID, name=NAME, path=m.rel, line=node.lineno,
                    symbol=f"{fn.qualname}:{leaf}",
                    message=(f"planner {leaf} called from "
                             f"{fn.qualname}, which never emits — the "
                             "decision would leave no replayable "
                             "record"),
                    hint="emit the decision event (inputs + "
                         "input_digest) in this wrapper, or route the "
                         "call through the one that does"))
    return findings
