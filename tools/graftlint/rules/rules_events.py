"""GL004 event-schema drift: every emitted event kind has a schema,
every schema has a live emit site.

Originating bug class: unvalidatable telemetry.  The obs plane's
contract is that ``tools/check_metrics.py`` can validate any sidecar
the pipeline produces — that is what the tier-1 CLI telemetry test and
every validator round-trip pin rely on.  An event kind emitted without
a schema sails through validation unchecked (the validator skips
unknown kinds), so a field rename or type change in that event is
silent drift; a schema without an emit site is dead weight that
documents an event nobody produces.

Ground truth on the schema side is the ``KNOWN_EVENTS`` tuple in
tools/check_metrics.py (the validator's own registry).  Ground truth on
the live side is every ``obs.emit("<kind>", ...)`` /
``events.emit(...)`` call with a literal kind in ``adam_tpu/``.

The dead-schema direction only runs when the scan actually covers the
``adam_tpu/`` tree — a partial scan (fixtures, one file) cannot prove
an emit site absent.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ..engine import Finding, Module, Repo

ID = "GL004"
NAME = "event-schema"

CHECK_METRICS = "tools/check_metrics.py"


def known_events(repo: Repo) -> Tuple[Optional[List[str]], int]:
    """(KNOWN_EVENTS contents, line) from check_metrics.py, or
    (None, 1) when the registry tuple is missing."""
    m = repo.reference(CHECK_METRICS)
    if m is None:
        return None, 1
    for stmt in m.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == "KNOWN_EVENTS" and \
                isinstance(stmt.value, (ast.Tuple, ast.List)):
            vals = [e.value for e in stmt.value.elts
                    if isinstance(e, ast.Constant) and
                    isinstance(e.value, str)]
            return vals, stmt.lineno
    return None, 1


def _is_obs_emit(m: Module, node: ast.Call) -> bool:
    d = m.dotted(node.func)
    if not d or d.split(".")[-1] != "emit":
        return False
    r = m.resolve(d) or d
    parts = r.split(".")
    # the obs plane's emit: adam_tpu.obs.emit, adam_tpu.obs.events.emit,
    # or a local alias of either
    if "obs" in parts or "events" in parts or r == "emit":
        return True
    # method emit on an EventLog instance — the obs convention names the
    # receiver `log`/`*_log` (events.write_manifest(log, ...),
    # run_with_events' `log.emit("summary", ...)`); stdlib loggers never
    # take a literal kind as first arg, so this stays precise
    recv = parts[-2] if len(parts) >= 2 else ""
    return "log" in recv.lower()


def emit_sites(repo: Repo) -> Dict[str, Tuple[str, int]]:
    """kind -> (first path, line) over every literal obs emit in the
    scanned ``adam_tpu/`` modules."""
    out: Dict[str, Tuple[str, int]] = {}
    for m in repo.modules:
        if not m.rel.startswith("adam_tpu/"):
            continue
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call) and
                    _is_obs_emit(m, node) and node.args):
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                out.setdefault(a0.value, (m.rel, node.lineno))
    return out


def check(repo: Repo) -> Iterable[Finding]:
    findings: List[Finding] = []
    sites = emit_sites(repo)
    if not sites:
        return findings
    known, known_line = known_events(repo)
    if known is None:
        findings.append(Finding(
            rule=ID, name=NAME, path=CHECK_METRICS, line=1,
            symbol="KNOWN_EVENTS",
            message="tools/check_metrics.py has no KNOWN_EVENTS "
                    "registry tuple — the schema side of the drift "
                    "check has no ground truth",
            hint="declare KNOWN_EVENTS = (\"manifest\", \"summary\", "
                 "...) listing every validated event kind"))
        return findings

    for kind, (path, line) in sorted(sites.items()):
        if kind not in known:
            findings.append(Finding(
                rule=ID, name=NAME, path=path, line=line,
                symbol=f"emit:{kind}",
                message=(f"event kind {kind!r} is emitted but has no "
                         "schema in tools/check_metrics.py — its "
                         "sidecar lines validate as nothing"),
                hint="add the kind to KNOWN_EVENTS and a field-schema "
                     "branch in check_metrics.validate (document it in "
                     "docs/OBSERVABILITY.md)"))

    # the dead-schema direction needs the WHOLE product tree in the
    # scan set: `graftlint adam_tpu/obs` must not call every schema
    # whose emit site lives elsewhere dead
    if repo.covers_dir("adam_tpu"):
        for kind in known:
            if kind not in sites:
                findings.append(Finding(
                    rule=ID, name=NAME, path=CHECK_METRICS,
                    line=known_line, symbol=f"schema:{kind}",
                    message=(f"schema for event kind {kind!r} has no "
                             "live emit site in adam_tpu/ — dead "
                             "schema"),
                    hint="delete the schema (and its KNOWN_EVENTS "
                         "entry), or revive the emit site it "
                         "documented"))
    return findings
