"""GL006 static stage/race detector: module-global state written from
thread-reachable code without a lock.

Originating bug class: the PR 6 shared-stage-stack race —
``instrument.stage`` kept one process-global stack, and the moment
PR 3's feeder threads staged their own work, producer and consumer
popped each other's frames and mis-nested the whole timing tree.  The
fix (per-thread contextvar + one tree lock) is the discipline this rule
enforces everywhere: code reachable from a thread entry point may only
write module-global mutable state under a lock, through a contextvar,
or through the internally-locked registry helpers.

Entry points (where concurrency starts), discovered per module:

* ``threading.Thread(target=f, ...)``
* ``pool.submit(f, ...)`` (ThreadPoolExecutor)
* callables handed to ``ingest.pipelined`` / ``ingest.prefetched``
  (the named pools: ``ingest-pool``, ``realign-prep``, device-feed
  feeder loops, shardstream heartbeats, the serve loop's workers)

From those roots a lightweight call-graph walk (same-module bare names,
``self.method``, and cross-module ``pkg.mod.fn`` through the import
map; depth-capped) visits every statically reachable function.  Inside,
a write to a module-global container or a ``global`` rebind is flagged
unless an enclosing ``with`` holds a module-level ``threading.Lock`` /
``RLock`` (or any ``*lock*``-named context).  Contextvars, queues,
events and semaphores are internally synchronized and exempt.

This is deliberately lightweight (no aliasing, no cross-thread
happens-before): it catches the shipped bug shape — an unlocked
read-modify-write on shared module state from a pool thread — and
leaves provably-safe single-writer cases to a documented baseline
entry.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..engine import Finding, FuncInfo, Module, Repo

ID = "GL006"
NAME = "stage-race"

_MUTATORS = {"append", "add", "update", "pop", "clear", "setdefault",
             "extend", "insert", "remove", "discard", "popitem",
             "appendleft", "sort", "reverse"}

_LOCK_TYPES = {"threading.Lock", "threading.RLock",
               "threading.Condition", "threading.Semaphore",
               "threading.BoundedSemaphore"}
_SAFE_TYPES = {"queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
               "queue.PriorityQueue", "collections.deque",
               "contextvars.ContextVar", "threading.Event",
               "threading.local"}

_DEPTH_CAP = 12


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _ModFacts:
    """Per-module: mutable globals, lock globals, safe globals."""

    def __init__(self, m: Module):
        self.mutable: Set[str] = set()
        self.locks: Set[str] = set()
        self.safe: Set[str] = set()
        for stmt in m.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                name, val = stmt.targets[0].id, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and \
                    stmt.value is not None:
                name, val = stmt.target.id, stmt.value
            else:
                continue
            if isinstance(val, (ast.List, ast.Dict, ast.Set,
                                ast.ListComp, ast.DictComp, ast.SetComp)):
                self.mutable.add(name)
            elif isinstance(val, ast.Call):
                t = m.resolve(m.dotted(val.func)) or ""
                if t in _LOCK_TYPES:
                    self.locks.add(name)
                elif t in _SAFE_TYPES:
                    self.safe.add(name)
                elif t in ("dict", "list", "set",
                           "collections.defaultdict",
                           "collections.OrderedDict"):
                    self.mutable.add(name)
                else:
                    # any other instance held at module scope is shared
                    # state too (the PipelineReport tree)
                    self.mutable.add(name)
        # names rebound via `global` anywhere count as mutable state
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Global):
                self.mutable.update(n for n in node.names
                                    if n not in self.locks and
                                    n not in self.safe)


def _under_lock(m: Module, facts: _ModFacts, node: ast.AST) -> bool:
    """Any enclosing ``with`` whose context mentions a module lock or a
    ``*lock*``-named attribute (instance locks: ``self._lock``)."""
    cur = m.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                for n in ast.walk(item.context_expr):
                    if isinstance(n, ast.Name) and n.id in facts.locks:
                        return True
                    if isinstance(n, ast.Name) and \
                            "lock" in n.id.lower():
                        return True
                    if isinstance(n, ast.Attribute) and \
                            "lock" in n.attr.lower():
                        return True
        cur = m.parents.get(cur)
    return False


def _callable_name(node: ast.AST) -> Optional[str]:
    """Dotted name of a callable reference (Name / Attribute), else
    None (lambdas and calls are not chased)."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        return Module.dotted(node)
    return None


def _entry_refs(m: Module) -> List[Tuple[str, int]]:
    """Dotted callable refs handed to a thread/pool in this module."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        t = m.resolve(m.dotted(node.func)) or ""
        leaf = t.split(".")[-1]
        if t == "threading.Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    n = _callable_name(kw.value)
                    if n:
                        out.append((n, node.lineno))
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "submit" and node.args:
            n = _callable_name(node.args[0])
            if n:
                out.append((n, node.lineno))
        elif leaf in ("pipelined", "prefetched") and \
                ("ingest" in t.split(".") or leaf == t):
            # fn/prepare/put/on_chunk args run on the reader/feeder/pool
            for arg in list(node.args[1:]) + \
                    [kw.value for kw in node.keywords
                     if kw.arg in ("fn", "prepare", "put", "on_chunk")]:
                n = _callable_name(arg)
                if n:
                    out.append((n, node.lineno))
    return out


class _Graph:
    """Resolution of function references + call edges across the scan
    set — bare same-module names, ``self.method``, and dotted
    ``pkg.mod.fn`` through each module's import map."""

    def __init__(self, repo: Repo):
        self.repo = repo
        self.by_dotted_mod: Dict[str, Module] = {}
        for m in repo.modules:
            if m.rel.endswith(".py"):
                dotted = m.rel[:-3].replace("/", ".")
                if dotted.endswith(".__init__"):
                    dotted = dotted[:-9]
                self.by_dotted_mod[dotted] = m

    def resolve_ref(self, m: Module, ref: str
                    ) -> List[Tuple[Module, FuncInfo]]:
        """All functions a dotted reference may denote."""
        out: List[Tuple[Module, FuncInfo]] = []
        parts = ref.split(".")
        if parts[0] == "self" and len(parts) == 2:
            # any same-module method with that name (class-insensitive:
            # cheap and safe — extra edges only widen the walk)
            for f in m.functions:
                if f.qualname.split(".")[-1] == parts[1] and \
                        f.class_name is not None:
                    out.append((m, f))
            return out
        if len(parts) == 1:
            for f in m.functions:
                qn = f.qualname.split(".")
                if qn[-1] == parts[0]:
                    out.append((m, f))
            if out:
                return out
            # no same-module match: a bare name may be imported from
            # another module (`from .state import record;
            # Thread(target=record)`) — fall through to cross-module
            # resolution via the import map
        resolved = m.resolve(ref) or ref
        rparts = resolved.split(".")
        for split in range(len(rparts) - 1, 0, -1):
            mod_dotted = ".".join(rparts[:split])
            target = self.by_dotted_mod.get(mod_dotted)
            if target is None:
                continue
            tail = rparts[split:]
            for f in target.functions:
                qn = f.qualname.split(".")
                if qn[-len(tail):] == tail:
                    out.append((target, f))
            break
        return out

    def callees(self, m: Module, fn: FuncInfo
                ) -> List[Tuple[Module, FuncInfo]]:
        out: List[Tuple[Module, FuncInfo]] = []
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                ref = _callable_name(node.func)
                if ref:
                    out.extend(self.resolve_ref(m, ref))
        return out


def check(repo: Repo) -> Iterable[Finding]:
    findings: List[Finding] = []
    facts: Dict[str, _ModFacts] = {m.rel: _ModFacts(m)
                                   for m in repo.modules}
    graph = _Graph(repo)

    # -- collect thread-reachable functions --------------------------------
    roots: List[Tuple[Module, FuncInfo]] = []
    for m in repo.modules:
        for ref, _line in _entry_refs(m):
            roots.extend(graph.resolve_ref(m, ref))
    seen: Set[Tuple[str, str]] = set()
    frontier = [(m, f, 0) for m, f in roots]
    reachable: List[Tuple[Module, FuncInfo]] = []
    while frontier:
        m, f, depth = frontier.pop()
        key = (m.rel, f.qualname)
        if key in seen or depth > _DEPTH_CAP:
            continue
        seen.add(key)
        reachable.append((m, f))
        for cm, cf in graph.callees(m, f):
            frontier.append((cm, cf, depth + 1))

    # -- flag unlocked writes to module-global state -----------------------
    reported: Set[Tuple[str, str]] = set()
    for m, fn in reachable:
        fx = facts.get(m.rel)
        if fx is None:
            continue
        declared_global: Set[str] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        for node in ast.walk(fn.node):
            target_name = None
            verb = None
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        r = _root_name(t)
                        if r in fx.mutable and r not in fx.safe:
                            target_name, verb = r, "writes"
                    elif isinstance(t, ast.Name) and \
                            t.id in declared_global:
                        target_name, verb = t.id, "rebinds"
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                r = _root_name(node.func)
                if r in fx.mutable and r not in fx.safe:
                    target_name, verb = r, f"mutates ({node.func.attr})"
            if target_name is None:
                continue
            if _under_lock(m, facts[m.rel], node):
                continue
            key = (m.rel, f"{fn.qualname}:{target_name}")
            if key in reported:
                continue
            reported.add(key)
            findings.append(Finding(
                rule=ID, name=NAME, path=m.rel, line=node.lineno,
                symbol=f"{fn.qualname}:{target_name}",
                message=(f"{fn.qualname} {verb} module-global "
                         f"{target_name} and is reachable from a "
                         "thread entry point without a lock — an "
                         "interleaved read-modify-write corrupts it "
                         "(the PR 6 shared-stage-stack race class)"),
                hint="guard the write with a module-level "
                     "threading.Lock (`with _LOCK:`), make the state "
                     "per-thread (contextvars.ContextVar), or go "
                     "through the locked registry helpers"))
    return findings
