"""graftlint — the repo's own conventions, machine-checked.

Ten PRs of this codebase accreted load-bearing invariants, and every one
of them exists because a real bug shipped first:

* pure replayable ``decide_*`` planners (the executor/fusion/fault/serve
  convention — ``tools/check_executor.py`` replays them offline);
* memoized jit constructors (the PR 10 per-call ``jax.jit`` recompile
  leak: a fresh wrapper per serve job recompiled what the previous job
  already compiled);
* atomic tmp+rename(+fsync) durable writes (``checkpoint.atomic_write``
  — a torn manifest must be invisible to resume);
* the event-schema registry (``tools/check_metrics.py`` — an emitted
  kind without a schema is unvalidatable telemetry);
* the registered fault-site table (``resilience.faults.SITES`` — the
  PR 9 site-table drift pin, generalized);
* lock discipline on module-global state written from pool threads (the
  PR 6 shared-stage-stack race).

graftlint is a stdlib-``ast`` static pass that enforces all six as lint
rules over ``adam_tpu/`` + ``tools/``: the same "replay the decision
offline" discipline the ``check_*`` validators apply to runtime
sidecars, applied to the source itself.  Findings carry file:line, a
rule id and a one-line fix hint; grandfathered findings live in a
checked-in baseline (``tools/graftlint/baseline.json``) with a
documented reason each, and a stale baseline entry is itself a finding
— the baseline can only shrink.

CLI::

    python -m tools.graftlint [--baseline FILE] [--rule ID] [PATHS...]

Exit 0 when the scan is clean modulo baseline; 1 on any non-baselined
finding (or stale baseline entry); 2 on usage error.  The whole pass
runs in tier-1 via tests/test_graftlint.py.  Rule catalog:
docs/STATIC_ANALYSIS.md.
"""

from .engine import Finding, Repo, load_baseline, scan  # noqa: F401
from .rules import RULES  # noqa: F401

__all__ = ["Finding", "Repo", "RULES", "load_baseline", "scan"]
