"""CLI: ``python -m tools.graftlint [--baseline FILE] [--rule ID]
[PATHS...]``.

Defaults: scan ``adam_tpu/`` + ``tools/`` from the repo root with the
checked-in baseline.  Exit 0 clean-modulo-baseline, 1 on any
non-baselined finding (stale baseline entries included), 2 on usage
error.
"""

from __future__ import annotations

import argparse
import os
import sys


def _repo_root() -> str:
    # tools/graftlint/__main__.py -> repo root is two levels up from
    # the package directory
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    root = _repo_root()
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST invariant linter + static race detector for "
                    "the repo's own conventions (rule catalog: "
                    "docs/STATIC_ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: adam_tpu "
                        "tools, relative to the repo root)")
    p.add_argument("--baseline",
                   default=os.path.join(root, "tools", "graftlint",
                                        "baseline.json"),
                   help="grandfathered-findings file (default: the "
                        "checked-in tools/graftlint/baseline.json; "
                        "pass an empty string for none)")
    p.add_argument("--rule", action="append", default=None,
                   help="run only this rule id or name (repeatable)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--root", default=root,
                   help=argparse.SUPPRESS)  # test hook
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    from .engine import load_baseline, scan
    from .rules import RULES, RULES_BY_NAME

    if args.list_rules:
        for rid, mod in sorted(RULES.items()):
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{rid}  {mod.NAME:<22s} {doc}")
        return 0

    only = None
    if args.rule:
        only = set()
        for r in args.rule:
            if r in RULES:
                only.add(r)
            elif r in RULES_BY_NAME:
                only.add(RULES_BY_NAME[r].ID)
            else:
                print(f"unknown rule {r!r} (known: "
                      f"{', '.join(sorted(RULES))} / "
                      f"{', '.join(sorted(RULES_BY_NAME))})",
                      file=sys.stderr)
                return 2

    paths = args.paths or ["adam_tpu", "tools"]
    baseline = args.baseline or None
    try:
        active, suppressed, errors = scan(
            args.root, paths, RULES, baseline_path=baseline, only=only)
    except ValueError as e:          # malformed baseline
        print(f"error: {e}", file=sys.stderr)
        return 2
    for e in errors:
        print(f"error: {e}", file=sys.stderr)
    for f in active:
        print(f.format())
    n_mod = sum(1 for _ in active)
    tail = (f"{n_mod} finding(s)" if active else "clean")
    if suppressed:
        tail += f" ({len(suppressed)} baselined)"
    print(f"graftlint: {tail}")
    return 1 if (active or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
