"""Rule engine: module loading, scope/import resolution, baseline.

Everything here is stdlib ``ast`` — graftlint must run in any
environment the repo's validators run in (no jax import, no third-party
parser), and it must never execute the code it checks (the same
"replay offline" discipline as tools/check_executor.py).

Suppression mechanisms, narrowest first:

* line pragma   ``# graftlint: disable=GL00X — reason`` silences the
  named rule(s) on that source line;
* file pragma   ``# graftlint-file: disable=GL00X — reason`` silences
  the named rule(s) for the whole file (one-shot harness scripts);
* baseline      ``tools/graftlint/baseline.json`` — grandfathered
  findings keyed (rule, path, symbol) with a documented reason each.
  A baseline entry that no longer matches any finding is STALE and is
  reported as a finding itself (rule GL000), so the baseline can only
  shrink.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: rule id for stale-baseline findings (not a real rule — the round-trip
#: guard on the baseline file itself)
STALE_RULE = "GL000"

_PRAGMA_LINE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9,\s-]+)")
_PRAGMA_FILE = re.compile(r"#\s*graftlint-file:\s*disable=([A-Z0-9,\s-]+)")


@dataclass(frozen=True)
class Finding:
    rule: str       # "GL001"
    name: str       # "decider-purity"
    path: str       # repo-relative, forward slashes
    line: int
    symbol: str     # stable baseline key: enclosing qualname or detail
    message: str
    hint: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def format(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule}[{self.name}] "
                f"{self.message}\n    hint: {self.hint}")


@dataclass
class FuncInfo:
    """One function (or method / nested function) in a module."""
    node: ast.AST                    # FunctionDef | AsyncFunctionDef
    qualname: str                    # dotted through classes/functions
    class_name: Optional[str]        # nearest enclosing class, if any
    parent: Optional["FuncInfo"]     # nearest enclosing function, if any
    decorators: List[str] = field(default_factory=list)  # resolved dotted


class Module:
    """Parsed source file + the resolution maps every rule needs."""

    def __init__(self, root: str, abspath: str):
        self.abspath = abspath
        self.rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        self.package = self._package()
        self.imports = self._imports()
        self.parents: Dict[ast.AST, ast.AST] = {}
        for p in ast.walk(self.tree):
            for c in ast.iter_child_nodes(p):
                self.parents[c] = p
        self.functions: List[FuncInfo] = []
        self.scope_of: Dict[ast.AST, Optional[FuncInfo]] = {}
        self._assign_scopes(self.tree, scope=None, prefix="", cls=None)
        self.file_disables, self.line_disables = self._pragmas()

    # -- structure ---------------------------------------------------------

    def _package(self) -> str:
        """Dotted package of this module ('adam_tpu.parallel' for
        adam_tpu/parallel/ingest.py) — anchors relative imports."""
        parts = self.rel.split("/")
        return ".".join(parts[:-1])

    def _imports(self) -> Dict[str, str]:
        """alias -> absolute dotted target for every import statement."""
        out: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self.package.split(".")
                    base = base[:len(base) - (node.level - 1)]
                    mod = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = f"{mod}.{a.name}"
        return out

    def _assign_scopes(self, node: ast.AST, scope: Optional[FuncInfo],
                       prefix: str, cls: Optional[str]) -> None:
        """Map every node to the function whose BODY executes it.

        Decorator expressions and default-value expressions of a
        function run in the ENCLOSING scope (a module-level
        ``@partial(jax.jit, ...)`` is a module-scope jit construction,
        not a call inside the function it decorates)."""
        self.scope_of[node] = scope
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = f"{prefix}{node.name}"
            info = FuncInfo(node=node, qualname=qn, class_name=cls,
                            parent=scope,
                            decorators=[d for d in
                                        (self.resolve(self.call_target(dec)
                                                      or self.dotted(dec))
                                         for dec in node.decorator_list)
                                        if d])
            self.functions.append(info)
            for dec in node.decorator_list:
                self._walk_in(dec, scope, prefix, cls)
            for default in (node.args.defaults +
                            [d for d in node.args.kw_defaults
                             if d is not None]):
                self._walk_in(default, scope, prefix, cls)
            for stmt in node.body:
                self._walk_in(stmt, info, f"{qn}.", cls)
        elif isinstance(node, ast.ClassDef):
            for dec in node.decorator_list:
                self._walk_in(dec, scope, prefix, cls)
            for stmt in node.body:
                self._walk_in(stmt, scope, f"{prefix}{node.name}.",
                              node.name)
        else:
            for child in ast.iter_child_nodes(node):
                self._walk_in(child, scope, prefix, cls)

    def _walk_in(self, node, scope, prefix, cls):
        self._assign_scopes(node, scope, prefix, cls)

    def _pragmas(self):
        file_dis: Set[str] = set()
        line_dis: Dict[int, Set[str]] = {}
        for i, ln in enumerate(self.lines, 1):
            m = _PRAGMA_FILE.search(ln)
            if m:
                file_dis |= {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
                continue
            m = _PRAGMA_LINE.search(ln)
            if m:
                line_dis[i] = {r.strip() for r in m.group(1).split(",")
                               if r.strip()}
        return file_dis, line_dis

    # -- resolution helpers ------------------------------------------------

    @staticmethod
    def dotted(node: ast.AST) -> Optional[str]:
        """'a.b.c' for a Name/Attribute chain, else None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    def resolve(self, dotted: Optional[str]) -> Optional[str]:
        """Rewrite the first segment through the module's import map
        ('np.random.rand' -> 'numpy.random.rand')."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        target = self.imports.get(head)
        if target is None:
            return dotted
        return f"{target}.{rest}" if rest else target

    def call_target(self, node: ast.AST) -> Optional[str]:
        """Resolved dotted target of a Call node (else None)."""
        if isinstance(node, ast.Call):
            return self.resolve(self.dotted(node.func))
        return None

    def enclosing(self, node: ast.AST) -> Optional[FuncInfo]:
        """The function whose body executes this node (None = module)."""
        return self.scope_of.get(node)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disables:
            return True
        return rule in self.line_disables.get(line, set())


class Repo:
    """The scan unit: parsed modules + lazily shared cross-module facts.

    ``modules`` is the scan set (what findings are reported against);
    ``reference(rel)`` loads well-known files (faults.py,
    check_metrics.py) even when PATHS excluded them, so the drift rules
    always compare against the real registries."""

    def __init__(self, root: str, paths: Sequence[str]):
        self.root = os.path.abspath(root)
        self.modules: List[Module] = []
        self.errors: List[str] = []
        self.scanned_dirs: List[str] = []
        self._refs: Dict[str, Optional[Module]] = {}
        for path in paths:
            ap = path if os.path.isabs(path) else \
                os.path.join(self.root, path)
            if os.path.isfile(ap) and ap.endswith(".py"):
                self._load(ap)
            elif os.path.isdir(ap):
                self.scanned_dirs.append(os.path.abspath(ap))
                for dirpath, dirnames, filenames in os.walk(ap):
                    dirnames[:] = sorted(
                        d for d in dirnames
                        if d not in ("__pycache__", ".git"))
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            self._load(os.path.join(dirpath, fn))
            else:
                self.errors.append(f"{path}: not a .py file or directory")

    def _load(self, abspath: str) -> None:
        try:
            self.modules.append(Module(self.root, abspath))
        except (OSError, SyntaxError, UnicodeDecodeError,
                ValueError) as e:
            self.errors.append(f"{abspath}: unparseable: {e}")

    def module(self, rel: str) -> Optional[Module]:
        for m in self.modules:
            if m.rel == rel:
                return m
        return None

    def covers_dir(self, rel: str) -> bool:
        """True when the scan set includes the WHOLE tree at
        root/*rel* — i.e. some scanned directory is that directory or
        an ancestor of it.  Absence-of-X rules (a dead schema = no
        emit site anywhere) may only fire on a scan that could have
        seen X; a partial scan proves nothing absent."""
        target = os.path.abspath(os.path.join(self.root, rel))
        for d in self.scanned_dirs:
            if target == d or target.startswith(d + os.sep):
                return True
        return False

    def reference(self, rel: str) -> Optional[Module]:
        """A well-known file by repo-relative path, loaded on demand and
        cached; falls back to the scan set when already loaded."""
        if rel in self._refs:
            return self._refs[rel]
        m = self.module(rel)
        if m is None:
            ap = os.path.join(self.root, rel)
            if os.path.isfile(ap):
                try:
                    m = Module(self.root, ap)
                except (OSError, SyntaxError, UnicodeDecodeError,
                        ValueError):
                    m = None
        self._refs[rel] = m
        return m


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Optional[str]) -> List[dict]:
    """Baseline entries [{rule, path, symbol, reason}, ...]; every entry
    must carry a non-empty reason (an undocumented grandfathering is a
    usage error — the whole point is the documented WHY)."""
    if not path or not os.path.isfile(path):
        return []
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", [])
    for e in entries:
        for fld in ("rule", "path", "symbol", "reason"):
            if not isinstance(e.get(fld), str) or not e[fld].strip():
                raise ValueError(
                    f"baseline entry {e!r} missing non-empty {fld!r} "
                    "(every grandfathered finding needs a documented "
                    "reason)")
    return entries


def apply_baseline(findings: List[Finding],
                   entries: List[dict],
                   baseline_path: str) -> Tuple[List[Finding],
                                                List[Finding]]:
    """Split into (active, suppressed); stale baseline entries are
    appended to *active* as GL000 findings — a baseline row that no
    longer matches anything must be deleted, not carried."""
    keys = {(e["rule"], e["path"], e["symbol"]): e for e in entries}
    hit: Set[Tuple[str, str, str]] = set()
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        if f.key in keys:
            hit.add(f.key)
            suppressed.append(f)
        else:
            active.append(f)
    for e in entries:
        k = (e["rule"], e["path"], e["symbol"])
        if k not in hit:
            active.append(Finding(
                rule=STALE_RULE, name="stale-baseline",
                path=baseline_path.replace(os.sep, "/"), line=1,
                symbol=f"{e['rule']}:{e['path']}:{e['symbol']}",
                message=(f"stale baseline entry {e['rule']} "
                         f"{e['path']}::{e['symbol']} matches no "
                         "current finding"),
                hint="delete the entry — the violation it grandfathered "
                     "is gone (the baseline only shrinks)"))
    return active, suppressed


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def scan(root: str, paths: Sequence[str], rules: Dict[str, "object"],
         baseline_path: Optional[str] = None,
         only: Optional[Iterable[str]] = None):
    """Run the rule set over PATHS.  Returns (active, suppressed,
    errors): non-baselined findings (incl. stale-baseline rows),
    baseline-suppressed findings, and unparseable-file errors."""
    repo = Repo(root, paths)
    findings: List[Finding] = []
    wanted = set(only) if only else None
    for rule_id, rule in sorted(rules.items()):
        if wanted and rule_id not in wanted and \
                getattr(rule, "NAME", "") not in wanted:
            continue
        for f in rule.check(repo):
            m = repo.module(f.path)
            if m is not None and m.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    entries = load_baseline(baseline_path)
    rel_base = (os.path.relpath(baseline_path, root)
                if baseline_path else "baseline.json")
    active, suppressed = apply_baseline(findings, entries, rel_base)
    return active, suppressed, repo.errors
