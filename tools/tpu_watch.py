"""Tunnel watcher: poll the TPU tunnel; the moment it answers, run the full
benchmark and save the one-line JSON to BENCH_TPU_EVIDENCE.json.

The tunnel's control and data planes flap on minute-to-hour scales (observed
rounds 2-3), so evidence capture cannot wait for a human to notice the
tunnel is back — run this under tmux and let it grab the artifact:

    python tools/tpu_watch.py [--once] [--out BENCH_TPU_EVIDENCE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from adam_tpu.evidence.ledger import (Ledger, default_path,  # noqa: E402
                                      new_window_id)

PROBE = ("import jax; d = jax.devices()[0]; "
         "print(getattr(d, 'device_kind', '?'), d.platform)")

#: the measurement stages the ledger tracks (probe always re-runs — it
#: is the window's health check, not evidence to converge on)
BENCH_STAGES = ("bqsr_race", "pallas", "ragged_race", "transform",
                "flagstat", "bqsr_race8")
LEDGER_NAME = "EVIDENCE_LEDGER.json"


def probe_ok(timeout_s: float = 45.0) -> bool:
    try:
        rc = subprocess.run([sys.executable, "-c", PROBE],
                            timeout=timeout_s, capture_output=True)
    except subprocess.TimeoutExpired:
        return False
    out = rc.stdout.decode(errors="replace").lower()
    return rc.returncode == 0 and ("tpu" in out or "axon" in out)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_TPU_EVIDENCE.json")
    ap.add_argument("--once", action="store_true",
                    help="stop after the first captured TPU artifact")
    ap.add_argument("--interval", type=float, default=180.0,
                    help="seconds between probes; each probe costs a jax "
                         "import subprocess, so keep this sparse — CPU "
                         "benchmarks share the box")
    args = ap.parse_args()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    ledger_path = default_path(repo)

    while True:
        t0 = time.strftime("%H:%M:%S")
        # one-line convergence status per wake-up: the log shows the
        # evidence set filling in across windows
        led = Ledger(ledger_path)
        print(f"[{t0}] {led.summary_line(BENCH_STAGES)}", flush=True)
        if not probe_ok():
            print(f"[{t0}] tunnel down", flush=True)
            _capture_aot(repo)
            time.sleep(args.interval)
            continue
        on_chip_before = {s for s in BENCH_STAGES
                          if led.captured_on_tpu(s)}
        print(f"[{t0}] tunnel UP — running bench.py", flush=True)
        try:
            # the watcher's run is the round's main TPU-evidence channel:
            # give it a bigger budget than the driver's default so every
            # stage (incl. the 6-leg count race) fits one window with
            # cold per-worker compiles
            env = dict(os.environ)
            env.setdefault("ADAM_TPU_BENCH_TOTAL_BUDGET", "900")
            # every watcher-driven window leaves a timeline behind:
            # bench stamps per-attempt ADAM_TPU_TRACE sidecars
            # (BENCH_trace_<tag>.json) into each payload, and payloads
            # persist through the evidence ledger — an on-chip capture
            # is then inspectable in Perfetto, not just a number
            env.setdefault("ADAM_TPU_TRACE_BENCH", "1")
            # flap resilience (r5): the 51.5M-read default packs+ships a
            # 206 MB wire ×3 through a tunnel that stalls on minute
            # scales — the exact shape of r5-window-1's flagstat hang.
            # 12M reads (48 MB) measures the same per-read rates with
            # 4x less stall exposure; rates are size-independent past
            # ~4M reads (one resident chain block).
            env.setdefault("ADAM_TPU_BENCH_FLAGSTAT_READS", "12000000")
            reenter = _reentry_env(led)
            for k, v in reenter.items():
                env.setdefault(k, v)
            if "ADAM_TPU_BENCH_ONLY" in reenter:
                print(f"re-entering with missing stages only: "
                      f"{reenter['ADAM_TPU_BENCH_ONLY']}", flush=True)
            budget = float(env["ADAM_TPU_BENCH_TOTAL_BUDGET"])
            rc = subprocess.run(
                [sys.executable, os.path.join(repo, "bench.py")],
                timeout=budget + 100, capture_output=True, text=True,
                cwd=repo, env=env)
        except subprocess.TimeoutExpired:
            # the run died but benchlib checkpointed the ledger after
            # every attempt — commit whatever on-chip evidence landed
            # before the hang (uncommitted evidence is round-3's story)
            print("bench timed out; re-probing", flush=True)
            _ledger_progress(repo, ledger_path, on_chip_before)
            continue
        line = rc.stdout.strip().splitlines()[-1] if rc.stdout.strip() else ""
        try:
            doc = json.loads(line)
        except ValueError:
            print(f"bench emitted no JSON (rc={rc.returncode})", flush=True)
            _ledger_progress(repo, ledger_path, on_chip_before)
            time.sleep(args.interval)
            continue
        doc["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
        got_tpu = doc.get("platform") == "tpu"
        if _save_artifact(repo, args.out, doc) == "kept":
            print("bench fell back to CPU; keeping existing TPU artifact",
                  flush=True)
        print(f"captured platform={doc.get('platform')} "
              f"flagstat={doc.get('value')}", flush=True)
        # the ledger is the per-stage generalization of the whole-file
        # keep-dont-clobber above: bench merged its captures keep-best;
        # a partial window (headline fell back to CPU but the race
        # landed on-chip first) still advanced it — commit that progress
        # the moment it exists
        _ledger_progress(repo, ledger_path, on_chip_before,
                         extra=(args.out,))
        if got_tpu:
            # VERDICT r4 window priority: (a) bench incl. races — just
            # landed, commit immediately; (b) the flagstat-v2 roofline +
            # LUT-apply race (probe suite); (c) the TPU e2e breakdown.
            # Commit after EACH step: a flap mid-(c) must not cost (b).
            _commit_evidence(repo, [args.out, LEDGER_NAME])
            _capture_probes(repo)
            _commit_evidence(repo, ["PROBES_TPU.jsonl"])
            _capture_e2e(repo)
            _commit_evidence(repo, [args.out, "E2E_BENCH_TPU.json",
                                    "PROBES_TPU.jsonl", LEDGER_NAME])
            if args.once:
                return 0
        time.sleep(args.interval)


def _ledger_progress(repo: str, ledger_path: str, on_chip_before: set,
                     extra=()) -> Ledger:
    """Reload the ledger, log the convergence line, and commit it (plus
    ``extra`` artifacts) if this window added on-chip evidence.  Runs on
    EVERY exit path from a bench attempt — including timeouts and
    no-JSON crashes, where benchlib's per-attempt checkpoints may hold
    evidence the dead run never reported."""
    led = Ledger(ledger_path)
    print(led.summary_line(BENCH_STAGES), flush=True)
    on_chip_after = {s for s in BENCH_STAGES if led.captured_on_tpu(s)}
    if on_chip_after - on_chip_before:
        _commit_evidence(repo, [LEDGER_NAME, *extra])
    return led


def _reentry_env(led: Ledger) -> dict:
    """Env overrides for a window's bench run: one fresh window id per
    wake-up (every ledger record the run captures cites it), and
    ledger re-entry — when some stages already hold on-chip numbers,
    ``ADAM_TPU_BENCH_ONLY`` limits the run to the missing ones so a
    window never re-pays captured evidence (bench re-sorts the subset
    information-first)."""
    env = {"ADAM_TPU_WINDOW_ID": new_window_id()}
    missing = led.missing_stages(BENCH_STAGES)
    if missing and set(missing) != set(BENCH_STAGES):
        env["ADAM_TPU_BENCH_ONLY"] = ",".join(missing)
    return env


def _save_artifact(repo: str, out_name: str, doc: dict) -> str:
    """Write the bench artifact UNLESS that would clobber a captured TPU
    artifact with a worse one — a tunnel flap mid-bench would otherwise
    destroy the very evidence this tool exists to preserve.  Worse
    means: a CPU-fallback doc over a TPU one, or a headline-less doc
    (value 0 — e.g. a ledger re-entry run that never measured flagstat)
    over a TPU doc with a real value.  Returns "saved" or "kept"."""
    out_path = os.path.join(repo, out_name)
    existing = None
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                existing = json.load(f)
        except ValueError:
            existing = None  # corrupt existing file: overwrite it
    if existing and existing.get("platform") == "tpu":
        if doc.get("platform") != "tpu":
            return "kept"
        if not doc.get("value") and existing.get("value"):
            return "kept"
    # atomic_write: this artifact may be git-committed the moment it
    # lands (_commit_evidence) — a torn write must never publish
    from adam_tpu.checkpoint import atomic_write

    atomic_write(out_path, json.dumps(doc, indent=1))
    return "saved"


def _commit_evidence(repo: str, names) -> None:
    """Commit captured TPU artifacts the moment they exist — a tunnel
    window can open and close while nobody is watching, and an
    uncommitted artifact is one `rm`/crash away from being round-3's
    story again.  Stages ONLY the named files."""
    present = [n for n in names if os.path.exists(os.path.join(repo, n))]
    if not present:
        return
    try:
        # add first: a pathspec'd `git commit -- FILE` errors on files
        # git has never seen, and fresh-window evidence files are
        # exactly that (caught in a round-5 rehearsal — a real window's
        # artifacts would have sat uncommitted, round-3's story again)
        subprocess.run(["git", "add", "--"] + present, cwd=repo,
                       capture_output=True, text=True, timeout=30)
        # pathspec'd commit: ONLY the named files land in it, regardless
        # of whatever else a concurrent session may have staged
        rc = subprocess.run(
            ["git", "commit", "-m",
             "Record TPU evidence artifacts captured by tpu_watch",
             "--"] + present,
            cwd=repo, capture_output=True, text=True, timeout=30)
        if rc.returncode == 0:
            print(f"committed evidence: {', '.join(present)}", flush=True)
        elif "nothing to commit" in (rc.stdout + rc.stderr) or \
                "no changes added" in (rc.stdout + rc.stderr):
            pass                      # already committed last window
        else:
            print(f"evidence commit rc={rc.returncode}: "
                  f"{(rc.stderr or rc.stdout).strip()[:300]}", flush=True)
    except Exception as e:  # noqa: BLE001 — capture keeps priority
        print(f"evidence commit failed: {e}", flush=True)


_AOT_TRIED = False


def _capture_aot(repo: str) -> None:
    """The no-tunnel branch's evidence (VERDICT r4 #2): AOT-lower every
    product Pallas kernel for the TPU target — trace + StableHLO + Mosaic
    serialization need no device.  At most ONE attempt per watcher
    process (success or not): a crash-looping aot_check must not blind
    the probe loop to minute-scale tunnel up-windows, and its failure
    output is surfaced, not discarded."""
    global _AOT_TRIED
    out = "AOT_CHECK.json"
    if _AOT_TRIED or os.path.exists(os.path.join(repo, out)):
        return
    _AOT_TRIED = True
    print("tunnel down — capturing AOT lowering evidence", flush=True)
    try:
        rc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "aot_check.py"),
             "--out", out],
            timeout=900, capture_output=True, text=True, cwd=repo)
        if rc.returncode != 0:
            tail = (rc.stderr or rc.stdout or "").strip().splitlines()[-5:]
            print(f"aot check rc={rc.returncode}: " + " | ".join(tail),
                  flush=True)
    except subprocess.TimeoutExpired:
        print("aot check timed out", flush=True)
    if os.path.exists(os.path.join(repo, out)):
        _commit_evidence(repo, [out])


_PROBE_IDS = ("7", "6", "4", "5", "2", "3", "1")


def _probe_output_complete(text: str) -> bool:
    """TPU-platform env line + a *_done line for every probe."""
    lines = []
    for ln in text.splitlines():
        try:
            if ln.strip():
                lines.append(json.loads(ln))
        except ValueError:
            continue
    envs = [d for d in lines if d.get("probe") == "env"]
    if not envs or "tpu" not in (envs[0].get("device_kind", "") +
                                 envs[0].get("platform", "")).lower():
        return False
    done = {d["probe"] for d in lines
            if d.get("probe", "").endswith("_done")}
    return len(done) >= len(_PROBE_IDS)


def _capture_probes(repo: str) -> None:
    """One-shot probe suite (block sweeps, kernel attribution) after the
    bench + e2e artifacts are safe — the lowest-priority use of a tunnel
    window, but the one that decides which kernel variants ship.  Retries
    in later windows until a COMPLETE on-TPU run exists: a CPU-fallback
    or partial (timed-out) capture is kept for inspection but does not
    satisfy the guard."""
    out_path = os.path.join(repo, "PROBES_TPU.jsonl")
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                if _probe_output_complete(f.read()):
                    return
        except ValueError:
            pass
    print("running probe suite", flush=True)
    out = ""
    try:
        rc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "tpu_probe_suite.py")],
            timeout=1200, capture_output=True, text=True, cwd=repo)
        out = rc.stdout
        if not out.strip():
            # an import-time death produces zero probe lines; say WHY
            # instead of silently recording an empty capture
            tail = (rc.stderr or "").strip().splitlines()[-5:]
            print(f"probe suite emitted nothing (rc={rc.returncode}): "
                  + " | ".join(tail), flush=True)
    except subprocess.TimeoutExpired as e:
        # keep whatever probes streamed before the deadline (a later
        # window re-runs the whole suite; probes are idempotent)
        out = (e.stdout or b"").decode(errors="replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        print("probe suite timed out; partial output kept", flush=True)
    if out.strip():
        with open(out_path, "w") as f:
            f.write(out)
    print(f"probe capture: complete={_probe_output_complete(out)} "
          f"({len(out.splitlines())} lines)", flush=True)


def _capture_e2e(repo: str) -> None:
    """After a TPU bench lands, also run the end-to-end product-path bench
    against the chip (VERDICT r3 #7: per-stage breakdown with a tpu
    platform field).  Small read count: the 45 MB/s tunnel carries every
    chunk's device_put.  Never clobbers an existing TPU e2e artifact."""
    out_path = os.path.join(repo, "E2E_BENCH_TPU.json")
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                if json.load(f).get("platform") == "tpu":
                    return
        except ValueError:
            pass
    print("running bench_e2e against the chip", flush=True)
    try:
        rc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench_e2e.py"),
             "--reads", os.environ.get("ADAM_TPU_E2E_TPU_READS", "250000"),
             "--out", out_path],
            timeout=1500, capture_output=True, text=True, cwd=repo)
    except subprocess.TimeoutExpired:
        print("e2e bench timed out", flush=True)
        return
    if rc.returncode != 0:
        tail = (rc.stderr or rc.stdout or "").strip().splitlines()[-8:]
        print(f"e2e bench rc={rc.returncode}:", flush=True)
        for line in tail:
            print(f"  {line}", flush=True)
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                doc = json.load(f)
            print(f"e2e captured platform={doc.get('platform')} "
                  f"reads/s={doc.get('reads_per_sec')}", flush=True)
            if doc.get("platform") != "tpu":
                os.remove(out_path)     # CPU fallback is not the artifact
        except ValueError:
            pass


if __name__ == "__main__":
    raise SystemExit(main())
