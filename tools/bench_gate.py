#!/usr/bin/env python3
"""Gate the committed transform BENCH artifacts through compare_bench.

Two checks, both running :mod:`tools.compare_bench` (the PR 6 artifact
differ) with ``--threshold``:

1. **The fusion win is pinned.**  ``BENCH_TRANSFORM_BASELINE.json``
   (the legacy 4-pass ledger) vs ``BENCH_TRANSFORM.json`` (the fused
   streams) on ``io_spill_amplification`` with ``--threshold=-40``: a
   negative threshold inverts the gate into a REQUIREMENT — the fused
   artifact must be at least 40% below the legacy baseline (ISSUE 7's
   acceptance number), or this exits nonzero.

2. **Future PRs cannot regress the fused numbers.**  When a freshly
   generated artifact is passed (``bench_gate.py NEW.json``, produced
   by ``python bench_transform.py --stream --artifacts DIR``), it is
   diffed against the committed ``BENCH_TRANSFORM.json`` at the
   standard 10% threshold over the amplification AND the wall — a
   transform io/wall regression exits nonzero locally before it ships.

Usage::

    python tools/bench_gate.py            # check 1 only (committed pair)
    python tools/bench_gate.py NEW.json   # checks 1 + 2

Exit 0 when every gate holds; the first failing compare_bench exit code
otherwise.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import compare_bench  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(ROOT, "BENCH_TRANSFORM_BASELINE.json")
CURRENT = os.path.join(ROOT, "BENCH_TRANSFORM.json")

#: the ISSUE 7 acceptance number: fused must cut the spill-I/O
#: amplification by at least this much vs the legacy baseline
REQUIRED_CUT_PCT = 40.0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    for path in (BASELINE, CURRENT):
        if not os.path.exists(path):
            print(f"bench_gate: missing committed artifact {path} "
                  "(regenerate with: python bench_transform.py --stream "
                  "--artifacts .)", file=sys.stderr)
            return 2

    print(f"== gate 1: fused cuts io_spill_amplification >= "
          f"{REQUIRED_CUT_PCT}% vs the legacy baseline ==")
    rc = compare_bench.main([BASELINE, CURRENT,
                             "--keys", "io_spill_amplification",
                             f"--threshold=-{REQUIRED_CUT_PCT}"])
    if rc != 0:
        print("bench_gate: the committed fused artifact no longer cuts "
              f"spill amplification by {REQUIRED_CUT_PCT}% — the fusion "
              "win regressed", file=sys.stderr)
        return rc

    if argv:
        fresh = argv[0]
        print(f"\n== gate 2: {fresh} vs committed {CURRENT} "
              "(10% regression threshold) ==")
        rc = compare_bench.main([
            CURRENT, fresh,
            "--keys", "io_spill_amplification,transform_stream_wall_s",
            "--threshold", "10"])
        if rc != 0:
            print("bench_gate: transform io/wall regressed past 10% vs "
                  "the committed artifact", file=sys.stderr)
            return rc

    print("\nbench_gate: all gates hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
