#!/usr/bin/env python3
"""Gate the committed BENCH artifacts through compare_bench.

Checks, each running :mod:`tools.compare_bench` (the PR 6 artifact
differ) with ``--threshold`` where applicable:

1. **The fusion win is pinned.**  ``BENCH_TRANSFORM_BASELINE.json``
   (the legacy 4-pass ledger) vs ``BENCH_TRANSFORM.json`` (the fused
   streams) on ``io_spill_amplification`` with ``--threshold=-40``: a
   negative threshold inverts the gate into a REQUIREMENT — the fused
   artifact must be at least 40% below the legacy baseline (ISSUE 7's
   acceptance number), or this exits nonzero.

2. **Future PRs cannot regress the fused numbers.**  When a freshly
   generated artifact is passed (``bench_gate.py NEW.json``, produced
   by ``python bench_transform.py --stream --artifacts DIR``), it is
   diffed against the committed ``BENCH_TRANSFORM.json`` at the
   standard 10% threshold over the amplification AND the wall — a
   transform io/wall regression exits nonzero locally before it ships.

3. **The ragged-layout win is pinned.**  ``BENCH_RAGGED.json`` (the
   committed length-skewed CPU ``ragged_race`` artifact) must show the
   ragged realign sweep beating the 4-axis-padded form by >= 20% of
   sweep wall on the skewed input (ISSUE 8's acceptance number), and
   every raced ragged kernel bit-identical to its padded twin.  A
   fresh ragged artifact (``--ragged NEW_RAGGED.json``, from
   ``python bench.py --worker ragged_race``) additionally diffs BOTH
   layouts' sweep walls against the committed numbers at 10% — a
   regression in either layout fails the check.

4. **The fleet scaling is pinned.**  ``BENCH_SHARD.json`` (the
   committed CPU-mesh ``shard_scale`` artifact, ISSUE 9) must show the
   2-host fleet beating the 1-host fleet by the committed floor on
   streaming-flagstat wall, with every fleet leg's counters
   byte-identical to the single-host product path.  A fresh artifact
   (``--shard NEW_SHARD.json``, from ``python bench.py --worker
   shard_scale``) additionally diffs the 1/2-host walls at the
   standard 10 % threshold.  The artifact records ``cpu_count``:
   hosts beyond the box's cores are reported (oversubscription data),
   never gated.

5. **The warm-serve win is pinned.**  ``BENCH_SERVE.json`` (the
   committed ``serve_warm`` artifact, ISSUE 10) must show a warm-serve
   job (job 2+, median) at least 2x faster than the same job as a cold
   CLI invocation, every warm AND packed-dispatch report byte-identical
   to the cold CLI output, and zero recompiles on warm jobs 2+.  A
   fresh artifact (``--serve NEW_SV.json``, from ``python bench.py
   --worker serve_warm``) additionally diffs the cold/warm job walls at
   the standard 10% threshold.

6. **The fleet-serve scaling is pinned.**  ``BENCH_FLEET_SERVE.json``
   (the committed ``fleet_serve`` artifact, ISSUE 12) must show the
   2-worker always-warm fleet beating the 1-worker fleet on K-tenant
   serve wall — armed, like gate 4, only when the artifact's own
   ``host_parallel_capacity`` probe saw real parallelism on the
   measuring box.  Tenant-report byte-identity against the in-process
   solo run and zero recompiles on jobs 2+ PER WORKER are enforced
   unconditionally.  A fresh artifact (``--fleet-serve NEW_FS.json``,
   from ``python bench.py --worker fleet_serve``) additionally diffs
   the 1/2-worker walls at the standard 10% threshold.

7. **The resident-paging win is pinned.**  ``BENCH_PAGED.json`` (the
   committed ``paged_race`` artifact, ISSUE 13) must show the paged
   serve leg shipping >= 2x fewer host→device bytes than the unpaged
   refill path on the steady-state round, every paged kernel twin
   bit-identical to its ragged form, per-tenant counters byte-identical
   to solo runs, and zero recompiles on a steady-state paged round —
   identity and zero-recompile unconditional.  A fresh artifact
   (``--paged NEW_P.json``, from ``python bench.py --worker
   paged_race``) additionally diffs both serve walls at 10%.

8. **The overload plane is pinned.**  ``BENCH_OVERLOAD.json`` (the
   committed ``overload`` artifact, ISSUE 14) drives one warm server
   at 2x its accepted backlog capacity, with and without the brownout
   ladder + admission caps armed.  Unconditional: every ACCEPTED job's
   report byte-identical to the solo oracle, zero warm recompiles,
   the ladder actually engaged (``overload_max_level`` >= 1), and
   every shed job left a typed ``rejected/`` doc with a
   ``retry_after_s`` hint (never a silent drop).  Capacity-armed (the
   gate-4/6 discipline): accepted-job goodput >= the unprotected
   baseline and accepted-job queue p99 <= the unprotected tail.  A
   fresh artifact (``--overload NEW_O.json``, from ``python bench.py
   --worker overload``) additionally diffs both serve walls at 10%.

9. **The variant-calling plane is pinned.**  ``BENCH_CALL.json`` (the
   committed ``call`` artifact, ISSUE 17) runs solo ``streaming_call``
   with the scalar-oracle differential, a warm rerun, and the served
   co-tenant leg.  Unconditional: the device VCF byte-identical to the
   oracle, the served VCF byte-identical to solo, the warm rerun's sha
   unchanged, zero warm recompiles.  Capacity-armed (the gate-4/6/8
   discipline): the warm read-throughput floor.  A fresh artifact
   (``--call NEW_C.json``, from ``python bench.py --worker call``)
   additionally diffs the call walls at 10%.

10. **The fused mega-pass is pinned.**  ``BENCH_MEGA.json`` (the
    committed ``mega_race`` artifact, ISSUE 18) must show the fused
    multi-output kernel issuing >= 2x fewer per-chunk device
    dispatches than the three unfused kernels on the combined leg
    (the ``dispatch_count{pass=}`` accounting), every fused leg —
    flagstat block, markdup keys, BQSR covariates, across padded/
    ragged/paged and the XLA + Mosaic-interpreter routes —
    bit-identical to its unfused twin, and zero recompiles on a warm
    fused round — all unconditional.  Capacity-armed (the gate-4/6/8/9
    discipline): the fused wall must stay within slack of the unfused
    wall.  A fresh artifact (``--mega NEW_M.json``, from
    ``python bench.py --worker mega_race``) additionally diffs the
    combined-leg walls at 10%.

Usage::

    python tools/bench_gate.py                       # committed gates
    python tools/bench_gate.py NEW.json              # + transform diff
    python tools/bench_gate.py --ragged NEW_R.json   # + ragged diff
    python tools/bench_gate.py --shard NEW_S.json    # + fleet diff
    python tools/bench_gate.py --serve NEW_SV.json   # + serve diff
    python tools/bench_gate.py --fleet-serve NEW_FS.json  # + diff
    python tools/bench_gate.py --paged NEW_P.json    # + paged diff
    python tools/bench_gate.py --overload NEW_O.json # + overload diff
    python tools/bench_gate.py --call NEW_C.json     # + call diff
    python tools/bench_gate.py --mega NEW_M.json     # + mega diff

Exit 0 when every gate holds; the first failing check's exit code
otherwise.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import compare_bench  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(ROOT, "BENCH_TRANSFORM_BASELINE.json")
CURRENT = os.path.join(ROOT, "BENCH_TRANSFORM.json")
RAGGED = os.path.join(ROOT, "BENCH_RAGGED.json")

#: the ISSUE 7 acceptance number: fused must cut the spill-I/O
#: amplification by at least this much vs the legacy baseline
REQUIRED_CUT_PCT = 40.0

#: the ISSUE 8 acceptance number: the ragged realign sweep must beat
#: the 4-axis-padded form by >= 20% of sweep wall on the committed
#: length-skewed artifact (wall_padded / wall_ragged >= 1.25)
RAGGED_REQUIRED_SPEEDUP = 1.25

#: the ragged-vs-padded walls a fresh artifact is regression-diffed on
#: (both layouts: a regression in EITHER fails)
RAGGED_WALL_KEYS = ("ragged_realign_skewed_padded_wall_s",
                    "ragged_realign_skewed_ragged_wall_s",
                    "ragged_realign_uniform_padded_wall_s",
                    "ragged_realign_uniform_ragged_wall_s")

SHARD = os.path.join(ROOT, "BENCH_SHARD.json")

#: the ISSUE 9 acceptance floor: the 2-host fleet must beat the 1-host
#: fleet on streaming-flagstat wall.  The committed box advertises 2
#: CPUs but its MEASURED aggregate parallel capacity (the artifact's
#: ``host_parallel_capacity``, a 2-process burn ratio) fluctuates with
#: neighbor load between ~0.8x (LESS than one core available) and
#: ~1.3x — that capacity, not the host count, caps what process-level
#: scaling can show here.  So the scaling floor applies ONLY when the
#: artifact's own capacity probe saw real parallelism
#: (>= SHARD_CAPACITY_FLOOR); below it the gate still enforces counter
#: identity and reports the run as capacity-limited.  On a real pod
#: (per-host cores), regenerate and the floor re-arms automatically.
SHARD_REQUIRED_SPEEDUP = 1.05
SHARD_CAPACITY_FLOOR = 1.2
#: enforced UNCONDITIONALLY, capacity-limited or not: adding a host may
#: buy nothing on a starved box, but it must never make the fleet
#: catastrophically slower — a 2-host run below this fraction of the
#: 1-host wall means the fleet machinery itself regressed
SHARD_MIN_SPEEDUP_ANY = 0.5

#: the fleet walls a fresh artifact is regression-diffed on
SHARD_WALL_KEYS = ("shard_hosts1_wall_s", "shard_hosts2_wall_s")

#: ISSUE 19 data-plane acceptance, enforced when the artifact carries
#: the keys (older artifacts predate the ring plane and still pass):
#: the batched-spool ring leg must cut commit fsyncs >= 3x vs the
#: forced fleet_dir + per-file-fsync leg on the same input, and the
#: index-assisted BAM fleet's ledger must decode ~1x the file (the
#: frac is bytes decoded BEYOND one pass, per file byte — BGZF member
#: granularity and the per-shard header parse put the honest floor a
#: few percent above zero, where the forward fleet pays ~1.0)
SHARD_FSYNC_REDUCTION_FLOOR = 3.0
SHARD_REDECODE_FRAC_MAX = 0.15
SHARD_TRANSPORTS = ("ring", "fleet_dir")

FLEET_SERVE = os.path.join(ROOT, "BENCH_FLEET_SERVE.json")

#: the ISSUE 12 acceptance numbers, the gate-4 capacity discipline: the
#: 2-worker fleet must beat the 1-worker fleet on K-tenant serve wall
#: ONLY when the artifact's own ``host_parallel_capacity`` probe saw
#: real parallelism (this box advertises 2 CPUs, delivers ~0.8-1.3x);
#: byte-identity of every tenant's report against the in-process solo
#: run and zero recompiles on jobs 2+ PER WORKER are enforced
#: unconditionally — wrong bytes or a warm-path recompile is a
#: machinery regression whatever the box's load.
FLEET_SERVE_REQUIRED_SPEEDUP = 1.05
FLEET_SERVE_CAPACITY_FLOOR = 1.2
#: enforced unconditionally: a second warm worker may buy nothing on a
#: starved box, but below this fraction of the 1-worker wall the fleet
#: scheduler itself regressed (the SHARD_MIN_SPEEDUP_ANY discipline).
#: Two warm jax worker processes on this sub-1-core container are pure
#: oversubscription — three consecutive artifact runs measured 0.46x /
#: 0.63x / 0.87x from neighbor load alone — so the floor sits below
#: that noise band; a genuine serialization collapse lands far under it
FLEET_SERVE_MIN_SPEEDUP_ANY = 0.35

#: the fleet-serve walls a fresh artifact is regression-diffed on
FLEET_SERVE_WALL_KEYS = ("fleet_hosts1_wall_s", "fleet_hosts2_wall_s")

SERVE = os.path.join(ROOT, "BENCH_SERVE.json")

#: the ISSUE 10 acceptance number: a warm-serve job (job 2+, median)
#: must run >= 2x faster than the same job as a cold CLI invocation
#: (job 2+, median — job 1 pays first-compile on both sides and is
#: reported, not gated).  Identity and the zero-recompile pin are
#: enforced unconditionally: amortization may vary with box load, but
#: wrong bytes or a warm-path recompile is a machinery regression.
SERVE_REQUIRED_SPEEDUP = 2.0

#: the serve walls a fresh artifact is regression-diffed on
SERVE_WALL_KEYS = ("serve_cold_job_wall_s", "serve_warm_job_wall_s")

PAGED = os.path.join(ROOT, "BENCH_PAGED.json")

#: the ISSUE 13 acceptance number: the paged serve leg must ship at
#: least this factor fewer host→device bytes than the unpaged refill
#: path on the steady-state round (round 2+, resident pool + warm
#: shapes).  Identity and the zero-recompile pin are enforced
#: unconditionally — the byte reduction is deterministic accounting
#: (the h2d_bytes counter), not a wall-clock measurement, so the gate
#: never disarms for box load.
PAGED_REQUIRED_H2D_REDUCTION = 2.0

#: the paged walls a fresh artifact is regression-diffed on
PAGED_WALL_KEYS = ("unpaged_serve_wall_s", "paged_serve_wall_s")

#: every kernel twin gate 7 requires — REQUIRED, not scanned: a twin
#: that crashed outright records ``paged_*_error`` and omits its key,
#: which must fail the gate, never pass it silently
PAGED_TWIN_KEYS = ("paged_flagstat_matches_ragged",
                   "paged_segmented_matches_ragged",
                   "paged_bqsr_matches_ragged",
                   "paged_realign_matches_ragged")

OVERLOAD = os.path.join(ROOT, "BENCH_OVERLOAD.json")

#: the ISSUE 14 acceptance numbers.  Capacity-armed (gate-4/6
#: discipline): under 2x-capacity offered load the armed server's
#: accepted-job goodput must not fall below the unprotected baseline
#: and its accepted-job queue p99 must not exceed the unprotected
#: tail.  On a starved box both ratios are neighbor-noise — the
#: committed container delivers ~0.8-1.3x of one core — so, like gates
#: 4 and 6, they arm only when the artifact's own capacity probe saw
#: real parallelism; identity, zero warm recompiles, ladder
#: engagement, and typed rejections are enforced unconditionally.
OVERLOAD_GOODPUT_FLOOR = 1.0
OVERLOAD_QUEUE_P99_CEIL = 1.0
OVERLOAD_CAPACITY_FLOOR = 1.2
#: enforced unconditionally (the SHARD_MIN_SPEEDUP_ANY discipline):
#: shedding half the offered load may buy nothing on a noisy box, but
#: below this fraction of baseline goodput the overload machinery
#: itself is eating throughput
OVERLOAD_GOODPUT_MIN_ANY = 0.35

#: the overload walls a fresh artifact is regression-diffed on
OVERLOAD_WALL_KEYS = ("overload_baseline_wall_s",
                      "overload_armed_wall_s")

CALL = os.path.join(ROOT, "BENCH_CALL.json")

#: the ISSUE 17 acceptance numbers.  Unconditional: the device VCF
#: byte-identical to the scalar oracle (``call_identical``), the
#: served co-tenant VCF byte-identical to the solo run
#: (``call_served_identical``), the warm rerun's sha unchanged, and
#: zero warm recompiles.  Capacity-armed (the gate-4/6/8 discipline):
#: the warm-run read throughput floor applies only when the artifact's
#: own ``host_parallel_capacity`` probe saw real parallelism — the
#: committed sub-1-core container delivers ~0.8-1.0x, so on it the
#: rate is reported, not gated.
CALL_READS_PER_SEC_FLOOR = 800
CALL_CAPACITY_FLOOR = 1.2
#: enforced unconditionally (the SHARD_MIN_SPEEDUP_ANY discipline):
#: box load can halve the rate, but below this the calling machinery
#: itself regressed
CALL_READS_PER_SEC_MIN_ANY = 100

#: the call walls a fresh artifact is regression-diffed on
CALL_WALL_KEYS = ("call_solo_wall_s", "call_warm_wall_s",
                  "call_served_wall_s")

MEGA = os.path.join(ROOT, "BENCH_MEGA.json")

#: the ISSUE 18 acceptance numbers.  Unconditional: every fused leg
#: bit-identical to its unfused twin (``mega_identical`` + the twin
#: keys below), zero recompiles on a warm fused round, and the
#: per-chunk device-dispatch collapse — the combined leg's
#: ``dispatch_count{pass=}`` ratio must show the fused route issuing
#: at least this factor fewer dispatches than the three unfused
#: kernels over the same chunks.  The reduction is deterministic
#: accounting (the dispatch_count counter), not a wall measurement,
#: so it never disarms for box load.
MEGA_REQUIRED_DISPATCH_REDUCTION = 2.0
#: capacity-armed (the gate-4/6/8/9 discipline): the fused wall must
#: not fall behind the unfused wall beyond this slack — but only when
#: the artifact's own ``host_parallel_capacity`` probe saw real
#: parallelism; on the committed sub-1-core container the walls are
#: neighbor-noise and are reported, not gated
MEGA_WALL_SLACK = 1.05
MEGA_CAPACITY_FLOOR = 1.2

#: the mega walls a fresh artifact is regression-diffed on
MEGA_WALL_KEYS = ("mega_unfused_wall_s", "mega_fused_wall_s")

#: every kernel twin gate 10 requires — REQUIRED, not scanned: a twin
#: that crashed outright records ``mega_*_error`` and omits its key,
#: which must fail the gate, never pass it silently (the
#: PAGED_TWIN_KEYS discipline)
MEGA_TWIN_KEYS = ("mega_padded_xla_matches_unfused",
                  "mega_padded_pallas_matches_unfused",
                  "mega_ragged_matches_unfused",
                  "mega_paged_matches_ragged",
                  "mega_combined_identical")


def _check_call_artifact(path: str) -> int:
    """Gate 9's committed-artifact half: oracle identity, served
    co-tenant identity, warm-rerun sha stability, zero warm recompiles
    (unconditional); warm read throughput floor (capacity-armed)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable call artifact {path}: {e}",
              file=sys.stderr)
        return 2
    rc = 0
    if doc.get("call_identical") is not True:
        print(f"bench_gate: call_identical is not true in {path} — "
              "the device VCF is no longer byte-identical to the "
              "scalar oracle", file=sys.stderr)
        rc = 1
    if doc.get("call_served_identical") is not True:
        print(f"bench_gate: call_served_identical is not true in "
              f"{path} — the served co-tenant VCF diverged from the "
              "solo run", file=sys.stderr)
        rc = 1
    if doc.get("call_warm_sha_matches") is not True:
        print(f"bench_gate: call_warm_sha_matches is not true in "
              f"{path} — a warm rerun changed the VCF bytes",
              file=sys.stderr)
        rc = 1
    if doc.get("call_warm_recompiles") != 0:
        print(f"bench_gate: call_warm_recompiles "
              f"{doc.get('call_warm_recompiles')!r} in {path} — a "
              "warm call rerun must reuse every compiled shape "
              "(compile-count delta 0)", file=sys.stderr)
        rc = 1
    rate = doc.get("call_reads_per_sec")
    capacity = doc.get("host_parallel_capacity")
    gated = isinstance(capacity, (int, float)) and \
        capacity >= CALL_CAPACITY_FLOOR
    if not isinstance(rate, (int, float)):
        print(f"bench_gate: call artifact {path} carries no "
              "call_reads_per_sec", file=sys.stderr)
        rc = 1
    elif gated and rate < CALL_READS_PER_SEC_FLOOR:
        print(f"bench_gate: call throughput {rate!r} reads/s in "
              f"{path} is below the required "
              f"{CALL_READS_PER_SEC_FLOOR} on a box with measured "
              f"parallel capacity {capacity}x — the calling plane "
              "regressed", file=sys.stderr)
        rc = 1
    elif rate < CALL_READS_PER_SEC_MIN_ANY:
        print(f"bench_gate: call throughput {rate!r} reads/s in "
              f"{path} is below the unconditional floor "
              f"{CALL_READS_PER_SEC_MIN_ANY} — the calling machinery "
              "itself regressed (this floor applies even on a "
              "capacity-limited box)", file=sys.stderr)
        rc = 1
    if rc == 0:
        how = (f"{rate} reads/s >= {CALL_READS_PER_SEC_FLOOR}"
               if gated else
               f"{rate} reads/s reported, not gated — measured "
               f"parallel capacity {capacity}x < "
               f"{CALL_CAPACITY_FLOOR}x (capacity-limited box)")
        print(f"call gate: {doc.get('call_n_reads')} reads -> "
              f"{doc.get('call_calls')} calls, oracle byte-identical "
              "solo AND served, 0 warm recompiles; " + how)
    return rc


def _check_paged_artifact(path: str) -> int:
    """Gate 7's committed-artifact half: the >= 2x steady-state
    h2d-byte reduction on the serve leg, kernel-twin bit-identity, and
    the identity + zero-recompile pins (both unconditional)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable paged artifact {path}: {e}",
              file=sys.stderr)
        return 2
    rc = 0
    red = doc.get("paged_h2d_reduction")
    if not isinstance(red, (int, float)) or \
            red < PAGED_REQUIRED_H2D_REDUCTION:
        print(f"bench_gate: paged h2d-byte reduction {red!r} in {path} "
              f"is below the required {PAGED_REQUIRED_H2D_REDUCTION}x "
              "on the steady-state serve leg — the resident-paging win "
              "regressed", file=sys.stderr)
        rc = 1
    if doc.get("paged_identical") is not True:
        print(f"bench_gate: paged_identical is not true in {path} — "
              "paged serve counters no longer byte-identical to solo "
              "runs", file=sys.stderr)
        rc = 1
    if doc.get("paged_steady_recompiles") != 0:
        print(f"bench_gate: paged_steady_recompiles "
              f"{doc.get('paged_steady_recompiles')!r} in {path} — a "
              "steady-state paged round must reuse every compiled "
              "shape (compile-count delta 0)", file=sys.stderr)
        rc = 1
    mism = [k for k in PAGED_TWIN_KEYS if doc.get(k) is not True]
    mism += sorted(k for k in doc
                   if k.startswith("paged_") and k.endswith("_error"))
    if mism:
        print("bench_gate: paged kernel twins no longer bit-identical "
              f"to their ragged forms in {path}: {mism}",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print(f"paged gate: steady-state h2d bytes {red}x >= "
              f"{PAGED_REQUIRED_H2D_REDUCTION}x reduction "
              f"({doc.get('paged_n_jobs')} tenants x "
              f"{doc.get('paged_n_reads')} reads), all twins "
              "bit-identical, identity true, 0 steady recompiles")
    return rc


def _check_mega_artifact(path: str) -> int:
    """Gate 10's committed-artifact half: the >= 2x per-chunk
    dispatch-count collapse on the combined leg, every fused leg
    bit-identical to its unfused twin, and the zero-recompile pin
    (all unconditional); the fused-wall slack (capacity-armed)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable mega artifact {path}: {e}",
              file=sys.stderr)
        return 2
    rc = 0
    red = doc.get("mega_dispatch_reduction")
    if not isinstance(red, (int, float)) or \
            red < MEGA_REQUIRED_DISPATCH_REDUCTION:
        print(f"bench_gate: mega dispatch reduction {red!r} in {path} "
              f"is below the required "
              f"{MEGA_REQUIRED_DISPATCH_REDUCTION}x on the combined "
              "leg — the fused mega-pass no longer collapses the "
              "per-chunk dispatches", file=sys.stderr)
        rc = 1
    if doc.get("mega_identical") is not True:
        print(f"bench_gate: mega_identical is not true in {path} — a "
              "fused mega-pass leg no longer byte-identical to its "
              "unfused twin", file=sys.stderr)
        rc = 1
    if doc.get("mega_steady_recompiles") != 0:
        print(f"bench_gate: mega_steady_recompiles "
              f"{doc.get('mega_steady_recompiles')!r} in {path} — a "
              "warm fused round must reuse every compiled shape "
              "(compile-count delta 0)", file=sys.stderr)
        rc = 1
    mism = [k for k in MEGA_TWIN_KEYS if doc.get(k) is not True]
    mism += sorted(k for k in doc
                   if k.startswith("mega_") and k.endswith("_error"))
    if mism:
        print("bench_gate: mega-pass legs no longer bit-identical to "
              f"their unfused twins in {path}: {mism}",
              file=sys.stderr)
        rc = 1
    un = doc.get("mega_unfused_wall_s")
    fu = doc.get("mega_fused_wall_s")
    capacity = doc.get("host_parallel_capacity")
    gated = isinstance(capacity, (int, float)) and \
        capacity >= MEGA_CAPACITY_FLOOR
    walls_ok = isinstance(un, (int, float)) and \
        isinstance(fu, (int, float))
    if not walls_ok:
        print(f"bench_gate: mega artifact {path} carries no "
              "mega_unfused_wall_s/mega_fused_wall_s pair",
              file=sys.stderr)
        rc = 1
    elif gated and fu > MEGA_WALL_SLACK * un:
        print(f"bench_gate: fused wall {fu}s exceeds "
              f"{MEGA_WALL_SLACK}x the unfused wall {un}s in {path} "
              f"on a box with measured parallel capacity {capacity}x "
              "— one dispatch per chunk got slower than three",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        how = (f"fused wall {fu}s within {MEGA_WALL_SLACK}x of "
               f"unfused {un}s"
               if gated else
               f"walls {un}s unfused / {fu}s fused reported, not "
               f"gated — measured parallel capacity {capacity}x < "
               f"{MEGA_CAPACITY_FLOOR}x (capacity-limited box)")
        print(f"mega gate: combined leg {red}x >= "
              f"{MEGA_REQUIRED_DISPATCH_REDUCTION}x dispatch-count "
              f"reduction ({doc.get('mega_n_chunks')} chunks x "
              f"{doc.get('mega_chunk_rows')} rows), every leg "
              f"bit-identical, 0 steady recompiles, {how}")
    return rc


def _check_overload_artifact(path: str) -> int:
    """Gate 8's committed-artifact half: accepted-job identity, zero
    warm recompiles, ladder engagement, typed rejections
    (unconditional); goodput floor + bounded accepted-job p99
    (capacity-armed)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable overload artifact {path}: {e}",
              file=sys.stderr)
        return 2
    rc = 0
    if doc.get("overload_identical") is not True:
        print(f"bench_gate: overload_identical is not true in {path} "
              "— accepted jobs under overload no longer byte-identical "
              "to the solo oracle", file=sys.stderr)
        rc = 1
    if doc.get("overload_warm_recompiles") != 0:
        print(f"bench_gate: overload_warm_recompiles "
              f"{doc.get('overload_warm_recompiles')!r} in {path} — "
              "warm jobs 2+ must reuse the compiled shapes under "
              "overload too", file=sys.stderr)
        rc = 1
    lvl = doc.get("overload_max_level")
    if not (isinstance(lvl, int) and lvl >= 1):
        print(f"bench_gate: overload_max_level {lvl!r} in {path} — "
              "the brownout ladder never engaged; the artifact is not "
              "measuring overload", file=sys.stderr)
        rc = 1
    if doc.get("overload_rejects_typed") is not True:
        print(f"bench_gate: overload_rejects_typed is not true in "
              f"{path} — a shed job left no typed rejected/ doc with "
              "retry_after_s (a silent drop)", file=sys.stderr)
        rc = 1
    good = doc.get("overload_goodput_ratio")
    p99r = doc.get("overload_queue_p99_ratio")
    capacity = doc.get("host_parallel_capacity")
    gated = isinstance(capacity, (int, float)) and \
        capacity >= OVERLOAD_CAPACITY_FLOOR
    if not isinstance(good, (int, float)):
        print(f"bench_gate: overload artifact {path} carries no "
              "overload_goodput_ratio", file=sys.stderr)
        rc = 1
    elif gated and good < OVERLOAD_GOODPUT_FLOOR:
        print(f"bench_gate: overload goodput ratio {good!r} in {path} "
              f"is below the required {OVERLOAD_GOODPUT_FLOOR}x on a "
              f"box with measured parallel capacity {capacity}x — "
              "shedding is eating accepted-job throughput",
              file=sys.stderr)
        rc = 1
    elif good < OVERLOAD_GOODPUT_MIN_ANY:
        print(f"bench_gate: overload goodput ratio {good!r} in {path} "
              f"is below the unconditional floor "
              f"{OVERLOAD_GOODPUT_MIN_ANY}x — the overload machinery "
              "itself regressed (this floor applies even on a "
              "capacity-limited box)", file=sys.stderr)
        rc = 1
    if gated and isinstance(p99r, (int, float)) and \
            p99r > OVERLOAD_QUEUE_P99_CEIL:
        print(f"bench_gate: overload accepted-job queue p99 ratio "
              f"{p99r!r} in {path} exceeds {OVERLOAD_QUEUE_P99_CEIL} "
              "— the armed tail is WORSE than the unprotected tail",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        how = (f"goodput {good}x >= {OVERLOAD_GOODPUT_FLOOR}x, p99 "
               f"ratio {p99r}"
               if gated else
               f"goodput {good}x / p99 ratio {p99r} reported, not "
               f"gated — measured parallel capacity {capacity}x < "
               f"{OVERLOAD_CAPACITY_FLOOR}x (capacity-limited box)")
        print(f"overload gate: {doc.get('overload_offered_jobs')} "
              f"jobs at {doc.get('overload_offered_ratio')}x "
              f"capacity, ladder reached level {lvl}, "
              f"{doc.get('overload_armed_rejected')} typed "
              f"rejection(s), identity true, 0 warm recompiles; {how}")
    return rc


def _check_serve_artifact(path: str) -> int:
    """Gate 5's committed-artifact half: the >= 2x warm-vs-cold win on
    job 2+, byte-identity of every warm/packed report against the cold
    CLI, and zero recompiles on warm jobs 2+."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable serve artifact {path}: {e}",
              file=sys.stderr)
        return 2
    rc = 0
    speedup = doc.get("serve_warm_speedup")
    if not isinstance(speedup, (int, float)) or \
            speedup < SERVE_REQUIRED_SPEEDUP:
        print(f"bench_gate: warm-serve speedup {speedup!r} in {path} "
              f"is below the required {SERVE_REQUIRED_SPEEDUP}x on "
              "job 2+ — the always-warm amortization regressed",
              file=sys.stderr)
        rc = 1
    for key in ("serve_identical", "serve_packed_identical"):
        if doc.get(key) is not True:
            print(f"bench_gate: {key} is not true in {path} — serve "
                  "output no longer byte-identical to the solo CLI",
                  file=sys.stderr)
            rc = 1
    if doc.get("serve_warm_recompiles") != 0:
        print(f"bench_gate: serve_warm_recompiles "
              f"{doc.get('serve_warm_recompiles')!r} in {path} — warm "
              "jobs 2+ must reuse the compiled shapes (compile-count "
              "delta 0)", file=sys.stderr)
        rc = 1
    # telemetry-honesty pin (conditional: artifacts regenerated before
    # the sampling plane existed carry no series fields): the always-on
    # sampler must cost NOTHING measurable on the warm path, and must
    # actually have sampled
    on_w = doc.get("serve_series_on_wall_s")
    off_w = doc.get("serve_series_off_wall_s")
    if isinstance(on_w, (int, float)) and isinstance(off_w,
                                                     (int, float)):
        budget = max(1.5 * off_w, off_w + 0.5)
        if on_w > budget:
            print(f"bench_gate: series-on warm wall {on_w}s exceeds "
                  f"{budget:.3f}s (series-off {off_w}s) in {path} — "
                  "the always-on sampler is taxing the warm hot path",
                  file=sys.stderr)
            rc = 1
        rows = doc.get("serve_series_rows")
        if not (isinstance(rows, int) and rows >= 1):
            print(f"bench_gate: serve_series_rows {rows!r} in {path} "
                  "— the series-on leg never sampled (the plane was "
                  "silently off, so the overhead pin proves nothing)",
                  file=sys.stderr)
            rc = 1
        if doc.get("serve_series_off_inert") is not True:
            print(f"bench_gate: serve_series_off_inert is not true in "
                  f"{path} — '-no_series' still wrote a series file "
                  "(off must mean OFF)", file=sys.stderr)
            rc = 1
    if rc == 0:
        print(f"serve gate: warm job {speedup}x >= "
              f"{SERVE_REQUIRED_SPEEDUP}x cold (job 2+ medians, "
              f"{doc.get('serve_n_jobs')} jobs x "
              f"{doc.get('serve_n_reads')} reads), all reports "
              "byte-identical, 0 warm recompiles")
    return rc


def _check_fleet_serve_artifact(path: str) -> int:
    """Gate 6's committed-artifact half: the capacity-armed 2-worker
    scaling floor, plus tenant-report identity and the per-worker
    zero-recompile pin — both unconditional."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable fleet-serve artifact {path}: "
              f"{e}", file=sys.stderr)
        return 2
    rc = 0
    speedup = doc.get("fleet_serve_speedup_2")
    capacity = doc.get("host_parallel_capacity")
    gated = isinstance(capacity, (int, float)) and \
        capacity >= FLEET_SERVE_CAPACITY_FLOOR
    if not isinstance(speedup, (int, float)):
        print(f"bench_gate: fleet-serve artifact {path} carries no "
              "fleet_serve_speedup_2", file=sys.stderr)
        rc = 1
    elif gated and speedup < FLEET_SERVE_REQUIRED_SPEEDUP:
        print(f"bench_gate: fleet-serve 2-worker speedup {speedup!r} "
              f"in {path} is below the required "
              f"{FLEET_SERVE_REQUIRED_SPEEDUP}x on a box with measured "
              f"parallel capacity {capacity}x — the fleet-serve "
              "scaling regressed", file=sys.stderr)
        rc = 1
    elif speedup < FLEET_SERVE_MIN_SPEEDUP_ANY:
        print(f"bench_gate: fleet-serve 2-worker speedup {speedup!r} "
              f"in {path} is below the unconditional floor "
              f"{FLEET_SERVE_MIN_SPEEDUP_ANY}x — the scheduler "
              "machinery itself regressed (this floor applies even on "
              "a capacity-limited box)", file=sys.stderr)
        rc = 1
    if doc.get("fleet_serve_identical") is not True:
        print("bench_gate: fleet-serve tenant reports no longer "
              f"byte-identical to the solo run in {path}",
              file=sys.stderr)
        rc = 1
    if doc.get("fleet_serve_recompiles") != 0:
        print(f"bench_gate: fleet_serve_recompiles "
              f"{doc.get('fleet_serve_recompiles')!r} in {path} — "
              "jobs 2+ on every warm worker must reuse the compiled "
              "shapes (compile-count delta 0)", file=sys.stderr)
        rc = 1
    if rc == 0:
        how = (f"speedup {speedup}x >= {FLEET_SERVE_REQUIRED_SPEEDUP}x"
               if gated else
               f"speedup {speedup}x reported, not gated — measured "
               f"parallel capacity {capacity}x < "
               f"{FLEET_SERVE_CAPACITY_FLOOR}x (capacity-limited box)")
        print(f"fleet-serve gate: 2-worker fleet {how} "
              f"({doc.get('fleet_serve_n_jobs')} tenants x "
              f"{doc.get('fleet_serve_n_reads')} reads), all reports "
              "byte-identical, 0 warm recompiles per worker")
    return rc


def _check_shard_artifact(path: str) -> int:
    """Gate 4's committed-artifact half: the 2-host scaling floor plus
    fleet-vs-single-host counter identity on every leg."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable shard artifact {path}: {e}",
              file=sys.stderr)
        return 2
    rc = 0
    speedup = doc.get("shard_speedup_2")
    capacity = doc.get("host_parallel_capacity")
    gated = isinstance(capacity, (int, float)) and \
        capacity >= SHARD_CAPACITY_FLOOR
    if not isinstance(speedup, (int, float)):
        print(f"bench_gate: shard artifact {path} carries no "
              "shard_speedup_2", file=sys.stderr)
        rc = 1
    elif gated and speedup < SHARD_REQUIRED_SPEEDUP:
        print(f"bench_gate: fleet 2-host speedup {speedup!r} in {path} "
              f"is below the required {SHARD_REQUIRED_SPEEDUP}x on a "
              f"box with measured parallel capacity {capacity}x — the "
              "shard-fleet scaling regressed", file=sys.stderr)
        rc = 1
    elif speedup < SHARD_MIN_SPEEDUP_ANY:
        print(f"bench_gate: fleet 2-host speedup {speedup!r} in {path} "
              f"is below the unconditional floor "
              f"{SHARD_MIN_SPEEDUP_ANY}x — the fleet machinery itself "
              "regressed (this floor applies even on a "
              "capacity-limited box)", file=sys.stderr)
        rc = 1
    if doc.get("shard_scale_identical") is not True:
        print("bench_gate: fleet flagstat counters no longer "
              f"byte-identical to the single-host run in {path}",
              file=sys.stderr)
        rc = 1
    # -- data-plane keys (ISSUE 19): enforced only when present, so
    # pre-ring artifacts (and forced-fleet_dir regenerations, which
    # simply skip the ring stamps) still pass
    transport = doc.get("shard_transport")
    if transport is not None and transport not in SHARD_TRANSPORTS:
        print(f"bench_gate: unknown shard_transport {transport!r} in "
              f"{path} (expected one of {SHARD_TRANSPORTS})",
              file=sys.stderr)
        rc = 1
    for key in ("shard_scale_fleetdir_identical", "shard_bam_identical"):
        if key in doc and doc[key] is not True:
            print(f"bench_gate: {key} is not True in {path} — a "
                  "data-plane leg no longer matches the single-host "
                  "oracle", file=sys.stderr)
            rc = 1
    reduction = doc.get("shard_fsync_reduction")
    if reduction is not None and reduction < SHARD_FSYNC_REDUCTION_FLOOR:
        print(f"bench_gate: shard_fsync_reduction {reduction!r} in "
              f"{path} is below the required "
              f"{SHARD_FSYNC_REDUCTION_FLOOR}x — the batched spool no "
              "longer amortizes commit fsyncs", file=sys.stderr)
        rc = 1
    frac = doc.get("shard_entry_redecode_frac")
    if frac is not None and frac > SHARD_REDECODE_FRAC_MAX:
        print(f"bench_gate: shard_entry_redecode_frac {frac!r} in "
              f"{path} exceeds {SHARD_REDECODE_FRAC_MAX} — the "
              "index-assisted BAM entry is re-decoding input it should "
              "seek past", file=sys.stderr)
        rc = 1
    if rc == 0:
        how = (f"speedup {speedup}x >= {SHARD_REQUIRED_SPEEDUP}x"
               if gated else
               f"speedup {speedup}x reported, not gated — measured "
               f"parallel capacity {capacity}x < "
               f"{SHARD_CAPACITY_FLOOR}x (capacity-limited box)")
        plane = ""
        if transport is not None:
            bits = [f"transport={transport}"]
            if reduction is not None:
                bits.append(f"fsyncs cut {reduction}x")
            if frac is not None:
                bits.append(f"indexed-BAM re-decode {frac}")
            plane = ", " + ", ".join(bits)
        print(f"shard gate: 2-host fleet {how} "
              f"({doc.get('cpu_count')} advertised cores), all legs "
              f"byte-identical{plane}")
    return rc


def _check_ragged_artifact(path: str) -> int:
    """Gate 3's committed-artifact half: the >= 20% skewed sweep win
    plus bit-identity on every raced ragged kernel."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: unreadable ragged artifact {path}: {e}",
              file=sys.stderr)
        return 2
    rc = 0
    speedup = doc.get("ragged_realign_skewed_speedup")
    if not isinstance(speedup, (int, float)) or \
            speedup < RAGGED_REQUIRED_SPEEDUP:
        print(f"bench_gate: ragged realign sweep speedup {speedup!r} on "
              "the committed skewed artifact is below the required "
              f"{RAGGED_REQUIRED_SPEEDUP}x (>= 20% sweep-wall cut) — "
              "the ragged-layout win regressed", file=sys.stderr)
        rc = 1
    mism = [k for k, v in doc.items()
            if k.endswith("_matches_padded") and v is not True]
    if mism:
        print("bench_gate: ragged kernels no longer bit-identical to "
              f"their padded twins in {path}: {mism}", file=sys.stderr)
        rc = 1
    if rc == 0:
        print(f"ragged gate: skewed realign sweep speedup {speedup}x "
              f">= {RAGGED_REQUIRED_SPEEDUP}x, all kernels bit-identical")
    return rc


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fresh_ragged = None
    if "--ragged" in argv:
        i = argv.index("--ragged")
        try:
            fresh_ragged = argv[i + 1]
        except IndexError:
            print("bench_gate: --ragged needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    fresh_shard = None
    if "--shard" in argv:
        i = argv.index("--shard")
        try:
            fresh_shard = argv[i + 1]
        except IndexError:
            print("bench_gate: --shard needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    fresh_serve = None
    if "--serve" in argv:
        i = argv.index("--serve")
        try:
            fresh_serve = argv[i + 1]
        except IndexError:
            print("bench_gate: --serve needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    fresh_fleet_serve = None
    if "--fleet-serve" in argv:
        i = argv.index("--fleet-serve")
        try:
            fresh_fleet_serve = argv[i + 1]
        except IndexError:
            print("bench_gate: --fleet-serve needs a path",
                  file=sys.stderr)
            return 2
        del argv[i:i + 2]
    fresh_paged = None
    if "--paged" in argv:
        i = argv.index("--paged")
        try:
            fresh_paged = argv[i + 1]
        except IndexError:
            print("bench_gate: --paged needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    fresh_overload = None
    if "--overload" in argv:
        i = argv.index("--overload")
        try:
            fresh_overload = argv[i + 1]
        except IndexError:
            print("bench_gate: --overload needs a path",
                  file=sys.stderr)
            return 2
        del argv[i:i + 2]
    fresh_call = None
    if "--call" in argv:
        i = argv.index("--call")
        try:
            fresh_call = argv[i + 1]
        except IndexError:
            print("bench_gate: --call needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    fresh_mega = None
    if "--mega" in argv:
        i = argv.index("--mega")
        try:
            fresh_mega = argv[i + 1]
        except IndexError:
            print("bench_gate: --mega needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    for path in (BASELINE, CURRENT):
        if not os.path.exists(path):
            print(f"bench_gate: missing committed artifact {path} "
                  "(regenerate with: python bench_transform.py --stream "
                  "--artifacts .)", file=sys.stderr)
            return 2
    if not os.path.exists(RAGGED):
        print(f"bench_gate: missing committed artifact {RAGGED} "
              "(regenerate with: python bench.py --worker ragged_race "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2
    if not os.path.exists(SHARD):
        print(f"bench_gate: missing committed artifact {SHARD} "
              "(regenerate with: python bench.py --worker shard_scale "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2
    if not os.path.exists(SERVE):
        print(f"bench_gate: missing committed artifact {SERVE} "
              "(regenerate with: python bench.py --worker serve_warm "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2
    if not os.path.exists(FLEET_SERVE):
        print(f"bench_gate: missing committed artifact {FLEET_SERVE} "
              "(regenerate with: python bench.py --worker fleet_serve "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2
    if not os.path.exists(PAGED):
        print(f"bench_gate: missing committed artifact {PAGED} "
              "(regenerate with: python bench.py --worker paged_race "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2
    if not os.path.exists(OVERLOAD):
        print(f"bench_gate: missing committed artifact {OVERLOAD} "
              "(regenerate with: python bench.py --worker overload "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2
    if not os.path.exists(CALL):
        print(f"bench_gate: missing committed artifact {CALL} "
              "(regenerate with: python bench.py --worker call "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2
    if not os.path.exists(MEGA):
        print(f"bench_gate: missing committed artifact {MEGA} "
              "(regenerate with: python bench.py --worker mega_race "
              "> out.jsonl on the CPU backend)", file=sys.stderr)
        return 2

    print(f"== gate 1: fused cuts io_spill_amplification >= "
          f"{REQUIRED_CUT_PCT}% vs the legacy baseline ==")
    rc = compare_bench.main([BASELINE, CURRENT,
                             "--keys", "io_spill_amplification",
                             f"--threshold=-{REQUIRED_CUT_PCT}"])
    if rc != 0:
        print("bench_gate: the committed fused artifact no longer cuts "
              f"spill amplification by {REQUIRED_CUT_PCT}% — the fusion "
              "win regressed", file=sys.stderr)
        return rc

    if argv:
        fresh = argv[0]
        print(f"\n== gate 2: {fresh} vs committed {CURRENT} "
              "(10% regression threshold) ==")
        rc = compare_bench.main([
            CURRENT, fresh,
            "--keys", "io_spill_amplification,transform_stream_wall_s",
            "--threshold", "10"])
        if rc != 0:
            print("bench_gate: transform io/wall regressed past 10% vs "
                  "the committed artifact", file=sys.stderr)
            return rc

    print(f"\n== gate 3: ragged realign sweep >= "
          f"{RAGGED_REQUIRED_SPEEDUP}x on the committed skewed "
          "artifact ==")
    rc = _check_ragged_artifact(RAGGED)
    if rc != 0:
        return rc

    if fresh_ragged:
        print(f"\n== gate 3b: {fresh_ragged} vs committed {RAGGED} "
              "(10% regression threshold, both layouts) ==")
        rc = _check_ragged_artifact(fresh_ragged)
        if rc != 0:
            return rc
        rc = compare_bench.main([RAGGED, fresh_ragged,
                                 "--keys", ",".join(RAGGED_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: a ragged or padded sweep wall regressed "
                  "past 10% vs the committed artifact", file=sys.stderr)
            return rc

    print(f"\n== gate 4: fleet 2-host scaling >= "
          f"{SHARD_REQUIRED_SPEEDUP}x on the committed shard_scale "
          "artifact ==")
    rc = _check_shard_artifact(SHARD)
    if rc != 0:
        return rc

    if fresh_shard:
        print(f"\n== gate 4b: {fresh_shard} vs committed {SHARD} "
              "(10% regression threshold on the fleet walls) ==")
        rc = _check_shard_artifact(fresh_shard)
        if rc != 0:
            return rc
        rc = compare_bench.main([SHARD, fresh_shard,
                                 "--keys", ",".join(SHARD_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: a fleet wall regressed past 10% vs the "
                  "committed artifact", file=sys.stderr)
            return rc

    print(f"\n== gate 5: warm-serve job 2+ >= "
          f"{SERVE_REQUIRED_SPEEDUP}x the cold CLI on the committed "
          "serve_warm artifact ==")
    rc = _check_serve_artifact(SERVE)
    if rc != 0:
        return rc

    if fresh_serve:
        print(f"\n== gate 5b: {fresh_serve} vs committed {SERVE} "
              "(10% regression threshold on the serve walls) ==")
        rc = _check_serve_artifact(fresh_serve)
        if rc != 0:
            return rc
        rc = compare_bench.main([SERVE, fresh_serve,
                                 "--keys", ",".join(SERVE_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: a serve wall regressed past 10% vs the "
                  "committed artifact", file=sys.stderr)
            return rc

    print(f"\n== gate 6: fleet-serve 2-worker scaling >= "
          f"{FLEET_SERVE_REQUIRED_SPEEDUP}x (capacity-armed) on the "
          "committed fleet_serve artifact ==")
    rc = _check_fleet_serve_artifact(FLEET_SERVE)
    if rc != 0:
        return rc

    if fresh_fleet_serve:
        print(f"\n== gate 6b: {fresh_fleet_serve} vs committed "
              f"{FLEET_SERVE} (10% regression threshold on the fleet "
              "walls) ==")
        rc = _check_fleet_serve_artifact(fresh_fleet_serve)
        if rc != 0:
            return rc
        rc = compare_bench.main([FLEET_SERVE, fresh_fleet_serve,
                                 "--keys",
                                 ",".join(FLEET_SERVE_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: a fleet-serve wall regressed past 10% "
                  "vs the committed artifact", file=sys.stderr)
            return rc

    print(f"\n== gate 7: paged serve leg h2d reduction >= "
          f"{PAGED_REQUIRED_H2D_REDUCTION}x on the committed "
          "paged_race artifact ==")
    rc = _check_paged_artifact(PAGED)
    if rc != 0:
        return rc

    if fresh_paged:
        print(f"\n== gate 7b: {fresh_paged} vs committed {PAGED} "
              "(10% regression threshold on the serve walls) ==")
        rc = _check_paged_artifact(fresh_paged)
        if rc != 0:
            return rc
        rc = compare_bench.main([PAGED, fresh_paged,
                                 "--keys", ",".join(PAGED_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: a paged serve wall regressed past 10% "
                  "vs the committed artifact", file=sys.stderr)
            return rc

    print("\n== gate 8: overload plane — accepted-job identity + "
          "typed shedding on the committed overload artifact ==")
    rc = _check_overload_artifact(OVERLOAD)
    if rc != 0:
        return rc

    if fresh_overload:
        print(f"\n== gate 8b: {fresh_overload} vs committed "
              f"{OVERLOAD} (10% regression threshold on the serve "
              "walls) ==")
        rc = _check_overload_artifact(fresh_overload)
        if rc != 0:
            return rc
        rc = compare_bench.main([OVERLOAD, fresh_overload,
                                 "--keys",
                                 ",".join(OVERLOAD_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: an overload serve wall regressed past "
                  "10% vs the committed artifact", file=sys.stderr)
            return rc

    print("\n== gate 9: variant-calling plane — oracle + served "
          "identity on the committed call artifact ==")
    rc = _check_call_artifact(CALL)
    if rc != 0:
        return rc

    if fresh_call:
        print(f"\n== gate 9b: {fresh_call} vs committed {CALL} "
              "(10% regression threshold on the call walls) ==")
        rc = _check_call_artifact(fresh_call)
        if rc != 0:
            return rc
        rc = compare_bench.main([CALL, fresh_call,
                                 "--keys", ",".join(CALL_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: a call wall regressed past 10% vs the "
                  "committed artifact", file=sys.stderr)
            return rc

    print(f"\n== gate 10: fused mega-pass dispatch collapse >= "
          f"{MEGA_REQUIRED_DISPATCH_REDUCTION}x on the committed "
          "mega_race artifact ==")
    rc = _check_mega_artifact(MEGA)
    if rc != 0:
        return rc

    if fresh_mega:
        print(f"\n== gate 10b: {fresh_mega} vs committed {MEGA} "
              "(10% regression threshold on the combined-leg walls) ==")
        rc = _check_mega_artifact(fresh_mega)
        if rc != 0:
            return rc
        rc = compare_bench.main([MEGA, fresh_mega,
                                 "--keys", ",".join(MEGA_WALL_KEYS),
                                 "--threshold", "10"])
        if rc != 0:
            print("bench_gate: a mega combined-leg wall regressed past "
                  "10% vs the committed artifact", file=sys.stderr)
            return rc

    print("\nbench_gate: all gates hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
