#!/usr/bin/env python3
"""Validate an adam-tpu metrics/telemetry JSONL file (schema 1).

The schema is documented in docs/OBSERVABILITY.md and produced by
``adam_tpu.obs`` (the CLI's ``-metrics PATH`` flag, the bench sidecars,
elastic worker sidecars).  Contract checked here:

* every line is a JSON object with an ``event`` string and numeric ``t``;
* line 1 is the ``manifest``: ``schema == 1``, ``argv`` a list of
  strings, a hex ``config_fingerprint``, host/pid present;
* ``stage`` events carry ``name`` (str) and ``seconds`` (number >= 0),
  plus an optional ``thread`` (str — the lane name, present when the
  span ran off the main thread: feeder threads, prep pools);
* ``chunk`` events carry ``pass`` (str) and ``rows`` (int >= 0);
* ``executor_bucket_selected`` events carry ``pass``, ``chunk_rows``
  (int > 0), a strictly ascending int ``ladder`` whose top rung equals
  ``chunk_rows``, ``ladder_base`` (> 1), ``inputs`` (object), a hex
  ``input_digest`` (tools/check_executor.py replays the decision), a
  ``layout`` of padded|ragged|paged (paged adds positive ``page_rows``/
  ``pool_pages``) and — since the fused mega-pass dimension — an
  optional boolean ``fused_device``;
* ``mega_plan_selected`` events carry ``pass`` (str), boolean
  ``fused_device`` and ``reason`` (str) — the companion receipt for
  the fused mega-pass decision (replayability lives in the matching
  ``executor_bucket_selected`` event's recorded inputs);
* ``dispatch_count`` events (one rollup per pass at finish, emitted
  when the pass dispatched at all) carry ``pass`` (str),
  ``dispatches`` (int >= 1), ``chunks`` (int >= 0), a ``layout`` of
  padded|ragged|paged and boolean ``fused_device`` — the per-chunk
  dispatch accounting the mega-pass win (three dispatches became one)
  is gated on;
* ``executor_recompile`` events carry ``pass``, ``rows`` (a member of
  that pass's announced ladder) and ``n_shapes`` (int >= 1 — counts
  (rows, len) pairs, so it may exceed the ROW ladder length when the
  length bucket grows mid-pass);
* ``executor_prefetch_stall_s`` events carry ``pass``, ``seconds``
  (>= 0) and ``inflight_peak <= depth`` (the feed's bound held);
* ``fusion_plan_selected`` events carry ``mode`` (fused/legacy), the
  ``streams`` list the run will execute (fused runs start at ``s1``),
  boolean ``route_in_s1``/``carry_ridx``/``wire_spill``/
  ``direct_emit``, ``inputs`` (object) and a hex ``input_digest``
  (tools/check_executor.py replays the decision); ``io_ledger``
  transform-pass rows must belong to an announced stream set;
* ``realign_plan_selected`` events carry ``pipeline_depth`` (int >= 0),
  boolean ``donate``, an optional ``layout`` of padded|ragged|paged,
  ``inputs`` (object) and a hex ``input_digest`` (the decision is pure
  and replayable, like the executor's);
* ``realign_bin`` events carry ``bin``/``rows``/``groups``/``jobs``
  (non-negative ints) and non-negative per-stage walls
  (``load_s``/``prep_s``/``sweep_s``/``finish_s``/``emit_s``);
* ``realign_sweep_dispatch`` events carry ``shape`` (three positive
  ints — padded (R, L, CL), or the ragged (rows_pad, bases_pad, CL)),
  ``jobs >= 1``, padded lane count ``g >= jobs``, ``units >= 1``
  (distinct bins sharing the dispatch), and — since the ragged layout —
  a ``layout`` of padded|ragged|paged plus the per-axis pad-waste
  fractions ``waste_r``/``waste_l``/``waste_cl``/``waste_g`` in [0, 1];
* ``fault_injected`` events carry ``site`` (a known injection site),
  ``occurrence`` (int >= 1), ``fault`` (a known fault kind),
  ``inputs`` (object) and a hex ``input_digest``
  (tools/check_resilience.py replays the firing decision);
* ``retry_attempt`` events carry ``site``, ``attempt`` (int >= 1),
  ``error_kind``, ``action`` (retry/split/fallback_cpu/raise),
  ``delay_s`` (number >= 0), ``inputs`` (object) and a hex
  ``input_digest`` (the policy decision is pure and replayable);
* ``degraded_dispatch`` events carry ``site``, ``attempt`` (int >= 1)
  and ``error_kind`` — the chunk completed on the CPU fallback;
* ``io_ledger`` events (one per pass + a ``total`` rollup at run end)
  carry ``pass`` (str), non-negative int ``decoded``/``spilled``/
  ``reread`` byte counts and an ``amplification`` ratio — non-negative
  number, or null when the run decoded nothing ((spilled + reread) /
  run decoded — the spill-I/O number ROADMAP item 1 targets);
* ``trace_written`` events carry ``path`` (str), ``events`` (int >= 0)
  and ``lanes`` (int >= 0) — the receipt for the run's Chrome-trace
  timeline (validated separately by tools/check_trace.py);
* ``shard_plan_selected`` events carry ``n_hosts``/``n_units``/
  ``unit_rows`` (ints >= 1), ``assignments`` ([lo, hi) pairs tiling
  [0, n_units) contiguously), ``reason``, ``inputs`` and a hex
  ``input_digest`` (tools/check_executor.py replays the decision);
* ``shard_reassigned`` events carry ``cause`` (death/speculation),
  ``action`` (none/respawn/redistribute/fail/speculate), ``shard``
  (int >= 0), the cause's payload (``splits`` for death, ``tail_runs``
  for speculation), ``inputs`` and a hex ``input_digest`` (replayed by
  tools/check_executor.py);
* ``shard_lease_expired`` events carry ``shard`` (int >= 0), ``age_s``
  (>= 0) and ``ttl_s`` (> 0) — a fleet worker's heartbeat went stale
  past its lease;
* ``shard_merge`` events carry ``units``/``duplicates`` (ints >= 0)
  and ``shards`` (int >= 1) — the fleet reduce receipt (duplicates are
  speculation/recovery overlap the per-unit merge deduplicated);
* ``admission_selected`` events (the serve front-end's scheduler,
  adam_tpu/serve/admission.py) carry ``admit`` (a list of job-id
  strings), ``pack_groups`` (a list of >= 2-element job-id lists, each
  member also admitted), ``reason`` (str), ``inputs`` (object) and a
  hex ``input_digest`` (tools/check_executor.py replays the decision);
* ``tenant_job`` events carry ``job_id``/``tenant``/``command``
  (strings), ``status`` (ok/failed), ``seconds`` (number >= 0) and
  ``compiles`` (int >= 0) — one per served job, the per-tenant label
  sidecar consumers split on; optional ``queue_s``/``service_s``
  (numbers >= 0) split the job's latency into submit→start wait and
  execution wall — the per-tenant SLO numbers the serve shutdown
  report summarizes as p50/p99;
* ``placement_selected`` events (the fleet-serve cluster scheduler,
  adam_tpu/serve/scheduler.py) carry ``place`` (a list of
  ``[job_id, worker]`` pairs), ``reason`` (str), ``inputs`` (object)
  and a hex ``input_digest`` (tools/check_executor.py replays the
  decision);
* ``job_requeued`` events carry ``cause``
  (worker_death/lease_expiry/drain/steal), ``action``
  (requeue/quarantine/steal), ``reason`` (str), ``inputs`` (object)
  and a hex ``input_digest`` (replayed by tools/check_executor.py);
  steal events carry ``moves`` (``[job_id, from, to]`` triples), the
  rest carry the ``job_id`` being requeued or quarantined;
* ``worker_lease_expired`` events carry ``worker`` (int >= 0),
  ``age_s`` (>= 0) and ``ttl_s`` (> 0) — a fleet-serve worker's
  heartbeat went stale past its lease (the scheduler fences it with
  SIGKILL before requeuing its jobs);
* ``startup_seconds`` events carry only non-negative numeric fields —
  the cold-start breakdown (backend init / first compile / first
  dispatch) every command stamps so the serve warmup win is measured
  against a recorded baseline;
* ``overload_state`` events (the brownout ladder, serve/overload.py)
  carry ``level`` (0-3) naming ``state``
  (normal/shed_batch/reject_low/reject_all), the bool ``actions``
  object, ``reason``, ``inputs`` + hex ``input_digest`` (replayed by
  tools/check_executor.py);
* ``admission_rejected`` events carry ``job_id``/``tenant``, a typed
  ``code`` (over_backlog/tenant_quota/brownout_low/brownout_all) and a
  non-negative ``retry_after_s`` — every shed job tells its client
  when to come back;
* ``deadline_missed`` events carry ``job_id``/``tenant``, ``wait_s``
  (>= 0) and ``deadline_s`` (> 0) — a queued job cancelled past its
  deadline instead of wasting a warm dispatch;
* ``breaker_state`` events (the backend circuit breaker,
  resilience/retry.py) carry ``site``, ``state``
  (closed/open/half_open), ``failures`` (int >= 0), ``reason``,
  ``inputs`` + hex ``input_digest`` (replayed by
  tools/check_executor.py);
* ``series_written`` events carry ``path`` (str), ``rows`` (int >= 0)
  and ``dropped`` (int >= 0) — the receipt for the run's time-series
  file (validated separately by tools/check_series.py);
* ``serve_report_checkpoint`` events carry ``path`` (str), ``jobs``
  (int >= 0) and ``reason`` (periodic/final) — the SLO report was
  checkpointed durably mid-serve, not only at exit;
* ``call_plan_selected`` events (the variant-calling plan,
  call/plan.decide_call_plan) carry ``stripe_span`` (int >= 1),
  ``min_depth``/``min_alt`` (int >= 1), ``reason``, ``inputs`` + hex
  ``input_digest`` (replayed by tools/check_executor.py);
* ``call_stripe`` events carry ``refid`` (int >= 0), ``stripe_start``
  (int >= 0), ``span`` (int >= 1), ``sample`` (str), ``covered`` and
  ``called`` (int >= 0) — one genotyped (stripe, sample) tile;
* ``call_emit`` events carry ``path`` (str), ``reads``/``admitted``/
  ``stripes``/``calls``/``variants``/``genotypes``/``samples`` (int
  >= 0), hex ``vcf_sha256``, plus nullable ``identical`` (bool; the
  oracle verdict, only under -validate) and nullable ``rod_coverage``
  (number >= 0; the rods-plane summary) — the pass's output receipt;
* ``transport_selected`` events (the fleet data plane,
  parallel/ringplane.decide_transport) carry ``transport``
  (ring/fleet_dir), ``spool_sync`` (batched/every), ``reason``,
  ``inputs`` + hex ``input_digest`` (replayed by
  tools/check_executor.py);
* ``shard_entry_selected`` events
  (parallel/ringplane.decide_shard_entry — emitted only for SAM/BAM
  fleet inputs, where the entry question exists) carry ``entry``
  (index/forward/rowgroup), ``reason``, ``inputs`` + hex
  ``input_digest`` (replayed by tools/check_executor.py);
* ``unit_stolen`` events carry ``unit``/``victim``/``thief``/
  ``incarnation`` (ints >= 0, victim != thief) — an idle fleet worker
  claimed one pending unit off a straggler's tail (exactly-once via
  the O_EXCL claim table);
* the last line is the ``summary``: ``wall_seconds``, ``ok``, and a
  ``metrics`` snapshot whose counters/gauges are numeric and whose
  histograms are internally consistent (count == sum of bucket counts);
* exactly one manifest, exactly one summary.

Usage::

    python tools/check_metrics.py RUN.metrics.jsonl [...]

Exit 0 when every file validates; 1 otherwise, with one error line per
violation.  Used by the tier-1 CLI telemetry test (tests/test_obs.py)
so the documented schema and the produced schema cannot drift.
"""

from __future__ import annotations

import json
import sys
from typing import List

SCHEMA_VERSION = 1

_NUM = (int, float)

#: THE event-kind registry: every kind the adam_tpu product tree emits.
#: tools/graftlint rule GL004 (event-schema drift) checks this tuple
#: against the live ``obs.emit("<kind>", ...)`` sites — an emitted kind
#: missing here, or a kind here with no emit site, fails the lint.  A
#: kind outside this tuple fails validation below: an unregistered
#: event is unvalidatable telemetry.
KNOWN_EVENTS = (
    "manifest", "summary",
    "stage", "chunk", "run_totals",
    "executor_bucket_selected", "executor_recompile",
    "executor_prefetch_stall_s",
    "fusion_plan_selected",
    "realign_plan_selected", "realign_bin", "realign_sweep_dispatch",
    "fault_injected", "retry_attempt", "degraded_dispatch",
    "io_ledger", "trace_written",
    "incarnation", "worker_death",
    "shard_plan_selected", "shard_reassigned", "shard_lease_expired",
    "shard_merge",
    "admission_selected", "tenant_job", "startup_seconds",
    "serve_boot", "serve_pack_dispatch", "serve_pack_degraded",
    "placement_selected", "job_requeued", "worker_lease_expired",
    "ledger_stage",
    "pages_selected", "h2d_bytes",
    "mega_plan_selected", "dispatch_count",
    "overload_state", "admission_rejected", "deadline_missed",
    "breaker_state",
    "series_written", "serve_report_checkpoint",
    "call_plan_selected", "call_stripe", "call_emit",
    "transport_selected", "shard_entry_selected", "unit_stolen",
    "net_connect", "net_retry", "net_degraded", "spool_gc",
)

#: mirror of adam_tpu.resilience.faults.SITES / FAULTS (kept literal so
#: the validator runs without importing the package, like the rest of
#: this file's schema knowledge)
_FAULT_SITES = ("device_dispatch", "device_put", "spill_write",
                "checkpoint_write", "feeder_load", "worker_proc",
                "input_record", "shard_lease", "ring_write",
                "net_send", "net_recv", "net_accept")
_FAULT_KINDS = ("error", "latency", "truncate", "corrupt", "kill")
_RETRY_ACTIONS = ("retry", "split", "fallback_cpu", "raise")
_SHARD_CAUSES = ("death", "speculation")
_SHARD_ACTIONS = ("none", "respawn", "redistribute", "fail",
                  "speculate")
_REQUEUE_CAUSES = ("worker_death", "lease_expiry", "drain", "steal")
_REQUEUE_ACTIONS = ("requeue", "quarantine", "steal")
#: mirror of adam_tpu.serve.overload.LEVEL_NAMES /
#: adam_tpu.serve.admission.REJECT_CODES /
#: adam_tpu.resilience.retry.BREAKER_STATES (kept literal, like
#: _FAULT_SITES above)
_OVERLOAD_STATES = ("normal", "shed_batch", "reject_low", "reject_all")
#: mirror of adam_tpu.parallel.ringplane's decision vocabularies
_TRANSPORTS = ("ring", "fleet_dir", "net")
_SPOOL_SYNCS = ("batched", "every")
_ENTRIES = ("index", "forward", "rowgroup")
_REJECT_CODES = ("over_backlog", "tenant_quota", "brownout_low",
                 "brownout_all")
_BREAKER_STATES = ("closed", "open", "half_open")


def _is_hex(v) -> bool:
    return (isinstance(v, str) and len(v) >= 8 and
            all(c in "0123456789abcdef" for c in v))


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def validate(path: str) -> List[str]:
    """Return a list of human-readable schema violations (empty = valid)."""
    errs: List[str] = []

    def err(line_no, msg):
        errs.append(f"{path}:{line_no}: {msg}")

    try:
        with open(path) as f:
            raw = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    if not raw:
        return [f"{path}: empty file"]

    docs = []
    for i, ln in enumerate(raw, 1):
        try:
            doc = json.loads(ln)
        except ValueError as e:
            err(i, f"invalid JSON: {e}")
            continue
        if not isinstance(doc, dict):
            err(i, "line is not a JSON object")
            continue
        if not isinstance(doc.get("event"), str):
            err(i, "missing/non-string 'event'")
        if not _is_num(doc.get("t")):
            err(i, "missing/non-numeric 't'")
        docs.append((i, doc))

    if not docs:
        return errs

    manifests = [(i, d) for i, d in docs if d.get("event") == "manifest"]
    summaries = [(i, d) for i, d in docs if d.get("event") == "summary"]
    if len(manifests) != 1:
        errs.append(f"{path}: expected exactly 1 manifest, "
                    f"found {len(manifests)}")
    if len(summaries) != 1:
        errs.append(f"{path}: expected exactly 1 summary, "
                    f"found {len(summaries)}")

    if manifests:
        i, m = manifests[0]
        if (i, m) != docs[0] and docs[0][1].get("event") != "manifest":
            err(i, "manifest is not the first line")
        if m.get("schema") != SCHEMA_VERSION:
            err(i, f"manifest schema {m.get('schema')!r} != "
                   f"{SCHEMA_VERSION}")
        argv = m.get("argv")
        if not (isinstance(argv, list) and
                all(isinstance(a, str) for a in argv)):
            err(i, "manifest argv is not a list of strings")
        fp = m.get("config_fingerprint")
        if not (isinstance(fp, str) and len(fp) >= 8 and
                all(c in "0123456789abcdef" for c in fp)):
            err(i, "manifest config_fingerprint is not a hex digest")
        for field in ("host", "pid"):
            if field not in m:
                err(i, f"manifest missing {field!r}")

    ladders: dict = {}   # pass -> announced ladder (latest wins)
    # union of every fusion plan's announced streams: io_ledger rows for
    # transform-shaped pass names must belong to an announced stream set
    # (the collapsed-pass consistency the fused dataflow promises)
    fusion_streams: set = set()
    _TRANSFORM_PASSES = {"p1", "p2", "p3", "p4", "s1", "s2", "s3"}
    for i, d in docs:
        ev = d.get("event")
        if isinstance(ev, str) and ev not in KNOWN_EVENTS:
            err(i, f"unknown event kind {ev!r} — every emitted kind "
                   "needs a schema here (KNOWN_EVENTS; see graftlint "
                   "rule GL004)")
        if ev == "stage":
            if not isinstance(d.get("name"), str):
                err(i, "stage event missing string 'name'")
            if not (_is_num(d.get("seconds")) and d["seconds"] >= 0):
                err(i, "stage event missing non-negative 'seconds'")
            if "thread" in d and not isinstance(d["thread"], str):
                err(i, "stage event 'thread' lane is not a string")
        elif ev == "chunk":
            if not isinstance(d.get("pass"), str):
                err(i, "chunk event missing string 'pass'")
            rows = d.get("rows")
            if not (isinstance(rows, int) and not isinstance(rows, bool)
                    and rows >= 0):
                err(i, "chunk event missing non-negative int 'rows'")
        elif ev == "executor_bucket_selected":
            if not isinstance(d.get("pass"), str):
                err(i, "executor_bucket_selected missing string 'pass'")
            cr = d.get("chunk_rows")
            if not (isinstance(cr, int) and not isinstance(cr, bool)
                    and cr > 0):
                err(i, "executor_bucket_selected missing positive int "
                       "'chunk_rows'")
            ladder = d.get("ladder")
            if not (isinstance(ladder, list) and ladder and
                    all(isinstance(r, int) and not isinstance(r, bool)
                        and r > 0 for r in ladder) and
                    all(a < b for a, b in zip(ladder, ladder[1:]))):
                err(i, "executor_bucket_selected 'ladder' is not a "
                       "strictly ascending list of positive ints")
            elif isinstance(cr, int) and ladder[-1] != cr:
                err(i, f"executor ladder top rung {ladder[-1]} != "
                       f"chunk_rows {cr}")
            else:
                ladders[d.get("pass")] = ladder
            if not (_is_num(d.get("ladder_base")) and
                    d["ladder_base"] > 1):
                err(i, "executor_bucket_selected 'ladder_base' must "
                       "exceed 1")
            if not isinstance(d.get("inputs"), dict):
                err(i, "executor_bucket_selected missing 'inputs' "
                       "object (decision must be replayable)")
            dig = d.get("input_digest")
            if not (isinstance(dig, str) and len(dig) >= 8 and
                    all(c in "0123456789abcdef" for c in dig)):
                err(i, "executor_bucket_selected missing hex "
                       "'input_digest'")
            if "layout" in d and d["layout"] not in ("padded", "ragged",
                                                     "paged"):
                err(i, f"executor_bucket_selected unknown layout "
                       f"{d['layout']!r}")
            if d.get("layout") == "paged":
                for field in ("page_rows", "pool_pages"):
                    v = d.get(field)
                    if not (isinstance(v, int) and
                            not isinstance(v, bool) and v > 0):
                        err(i, f"executor_bucket_selected paged layout "
                               f"missing positive int {field!r}")
            if "fused_device" in d and \
                    not isinstance(d["fused_device"], bool):
                err(i, "executor_bucket_selected 'fused_device' is "
                       "not a boolean")
        elif ev == "executor_recompile":
            if not isinstance(d.get("pass"), str):
                err(i, "executor_recompile missing string 'pass'")
            rows = d.get("rows")
            if not (isinstance(rows, int) and not isinstance(rows, bool)
                    and rows > 0):
                err(i, "executor_recompile missing positive int 'rows'")
            elif d.get("pass") in ladders and \
                    rows not in ladders[d["pass"]]:
                err(i, f"executor_recompile rows {rows} not a rung of "
                       f"pass {d['pass']!r}'s announced ladder")
            ns = d.get("n_shapes")
            if not (isinstance(ns, int) and not isinstance(ns, bool)
                    and ns >= 1):
                err(i, "executor_recompile missing int 'n_shapes' >= 1")
            # NOTE: n_shapes counts distinct (rows, len) PAIRS, so its
            # bound is len(ladder) x length-buckets, not len(ladder) —
            # a growing length bucket mid-pass legitimately exceeds the
            # row-ladder length.  Only rows-membership is checkable.
        elif ev == "executor_prefetch_stall_s":
            if not isinstance(d.get("pass"), str):
                err(i, "executor_prefetch_stall_s missing string 'pass'")
            if not (_is_num(d.get("seconds")) and d["seconds"] >= 0):
                err(i, "executor_prefetch_stall_s missing non-negative "
                       "'seconds'")
            peak = d.get("inflight_peak")
            depth = d.get("depth")
            if _is_num(peak) and _is_num(depth) and depth > 0 and \
                    peak > depth:
                err(i, f"executor prefetch inflight_peak {peak} exceeds "
                       f"its depth bound {depth}")
        elif ev == "fusion_plan_selected":
            if d.get("mode") not in ("fused", "legacy"):
                err(i, f"fusion_plan_selected unknown mode "
                       f"{d.get('mode')!r}")
            streams = d.get("streams")
            if not (isinstance(streams, list) and streams and
                    all(isinstance(s, str) and s for s in streams)):
                err(i, "fusion_plan_selected 'streams' is not a "
                       "non-empty string list")
            else:
                if d.get("mode") == "fused" and streams[0] != "s1":
                    err(i, "fusion_plan_selected fused mode must start "
                           "at stream 's1'")
                fusion_streams.update(streams)
            for field in ("route_in_s1", "carry_ridx", "wire_spill",
                          "direct_emit"):
                if not isinstance(d.get(field), bool):
                    err(i, f"fusion_plan_selected missing boolean "
                           f"{field!r}")
            if not isinstance(d.get("inputs"), dict):
                err(i, "fusion_plan_selected missing 'inputs' object "
                       "(decision must be replayable)")
            dig = d.get("input_digest")
            if not (isinstance(dig, str) and len(dig) >= 8 and
                    all(c in "0123456789abcdef" for c in dig)):
                err(i, "fusion_plan_selected missing hex 'input_digest'")
        elif ev == "realign_plan_selected":
            pd = d.get("pipeline_depth")
            if not (isinstance(pd, int) and not isinstance(pd, bool)
                    and pd >= 0):
                err(i, "realign_plan_selected missing non-negative int "
                       "'pipeline_depth'")
            if not isinstance(d.get("donate"), bool):
                err(i, "realign_plan_selected missing boolean 'donate'")
            if "layout" in d and d["layout"] not in ("padded", "ragged",
                                                     "paged"):
                err(i, f"realign_plan_selected unknown layout "
                       f"{d['layout']!r}")
            if not isinstance(d.get("inputs"), dict):
                err(i, "realign_plan_selected missing 'inputs' object "
                       "(decision must be replayable)")
            dig = d.get("input_digest")
            if not (isinstance(dig, str) and len(dig) >= 8 and
                    all(c in "0123456789abcdef" for c in dig)):
                err(i, "realign_plan_selected missing hex 'input_digest'")
        elif ev == "realign_bin":
            for field in ("bin", "rows", "groups", "jobs"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"realign_bin missing non-negative int "
                           f"{field!r}")
            for field in ("load_s", "prep_s", "sweep_s", "finish_s",
                          "emit_s"):
                v = d.get(field)
                if not (_is_num(v) and v >= 0):
                    err(i, f"realign_bin missing non-negative {field!r}")
        elif ev == "realign_sweep_dispatch":
            shape = d.get("shape")
            if not (isinstance(shape, list) and len(shape) == 3 and
                    all(isinstance(s, int) and not isinstance(s, bool)
                        and s > 0 for s in shape)):
                err(i, "realign_sweep_dispatch 'shape' is not three "
                       "positive ints")
            jobs = d.get("jobs")
            g = d.get("g")
            if not (isinstance(jobs, int) and not isinstance(jobs, bool)
                    and jobs >= 1):
                err(i, "realign_sweep_dispatch missing int 'jobs' >= 1")
            if not (isinstance(g, int) and not isinstance(g, bool)
                    and g >= 1):
                err(i, "realign_sweep_dispatch missing int 'g' >= 1")
            elif isinstance(jobs, int) and g < jobs:
                err(i, f"realign_sweep_dispatch g {g} below its jobs "
                       f"count {jobs} (lanes cannot undercount jobs)")
            units = d.get("units")
            if not (isinstance(units, int) and not isinstance(units, bool)
                    and units >= 1):
                err(i, "realign_sweep_dispatch missing int 'units' >= 1")
            if "layout" in d and d["layout"] not in ("padded", "ragged",
                                                     "paged"):
                err(i, f"realign_sweep_dispatch unknown layout "
                       f"{d['layout']!r}")
            for field in ("waste_r", "waste_l", "waste_cl", "waste_g"):
                if field in d and not (_is_num(d[field]) and
                                       0 <= d[field] <= 1):
                    err(i, f"realign_sweep_dispatch {field!r} must be a "
                           "fraction in [0, 1] (per-axis pad waste)")
        elif ev == "fault_injected":
            if d.get("site") not in _FAULT_SITES:
                err(i, f"fault_injected unknown site {d.get('site')!r}")
            occ = d.get("occurrence")
            if not (isinstance(occ, int) and not isinstance(occ, bool)
                    and occ >= 1):
                err(i, "fault_injected missing int 'occurrence' >= 1")
            if d.get("fault") not in _FAULT_KINDS:
                err(i, f"fault_injected unknown fault {d.get('fault')!r}")
            if not isinstance(d.get("inputs"), dict):
                err(i, "fault_injected missing 'inputs' object "
                       "(firing must be replayable)")
            dig = d.get("input_digest")
            if not (isinstance(dig, str) and len(dig) >= 8 and
                    all(c in "0123456789abcdef" for c in dig)):
                err(i, "fault_injected missing hex 'input_digest'")
        elif ev == "retry_attempt":
            if d.get("site") not in _FAULT_SITES:
                err(i, f"retry_attempt unknown site {d.get('site')!r}")
            att = d.get("attempt")
            if not (isinstance(att, int) and not isinstance(att, bool)
                    and att >= 1):
                err(i, "retry_attempt missing int 'attempt' >= 1")
            if not isinstance(d.get("error_kind"), str):
                err(i, "retry_attempt missing string 'error_kind'")
            if d.get("action") not in _RETRY_ACTIONS:
                err(i, f"retry_attempt unknown action "
                       f"{d.get('action')!r}")
            if not (_is_num(d.get("delay_s")) and d["delay_s"] >= 0):
                err(i, "retry_attempt missing non-negative 'delay_s'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "retry_attempt missing 'inputs' object "
                       "(decision must be replayable)")
            dig = d.get("input_digest")
            if not (isinstance(dig, str) and len(dig) >= 8 and
                    all(c in "0123456789abcdef" for c in dig)):
                err(i, "retry_attempt missing hex 'input_digest'")
        elif ev == "degraded_dispatch":
            if d.get("site") not in _FAULT_SITES:
                err(i, f"degraded_dispatch unknown site "
                       f"{d.get('site')!r}")
            att = d.get("attempt")
            if not (isinstance(att, int) and not isinstance(att, bool)
                    and att >= 1):
                err(i, "degraded_dispatch missing int 'attempt' >= 1")
            if not isinstance(d.get("error_kind"), str):
                err(i, "degraded_dispatch missing string 'error_kind'")
        elif ev == "io_ledger":
            if not isinstance(d.get("pass"), str):
                err(i, "io_ledger missing string 'pass'")
            elif fusion_streams and d["pass"] in _TRANSFORM_PASSES and \
                    d["pass"] not in fusion_streams:
                err(i, f"io_ledger pass {d['pass']!r} is not in the "
                       "announced fusion stream set "
                       f"{sorted(fusion_streams)} — ledger attribution "
                       "must follow the collapsed pass structure")
            for field in ("decoded", "spilled", "reread"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"io_ledger missing non-negative int "
                           f"{field!r}")
            amp = d.get("amplification")
            if not (amp is None or (_is_num(amp) and amp >= 0)):
                err(i, "io_ledger 'amplification' must be a "
                       "non-negative number or null (undefined when "
                       "the run decoded nothing)")
        elif ev == "trace_written":
            if not isinstance(d.get("path"), str):
                err(i, "trace_written missing string 'path'")
            for field in ("events", "lanes"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"trace_written missing non-negative int "
                           f"{field!r}")
            dr = d.get("dropped")
            if dr is not None and not (
                    isinstance(dr, int) and not isinstance(dr, bool)
                    and dr >= 1):
                err(i, "trace_written 'dropped' must be a positive "
                       "int when present (the ring-cap overflow "
                       "count)")
        elif ev == "shard_plan_selected":
            for field in ("n_hosts", "n_units", "unit_rows"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 1):
                    err(i, f"shard_plan_selected missing int "
                           f"{field!r} >= 1")
            a = d.get("assignments")
            ok_shape = (isinstance(a, list) and a and all(
                isinstance(r, list) and len(r) == 2 and
                all(isinstance(x, int) and not isinstance(x, bool)
                    for x in r) and r[0] < r[1] for r in a))
            if not ok_shape:
                err(i, "shard_plan_selected 'assignments' is not a "
                       "non-empty list of [lo, hi) int pairs")
            else:
                if a[0][0] != 0 or any(
                        a[k][1] != a[k + 1][0]
                        for k in range(len(a) - 1)) or \
                        (isinstance(d.get("n_units"), int) and
                         a[-1][1] != d["n_units"]):
                    err(i, "shard_plan_selected assignments must tile "
                           "[0, n_units) contiguously without overlap")
            if not isinstance(d.get("reason"), str):
                err(i, "shard_plan_selected missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "shard_plan_selected missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "shard_plan_selected missing hex 'input_digest'")
        elif ev == "shard_reassigned":
            if d.get("cause") not in _SHARD_CAUSES:
                err(i, f"shard_reassigned unknown cause "
                       f"{d.get('cause')!r}")
            if d.get("action") not in _SHARD_ACTIONS:
                err(i, f"shard_reassigned unknown action "
                       f"{d.get('action')!r}")
            sh = d.get("shard")
            if not (isinstance(sh, int) and not isinstance(sh, bool)
                    and sh >= 0):
                err(i, "shard_reassigned missing int 'shard' >= 0")
            if d.get("cause") == "death":
                if not isinstance(d.get("splits"), list):
                    err(i, "shard_reassigned (death) missing 'splits' "
                           "list")
            elif not isinstance(d.get("tail_runs"), list):
                err(i, "shard_reassigned (speculation) missing "
                       "'tail_runs' list")
            if not isinstance(d.get("inputs"), dict):
                err(i, "shard_reassigned missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "shard_reassigned missing hex 'input_digest'")
        elif ev == "shard_lease_expired":
            sh = d.get("shard")
            if not (isinstance(sh, int) and not isinstance(sh, bool)
                    and sh >= 0):
                err(i, "shard_lease_expired missing int 'shard' >= 0")
            if not (_is_num(d.get("age_s")) and d["age_s"] >= 0):
                err(i, "shard_lease_expired missing non-negative "
                       "'age_s'")
            if not (_is_num(d.get("ttl_s")) and d["ttl_s"] > 0):
                err(i, "shard_lease_expired missing positive 'ttl_s'")
        elif ev == "shard_merge":
            for field in ("units", "duplicates"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"shard_merge missing non-negative int "
                           f"{field!r}")
            sh = d.get("shards")
            if not (isinstance(sh, int) and not isinstance(sh, bool)
                    and sh >= 1):
                err(i, "shard_merge missing int 'shards' >= 1")
        elif ev == "admission_selected":
            admit = d.get("admit")
            if not (isinstance(admit, list) and
                    all(isinstance(j, str) and j for j in admit)):
                err(i, "admission_selected 'admit' is not a list of "
                       "job-id strings")
            groups = d.get("pack_groups")
            if not (isinstance(groups, list) and all(
                    isinstance(g, list) and len(g) >= 2 and
                    all(isinstance(j, str) and j for j in g)
                    for g in groups)):
                err(i, "admission_selected 'pack_groups' is not a list "
                       "of >= 2-element job-id lists")
            elif isinstance(admit, list):
                stray = [j for g in groups for j in g if j not in admit]
                if stray:
                    err(i, f"admission_selected pack_groups members "
                           f"{stray} are not in 'admit' — a job cannot "
                           "co-dispatch without being admitted")
            if "reject" in d:
                rej = d["reject"]
                if not (isinstance(rej, list) and all(
                        isinstance(r, dict) and
                        isinstance(r.get("job_id"), str) and
                        r.get("code") in _REJECT_CODES and
                        _is_num(r.get("retry_after_s")) and
                        r["retry_after_s"] >= 0 for r in rej)):
                    err(i, "admission_selected 'reject' is not a list "
                           "of {job_id, code, retry_after_s} objects")
            if "cancel" in d:
                can = d["cancel"]
                if not (isinstance(can, list) and all(
                        isinstance(c, dict) and
                        isinstance(c.get("job_id"), str) and
                        _is_num(c.get("wait_s")) and
                        _is_num(c.get("deadline_s")) for c in can)):
                    err(i, "admission_selected 'cancel' is not a list "
                           "of {job_id, wait_s, deadline_s} objects")
            if not isinstance(d.get("reason"), str):
                err(i, "admission_selected missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "admission_selected missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "admission_selected missing hex 'input_digest'")
        elif ev == "tenant_job":
            for field in ("job_id", "tenant", "command"):
                if not isinstance(d.get(field), str):
                    err(i, f"tenant_job missing string {field!r}")
            if d.get("status") not in ("ok", "failed"):
                err(i, f"tenant_job unknown status {d.get('status')!r}")
            if not (_is_num(d.get("seconds")) and d["seconds"] >= 0):
                err(i, "tenant_job missing non-negative 'seconds'")
            c = d.get("compiles")
            if not (isinstance(c, int) and not isinstance(c, bool)
                    and c >= 0):
                err(i, "tenant_job missing non-negative int 'compiles'")
            for field in ("queue_s", "service_s"):
                if field in d and not (_is_num(d[field]) and
                                       d[field] >= 0):
                    err(i, f"tenant_job {field!r} must be a "
                           "non-negative number (the per-tenant SLO "
                           "latency split)")
        elif ev == "placement_selected":
            place = d.get("place")
            if not (isinstance(place, list) and all(
                    isinstance(p, list) and len(p) == 2 and
                    isinstance(p[0], str) and p[0] and
                    isinstance(p[1], int) and not isinstance(p[1], bool)
                    and p[1] >= 0 for p in place)):
                err(i, "placement_selected 'place' is not a list of "
                       "[job_id, worker] pairs")
            if not isinstance(d.get("reason"), str):
                err(i, "placement_selected missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "placement_selected missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "placement_selected missing hex 'input_digest'")
        elif ev == "job_requeued":
            if d.get("cause") not in _REQUEUE_CAUSES:
                err(i, f"job_requeued unknown cause {d.get('cause')!r}")
            if d.get("action") not in _REQUEUE_ACTIONS:
                err(i, f"job_requeued unknown action "
                       f"{d.get('action')!r}")
            if d.get("cause") == "steal":
                moves = d.get("moves")
                if not (isinstance(moves, list) and all(
                        isinstance(m, list) and len(m) == 3 and
                        isinstance(m[0], str) and m[0] and
                        all(isinstance(x, int) and
                            not isinstance(x, bool) and x >= 0
                            for x in m[1:]) for m in moves)):
                    err(i, "job_requeued (steal) 'moves' is not a list "
                           "of [job_id, from, to] triples")
            elif not isinstance(d.get("job_id"), str):
                err(i, "job_requeued missing string 'job_id'")
            if not isinstance(d.get("reason"), str):
                err(i, "job_requeued missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "job_requeued missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "job_requeued missing hex 'input_digest'")
        elif ev == "worker_lease_expired":
            w = d.get("worker")
            if not (isinstance(w, int) and not isinstance(w, bool)
                    and w >= 0):
                err(i, "worker_lease_expired missing int 'worker' >= 0")
            if not (_is_num(d.get("age_s")) and d["age_s"] >= 0):
                err(i, "worker_lease_expired missing non-negative "
                       "'age_s'")
            if not (_is_num(d.get("ttl_s")) and d["ttl_s"] > 0):
                err(i, "worker_lease_expired missing positive 'ttl_s'")
        elif ev == "pages_selected":
            if not isinstance(d.get("pass"), str):
                err(i, "pages_selected missing string 'pass'")
            if d.get("action") not in ("alloc", "fallback"):
                err(i, f"pages_selected unknown action "
                       f"{d.get('action')!r}")
            pages = d.get("pages")
            if not (isinstance(pages, list) and all(
                    isinstance(p, int) and not isinstance(p, bool)
                    and p >= 0 for p in pages)):
                err(i, "pages_selected 'pages' is not a list of "
                       "non-negative page ids")
            elif d.get("action") == "fallback" and pages:
                err(i, "pages_selected fallback must select no pages")
            if not isinstance(d.get("reason"), str):
                err(i, "pages_selected missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "pages_selected missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "pages_selected missing hex 'input_digest'")
        elif ev == "h2d_bytes":
            if not isinstance(d.get("pass"), str):
                err(i, "h2d_bytes missing string 'pass'")
            b = d.get("bytes")
            if not (isinstance(b, int) and not isinstance(b, bool)
                    and b >= 0):
                err(i, "h2d_bytes missing non-negative int 'bytes'")
            p = d.get("puts")
            if not (isinstance(p, int) and not isinstance(p, bool)
                    and p >= 1):
                err(i, "h2d_bytes missing int 'puts' >= 1")
        elif ev == "mega_plan_selected":
            if not isinstance(d.get("pass"), str):
                err(i, "mega_plan_selected missing string 'pass'")
            if not isinstance(d.get("fused_device"), bool):
                err(i, "mega_plan_selected missing boolean "
                       "'fused_device'")
            if not isinstance(d.get("reason"), str):
                err(i, "mega_plan_selected missing string 'reason'")
        elif ev == "dispatch_count":
            if not isinstance(d.get("pass"), str):
                err(i, "dispatch_count missing string 'pass'")
            n = d.get("dispatches")
            if not (isinstance(n, int) and not isinstance(n, bool)
                    and n >= 1):
                err(i, "dispatch_count missing int 'dispatches' >= 1")
            c = d.get("chunks")
            if not (isinstance(c, int) and not isinstance(c, bool)
                    and c >= 0):
                err(i, "dispatch_count missing non-negative int "
                       "'chunks'")
            if d.get("layout") not in ("padded", "ragged", "paged"):
                err(i, f"dispatch_count unknown layout "
                       f"{d.get('layout')!r}")
            if not isinstance(d.get("fused_device"), bool):
                err(i, "dispatch_count missing boolean 'fused_device'")
        elif ev == "overload_state":
            lvl = d.get("level")
            if not (isinstance(lvl, int) and not isinstance(lvl, bool)
                    and 0 <= lvl < len(_OVERLOAD_STATES)):
                err(i, "overload_state missing int 'level' in "
                       f"[0, {len(_OVERLOAD_STATES) - 1}]")
            if d.get("state") not in _OVERLOAD_STATES:
                err(i, f"overload_state unknown state "
                       f"{d.get('state')!r}")
            elif isinstance(lvl, int) and not isinstance(lvl, bool) \
                    and 0 <= lvl < len(_OVERLOAD_STATES) and \
                    d["state"] != _OVERLOAD_STATES[lvl]:
                err(i, f"overload_state level {lvl} does not name "
                       f"state {d.get('state')!r}")
            acts = d.get("actions")
            if not (isinstance(acts, dict) and
                    all(isinstance(v, bool) for v in acts.values()) and
                    {"pack", "shard_split", "admit_low",
                     "admit_any"} <= set(acts)):
                err(i, "overload_state missing bool 'actions' "
                       "(pack/shard_split/admit_low/admit_any)")
            if not isinstance(d.get("reason"), str):
                err(i, "overload_state missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "overload_state missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "overload_state missing hex 'input_digest'")
        elif ev == "admission_rejected":
            for field in ("job_id", "tenant"):
                if not (isinstance(d.get(field), str) and d[field]):
                    err(i, f"admission_rejected missing string "
                           f"{field!r}")
            if d.get("code") not in _REJECT_CODES:
                err(i, f"admission_rejected unknown code "
                       f"{d.get('code')!r}")
            ra = d.get("retry_after_s")
            if not (_is_num(ra) and ra >= 0):
                err(i, "admission_rejected missing non-negative "
                       "'retry_after_s' (a rejection must always tell "
                       "the client when to come back)")
        elif ev == "deadline_missed":
            for field in ("job_id", "tenant"):
                if not (isinstance(d.get(field), str) and d[field]):
                    err(i, f"deadline_missed missing string {field!r}")
            if not (_is_num(d.get("wait_s")) and d["wait_s"] >= 0):
                err(i, "deadline_missed missing non-negative 'wait_s'")
            if not (_is_num(d.get("deadline_s"))
                    and d["deadline_s"] > 0):
                err(i, "deadline_missed missing positive 'deadline_s'")
        elif ev == "breaker_state":
            if not (isinstance(d.get("site"), str) and d["site"]):
                err(i, "breaker_state missing string 'site'")
            if d.get("state") not in _BREAKER_STATES:
                err(i, f"breaker_state unknown state "
                       f"{d.get('state')!r}")
            f_ = d.get("failures")
            if not (isinstance(f_, int) and not isinstance(f_, bool)
                    and f_ >= 0):
                err(i, "breaker_state missing non-negative int "
                       "'failures'")
            if not isinstance(d.get("reason"), str):
                err(i, "breaker_state missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "breaker_state missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "breaker_state missing hex 'input_digest'")
        elif ev == "series_written":
            if not isinstance(d.get("path"), str):
                err(i, "series_written missing string 'path'")
            for field in ("rows", "dropped"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"series_written missing non-negative int "
                           f"{field!r}")
        elif ev == "serve_report_checkpoint":
            if not isinstance(d.get("path"), str):
                err(i, "serve_report_checkpoint missing string 'path'")
            jobs = d.get("jobs")
            if not (isinstance(jobs, int) and not isinstance(jobs, bool)
                    and jobs >= 0):
                err(i, "serve_report_checkpoint missing non-negative "
                       "int 'jobs'")
            if d.get("reason") not in ("periodic", "final"):
                err(i, f"serve_report_checkpoint unknown reason "
                       f"{d.get('reason')!r} (periodic/final)")
        elif ev == "call_plan_selected":
            for field in ("stripe_span", "min_depth", "min_alt"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 1):
                    err(i, f"call_plan_selected missing positive int "
                           f"{field!r}")
            if not (isinstance(d.get("reason"), str) and d["reason"]):
                err(i, "call_plan_selected missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "call_plan_selected missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "call_plan_selected missing hex 'input_digest'")
        elif ev == "call_stripe":
            for field in ("refid", "stripe_start", "covered", "called"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"call_stripe missing non-negative int "
                           f"{field!r}")
            span = d.get("span")
            if not (isinstance(span, int) and not isinstance(span, bool)
                    and span >= 1):
                err(i, "call_stripe missing positive int 'span'")
            if not isinstance(d.get("sample"), str):
                err(i, "call_stripe missing string 'sample'")
        elif ev == "call_emit":
            if not isinstance(d.get("path"), str):
                err(i, "call_emit missing string 'path'")
            for field in ("reads", "admitted", "stripes", "calls",
                          "variants", "genotypes", "samples"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"call_emit missing non-negative int "
                           f"{field!r}")
            if not _is_hex(d.get("vcf_sha256")):
                err(i, "call_emit missing hex 'vcf_sha256'")
            ident = d.get("identical")
            if ident is not None and not isinstance(ident, bool):
                err(i, "call_emit 'identical' must be bool or null")
            rc = d.get("rod_coverage")
            if rc is not None and not (_is_num(rc) and rc >= 0):
                err(i, "call_emit 'rod_coverage' must be a "
                       "non-negative number or null")
        elif ev == "transport_selected":
            if d.get("transport") not in _TRANSPORTS:
                err(i, f"transport_selected unknown transport "
                       f"{d.get('transport')!r}")
            if d.get("spool_sync") not in _SPOOL_SYNCS:
                err(i, f"transport_selected unknown spool_sync "
                       f"{d.get('spool_sync')!r}")
            if not (isinstance(d.get("reason"), str) and d["reason"]):
                err(i, "transport_selected missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "transport_selected missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "transport_selected missing hex 'input_digest'")
        elif ev == "shard_entry_selected":
            if d.get("entry") not in _ENTRIES:
                err(i, f"shard_entry_selected unknown entry "
                       f"{d.get('entry')!r}")
            if not (isinstance(d.get("reason"), str) and d["reason"]):
                err(i, "shard_entry_selected missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "shard_entry_selected missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "shard_entry_selected missing hex "
                       "'input_digest'")
        elif ev == "unit_stolen":
            for field in ("unit", "victim", "thief", "incarnation"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"unit_stolen missing non-negative int "
                           f"{field!r}")
            if isinstance(d.get("victim"), int) and \
                    isinstance(d.get("thief"), int) and \
                    d["victim"] == d["thief"]:
                err(i, "unit_stolen victim equals thief — a shard "
                       "cannot steal its own unit")
        elif ev == "net_connect":
            sh = d.get("shard")
            if not (isinstance(sh, int) and not isinstance(sh, bool)
                    and sh >= 0):
                err(i, "net_connect missing non-negative int 'shard'")
            if not (isinstance(d.get("host"), str) and d["host"]):
                err(i, "net_connect missing string 'host'")
            port = d.get("port")
            if not (isinstance(port, int) and not isinstance(port, bool)
                    and 0 < port < 65536):
                err(i, "net_connect missing int 'port' in (0, 65536)")
        elif ev == "net_retry":
            sh = d.get("shard")
            if not (isinstance(sh, int) and not isinstance(sh, bool)
                    and sh >= 0):
                err(i, "net_retry missing non-negative int 'shard'")
            if not (isinstance(d.get("kind"), str) and d["kind"]):
                err(i, "net_retry missing string 'kind' (the message "
                       "type being retried)")
            att = d.get("attempt")
            if not (isinstance(att, int) and not isinstance(att, bool)
                    and att >= 1):
                err(i, "net_retry missing int 'attempt' >= 1")
            if not (_is_num(d.get("delay_s")) and d["delay_s"] >= 0):
                err(i, "net_retry missing non-negative 'delay_s'")
            if not isinstance(d.get("error"), str):
                err(i, "net_retry missing string 'error'")
        elif ev == "net_degraded":
            sh = d.get("shard")
            if not (isinstance(sh, int) and not isinstance(sh, bool)
                    and sh >= 0):
                err(i, "net_degraded missing non-negative int 'shard'")
            if not (isinstance(d.get("shared_dir"), str)
                    and d["shared_dir"]):
                err(i, "net_degraded missing string 'shared_dir'")
            if not isinstance(d.get("error"), str):
                err(i, "net_degraded missing string 'error'")
        elif ev == "spool_gc":
            if not (isinstance(d.get("spool"), str) and d["spool"]):
                err(i, "spool_gc missing string 'spool'")
            for field in ("collect", "removed", "kept"):
                v = d.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    err(i, f"spool_gc missing non-negative int "
                           f"{field!r}")
            if not isinstance(d.get("dry_run"), bool):
                err(i, "spool_gc missing boolean 'dry_run'")
            if not (isinstance(d.get("reason"), str) and d["reason"]):
                err(i, "spool_gc missing string 'reason'")
            if not isinstance(d.get("inputs"), dict):
                err(i, "spool_gc missing 'inputs' object "
                       "(decision must be replayable)")
            if not _is_hex(d.get("input_digest")):
                err(i, "spool_gc missing hex 'input_digest'")
        elif ev == "startup_seconds":
            for k, v in d.items():
                if k in ("event", "t"):
                    continue
                if not (_is_num(v) and v >= 0):
                    err(i, f"startup_seconds field {k!r} must be a "
                           "non-negative number (a cold-start phase "
                           "mark)")

    if summaries:
        i, s = summaries[0]
        if (i, s) != docs[-1]:
            err(i, "summary is not the last line")
        if not _is_num(s.get("wall_seconds")):
            err(i, "summary missing numeric 'wall_seconds'")
        if not isinstance(s.get("ok"), bool):
            err(i, "summary missing boolean 'ok'")
        snap = s.get("metrics")
        if not isinstance(snap, dict):
            err(i, "summary missing 'metrics' snapshot object")
        else:
            for kind in ("counters", "gauges", "histograms"):
                if not isinstance(snap.get(kind), dict):
                    err(i, f"metrics snapshot missing {kind!r} object")
            for k, v in (snap.get("counters") or {}).items():
                if not _is_num(v):
                    err(i, f"counter {k!r} value is not numeric")
            for k, v in (snap.get("gauges") or {}).items():
                if not _is_num(v):
                    err(i, f"gauge {k!r} value is not numeric")
            for k, h in (snap.get("histograms") or {}).items():
                if not isinstance(h, dict):
                    err(i, f"histogram {k!r} is not an object")
                    continue
                buckets = h.get("buckets")
                if not isinstance(buckets, dict):
                    err(i, f"histogram {k!r} missing buckets")
                    continue
                bad_keys = [b for b in buckets
                            if not b.lstrip("-").isdigit()]
                if bad_keys:
                    err(i, f"histogram {k!r} non-integer bucket keys "
                           f"{bad_keys[:3]}")
                if not _is_num(h.get("sum")):
                    err(i, f"histogram {k!r} missing numeric sum")
                total = sum(n for b, n in buckets.items()
                            if b not in bad_keys)
                if h.get("count") != total:
                    err(i, f"histogram {k!r} count {h.get('count')} != "
                           f"bucket total {total}")
    return errs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: check_metrics.py FILE.jsonl [...]", file=sys.stderr)
        return 2
    bad = 0
    for path in argv:
        errors = validate(path)
        if errors:
            bad += 1
            for e in errors:
                print(e, file=sys.stderr)
        else:
            with open(path) as f:
                n = sum(1 for ln in f if ln.strip())
            print(f"{path}: ok ({n} events, schema {SCHEMA_VERSION})")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
