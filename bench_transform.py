"""Device throughput of the transform pipeline's inner kernels — evidence
toward the north-star target (BASELINE.md: markdup+BQSR >= 10 M reads/s).

Measures the per-batch DEVICE work of `transform` on synthetic 100 bp reads:
markdup 5'-geometry + phred>=15 scoring, BQSR pass-1 covariate counting
(the psum-merged RecalTable scatter), and the BQSR apply rewrite — the three
per-read hot loops the reference runs as Scala inner loops inside Spark
executors (MarkDuplicates.scala:37-43, StandardCovariate.scala:27-103,
RecalUtil.scala:31-42).

Host->device transfer of the packed columns is included (batch streaming),
like bench.py.  Prints one JSON line per stage plus the fused pipeline.
Not run by the driver (bench.py stays the single-line flagstat bench); run
manually: `python bench_transform.py [n_reads]`.

``--stream [n_targets]`` runs the WHOLE-PIPELINE comparison instead (the
bench_realign.py convention): a warmed fused-vs-legacy streamed transform
on a synthetic many-target chromosome, reporting per-pass wall clocks,
the per-pass ``io_bytes_{decoded,spilled,reread}`` ledger breakdown, the
``io_spill_amplification`` gauge both ways, and the frozen fusion plan —
the ISSUE 7 acceptance gate's source numbers.  ``--artifacts DIR``
additionally writes ``BENCH_TRANSFORM_BASELINE.json`` (legacy) and
``BENCH_TRANSFORM.json`` (fused) for ``tools/bench_gate.py`` /
``tools/compare_bench.py`` to diff and gate.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

L = 100
C = 8
N_RG = 4


def make_batch(n, rng):
    return dict(
        n_cigar=np.ones(n, np.int32),
        flags=np.where(rng.rand(n) < 0.5, 16, 0).astype(np.int32),
        mapq=rng.randint(0, 61, size=n).astype(np.int32),
        start=rng.randint(0, 1 << 28, size=n).astype(np.int32),
        valid=np.ones(n, bool),
        read_group=rng.randint(0, N_RG, size=n).astype(np.int32),
        read_len=np.full(n, L, np.int32),
        bases=rng.randint(0, 4, size=(n, L)).astype(np.int8),
        quals=rng.randint(2, 41, size=(n, L)).astype(np.int8),
        state=rng.randint(0, 3, size=(n, L)).astype(np.int8),
        cigar_ops=np.concatenate(
            [np.zeros((n, 1), np.int8), np.full((n, C - 1), -1, np.int8)],
            axis=1),
        cigar_lens=np.concatenate(
            [np.full((n, 1), L, np.int32), np.zeros((n, C - 1), np.int32)],
            axis=1),
    )


def _pass_walls() -> dict:
    """Per-pass wall clocks from the instrument report's top-level
    stage tree (s1-*/s2-*/s3-*/p1-*.../p4-bins groups by prefix)."""
    from adam_tpu.instrument import report

    walls: dict = {}
    for name, node in report().root.children.items():
        key = name.split("-", 1)[0] if "-" in name else name
        walls[key] = round(walls.get(key, 0.0) + node.seconds, 3)
    return walls


def bench_stream(n_targets: int, n_bins: int = 4,
                 artifacts_dir=None) -> None:
    """Warmed fused-vs-legacy streamed transform (markdup + BQSR +
    realign + sort — the full pipeline) with the per-pass I/O ledger
    breakdown and the frozen fusion-plan stamp."""
    from adam_tpu import obs
    from adam_tpu.instrument import report
    from adam_tpu.obs import ioledger
    from adam_tpu.parallel.mesh import make_mesh
    from adam_tpu.parallel.pipeline import (decide_fusion_plan,
                                            resolve_fuse_opt,
                                            streaming_transform)
    from adam_tpu.platform import is_tpu_backend
    from tests._synth_realign import synth_sam

    workroot = tempfile.mkdtemp(prefix="bench_transform_")
    artifacts = {}
    try:
        src = f"{workroot}/synth.sam"
        with open(src, "w") as f:
            f.write(synth_sam(n_targets, reads_per_target=12, seed=0,
                              tail_reads=4))

        # warm the XLA compile caches on a smaller cut of the same
        # shapes (the bench_realign discipline: whichever mode ran
        # first would otherwise eat the compiles)
        warm_src = f"{workroot}/warm.sam"
        with open(warm_src, "w") as f:
            f.write(synth_sam(max(n_targets // 8, 8), reads_per_target=12,
                              seed=0, tail_reads=4))
        for fuse in (False, True):
            streaming_transform(
                warm_src, f"{workroot}/out_warm{int(fuse)}",
                markdup=True, bqsr=True, realign=True, sort=True,
                workdir=f"{workroot}/wk_warm{int(fuse)}",
                mesh=make_mesh(), chunk_rows=1 << 14, n_bins=n_bins,
                fuse=fuse)

        backend = "tpu" if is_tpu_backend() else "cpu"
        for mode, fuse in (("legacy", False), ("fused", True)):
            obs.reset_all()
            report().reset()
            t0 = time.perf_counter()
            n = streaming_transform(
                src, f"{workroot}/out_{mode}", markdup=True, bqsr=True,
                realign=True, sort=True, workdir=f"{workroot}/wk_{mode}",
                mesh=make_mesh(), chunk_rows=1 << 14, n_bins=n_bins,
                fuse=fuse)
            wall = time.perf_counter() - t0
            snap = ioledger.snapshot()
            amp = ioledger.spill_amplification(snap)
            totals = {k: sum(r.get(k, 0) for r in snap.values())
                      for k in ("decoded", "spilled", "reread")}
            line = {"metric": "transform_stream_wall_s", "mode": mode,
                    "value": round(wall, 3), "n_reads": n,
                    "n_targets": n_targets, "n_bins": n_bins,
                    "pass_walls": _pass_walls(),
                    "io_bytes": {p: dict(r) for p, r in
                                 sorted(snap.items())},
                    "io_spill_amplification":
                        None if amp is None else round(amp, 4)}
            print(json.dumps(line))
            artifacts[mode] = {
                "platform": backend,
                "schema": "bench_transform_stream",
                "mode": mode,
                "n_reads": n,
                "transform_stream_wall_s": round(wall, 3),
                "io_spill_amplification":
                    None if amp is None else round(amp, 4),
                "io_bytes_decoded": totals["decoded"],
                "io_bytes_spilled": totals["spilled"],
                "io_bytes_reread": totals["reread"],
            }

        # each artifact records the plan ITS leg actually executed
        # (pure + replayable); the summary line stamps the product
        # default
        def stamp_of(fuse):
            plan = decide_fusion_plan(markdup=True, bqsr=True,
                                      realign=True, sort=True,
                                      is_parquet=False, fuse=fuse)
            return {"mode": plan["mode"], "streams": plan["streams"],
                    "reason": plan["reason"],
                    "input_digest": plan["input_digest"]}

        stamp = stamp_of(resolve_fuse_opt(None))
        artifacts["fused"]["fusion_plan"] = stamp_of(True)
        artifacts["legacy"]["fusion_plan"] = stamp_of(False)
        al, af = artifacts["legacy"], artifacts["fused"]
        cut = None
        if al["io_spill_amplification"] and af["io_spill_amplification"]:
            cut = round(100 * (1 - af["io_spill_amplification"] /
                               al["io_spill_amplification"]), 1)
        print(json.dumps({
            "metric": "transform_fusion_io_cut_pct", "value": cut,
            "target": 40.0,
            "spill_reread_bytes_legacy":
                al["io_bytes_spilled"] + al["io_bytes_reread"],
            "spill_reread_bytes_fused":
                af["io_bytes_spilled"] + af["io_bytes_reread"],
            "fusion_plan": stamp}))

        if artifacts_dir is not None:
            for mode, name in (("legacy", "BENCH_TRANSFORM_BASELINE"),
                               ("fused", "BENCH_TRANSFORM")):
                path = os.path.join(artifacts_dir, f"{name}.json")
                with open(path, "w") as f:
                    json.dump(artifacts[mode], f, indent=1,
                              sort_keys=True)
                    f.write("\n")
                print(json.dumps({"metric": "artifact", "path": path}))
    finally:
        shutil.rmtree(workroot, ignore_errors=True)


def main() -> None:
    from adam_tpu.platform import honor_platform_env
    honor_platform_env()      # the axon plugin ignores bare JAX_PLATFORMS
    if "--stream" in sys.argv:
        # validate flags BEFORE the multi-minute runs: a missing
        # --artifacts value (or one swallowed as n_targets) must fail
        # here, not after both benchmark legs completed
        rest = sys.argv[1:]
        artifacts_dir = None
        if "--artifacts" in rest:
            i = rest.index("--artifacts")
            if i + 1 >= len(rest) or rest[i + 1].startswith("--"):
                sys.exit("bench_transform: --artifacts needs a "
                         "directory argument")
            artifacts_dir = rest[i + 1]
            if not os.path.isdir(artifacts_dir):
                sys.exit(f"bench_transform: --artifacts dir "
                         f"{artifacts_dir!r} does not exist")
            del rest[i:i + 2]
        pos = [a for a in rest if not a.startswith("--")]
        bench_stream(int(pos[0]) if pos else 400,
                     artifacts_dir=artifacts_dir)
        return
    import jax
    import jax.numpy as jnp
    from adam_tpu.bqsr.recalibrate import (_apply_kernel_lut,
                                           _build_apply_lut, _count_kernel)
    from adam_tpu.bqsr.table import RecalTable
    from adam_tpu.ops.markdup import _device_fiveprime_and_score

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8_000_000
    rng = np.random.RandomState(0)
    b = make_batch(n, rng)
    rt = RecalTable(n_read_groups=N_RG, max_read_len=L)

    def markdup(d):
        return _device_fiveprime_and_score(
            d["flags"], d["start"], d["cigar_ops"], d["cigar_lens"],
            d["n_cigar"], d["quals"])

    def bqsr_count(d):
        return _count_kernel(
            d["bases"], d["quals"], d["read_len"], d["flags"],
            d["read_group"], d["state"], d["valid"],
            n_qual_rg=rt.n_qual_rg, n_cycle=rt.n_cycle)

    fin = rt.finalize()
    fin_dev = tuple(jnp.asarray(a) for a in (
        fin.rg_delta, fin.qual_delta, fin.cycle_delta, fin.ctx_delta,
        fin.rg_of_qualrg))

    lut = _build_apply_lut(N_RG, *fin_dev)   # the product's r5 pass-2

    def bqsr_apply(d):
        mask = jnp.ones(d["bases"].shape[:1], bool)
        return _apply_kernel_lut(d["bases"], d["quals"], d["read_len"],
                                 d["flags"], d["read_group"], mask, lut,
                                 n_rg=N_RG)

    def fused(d):
        # the transform pipeline's device work for one batch, one dispatch
        return markdup(d), bqsr_count(d), bqsr_apply(d)

    stages = [("markdup_score", markdup), ("bqsr_count", bqsr_count),
              ("bqsr_apply", bqsr_apply), ("transform_fused", fused)]

    def sync(out):
        # pull one scalar of one output: a jit dispatch is one executable,
        # so any output materializing implies the whole program ran —
        # and device_get is a REAL round trip where the tunnel backend's
        # block_until_ready is a no-op (see bench.py's timing discipline)
        leaf = jax.tree_util.tree_leaves(out)[0]
        jax.device_get(leaf.ravel()[:1])

    for name, fn in stages:
        jfn = jax.jit(fn)
        put = {k: jax.device_put(v) for k, v in b.items()}
        sync(jfn(put))                   # compile + warm
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            put = {k: jax.device_put(v) for k, v in b.items()}
            sync(jfn(put))
        dt = (time.perf_counter() - t0) / iters
        print(json.dumps({"metric": f"{name}_reads_per_sec",
                          "value": round(n / dt), "unit": "reads/s"}))


if __name__ == "__main__":
    main()
