"""Device throughput of the transform pipeline's inner kernels — evidence
toward the north-star target (BASELINE.md: markdup+BQSR >= 10 M reads/s).

Measures the per-batch DEVICE work of `transform` on synthetic 100 bp reads:
markdup 5'-geometry + phred>=15 scoring, BQSR pass-1 covariate counting
(the psum-merged RecalTable scatter), and the BQSR apply rewrite — the three
per-read hot loops the reference runs as Scala inner loops inside Spark
executors (MarkDuplicates.scala:37-43, StandardCovariate.scala:27-103,
RecalUtil.scala:31-42).

Host->device transfer of the packed columns is included (batch streaming),
like bench.py.  Prints one JSON line per stage plus the fused pipeline.
Not run by the driver (bench.py stays the single-line flagstat bench); run
manually: `python bench_transform.py [n_reads]`.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

L = 100
C = 8
N_RG = 4


def make_batch(n, rng):
    return dict(
        n_cigar=np.ones(n, np.int32),
        flags=np.where(rng.rand(n) < 0.5, 16, 0).astype(np.int32),
        mapq=rng.randint(0, 61, size=n).astype(np.int32),
        start=rng.randint(0, 1 << 28, size=n).astype(np.int32),
        valid=np.ones(n, bool),
        read_group=rng.randint(0, N_RG, size=n).astype(np.int32),
        read_len=np.full(n, L, np.int32),
        bases=rng.randint(0, 4, size=(n, L)).astype(np.int8),
        quals=rng.randint(2, 41, size=(n, L)).astype(np.int8),
        state=rng.randint(0, 3, size=(n, L)).astype(np.int8),
        cigar_ops=np.concatenate(
            [np.zeros((n, 1), np.int8), np.full((n, C - 1), -1, np.int8)],
            axis=1),
        cigar_lens=np.concatenate(
            [np.full((n, 1), L, np.int32), np.zeros((n, C - 1), np.int32)],
            axis=1),
    )


def main() -> None:
    from adam_tpu.platform import honor_platform_env
    honor_platform_env()      # the axon plugin ignores bare JAX_PLATFORMS
    import jax
    import jax.numpy as jnp
    from adam_tpu.bqsr.recalibrate import (_apply_kernel_lut,
                                           _build_apply_lut, _count_kernel)
    from adam_tpu.bqsr.table import RecalTable
    from adam_tpu.ops.markdup import _device_fiveprime_and_score

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8_000_000
    rng = np.random.RandomState(0)
    b = make_batch(n, rng)
    rt = RecalTable(n_read_groups=N_RG, max_read_len=L)

    def markdup(d):
        return _device_fiveprime_and_score(
            d["flags"], d["start"], d["cigar_ops"], d["cigar_lens"],
            d["n_cigar"], d["quals"])

    def bqsr_count(d):
        return _count_kernel(
            d["bases"], d["quals"], d["read_len"], d["flags"],
            d["read_group"], d["state"], d["valid"],
            n_qual_rg=rt.n_qual_rg, n_cycle=rt.n_cycle)

    fin = rt.finalize()
    fin_dev = tuple(jnp.asarray(a) for a in (
        fin.rg_delta, fin.qual_delta, fin.cycle_delta, fin.ctx_delta,
        fin.rg_of_qualrg))

    lut = _build_apply_lut(N_RG, *fin_dev)   # the product's r5 pass-2

    def bqsr_apply(d):
        mask = jnp.ones(d["bases"].shape[:1], bool)
        return _apply_kernel_lut(d["bases"], d["quals"], d["read_len"],
                                 d["flags"], d["read_group"], mask, lut,
                                 n_rg=N_RG)

    def fused(d):
        # the transform pipeline's device work for one batch, one dispatch
        return markdup(d), bqsr_count(d), bqsr_apply(d)

    stages = [("markdup_score", markdup), ("bqsr_count", bqsr_count),
              ("bqsr_apply", bqsr_apply), ("transform_fused", fused)]

    def sync(out):
        # pull one scalar of one output: a jit dispatch is one executable,
        # so any output materializing implies the whole program ran —
        # and device_get is a REAL round trip where the tunnel backend's
        # block_until_ready is a no-op (see bench.py's timing discipline)
        leaf = jax.tree_util.tree_leaves(out)[0]
        jax.device_get(leaf.ravel()[:1])

    for name, fn in stages:
        jfn = jax.jit(fn)
        put = {k: jax.device_put(v) for k, v in b.items()}
        sync(jfn(put))                   # compile + warm
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            put = {k: jax.device_put(v) for k, v in b.items()}
            sync(jfn(put))
        dt = (time.perf_counter() - t0) / iters
        print(json.dumps({"metric": f"{name}_reads_per_sec",
                          "value": round(n / dt), "unit": "reads/s"}))


if __name__ == "__main__":
    main()
